"""Resource-exhaustion containment (utils/resources.py + wiring).

One degradation priority — model artifacts > training progress >
observability — wired through every allocating layer:

- fault kinds ``enospc``/``oom``/``rss`` (utils/faults.py) and the
  classifiers in utils/resources.py;
- checkpoint writer: tmp cleanup on failure, keep-last-K pruning, ENOSPC
  prune-and-retry (utils/checkpoint.py);
- telemetry report: degrade to a counted drop instead of crashing the
  driver at finalize (obs/report.py);
- replay cache: spool-write fallback to legacy re-stream with partial-file
  cleanup, torn-spool recovery with exact chunk parity, dead-letter write
  failure never masking the chunk error (io/pipeline.py);
- device OOM containment with evict-harder + budget shrink and bit parity
  in the RE training store (algorithm/re_store.py) and gc-and-retry in the
  serving store (serve/store.py);
- RSS watchdog levels, pressure tightening of pipeline depth and serving
  admission, and the clean hard-pressure error at the CD pass boundary.
"""

import errno
import glob
import os
import pickle
import threading

import numpy as np
import jax.numpy as jnp
import pytest

from photon_tpu.obs.metrics import registry, reset_registry
from photon_tpu.utils import faults, resources
from photon_tpu.utils.faults import FaultPlan, FaultRule


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv(faults.FAULT_PLAN_ENV, raising=False)
    monkeypatch.delenv(resources.RSS_LIMIT_ENV, raising=False)
    faults.reset()
    reset_registry()
    resources.stop_watchdog()
    yield
    faults.reset()
    resources.stop_watchdog()


def _plan(*rules, seed=0):
    return faults.configure(FaultPlan(seed=seed, rules=tuple(rules)))


# ---------------------------------------------------------------------------
# Fault kinds + classifiers
# ---------------------------------------------------------------------------


def test_enospc_fault_kind_raises_oserror_with_enospc_errno():
    _plan(FaultRule("w.x", kind="enospc", at=(0,)))
    with pytest.raises(OSError) as ei:
        faults.check("w.x")
    assert ei.value.errno == errno.ENOSPC
    assert resources.is_enospc(ei.value)
    assert isinstance(ei.value, faults.EnospcInjectedFault)


def test_oom_fault_kind_matches_resource_exhausted_classifier():
    _plan(FaultRule("u.y", kind="oom", at=(0,)))
    with pytest.raises(RuntimeError) as ei:
        faults.check("u.y")
    assert "RESOURCE_EXHAUSTED" in str(ei.value)
    assert resources.is_device_oom(ei.value)
    # Real non-exhaustion errors stay unclassified.
    assert not resources.is_device_oom(RuntimeError("boom"))
    assert not resources.is_enospc(OSError(errno.EIO, "io error"))


def test_rss_fault_kind_is_inert_outside_the_watchdog():
    _plan(FaultRule("rss.sample", kind="rss", p=1.0))
    faults.check("rss.sample")  # must not raise — only the sampler acts
    arr = faults.poison("rss.sample", np.ones(3))
    assert not np.isnan(arr).any()


def test_oom_retry_calls_evict_hook_and_counts():
    calls = []

    def attempt():
        calls.append("try")
        if calls.count("try") < 3:
            raise RuntimeError("RESOURCE_EXHAUSTED: arena full")
        return 42

    out = resources.oom_retry(
        attempt, site="t", evict=lambda i: calls.append(f"evict{i}"),
        retries=2,
    )
    assert out == 42
    assert calls == ["try", "evict0", "try", "evict1", "try"]
    assert registry().find("device_oom_retries_total", site="t").value == 2
    # Final OOM and non-OOM errors propagate untouched.
    with pytest.raises(RuntimeError):
        resources.oom_retry(
            lambda: (_ for _ in ()).throw(
                RuntimeError("RESOURCE_EXHAUSTED: no")),
            site="t", retries=1,
        )
    with pytest.raises(ValueError):
        resources.oom_retry(
            lambda: (_ for _ in ()).throw(ValueError("x")), site="t")


# ---------------------------------------------------------------------------
# Checkpoint writer: tmp cleanup, keep-last, ENOSPC prune-and-retry
# ---------------------------------------------------------------------------


def _no_tmp(directory):
    return glob.glob(os.path.join(directory, "*.tmp"))


def test_save_checkpoint_failure_leaves_no_tmp_file(tmp_path):
    from photon_tpu.utils.checkpoint import save_checkpoint

    d = str(tmp_path)
    # A non-disk-space write failure propagates — but the partial tmp must
    # be cleaned up either way (satellite: the old path leaked it).
    _plan(FaultRule("checkpoint.io", kind="transient", at=(0,)))
    with pytest.raises(faults.TransientInjectedFault):
        save_checkpoint(d, dict(w=np.arange(4.0)), 0)
    assert _no_tmp(d) == []
    assert not os.path.exists(os.path.join(d, "step_0.npz"))


def test_save_checkpoint_keep_last_prunes_oldest(tmp_path):
    from photon_tpu.utils.checkpoint import latest_step, save_checkpoint

    d = str(tmp_path)
    for step in range(5):
        save_checkpoint(d, dict(w=np.full(3, float(step))), step, keep_last=2)
    steps = [n for n in sorted(os.listdir(d)) if n.startswith("step_")]
    assert steps == ["step_3.npz", "step_4.npz"]
    assert latest_step(d) == 4
    assert registry().find("checkpoint_pruned_total").value == 3


def test_save_checkpoint_keep_last_env_default(tmp_path, monkeypatch):
    from photon_tpu.utils.checkpoint import (
        CHECKPOINT_KEEP_LAST_ENV,
        save_checkpoint,
    )

    monkeypatch.setenv(CHECKPOINT_KEEP_LAST_ENV, "1")
    d = str(tmp_path)
    for step in range(3):
        save_checkpoint(d, dict(w=np.zeros(2)), step)
    steps = [n for n in sorted(os.listdir(d)) if n.startswith("step_")]
    assert steps == ["step_2.npz"]


def test_save_checkpoint_enospc_prunes_and_retries(tmp_path):
    from photon_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

    d = str(tmp_path)
    for step in range(3):
        save_checkpoint(d, dict(w=np.full(3, float(step))), step)
    # Disk full exactly once, on the next save: the writer must prune older
    # steps, retry, and publish — no error to the caller, no tmp files.
    _plan(FaultRule("checkpoint.io", kind="enospc", at=(0,), max_count=1))
    save_checkpoint(d, dict(w=np.full(3, 3.0)), 3)
    steps = sorted(n for n in os.listdir(d) if n.startswith("step_"))
    assert steps == ["step_2.npz", "step_3.npz"]  # pruned to 1 + the new one
    assert _no_tmp(d) == []
    state, step = load_checkpoint(d)
    assert step == 3
    assert np.array_equal(np.asarray(state["w"]), np.full(3, 3.0))
    assert registry().find("disk_enospc_total", site="checkpoint.io").value == 1


def test_save_checkpoint_persistent_enospc_raises_without_tmp(tmp_path):
    from photon_tpu.utils.checkpoint import save_checkpoint

    d = str(tmp_path)
    _plan(FaultRule("checkpoint.io", kind="enospc", p=1.0))
    with pytest.raises(OSError) as ei:
        save_checkpoint(d, dict(w=np.zeros(2)), 0)
    assert resources.is_enospc(ei.value)
    assert _no_tmp(d) == []


# ---------------------------------------------------------------------------
# Telemetry report: degrade, never crash the driver at finalize
# ---------------------------------------------------------------------------


def test_write_run_report_degrades_on_write_failure(tmp_path):
    from photon_tpu.obs.report import write_run_report

    path = str(tmp_path / "report.jsonl")
    _plan(FaultRule("telemetry.write", kind="enospc", at=(0,)))
    write_run_report(path, [dict(record="meta", x=1)])  # must not raise
    assert not os.path.exists(path)
    assert _no_tmp(str(tmp_path)) == []
    assert registry().find("telemetry_write_failures_total").value == 1
    # Next write (disk recovered) succeeds normally.
    write_run_report(path, [dict(record="meta", x=2)])
    assert os.path.exists(path)


# ---------------------------------------------------------------------------
# Replay cache: spool ENOSPC fallback + torn-spool recovery
# ---------------------------------------------------------------------------


class _Chunk:
    def __init__(self, i):
        self.index = i
        self.data = np.full(64, float(i))


def _chunks(n=6):
    def factory():
        for i in range(n):
            yield _Chunk(i)

    return factory


def _indices(it):
    return [c.index for c in it]


def _replay_cache(spill):
    from photon_tpu.io.pipeline import ChunkReplayCache

    # Budget fits exactly two 512-byte chunks; the rest spools.
    return ChunkReplayCache(
        _chunks(), byte_budget=2 * 64 * 8 + 1,
        nbytes=lambda c: c.data.nbytes, spill_dir=spill,
    )


def test_replay_spool_enospc_falls_back_to_restream(tmp_path):
    spill = str(tmp_path / "spill")
    cache = _replay_cache(spill)
    _plan(FaultRule("spool.write", kind="enospc", at=(0,)))
    # The failure happens mid-pass; training must still see every chunk.
    assert _indices(cache) == list(range(6))
    assert cache.spilled
    # Fallback is sticky: legacy re-stream, no spool files left behind.
    assert glob.glob(os.path.join(spill, "spool-*.pkl")) == []
    assert _indices(cache) == list(range(6))
    assert cache.source_passes == 2  # decode re-paid: the legacy path
    assert registry().find("replay_spill_fallbacks_total").value == 1


def test_replay_torn_spool_recovers_with_exact_parity(tmp_path):
    spill = str(tmp_path / "spill")
    cache = _replay_cache(spill)
    assert _indices(cache) == list(range(6))  # pass 1: 2 in RAM, 4 spooled
    spools = glob.glob(os.path.join(spill, "spool-*.pkl"))
    assert len(spools) == 1
    # Tear the spool: keep one intact pickle record, truncate into garbage
    # (a crash or bit rot between passes).
    with open(spools[0], "rb") as f:
        first = pickle.load(f)
        intact = f.tell()
    assert first.index == 2  # memory prefix holds 0,1; spool starts at 2
    with open(spools[0], "rb+") as f:
        f.truncate(intact + 7)
    got = _indices(cache)  # replay pass hits the tear and must recover
    assert got == list(range(6))
    assert registry().find("replay_spool_torn_total").value == 1
    assert glob.glob(os.path.join(spill, "spool-*.pkl")) == []  # cleaned up
    # The cache rebuilds (memory + a fresh spool) on the next pass.
    assert _indices(cache) == list(range(6))
    assert _indices(cache) == list(range(6))


def test_dead_letter_write_failure_does_not_mask_chunk_error(tmp_path):
    from photon_tpu.io.pipeline import _SkipBudget

    dl = str(tmp_path / "letters.jsonl")
    _plan(FaultRule("deadletter.write", kind="enospc", p=1.0))
    budget = _SkipBudget(2, dl)
    # The sidecar append fails; dead_letter must swallow it (the original
    # chunk error is what the skip budget is accounting for) and count it.
    budget.dead_letter("decode", _Chunk(1), RuntimeError("original"))
    assert registry().find("dead_letter_write_failures_total").value == 1
    # No record landed (at most an empty file, as with a real full disk).
    assert not os.path.exists(dl) or os.path.getsize(dl) == 0
    budget.dead_letter("decode", _Chunk(2), RuntimeError("original"))
    assert registry().find("dead_letter_write_failures_total").value == 2
    # Disk recovers: the sidecar works again without a restart.
    faults.reset()
    budget.dead_letter("decode", _Chunk(3), RuntimeError("original"))
    with open(dl) as f:
        assert len(f.readlines()) == 1


# ---------------------------------------------------------------------------
# RE training store: spill fallback + device OOM containment, bit parity
# ---------------------------------------------------------------------------

RE_E, RE_D = 32, 4
_re_rng = np.random.default_rng(11)
_re_counts = _re_rng.integers(5, 11, size=RE_E)
RE_EIDS = np.repeat(np.arange(RE_E, dtype=np.int32), _re_counts)
RE_N = RE_EIDS.size
RE_X = _re_rng.normal(size=(RE_N, RE_D)).astype(np.float32)
RE_Y = (_re_rng.uniform(size=RE_N) < 0.5).astype(np.float32)
RE_W = np.ones(RE_N, np.float32)


def _re_dataset():
    from photon_tpu.data.random_effect import (
        RandomEffectDataConfig,
        build_random_effect_dataset,
    )

    cfg = RandomEffectDataConfig(
        re_type="userId", feature_shard="re", n_buckets=2,
        shape_bucketing=True,
    )
    return build_random_effect_dataset(RE_EIDS, RE_X, RE_Y, RE_W, RE_E, cfg)


def test_re_spill_enospc_falls_back_to_host_memory(tmp_path):
    from photon_tpu.algorithm.re_store import host_entity_block

    spill = str(tmp_path / "re-spill")
    os.makedirs(spill)
    block = _re_dataset().blocks[0]
    # Field 1 ("features") hits a full disk; it must stay in host RAM with
    # identical values while the other fields spill normally.
    _plan(FaultRule("re_store.spill", kind="enospc", at=(1,)))
    out = host_entity_block(block, spill_dir=spill, index=0)
    for name in ("entity_idx", "features", "label", "weight"):
        np.testing.assert_array_equal(
            np.asarray(getattr(out, name)), np.asarray(getattr(block, name))
        )
    assert not isinstance(out.features, np.memmap)
    assert isinstance(out.label, np.memmap)
    assert registry().find("re_spill_fallbacks_total").value == 1
    # No partial .npy left for the failed field.
    saved = sorted(os.path.basename(p) for p in glob.glob(f"{spill}/*.npy"))
    assert "block00000_features.npy" not in saved
    assert len(saved) == 5


def test_re_store_oom_shrinks_budget_and_retries():
    from photon_tpu.algorithm.re_store import ReDeviceStore

    blocks = _re_dataset().blocks
    assert len(blocks) >= 2
    store = ReDeviceStore(blocks, budget_bytes=1 << 30, coordinate_id="per-x")

    def w0(b):
        return np.zeros((b.num_entities, b.dim), np.float32)

    # Fill the working set, then inject one OOM on the next upload.
    for k in range(len(store.blocks) - 1):
        store.acquire(k, store.blocks[k], w0(store.blocks[k]), cacheable=True)
        store.release(k, cacheable=True)
    _plan(FaultRule("re_store.upload", kind="oom", at=(0,), max_count=1))
    last = len(store.blocks) - 1
    blk = store.blocks[last]
    dev_block, dev_w0 = store.acquire(last, blk, w0(blk), cacheable=True)
    # Containment: evicted the unprotected working set, halved the budget,
    # retried — the caller never saw the OOM and the data is bit-identical.
    np.testing.assert_array_equal(
        np.asarray(dev_block.features), np.asarray(blk.features)
    )
    np.testing.assert_array_equal(np.asarray(dev_w0), w0(blk))
    assert store.effective_budget == max(store._max_cost, (1 << 30) // 2)
    assert store.lru.resident == [last]
    assert registry().find(
        "re_device_budget_shrinks_total", coordinate="per-x"
    ).value == 1
    store.release(last, cacheable=True)


def test_re_store_oom_at_floor_raises_device_memory_error():
    from photon_tpu.algorithm.re_store import ReDeviceStore

    blocks = _re_dataset().blocks
    store = ReDeviceStore(blocks, budget_bytes=1, coordinate_id="per-y")
    _plan(FaultRule("re_store.upload", kind="oom", p=1.0))
    with pytest.raises(resources.DeviceMemoryError) as ei:
        store.acquire(
            0, store.blocks[0],
            np.zeros((store.blocks[0].num_entities, store.blocks[0].dim),
                     np.float32),
            cacheable=True,
        )
    assert "largest single" in str(ei.value)


def _train_re_ooc(plan):
    from photon_tpu.algorithm.random_effect import RandomEffectCoordinate
    from photon_tpu.data.game_data import GameBatch
    from photon_tpu.ops.losses import LogisticLoss
    from photon_tpu.ops.objective import GLMObjective
    from photon_tpu.optim.factory import OptimizerSpec
    from photon_tpu.types import OptimizerType, TaskType

    faults.reset()
    if plan is not None:
        faults.configure(plan)
    batch = GameBatch(
        label=jnp.asarray(RE_Y), offset=jnp.zeros(RE_N, jnp.float32),
        weight=jnp.asarray(RE_W), features={"re": jnp.asarray(RE_X)},
        entity_ids={"userId": jnp.asarray(RE_EIDS)},
    )
    coord = RandomEffectCoordinate(
        "per_user", _re_dataset(), TaskType.LOGISTIC_REGRESSION,
        GLMObjective(loss=LogisticLoss, l2_weight=0.5),
        optimizer_spec=OptimizerSpec(
            optimizer=OptimizerType.NEWTON, max_iter=20, tol=1e-9),
        device_budget_bytes=1,  # floor: one block resident at a time
    )
    model = None
    for it in range(2):
        coord.begin_cd_pass(it)
        model, _stats = coord.train(batch, None, model)
    return np.asarray(model.coefficients)


def test_re_store_oom_training_bit_parity():
    """End-to-end: an OOC RE training run with device OOM injected at the
    upload edge produces coefficients bit-identical to the fault-free run —
    containment changes residency, never values."""
    clean = _train_re_ooc(None)
    faulted = _train_re_ooc(FaultPlan(rules=(
        FaultRule("re_store.upload", kind="oom", at=(0, 5), max_count=2),
    )))
    assert np.array_equal(clean, faulted)  # bit parity, not approx


# ---------------------------------------------------------------------------
# Serving store: OOM gc-and-retry
# ---------------------------------------------------------------------------


def test_serve_oom_contained_retries_once_then_hard_fails():
    from photon_tpu.serve.store import _oom_contained

    calls = []

    def flaky():
        calls.append(1)
        if len(calls) == 1:
            raise RuntimeError("RESOURCE_EXHAUSTED: out of device memory")
        return 42

    assert _oom_contained("userId", flaky) == 42
    assert registry().find(
        "serve_store_oom_evictions_total", re_type="userId"
    ).value == 1
    with pytest.raises(resources.DeviceMemoryError):
        _oom_contained("userId", lambda: (_ for _ in ()).throw(
            RuntimeError("RESOURCE_EXHAUSTED: nope")))
    with pytest.raises(ValueError):
        _oom_contained("userId", lambda: (_ for _ in ()).throw(
            ValueError("not memory")))


# ---------------------------------------------------------------------------
# RSS watchdog: levels, tightening, clean hard-pressure error
# ---------------------------------------------------------------------------


def test_watchdog_levels_from_injected_rss_rules():
    wd = resources.RssWatchdog(limit_bytes=1 << 62)  # never trips for real
    assert wd.sample() == resources.LEVEL_OK
    _plan(
        FaultRule("rss.sample", kind="rss", at=(0,), message="soft squeeze"),
        FaultRule("rss.sample", kind="rss", at=(1,), message="hard limit"),
    )
    assert wd.sample() == resources.LEVEL_SOFT
    wd.check()  # soft: advisory only
    assert wd.sample() == resources.LEVEL_HARD
    with pytest.raises(resources.HostMemoryPressureError) as ei:
        wd.check("unit test")
    assert "OOM-killer" in str(ei.value) and "unit test" in str(ei.value)
    assert wd.sample() == resources.LEVEL_OK  # pressure clears
    assert registry().find(
        "rss_pressure_events_total", level="soft"
    ).value == 1
    assert registry().find("host_rss_bytes").value > 0


def test_watchdog_real_thresholds(monkeypatch):
    readings = iter([80, 90, 99])
    monkeypatch.setattr(resources, "_read_rss_bytes", lambda: next(readings))
    wd = resources.RssWatchdog(limit_bytes=100, soft_fraction=0.85,
                               hard_fraction=0.95)
    assert wd.sample() == resources.LEVEL_OK
    assert wd.sample() == resources.LEVEL_SOFT
    assert wd.sample() == resources.LEVEL_HARD


def test_watchdog_inert_without_a_limit(monkeypatch):
    monkeypatch.setattr(resources, "_cgroup_mem_limit", lambda: None)
    wd = resources.RssWatchdog()
    assert wd.limit_bytes is None
    assert wd.sample() == resources.LEVEL_OK
    wd.check()  # never raises


def test_pressure_tightens_depth_and_cap():
    assert resources.tightened_depth(4) == 4  # no watchdog: untouched
    assert resources.tightened_cap(64) == 64
    # interval_s is huge so the daemon thread never races the manual samples.
    wd = resources.start_watchdog(limit_bytes=1 << 62, interval_s=3600)
    _plan(FaultRule("rss.sample", kind="rss", at=(0,), message="soft"))
    wd.sample()
    assert resources.memory_pressure()
    assert resources.pressure_level() == resources.LEVEL_SOFT
    assert resources.tightened_depth(4) == 1
    assert resources.tightened_cap(64) == 32
    _plan(FaultRule("rss.sample", kind="rss", at=(0,), message="hard"))
    wd.sample()
    assert resources.tightened_cap(64) == 16
    with pytest.raises(resources.HostMemoryPressureError):
        resources.check_memory("here")


def test_replay_cache_stops_caching_under_memory_pressure(tmp_path):
    # Soft pressure folds into the replay cache's admission decision: the
    # in-RAM prefix stops growing even though the byte budget has room.
    wd = resources.start_watchdog(limit_bytes=1 << 62, interval_s=3600)
    _plan(FaultRule("rss.sample", kind="rss", p=1.0, message="soft"))
    wd.sample()
    cache = _replay_cache(str(tmp_path / "spill"))
    assert _indices(cache) == list(range(6))
    assert cache.cached_bytes == 0  # everything went to the spool
    assert cache.spilled


def test_batcher_sheds_under_pressure_instead_of_queueing():
    from photon_tpu.serve.batcher import (
        BackpressureError,
        MicroBatcher,
        ScoreRequest,
    )

    gate = threading.Event()

    def scorer(reqs):
        gate.wait(5.0)
        return [0.0] * len(reqs)

    b = MicroBatcher(scorer, max_batch_size=1, max_delay_s=0.005,
                     queue_cap=8, name="prs")
    try:
        wd = resources.start_watchdog(limit_bytes=1 << 62, interval_s=3600)
        _plan(FaultRule("rss.sample", kind="rss", p=1.0, message="hard"))
        wd.sample()
        # Effective admission cap under hard pressure is 8 // 4 = 2: far
        # fewer than 10 submissions fit before backpressure trips.
        with pytest.raises(BackpressureError) as ei:
            for _ in range(10):
                b.submit(ScoreRequest({}))
        assert "2" in str(ei.value)
    finally:
        gate.set()
        b.close(drain=False)


def test_cd_raises_clean_host_memory_error_at_pass_boundary(tmp_path):
    from photon_tpu.algorithm.coordinate_descent import CoordinateDescent
    from photon_tpu.algorithm.fixed_effect import FixedEffectCoordinate
    from photon_tpu.data.game_data import GameBatch
    from photon_tpu.ops.losses import LogisticLoss
    from photon_tpu.ops.objective import GLMObjective
    from photon_tpu.optim.factory import OptimizerSpec
    from photon_tpu.types import TaskType
    from photon_tpu.utils.checkpoint import latest_step

    rng = np.random.default_rng(3)
    n, d = 64, 4
    X = rng.normal(size=(n, d)).astype(np.float32)
    X[:, 0] = 1.0
    batch = GameBatch(
        label=jnp.asarray((rng.random(n) < 0.5).astype(np.float32)),
        offset=jnp.zeros(n, jnp.float32),
        weight=jnp.ones(n, jnp.float32),
        features={"global": jnp.asarray(X)},
        entity_ids={},
    )
    fixed = FixedEffectCoordinate(
        "global", "global", TaskType.LOGISTIC_REGRESSION,
        GLMObjective(loss=LogisticLoss, l2_weight=1.0, intercept_index=0),
        OptimizerSpec(),
    )
    wd = resources.start_watchdog(limit_bytes=1 << 62, interval_s=3600)
    _plan(FaultRule("rss.sample", kind="rss", p=1.0, message="hard"))
    wd.sample()
    ckpt = str(tmp_path / "ckpt")
    cd = CoordinateDescent({"global": fixed}, ["global"], num_iterations=3)
    with pytest.raises(resources.HostMemoryPressureError):
        cd.run(batch, checkpoint_dir=ckpt)
    # The pass boundary checkpointed before raising — the run is resumable.
    assert latest_step(ckpt) == 0
