"""Feature-dimension-sharded (TP analogue) fixed-effect training tests.

Runs on the 8-fake-CPU-device mesh (conftest). Checks that training with w
sharded over the feature axis reproduces the replicated-dense solve — the
sharding must be semantics-preserving (reference parity anchor: the sparse
fixed-effect path of FixedEffectCoordinate.scala:115-129 yields the same GLM
regardless of how coefficients are stored).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from photon_tpu.data.batch import LabeledBatch, SparseFeatures
from photon_tpu.data.normalization import NormalizationContext
from photon_tpu.ops.losses import LogisticLoss, PoissonLoss
from photon_tpu.ops.objective import GLMObjective
from photon_tpu.optim.common import OptimizerConfig
from photon_tpu.optim.lbfgs import minimize_lbfgs
from photon_tpu.parallel.feature_sharded import (
    padded_dim,
    place_feature_sharded,
    sparse_value_and_grad_feature_sharded,
    train_fixed_effect_feature_sharded,
)
from photon_tpu.parallel.mesh import make_mesh


def _sparse_problem(n=64, d=30, k=6, seed=0, binary=True):
    rng = np.random.default_rng(seed)
    indices = np.zeros((n, k), np.int32)
    values = np.zeros((n, k), np.float32)
    for i in range(n):
        nnz = rng.integers(2, k + 1)
        ix = rng.choice(d, size=nnz, replace=False)
        indices[i, :nnz] = np.sort(ix)
        values[i, :nnz] = rng.normal(size=nnz)
    # dense copy
    X = np.zeros((n, d), np.float32)
    for i in range(n):
        mask = values[i] != 0
        X[i, indices[i, mask]] += values[i, mask]
    w_true = rng.normal(size=d).astype(np.float32) / np.sqrt(d)
    logits = X @ w_true
    if binary:
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logits))).astype(np.float32)
    else:
        y = rng.poisson(np.exp(np.clip(logits, None, 3))).astype(np.float32)
    weight = rng.uniform(0.5, 1.5, size=n).astype(np.float32)
    offset = rng.normal(size=n).astype(np.float32) * 0.1
    return indices, values, X, y, weight, offset


def _pad_sparse(indices, values, dim_p):
    return SparseFeatures(jnp.asarray(indices), jnp.asarray(values), dim_p)


@pytest.fixture(scope="module")
def mesh24():
    return make_mesh(n_data=2, n_feature=4)


def test_value_and_grad_matches_replicated(mesh24):
    n, d = 64, 30
    indices, values, X, y, weight, offset = _sparse_problem(n=n, d=d)
    dim_p = padded_dim(d, 4)
    assert dim_p == 32

    obj = GLMObjective(loss=LogisticLoss, l2_weight=0.7, intercept_index=3)
    vg = sparse_value_and_grad_feature_sharded(obj, mesh24, dim_p)

    w = np.zeros(dim_p, np.float32)
    w[:d] = np.linspace(-0.5, 0.5, d)
    batch = LabeledBatch(
        jnp.asarray(y), _pad_sparse(indices, values, dim_p),
        jnp.asarray(offset), jnp.asarray(weight),
    )
    w_sh, batch_sh = place_feature_sharded(mesh24, jnp.asarray(w), batch)
    val, grad = jax.jit(vg)(w_sh, batch_sh)

    dense_batch = LabeledBatch(
        jnp.asarray(y),
        jnp.asarray(np.pad(X, ((0, 0), (0, dim_p - d)))),
        jnp.asarray(offset),
        jnp.asarray(weight),
    )
    val_ref, grad_ref = obj.value_and_grad(jnp.asarray(w), dense_batch)

    np.testing.assert_allclose(float(val), float(val_ref), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grad), np.asarray(grad_ref), rtol=1e-4, atol=1e-5)


def test_scale_normalization_folds(mesh24):
    n, d = 32, 14
    indices, values, X, y, weight, offset = _sparse_problem(n=n, d=d, seed=3)
    dim_p = padded_dim(d, 4)  # 16
    factors = np.ones(dim_p, np.float32)
    factors[:d] = np.linspace(0.5, 2.0, d)
    norm = NormalizationContext(factors=jnp.asarray(factors))

    obj = GLMObjective(loss=LogisticLoss, l2_weight=0.1, normalization=norm)
    vg = sparse_value_and_grad_feature_sharded(obj, mesh24, dim_p)

    w = np.linspace(-0.3, 0.3, dim_p).astype(np.float32)
    batch = LabeledBatch(
        jnp.asarray(y), _pad_sparse(indices, values, dim_p),
        jnp.asarray(offset), jnp.asarray(weight),
    )
    w_sh, batch_sh = place_feature_sharded(mesh24, jnp.asarray(w), batch)
    val, grad = jax.jit(vg)(w_sh, batch_sh)

    dense_batch = LabeledBatch(
        jnp.asarray(y),
        jnp.asarray(np.pad(X, ((0, 0), (0, dim_p - d)))),
        jnp.asarray(offset),
        jnp.asarray(weight),
    )
    val_ref, grad_ref = obj.value_and_grad(jnp.asarray(w), dense_batch)
    np.testing.assert_allclose(float(val), float(val_ref), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grad), np.asarray(grad_ref), rtol=1e-4, atol=1e-5)


def test_shift_normalization_rejected(mesh24):
    norm = NormalizationContext(
        factors=jnp.ones(8), shifts=jnp.ones(8), intercept_index=0
    )
    obj = GLMObjective(loss=LogisticLoss, normalization=norm)
    with pytest.raises(ValueError, match="scale normalization only"):
        sparse_value_and_grad_feature_sharded(obj, mesh24, 8)


@pytest.mark.parametrize("loss,binary", [(LogisticLoss, True), (PoissonLoss, False)])
def test_train_matches_replicated_solve(mesh24, loss, binary):
    n, d = 64, 30
    indices, values, X, y, weight, offset = _sparse_problem(
        n=n, d=d, seed=7, binary=binary
    )
    dim_p = padded_dim(d, 4)
    obj = GLMObjective(loss=loss, l2_weight=1.0, intercept_index=0)
    cfg = OptimizerConfig(max_iter=50, tol=1e-8, track_history=False)

    fit = train_fixed_effect_feature_sharded(mesh24, obj, cfg, dim_p)
    batch = LabeledBatch(
        jnp.asarray(y), _pad_sparse(indices, values, dim_p),
        jnp.asarray(offset), jnp.asarray(weight),
    )
    w0_sh, batch_sh = place_feature_sharded(
        mesh24, jnp.zeros(dim_p, jnp.float32), batch
    )
    res = fit(w0_sh, batch_sh)
    w_sharded = np.asarray(res.w)

    # Replicated dense reference solve.
    dense_batch = LabeledBatch(
        jnp.asarray(y),
        jnp.asarray(np.pad(X, ((0, 0), (0, dim_p - d)))),
        jnp.asarray(offset),
        jnp.asarray(weight),
    )
    ref = minimize_lbfgs(
        lambda w: obj.value_and_grad(w, dense_batch),
        jnp.zeros(dim_p, jnp.float32),
        cfg,
    )
    w_ref = np.asarray(ref.w)

    # Both should be at the same (strongly convex, L2'd) optimum.
    np.testing.assert_allclose(w_sharded, w_ref, rtol=2e-3, atol=2e-4)
    # Padded coefficients must stay exactly zero.
    np.testing.assert_array_equal(w_sharded[d:], 0.0)
    assert float(res.grad_norm) < 1e-2


def test_sharded_w_layout(mesh24):
    """result.w really is sharded over the feature axis (not gathered)."""
    n, d = 32, 16
    indices, values, X, y, weight, offset = _sparse_problem(n=n, d=d, seed=1)
    obj = GLMObjective(loss=LogisticLoss, l2_weight=1.0)
    cfg = OptimizerConfig(max_iter=5, track_history=False)
    fit = train_fixed_effect_feature_sharded(mesh24, obj, cfg, d)
    batch = LabeledBatch(
        jnp.asarray(y), _pad_sparse(indices, values, d),
        jnp.asarray(offset), jnp.asarray(weight),
    )
    w0_sh, batch_sh = place_feature_sharded(mesh24, jnp.zeros(d, jnp.float32), batch)
    res = fit(w0_sh, batch_sh)
    sharding = res.w.sharding
    spec = sharding.spec
    assert spec[0] == "feature", spec


def test_sharded_hvp_matches_dense(mesh24):
    """sparse_linearized_hvp_feature_sharded == dense Hessian product,
    with L2 + intercept exemption + scale normalization folded."""
    from photon_tpu.parallel.feature_sharded import (
        sparse_linearized_hvp_feature_sharded,
    )

    n, d = 64, 30
    indices, values, X, y, weight, offset = _sparse_problem(n=n, d=d, seed=11)
    dim_p = padded_dim(d, 4)
    factors = np.linspace(0.5, 1.5, dim_p).astype(np.float32)
    norm = NormalizationContext(factors=jnp.asarray(factors), intercept_index=0)
    for obj in [
        GLMObjective(loss=LogisticLoss, l2_weight=0.7, intercept_index=0),
        GLMObjective(loss=LogisticLoss, l2_weight=0.3, intercept_index=0,
                     normalization=norm),
    ]:
        make_hvp = sparse_linearized_hvp_feature_sharded(obj, mesh24, dim_p)
        batch = LabeledBatch(
            jnp.asarray(y), _pad_sparse(indices, values, dim_p),
            jnp.asarray(offset), jnp.asarray(weight),
        )
        rng = np.random.default_rng(3)
        w = (rng.normal(size=dim_p) * 0.3).astype(np.float32)
        v = rng.normal(size=dim_p).astype(np.float32)
        w_sh, batch_sh = place_feature_sharded(mesh24, jnp.asarray(w), batch)

        got = np.asarray(jax.jit(
            lambda ww, vv: make_hvp(ww, batch_sh)(vv)
        )(w_sh, jnp.asarray(v)))

        # Dense reference via the single-device linearized operator.
        dense_batch = LabeledBatch(
            jnp.asarray(y),
            jnp.asarray(np.pad(X, ((0, 0), (0, dim_p - d)))),
            jnp.asarray(offset), jnp.asarray(weight),
        )
        ref = np.asarray(
            obj.linearized_hvp(jnp.asarray(w), dense_batch)(jnp.asarray(v))
        )
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_train_tron_matches_replicated_solve(mesh24):
    """solver='tron' feature-sharded fit reaches the replicated TRON
    optimum (the reference's distributed TRON via hessianVector rounds)."""
    from photon_tpu.optim.tron import minimize_tron

    n, d = 64, 30
    indices, values, X, y, weight, offset = _sparse_problem(n=n, d=d, seed=13)
    dim_p = padded_dim(d, 4)
    obj = GLMObjective(loss=LogisticLoss, l2_weight=1.0, intercept_index=0)
    cfg = OptimizerConfig(max_iter=30, tol=1e-8, track_history=False)

    fit = train_fixed_effect_feature_sharded(mesh24, obj, cfg, dim_p, solver="tron")
    batch = LabeledBatch(
        jnp.asarray(y), _pad_sparse(indices, values, dim_p),
        jnp.asarray(offset), jnp.asarray(weight),
    )
    w0_sh, batch_sh = place_feature_sharded(
        mesh24, jnp.zeros(dim_p, jnp.float32), batch
    )
    res = fit(w0_sh, batch_sh)
    w_sharded = np.asarray(res.w)

    dense_batch = LabeledBatch(
        jnp.asarray(y),
        jnp.asarray(np.pad(X, ((0, 0), (0, dim_p - d)))),
        jnp.asarray(offset), jnp.asarray(weight),
    )
    ref = minimize_tron(
        lambda w: obj.value_and_grad(w, dense_batch), None,
        jnp.zeros(dim_p, jnp.float32), cfg,
        hvp_factory=lambda w: obj.linearized_hvp(w, dense_batch),
    )
    np.testing.assert_allclose(w_sharded, np.asarray(ref.w), rtol=2e-3, atol=2e-4)
    np.testing.assert_array_equal(w_sharded[d:], 0.0)
    assert float(res.grad_norm) < 1e-2
