"""Checkpoint/resume tests: pytree round-trip + mid-descent recovery."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from photon_tpu.algorithm.coordinate_descent import CoordinateDescent
from photon_tpu.algorithm.fixed_effect import FixedEffectCoordinate
from photon_tpu.algorithm.random_effect import RandomEffectCoordinate
from photon_tpu.data.game_data import GameBatch
from photon_tpu.data.random_effect import (
    RandomEffectDataConfig,
    build_random_effect_dataset,
)
from photon_tpu.models.coefficients import Coefficients
from photon_tpu.models.game import FixedEffectModel, GameModel, RandomEffectModel
from photon_tpu.models.glm import GeneralizedLinearModel
from photon_tpu.ops.losses import LogisticLoss
from photon_tpu.ops.objective import GLMObjective
from photon_tpu.optim.factory import OptimizerSpec
from photon_tpu.types import TaskType
from photon_tpu.utils.checkpoint import (
    latest_step,
    load_checkpoint,
    save_checkpoint,
)


def test_pytree_roundtrip(tmp_path):
    glm = GeneralizedLinearModel(
        task=TaskType.LOGISTIC_REGRESSION,
        coefficients=Coefficients(means=jnp.arange(4.0)),
    )
    state = dict(
        model=GameModel(
            {
                "fixed": FixedEffectModel(model=glm, feature_shard="g"),
                "re": RandomEffectModel(
                    coefficients=jnp.ones((3, 2)),
                    re_type="u",
                    feature_shard="r",
                    task=TaskType.LOGISTIC_REGRESSION,
                ),
            }
        ),
        scores={"fixed": jnp.arange(5.0)},
        history=[{"AUC": 0.9}, {"AUC": 0.95}],
        none_field=None,
        bf=jnp.arange(6, dtype=jnp.bfloat16),
    )
    save_checkpoint(str(tmp_path), state, 3)
    assert latest_step(str(tmp_path)) == 3
    restored, step = load_checkpoint(str(tmp_path))
    assert step == 3
    assert isinstance(restored["model"].models["fixed"], FixedEffectModel)
    assert restored["model"].models["re"].re_type == "u"
    np.testing.assert_array_equal(
        np.asarray(restored["model"].models["fixed"].model.coefficients.means),
        np.arange(4.0),
    )
    assert restored["none_field"] is None
    assert [float(h["AUC"]) for h in restored["history"]] == [0.9, 0.95]
    assert restored["bf"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(restored["bf"], np.float32), np.arange(6.0))


def test_latest_step_empty(tmp_path):
    assert latest_step(str(tmp_path)) is None
    with pytest.raises(FileNotFoundError):
        load_checkpoint(str(tmp_path))


def test_latest_step_recovers_from_torn_pointer(tmp_path):
    """The LATEST pointer is an optimization, not the source of truth: a
    torn/garbage/stale pointer must never strand the self-contained
    step files — recovery falls back to scanning step_<N>.npz."""
    d = str(tmp_path)
    save_checkpoint(d, {"x": jnp.arange(3.0)}, 2)
    save_checkpoint(d, {"x": jnp.arange(4.0)}, 7)

    # Torn write: partial/garbage content in LATEST.
    (tmp_path / "LATEST").write_text("7\x00\xf3garbage")
    assert latest_step(d) == 7

    # Stale pointer at a step whose file was pruned.
    (tmp_path / "LATEST").write_text("99")
    assert latest_step(d) == 7
    restored, step = load_checkpoint(d)
    assert step == 7
    np.testing.assert_array_equal(np.asarray(restored["x"]), np.arange(4.0))

    # Pointer missing entirely.
    (tmp_path / "LATEST").unlink()
    assert latest_step(d) == 7

    # A valid pointer still wins over the scan (points at 2, not max 7).
    (tmp_path / "LATEST").write_text("2")
    assert latest_step(d) == 2


def _glmix_setup(seed=0):
    rng = np.random.default_rng(seed)
    n, d_fix, d_re, E = 512, 8, 4, 16
    Xf = rng.normal(size=(n, d_fix)).astype(np.float32)
    Xf[:, 0] = 1.0
    Xr = rng.normal(size=(n, d_re)).astype(np.float32)
    Xr[:, 0] = 1.0
    users = (np.arange(n) % E).astype(np.int32)
    logits = Xf @ (rng.normal(size=d_fix).astype(np.float32) / np.sqrt(d_fix))
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logits))).astype(np.float32)
    batch = GameBatch(
        label=jnp.asarray(y),
        offset=jnp.zeros(n, jnp.float32),
        weight=jnp.ones(n, jnp.float32),
        features={"global": jnp.asarray(Xf), "per_user": jnp.asarray(Xr)},
        entity_ids={"userId": jnp.asarray(users)},
    )
    fixed = FixedEffectCoordinate(
        "global", "global", TaskType.LOGISTIC_REGRESSION,
        GLMObjective(loss=LogisticLoss, l2_weight=1.0, intercept_index=0),
        OptimizerSpec(),
    )
    ds = build_random_effect_dataset(
        users, Xr, y, np.ones(n, np.float32), E,
        RandomEffectDataConfig(re_type="userId", feature_shard="per_user"),
    )
    rand = RandomEffectCoordinate(
        "per_user", ds, TaskType.LOGISTIC_REGRESSION,
        GLMObjective(loss=LogisticLoss, l2_weight=0.5, intercept_index=0),
    )
    return batch, {"global": fixed, "per_user": rand}


def test_cd_resume_matches_uninterrupted(tmp_path):
    """3-iteration descent == 2 iterations + crash + resume for the last."""
    batch, coords = _glmix_setup()
    seq = ["global", "per_user"]

    full = CoordinateDescent(dict(coords), seq, num_iterations=3).run(batch)

    ck = str(tmp_path / "ck")
    # "Crash" after 2 iterations (simulated by num_iterations=2).
    CoordinateDescent(dict(coords), seq, num_iterations=2).run(
        batch, checkpoint_dir=ck
    )
    assert latest_step(ck) == 1
    # Resume run asks for 3 total; should do only iteration 2.
    resumed = CoordinateDescent(dict(coords), seq, num_iterations=3).run(
        batch, checkpoint_dir=ck
    )
    w_full = np.asarray(full.model.models["global"].model.coefficients.means)
    w_res = np.asarray(resumed.model.models["global"].model.coefficients.means)
    np.testing.assert_allclose(w_res, w_full, rtol=1e-5, atol=1e-6)
    re_full = np.asarray(full.model.models["per_user"].coefficients)
    re_res = np.asarray(resumed.model.models["per_user"].coefficients)
    np.testing.assert_allclose(re_res, re_full, rtol=1e-5, atol=1e-6)


def test_cd_checkpoint_tag_mismatch_raises(tmp_path):
    batch, coords = _glmix_setup()
    seq = ["global", "per_user"]
    ck = str(tmp_path / "ck")
    CoordinateDescent(dict(coords), seq, num_iterations=1).run(
        batch, checkpoint_dir=ck, checkpoint_tag="lambda=1.0"
    )
    with pytest.raises(ValueError, match="different configuration"):
        CoordinateDescent(dict(coords), seq, num_iterations=1).run(
            batch, checkpoint_dir=ck, checkpoint_tag="lambda=2.0"
        )


def test_cd_checkpoint_every_validated(tmp_path):
    batch, coords = _glmix_setup()
    with pytest.raises(ValueError, match="checkpoint_every"):
        CoordinateDescent(dict(coords), ["global", "per_user"], num_iterations=1).run(
            batch, checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=0
        )


def test_cd_resume_preserves_tracker(tmp_path):
    batch, coords = _glmix_setup()
    seq = ["global", "per_user"]
    ck = str(tmp_path / "ck")
    CoordinateDescent(dict(coords), seq, num_iterations=2).run(batch, checkpoint_dir=ck)
    resumed = CoordinateDescent(dict(coords), seq, num_iterations=3).run(
        batch, checkpoint_dir=ck
    )
    # Tracker covers ALL iterations including the pre-resume ones.
    assert len(resumed.tracker["global"]) == 3
    assert len(resumed.tracker["per_user"]) == 3
    stats = resumed.tracker["per_user"][0]
    assert int(stats.num_entities) == 16


def test_cd_completed_run_replays_from_checkpoint(tmp_path):
    batch, coords = _glmix_setup()
    seq = ["global", "per_user"]
    ck = str(tmp_path / "ck")
    first = CoordinateDescent(dict(coords), seq, num_iterations=2).run(
        batch, checkpoint_dir=ck
    )
    again = CoordinateDescent(dict(coords), seq, num_iterations=2).run(
        batch, checkpoint_dir=ck
    )
    np.testing.assert_array_equal(
        np.asarray(first.model.models["global"].model.coefficients.means),
        np.asarray(again.model.models["global"].model.coefficients.means),
    )


def test_checkpoint_survives_class_rename(tmp_path):
    """The registry key, not the class name, is the durable identity: a
    renamed class re-registered under the same key loads old checkpoints
    (VERDICT r2 #10 done-criterion)."""
    import dataclasses

    import jax.numpy as jnp

    from photon_tpu.models.coefficients import Coefficients
    from photon_tpu.utils import checkpoint as ckpt

    state = {"c": Coefficients(jnp.arange(4, dtype=jnp.float32))}
    ckpt.save_checkpoint(str(tmp_path), state, 0)

    # Simulate a refactor: the class was renamed/moved; key stays stable.
    @jax.tree_util.register_dataclass
    @dataclasses.dataclass(frozen=True)
    class RenamedCoefficients:
        means: object
        variances: object = None

    old = ckpt._REGISTRY["coefficients"]
    try:
        ckpt.register_checkpoint_node("coefficients", RenamedCoefficients)
        loaded, step = ckpt.load_checkpoint(str(tmp_path))
        assert isinstance(loaded["c"], RenamedCoefficients)
        np.testing.assert_array_equal(np.asarray(loaded["c"].means), np.arange(4))
    finally:
        ckpt.register_checkpoint_node("coefficients", old)


def test_checkpoint_rejects_unregistered_and_pickle(tmp_path):
    """No pickle on either path: unregistered classes fail at SAVE, and
    object-dtype arrays (the npz pickle vector) fail at LOAD."""
    from photon_tpu.utils import checkpoint as ckpt

    class Evil:
        pass

    with pytest.raises(TypeError, match="not registered"):
        ckpt.save_checkpoint(str(tmp_path), {"x": Evil()}, 0)

    # A hand-crafted npz smuggling a pickled object array must not execute:
    # numpy refuses object arrays without allow_pickle.
    import json as _json

    manifest = {"version": 2, "root": {"t": "array", "i": 0, "shape": [], "dtype": "object"}}
    evil_path = tmp_path / "step_7.npz"
    np.savez(
        evil_path,
        __manifest__=np.frombuffer(_json.dumps(manifest).encode(), np.uint8),
        leaf_0=np.array({"pwn": True}, dtype=object),
    )
    (tmp_path / "LATEST").write_text("7")
    with pytest.raises(ValueError):
        ckpt.load_checkpoint(str(tmp_path))


def test_checkpoint_shape_validation(tmp_path):
    """Manifest shape/dtype mismatches are detected, not silently loaded."""
    import jax.numpy as jnp

    from photon_tpu.utils import checkpoint as ckpt

    path = ckpt.save_checkpoint(str(tmp_path), {"a": jnp.ones((3,))}, 0)
    import zipfile

    # Corrupt: replace the leaf with a different-shaped array.
    data = dict(np.load(path))
    data["leaf_0"] = np.ones((5,), np.float32)
    np.savez(path, **data)
    with pytest.raises(ValueError, match="manifest"):
        ckpt.load_checkpoint(str(tmp_path), 0)


def test_cd_legacy_checkpoint_restarts_instead_of_crashing(tmp_path, caplog):
    """A v1 (pickle-era) checkpoint must not crash-loop a resumed job: the
    descent logs a warning and restarts from step 0 (ADVICE r3)."""
    import logging

    batch, coords = _glmix_setup()
    seq = ["global", "per_user"]
    ck = tmp_path / "ck"
    ck.mkdir()
    # Fake a legacy checkpoint: an npz without the v2 __manifest__ entry.
    np.savez(ck / "step_0.npz", models=np.zeros(3))
    (ck / "LATEST").write_text("0")
    with caplog.at_level(logging.WARNING):
        result = CoordinateDescent(dict(coords), seq, num_iterations=1).run(
            batch, checkpoint_dir=str(ck)
        )
    assert result.model is not None
    assert any("legacy" in r.message for r in caplog.records)
    # The restart overwrote the legacy file with a loadable v2 checkpoint.
    from photon_tpu.utils.checkpoint import load_checkpoint

    state, step = load_checkpoint(str(ck))
    assert step == 0 and state["tag"] == "global,per_user"


def test_load_skips_torn_newest_step(tmp_path, caplog):
    """A machine crash can publish a step file whose data blocks never hit
    disk. step=None loads walk newest→oldest, skip the torn file with a
    warning, and resume one step earlier instead of stranding the run."""
    import logging

    d = str(tmp_path)
    save_checkpoint(d, {"x": jnp.arange(3.0)}, 0)
    save_checkpoint(d, {"x": jnp.arange(5.0)}, 1)
    (tmp_path / "step_1.npz").write_bytes(b"PK\x03\x04torn-checkpoint")
    with caplog.at_level(logging.WARNING):
        state, step = load_checkpoint(d)
    assert step == 0
    np.testing.assert_array_equal(np.asarray(state["x"]), np.arange(3.0))
    assert any("unreadable" in r.message for r in caplog.records)
    # An explicit step request for the torn file still raises: the caller
    # asked for exactly that step, silently substituting would be wrong.
    import zipfile

    with pytest.raises((ValueError, OSError, zipfile.BadZipFile)):
        load_checkpoint(d, step=1)


def test_load_all_steps_corrupt_raises(tmp_path):
    import zipfile

    d = str(tmp_path)
    save_checkpoint(d, {"x": jnp.arange(3.0)}, 0)
    (tmp_path / "step_0.npz").write_bytes(b"\x00garbage")
    with pytest.raises((ValueError, OSError, zipfile.BadZipFile)):
        load_checkpoint(d)


def test_torn_fault_injected_save_recovers(tmp_path):
    """The faults harness drives the torn-write path end to end: an injected
    torn save leaves garbage at the FINAL step path, and the next good save
    makes the directory loadable again (robust load skips the torn file)."""
    from photon_tpu.utils import faults
    from photon_tpu.utils.faults import (
        FaultPlan,
        FaultRule,
        PermanentInjectedFault,
    )

    d = str(tmp_path)
    try:
        faults.configure(FaultPlan(rules=(
            FaultRule("checkpoint.save", kind="torn", at=(0,)),
        )))
        with pytest.raises(PermanentInjectedFault):
            save_checkpoint(d, {"x": jnp.arange(3.0)}, 0)
        assert (tmp_path / "step_0.npz").exists()  # garbage at the final name
        assert not (tmp_path / "LATEST").exists()  # crash before publish
        assert latest_step(d) == 0  # the scan still sees the (torn) file

        # Fault exhausted: the next step saves cleanly and robust load
        # recovers from it, skipping the torn step 0.
        save_checkpoint(d, {"x": jnp.arange(4.0)}, 1)
        state, step = load_checkpoint(d)
        assert step == 1
        np.testing.assert_array_equal(np.asarray(state["x"]), np.arange(4.0))
    finally:
        faults.reset()
