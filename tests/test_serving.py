"""Online serving tests: micro-batcher semantics, hot/cold store residency,
engine parity + the zero-retrace contract, zero-downtime reload, and the
stdlib HTTP front end.

The parity assertions are atol=0 by design: the serving engine runs the SAME
jitted GameTransformer program as the batch driver, and the dense scorer's
per-row reduction is bit-stable across row counts (models/coefficients.py) —
so a micro-batched score must EQUAL the full-batch score, and any drift is a
real bug, not float noise. The reference here is therefore the batch path
itself (full (E, d) tables, true entity indices, one big batch), never
re-derived host math.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.data.game_data import GameBatch
from photon_tpu.data.index_map import EntityIndex, IndexMap
from photon_tpu.data.padding import bucket_grid, bucket_pow2, pad_game_batch
from photon_tpu.estimators.game_transformer import GameTransformer
from photon_tpu.models.coefficients import Coefficients
from photon_tpu.models.game import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_tpu.models.glm import GeneralizedLinearModel
from photon_tpu.serve import (
    BackpressureError,
    DeadlineExceededError,
    HotColdEntityStore,
    MicroBatcher,
    ScoreRequest,
    ServeConfig,
    ServingEngine,
)
from photon_tpu.types import TaskType

rng = np.random.default_rng(41)

D_FIX, D_RE, N_ENTITIES = 6, 4, 64


def make_model(scale=1.0, n_entities=N_ENTITIES):
    w_fix = (scale * np.linspace(-1, 1, D_FIX)).astype(np.float32)
    w_re = (scale * rng.normal(size=(n_entities, D_RE))).astype(np.float32)
    return GameModel({
        "global": FixedEffectModel(
            GeneralizedLinearModel(
                Coefficients(np.asarray(w_fix)), TaskType.LOGISTIC_REGRESSION
            ),
            "shardA",
        ),
        "per_user": RandomEffectModel(
            np.asarray(w_re), "userId", "shardB", TaskType.LOGISTIC_REGRESSION
        ),
    })


def make_entity_index(n=N_ENTITIES):
    eidx = EntityIndex()
    for e in range(n):
        eidx.intern(f"user{e}")
    return eidx


def batch_scores(model, xa, xb, users, offset=0.0):
    """Reference scores via the BATCH path: the full-table model scored as
    one n-row batch through the same jitted transformer program serving
    uses. Row-count invariance of the dense reduction makes this directly
    comparable (atol=0) to per-request micro-batched scores."""
    import jax

    n = len(users)
    b = GameBatch(
        label=jnp.zeros(n, jnp.float32),
        offset=jnp.full(n, offset, jnp.float32),
        weight=jnp.ones(n, jnp.float32),
        features={"shardA": jnp.asarray(xa), "shardB": jnp.asarray(xb)},
        entity_ids={"userId": jnp.asarray(np.asarray(users), jnp.int32)},
    )
    return np.asarray(GameTransformer(jax.device_put(model)).transform(b),
                      np.float32)


# ---------------------------------------------------------------------------
# MicroBatcher (stub score_fn — no jax, pure threading semantics)
# ---------------------------------------------------------------------------


def test_batcher_flushes_on_size():
    batches = []

    def score(reqs):
        batches.append(len(reqs))
        return [r.offset for r in reqs]

    mb = MicroBatcher(score, max_batch_size=4, max_delay_s=10.0, queue_cap=64)
    futs = [mb.submit(ScoreRequest({}, offset=float(i))) for i in range(8)]
    assert [f.result(timeout=5) for f in futs] == [float(i) for i in range(8)]
    mb.close()
    # Size-triggered flushing: no batch above the cap, and the 10s deadline
    # never fired (the test finishes in milliseconds).
    assert sum(batches) == 8 and max(batches) <= 4


def test_batcher_flushes_on_deadline():
    mb = MicroBatcher(
        lambda reqs: [1.0] * len(reqs),
        max_batch_size=1000, max_delay_s=0.02, queue_cap=64,
    )
    t0 = time.monotonic()
    assert mb.submit(ScoreRequest({})).result(timeout=5) == 1.0
    # One request can never fill max_batch_size: the deadline flushed it.
    assert time.monotonic() - t0 < 2.0
    mb.close()


def test_batcher_sheds_on_backpressure():
    release = threading.Event()

    def slow(reqs):
        release.wait(5)
        return [0.0] * len(reqs)

    mb = MicroBatcher(slow, max_batch_size=1, max_delay_s=0.0, queue_cap=2)
    futs = [mb.submit(ScoreRequest({})) for _ in range(2)]
    shed = 0
    for _ in range(20):
        try:
            futs.append(mb.submit(ScoreRequest({})))
        except BackpressureError:
            shed += 1
    assert shed > 0  # depth was at cap while the flusher sat blocked
    release.set()
    for f in futs:
        assert f.result(timeout=10) == 0.0
    mb.close()


def test_batcher_expires_deadline_in_queue():
    release = threading.Event()

    def slow(reqs):
        release.wait(5)
        return [0.0] * len(reqs)

    mb = MicroBatcher(slow, max_batch_size=1, max_delay_s=0.0, queue_cap=64)
    blocker = mb.submit(ScoreRequest({}))  # occupies the flusher
    doomed = mb.submit(ScoreRequest({}), deadline_s=0.01)
    time.sleep(0.05)
    release.set()
    assert blocker.result(timeout=10) == 0.0
    # The doomed request expired while queued: it fails WITHOUT scorer time.
    with pytest.raises(DeadlineExceededError):
        doomed.result(timeout=10)
    mb.close()


def test_batcher_score_error_fails_batch_not_batcher():
    calls = []

    def flaky(reqs):
        calls.append(len(reqs))
        if len(calls) == 1:
            raise RuntimeError("boom")
        return [2.0] * len(reqs)

    mb = MicroBatcher(flaky, max_batch_size=8, max_delay_s=0.005, queue_cap=8)
    bad = mb.submit(ScoreRequest({}))
    with pytest.raises(RuntimeError, match="boom"):
        bad.result(timeout=5)
    good = mb.submit(ScoreRequest({}))  # the batcher itself kept serving
    assert good.result(timeout=5) == 2.0
    mb.close()


# ---------------------------------------------------------------------------
# Hot/cold entity store
# ---------------------------------------------------------------------------


def test_store_pins_when_budget_covers_table():
    model = make_model()
    w_re = np.asarray(model.models["per_user"].coefficients)
    store = HotColdEntityStore(
        model, {"userId": make_entity_index()}, hot_bytes=1 << 30
    )
    assert store.group("userId").pinned
    # Pinned: entity ids pass through as slots; unknown ids resolve -1.
    slots = store.resolve("userId", ["user3", "user0", "nope", 5])
    np.testing.assert_array_equal(slots, [3, 0, -1, 5])
    table = np.asarray(store.scoring_model().models["per_user"].coefficients)
    np.testing.assert_array_equal(table, w_re)


def test_store_lru_promotes_and_demotes():
    model = make_model()
    w_re = np.asarray(model.models["per_user"].coefficients)
    # ~0-byte budget: capacity floors at min_hot_rows=8 < 64 entities.
    store = HotColdEntityStore(
        model, {"userId": make_entity_index()}, hot_bytes=1, min_hot_rows=8
    )
    group = store.group("userId")
    assert not group.pinned and group.capacity == 8

    slots = store.resolve("userId", [f"user{e}" for e in range(8)])
    assert sorted(slots) == list(range(8))
    table = np.asarray(store.scoring_model().models["per_user"].coefficients)
    for e in range(8):  # promoted rows hold the exact host coefficients
        np.testing.assert_array_equal(table[slots[e]], w_re[e])

    # Touch user0 (now MRU), then promote 7 fresh entities: the LRU victims
    # are users 1..7; user0 must survive in its slot, untouched.
    keep = store.resolve("userId", ["user0"])[0]
    slots2 = store.resolve("userId", [f"user{e}" for e in range(8, 15)])
    assert store.resolve("userId", ["user0"])[0] == keep
    table2 = np.asarray(store.scoring_model().models["per_user"].coefficients)
    np.testing.assert_array_equal(table2[keep], w_re[0])
    for j, e in enumerate(range(8, 15)):
        np.testing.assert_array_equal(table2[slots2[j]], w_re[e])


def test_store_overflow_batch_raises():
    store = HotColdEntityStore(
        make_model(), {"userId": make_entity_index()},
        hot_bytes=1, min_hot_rows=4,
    )
    # 5 unique entities in one batch > capacity 4: every resident slot is
    # in use by THIS batch, so there is no LRU victim to demote.
    with pytest.raises(RuntimeError, match="exhausted"):
        store.resolve("userId", [f"user{e}" for e in range(5)])


def test_store_cold_and_unknown_entities_resolve_minus_one():
    store = HotColdEntityStore(
        make_model(), {"userId": make_entity_index()},
        hot_bytes=1, min_hot_rows=8,
    )
    slots = store.resolve("userId", ["never-seen", -1, 10_000])
    np.testing.assert_array_equal(slots, [-1, -1, -1])
    assert store.resolve("noSuchType", ["x"]).tolist() == [-1]


# ---------------------------------------------------------------------------
# Hot/cold for PROJECTED (subspace) random-effect tables (satellite)
# ---------------------------------------------------------------------------

D_PROJ = 6
PROJ_ENTITIES = 24  # entity 23 is block -1 (cold: no model, scores 0)


def make_proj_model(n_entities=PROJ_ENTITIES, d_full=D_PROJ):
    """Fixed effect + one projected RE coordinate: 2 blocks with distinct
    column subspaces, entities alternating blocks, last entity modeless."""
    prng = np.random.default_rng(7)
    col_maps = [np.array([0, 1, 2], np.int32), np.array([2, 3, 4, 5], np.int32)]
    inv_maps = []
    for cmap in col_maps:
        inv = np.full(d_full, -1, np.int32)
        inv[cmap] = np.arange(len(cmap), dtype=np.int32)
        inv_maps.append(inv)
    entity_block = np.array(
        [e % 2 for e in range(n_entities)], np.int32
    )
    entity_block[-1] = -1
    entity_row = np.zeros(n_entities, np.int32)
    counts = [0, 0]
    for e in range(n_entities):
        b = int(entity_block[e])
        if b >= 0:
            entity_row[e] = counts[b]
            counts[b] += 1
    block_coefs = [
        prng.normal(size=(counts[b], len(col_maps[b]))).astype(np.float32)
        for b in range(2)
    ]
    from photon_tpu.models.game import ProjectedRandomEffectModel

    proj = ProjectedRandomEffectModel(
        block_coefs=[jnp.asarray(b) for b in block_coefs],
        col_maps=[jnp.asarray(c) for c in col_maps],
        inv_maps=[jnp.asarray(i) for i in inv_maps],
        entity_block=jnp.asarray(entity_block),
        entity_row=jnp.asarray(entity_row),
        d_full=d_full, re_type="userId", feature_shard="shardB",
        task=TaskType.LOGISTIC_REGRESSION,
    )
    w_fix = np.linspace(-1, 1, D_FIX).astype(np.float32)
    return GameModel({
        "global": FixedEffectModel(
            GeneralizedLinearModel(
                Coefficients(np.asarray(w_fix)), TaskType.LOGISTIC_REGRESSION
            ),
            "shardA",
        ),
        "per_user_proj": proj,
    })


def _proj_batch(ids, xa, xb):
    n = len(ids)
    return GameBatch(
        label=jnp.zeros(n, jnp.float32),
        offset=jnp.zeros(n, jnp.float32),
        weight=jnp.ones(n, jnp.float32),
        features={"shardA": jnp.asarray(xa), "shardB": jnp.asarray(xb)},
        entity_ids={"userId": jnp.asarray(np.asarray(ids), jnp.int32)},
    )


def test_store_projected_pins_when_budget_covers_blocks():
    import jax

    model = make_proj_model()
    store = HotColdEntityStore(
        model, {"userId": make_entity_index(PROJ_ENTITIES)}, hot_bytes=1 << 30
    )
    proj = store.proj_group("userId")
    assert proj is not None and proj.pinned
    assert "userId" in store.entity_re_types
    # Pinned: entity ids pass through as indices; the scoring model carries
    # the exact master tables and maps.
    ids = store.resolve("userId", ["user3", "nope", "user23"])
    np.testing.assert_array_equal(ids, [3, -1, 23])
    served = store.scoring_model().models["per_user_proj"]
    src = model.models["per_user_proj"]
    for b in range(2):
        np.testing.assert_array_equal(
            np.asarray(served.block_coefs[b]), np.asarray(src.block_coefs[b])
        )
    np.testing.assert_array_equal(
        np.asarray(served.entity_block), np.asarray(src.entity_block)
    )


def test_store_projected_hot_cold_parity_demotion_and_zero_retraces():
    """Satellite: projected tables under a byte budget. Every micro-batch
    promotes its entities into per-block hot pools, demoted entities' map
    entries go cold (-1), and the served scores stay BIT-equal to the
    full-table batch path — with zero scorer retraces across promotions,
    demotions, and scoring-model swaps."""
    import jax

    from photon_tpu.obs.metrics import registry

    model = make_proj_model()
    ref_tr = GameTransformer(jax.device_put(model))
    store = HotColdEntityStore(
        model, {"userId": make_entity_index(PROJ_ENTITIES)},
        hot_bytes=1, min_hot_rows=4,
    )
    proj = store.proj_group("userId")
    coord = proj.coords[0]
    assert not proj.pinned and coord.capacities == [4, 4]
    stats = store.stats()["userId"]
    assert stats["projected"] and not stats["pinned"]
    store.warm_uploads(4)

    demos0 = registry().counter(
        "serve_store_demotions_total", re_type="userId"
    ).value
    tr = GameTransformer(store.scoring_model())
    prng = np.random.default_rng(11)
    warm_traces = None
    # Cycle every entity (incl. the modeless one and an unknown key) in
    # batches of 4: 24 uniques through 4+4 hot rows forces demotion waves.
    keys = [f"user{e}" for e in range(PROJ_ENTITIES)] + ["nope"] * 4
    for start in range(0, len(keys), 4):
        group_keys = keys[start:start + 4]
        ids = store.resolve("userId", group_keys)
        true_ids = [
            int(k[4:]) if k.startswith("user") else -1 for k in group_keys
        ]
        np.testing.assert_array_equal(ids, true_ids)
        xa = prng.normal(size=(4, D_FIX)).astype(np.float32)
        xb = prng.normal(size=(4, D_PROJ)).astype(np.float32)
        batch = _proj_batch(ids, xa, xb)
        got = np.asarray(tr.transform(batch, model=store.scoring_model()))
        want = np.asarray(ref_tr.transform(_proj_batch(true_ids, xa, xb)))
        np.testing.assert_array_equal(got, want)  # atol=0: same program
        if warm_traces is None:
            warm_traces = tr.trace_count
    assert tr.trace_count == warm_traces  # swaps/promotions never retrace

    demos1 = registry().counter(
        "serve_store_demotions_total", re_type="userId"
    ).value
    assert demos1 - demos0 > 0
    # Hot pools hold at most capacity entities; every non-resident entity's
    # device map entry was scattered cold (-1) on demotion.
    dev_blk = np.asarray(coord.dev_entity_block)
    resident = set()
    for lru in coord.lrus:
        resident.update(lru.resident)
    for e in range(PROJ_ENTITIES):
        if int(coord.entity_block[e]) < 0 or e not in resident:
            assert dev_blk[e] == -1, e
        else:
            assert dev_blk[e] == int(coord.entity_block[e]), e

    # Re-promote long-demoted entities: parity still holds (round-trip
    # through demotion loses nothing; rows re-gather from the host master).
    ids = store.resolve("userId", ["user0", "user1", "user2", "user3"])
    xa = prng.normal(size=(4, D_FIX)).astype(np.float32)
    xb = prng.normal(size=(4, D_PROJ)).astype(np.float32)
    got = np.asarray(
        tr.transform(_proj_batch(ids, xa, xb), model=store.scoring_model())
    )
    want = np.asarray(ref_tr.transform(_proj_batch([0, 1, 2, 3], xa, xb)))
    np.testing.assert_array_equal(got, want)
    assert tr.trace_count == warm_traces


# ---------------------------------------------------------------------------
# Engine: parity, zero retraces, reload
# ---------------------------------------------------------------------------


def make_engine(scale=1.0, **cfg):
    model = make_model(scale)
    defaults = dict(max_batch_size=8, max_delay_ms=1.0, hot_bytes=1)
    defaults.update(cfg)
    eng = ServingEngine(
        model,
        entity_indexes={"userId": make_entity_index()},
        config=ServeConfig(**defaults),
    )
    return eng, model


def test_engine_concurrent_parity_and_zero_retraces():
    eng, model = make_engine()
    n = 200
    xa = rng.normal(size=(n, D_FIX)).astype(np.float32)
    xb = rng.normal(size=(n, D_RE)).astype(np.float32)
    users = rng.integers(-1, N_ENTITIES, size=n)
    expected = batch_scores(model, xa, xb, users, offset=0.25)

    results = [None] * n

    def worker(lo, hi):
        futs = [
            (i, eng.submit(ScoreRequest(
                {"shardA": xa[i], "shardB": xb[i]},
                {"userId": f"user{users[i]}" if users[i] >= 0 else "cold"},
                offset=0.25,
            )))
            for i in range(lo, hi)
        ]
        for i, f in futs:
            results[i] = np.float32(f.result(timeout=30))

    threads = [
        threading.Thread(target=worker, args=(lo, min(lo + 25, n)))
        for lo in range(0, n, 25)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    # Hot capacity is 8 of 64 entities (hot_bytes=1): these 200 requests
    # churned the LRU hard, and every score still equals the batch path's.
    np.testing.assert_array_equal(np.asarray(results, np.float32), expected)
    assert eng.retraces_since_warmup == 0, eng.stats()
    eng.close()


def test_engine_batch_size_invariance_bit_exact():
    """The same request must score bit-identically whether it rides a
    1-row, 3-row, or full batch — the property the batch-driver parity
    stage (ci.sh serve) builds on."""
    eng, _ = make_engine()
    xa = rng.normal(size=(8, D_FIX)).astype(np.float32)
    xb = rng.normal(size=(8, D_RE)).astype(np.float32)
    reqs = [
        ScoreRequest({"shardA": xa[i], "shardB": xb[i]}, {"userId": i})
        for i in range(8)
    ]
    solo = np.asarray([eng._score_batch([r])[0] for r in reqs], np.float32)
    grouped = np.asarray(eng._score_batch(reqs), np.float32)
    np.testing.assert_array_equal(solo, grouped)
    ragged = np.concatenate([
        np.asarray(eng._score_batch(reqs[:3]), np.float32),
        np.asarray(eng._score_batch(reqs[3:]), np.float32),
    ])
    np.testing.assert_array_equal(ragged, grouped)
    assert eng.retraces_since_warmup == 0
    eng.close()


def test_engine_dict_features_and_intercept():
    imap = IndexMap.build(
        [f"f{j}" for j in range(D_FIX - 1)], add_intercept=True
    )
    eng = ServingEngine(
        make_model(),
        entity_indexes={"userId": make_entity_index()},
        index_maps={"shardA": imap},
        config=ServeConfig(max_batch_size=4, max_delay_ms=1.0),
    )
    named = {f"f{j}": 0.5 * j for j in range(D_FIX - 1)}
    dense = np.zeros(D_FIX, np.float32)
    for k, v in named.items():
        dense[imap.get_index(k)] = v
    dense[imap.get_index(IndexMap.INTERCEPT)] = 1.0  # dict path auto-sets it
    s_named = eng.score({"shardA": named}, {"userId": "user1"})
    s_dense = eng.score({"shardA": dense}, {"userId": "user1"})
    assert np.float32(s_named) == np.float32(s_dense)
    # Unknown feature names drop silently (batch reader parity).
    s_extra = eng.score(
        {"shardA": {**named, "not-a-feature": 9.9}}, {"userId": "user1"}
    )
    assert np.float32(s_extra) == np.float32(s_named)
    eng.close()


def test_engine_reload_is_zero_downtime_and_retrace_free():
    eng, model = make_engine()
    xa = rng.normal(size=(1, D_FIX)).astype(np.float32)
    xb = rng.normal(size=(1, D_RE)).astype(np.float32)
    req = dict(features={"shardA": xa[0], "shardB": xb[0]},
               entity_ids={"userId": "user2"})
    s1 = np.float32(eng.score(**req))
    assert s1 == batch_scores(model, xa, xb, [2])[0]

    model2 = make_model(scale=-3.0)
    info = eng.reload(model2, "v2")
    assert info["model_version"] == "v2" and eng.model_version == "v2"
    s2 = np.float32(eng.score(**req))
    assert s2 == batch_scores(model2, xa, xb, [2])[0]
    assert s2 != s1
    # The new generation warmed its own transformer BEFORE the swap, so the
    # retrace contract holds across the reload too.
    assert eng.retraces_since_warmup == 0
    eng.close()


def test_engine_rejects_bad_feature_width():
    eng, _ = make_engine()
    with pytest.raises(ValueError, match="expects"):
        eng.score({"shardA": np.zeros(D_FIX + 1, np.float32),
                   "shardB": np.zeros(D_RE, np.float32)})
    eng.close()


# ---------------------------------------------------------------------------
# Transformer warm-up / trace_count across mixed bucket shapes (satellite)
# ---------------------------------------------------------------------------


def _bucket(n):
    from photon_tpu.data.random_effect import bucket_dim

    return bucket_dim(n)


def _batch_of(n):
    return GameBatch(
        label=jnp.zeros(n, jnp.float32),
        offset=jnp.zeros(n, jnp.float32),
        weight=jnp.ones(n, jnp.float32),
        features={
            "shardA": jnp.asarray(
                rng.normal(size=(n, D_FIX)).astype(np.float32)
            ),
            "shardB": jnp.asarray(
                rng.normal(size=(n, D_RE)).astype(np.float32)
            ),
        },
        entity_ids={
            "userId": jnp.asarray(
                rng.integers(0, N_ENTITIES, size=n).astype(np.int32)
            )
        },
    )


def test_transformer_trace_count_reused_across_mixed_buckets():
    import jax

    dev_model = jax.device_put(make_model())
    tr = GameTransformer(dev_model)
    # Mixed bucket shapes, repeated: one trace per DISTINCT shape, zero for
    # repeats — trace_count counts XLA traces, not Python calls.
    for n in (8, 16, 8, 16, 32, 8, 32, 16):
        tr.transform(_batch_of(n))
    assert tr.trace_count == 3

    # warm_up covers the whole grid up front; subsequent mixed-shape
    # traffic padded onto the grid then never traces (the serving
    # startup contract).
    tr2 = GameTransformer(dev_model)
    traced = tr2.warm_up(_batch_of(1), bucket_grid(32))
    assert traced == len(set(bucket_grid(32)))
    before = tr2.trace_count
    for n in (1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 5, 7, 17):
        tr2.transform(pad_game_batch(_batch_of(n), _bucket(n), xp=jnp))
    assert tr2.trace_count == before


def test_bucket_grid_covers_every_dispatch_size():
    for max_n in (1, 2, 7, 8, 33, 64):
        grid = bucket_grid(max_n)
        for n in range(1, max_n + 1):
            assert _bucket(n) in grid
        assert grid == sorted(set(grid))
    assert bucket_pow2(0) == 1 and bucket_pow2(5) == 8


# ---------------------------------------------------------------------------
# HTTP front end (handler-level: real sockets, ephemeral port)
# ---------------------------------------------------------------------------


@pytest.fixture()
def http_server():
    from http.server import ThreadingHTTPServer

    from photon_tpu.cli.game_serving import make_handler

    eng, model = make_engine(max_batch_size=4)
    server = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(eng, None))
    server.daemon_threads = True
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield server.server_address[1], model
    server.shutdown()
    server.server_close()
    eng.close()


def _post(port, path, payload: bytes):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=payload, method="POST"
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.read()


def test_http_score_and_health(http_server):
    port, model = http_server
    xa = rng.normal(size=D_FIX).astype(np.float32)
    xb = rng.normal(size=D_RE).astype(np.float32)
    body = json.dumps({
        "features": {"shardA": xa.tolist(), "shardB": xb.tolist()},
        "entityIds": {"userId": "user5"},
        "offset": 1.0,
    }).encode()
    out = json.loads(_post(port, "/v1/score", body))
    # float32 → python float → JSON → back is exact: parity survives HTTP.
    expected = batch_scores(model, xa[None], xb[None], [5], offset=1.0)[0]
    assert np.float32(out["score"]) == expected
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/healthz", timeout=10
    ) as resp:
        health = json.loads(resp.read())
    assert health["retraces_since_warmup"] == 0
    assert "userId" in health["store"]


def test_http_score_batch_jsonl_preserves_order(http_server):
    port, model = http_server
    n = 12
    xa = rng.normal(size=(n, D_FIX)).astype(np.float32)
    xb = rng.normal(size=(n, D_RE)).astype(np.float32)
    users = np.arange(n)
    lines = "".join(
        json.dumps({
            "features": {"shardA": xa[i].tolist(), "shardB": xb[i].tolist()},
            "entityIds": {"userId": int(users[i])},
        }) + "\n"
        for i in range(n)
    )
    raw = _post(port, "/v1/score-batch", lines.encode()).decode()
    got = np.asarray(
        [json.loads(line)["score"] for line in raw.splitlines()], np.float32
    )
    np.testing.assert_array_equal(got, batch_scores(model, xa, xb, users))


def test_http_bad_request_is_400_not_crash(http_server):
    port, _ = http_server
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/score", data=b"not json", method="POST"
    )
    with pytest.raises(urllib.error.HTTPError) as err:
        urllib.request.urlopen(req, timeout=10)
    assert err.value.code == 400


# ---------------------------------------------------------------------------
# Shared padding helper (dedupe satellite)
# ---------------------------------------------------------------------------


def test_pad_game_batch_identity_and_inertness():
    import jax

    model = make_model()
    n = 5
    b = _batch_of(n)
    assert pad_game_batch(b, n, xp=jnp) is b  # no-op → identity
    padded = pad_game_batch(b, 8, xp=jnp)
    assert padded.n == 8
    np.testing.assert_array_equal(np.asarray(padded.weight)[n:], 0.0)
    np.testing.assert_array_equal(
        np.asarray(padded.entity_ids["userId"])[n:], -1
    )
    # Inert padding: real-row scores are unchanged by the extra rows.
    tr = GameTransformer(jax.device_put(model))
    np.testing.assert_array_equal(
        np.asarray(tr.transform(padded))[:n], np.asarray(tr.transform(b))
    )


# ---------------------------------------------------------------------------
# Tenant admission: token buckets, priority classes, preemption (PR 7)
# ---------------------------------------------------------------------------


def test_token_bucket_exhaustion_and_recovery():
    from photon_tpu.serve import TokenBucket

    clk = [0.0]
    b = TokenBucket(rate=5.0, clock=lambda: clk[0])
    assert all(b.try_acquire() for _ in range(5))  # burst = max(rate, 1)
    assert not b.try_acquire()  # exhausted
    clk[0] += 0.5  # refill is continuous, not epoch-based
    assert b.try_acquire() and b.try_acquire()
    assert not b.try_acquire()
    clk[0] += 100.0  # refill saturates at burst, never beyond
    assert sum(b.try_acquire() for _ in range(10)) == 5


def test_admission_quota_shed_and_recovery():
    from photon_tpu.serve import AdmissionConfig, AdmissionController
    from photon_tpu.serve.admission import QuotaExceededError

    clk = [0.0]
    ctl = AdmissionController(
        AdmissionConfig(tenant_qps={"t": 2.0}), clock=lambda: clk[0]
    )
    ctl.admit("t", "interactive", 0, 100)
    ctl.admit("t", "interactive", 0, 100)
    with pytest.raises(QuotaExceededError) as err:
        ctl.admit("t", "interactive", 0, 100)
    assert err.value.tenant == "t" and err.value.reason == "quota"
    # Quota errors ARE backpressure (same 429 path), with a finer kind.
    assert isinstance(err.value, BackpressureError)
    clk[0] += 1.0  # bucket refills → tenant recovers without restart
    ctl.admit("t", "interactive", 0, 100)
    snap = ctl.snapshot()["t"]
    assert snap["admitted"] == 3 and snap["shed"] == 1
    # Unlisted tenants are quota-exempt (no default_qps configured).
    for _ in range(50):
        ctl.admit("other", "interactive", 0, 100)
    assert ctl.snapshot()["other"]["shed"] == 0


def test_admission_batch_class_shed_above_queue_fraction():
    from photon_tpu.serve import AdmissionConfig, AdmissionController
    from photon_tpu.serve.admission import QuotaExceededError

    ctl = AdmissionController(AdmissionConfig(batch_queue_fraction=0.5))
    ctl.admit("t", "batch", 49, 100)  # below the fraction: admitted
    with pytest.raises(QuotaExceededError) as err:
        ctl.admit("t", "batch", 50, 100)  # at/above: batch sheds first
    assert err.value.reason == "batch_capacity"
    ctl.admit("t", "interactive", 99, 100)  # interactive unaffected


def test_batcher_interactive_preempts_queued_batch_at_cap():
    release = threading.Event()

    def slow(reqs):
        release.wait(5)
        return [r.offset for r in reqs]

    mb = MicroBatcher(slow, max_batch_size=1, max_delay_s=0.0, queue_cap=2)
    blocker = mb.submit(ScoreRequest({}, offset=0.0))  # occupies the flusher
    time.sleep(0.05)
    victims = [
        mb.submit(ScoreRequest({}, offset=1.0), priority="batch"),
        mb.submit(ScoreRequest({}, offset=2.0), priority="batch"),
    ]
    # Queue is at cap with batch-class work: an interactive submit evicts
    # the NEWEST queued batch request instead of shedding itself.
    vip = mb.submit(ScoreRequest({}, offset=3.0))
    with pytest.raises(BackpressureError, match="preempted"):
        victims[1].result(timeout=5)
    # ...but a batch-class submit at cap still sheds itself.
    with pytest.raises(BackpressureError):
        mb.submit(ScoreRequest({}, offset=4.0), priority="batch")
    release.set()
    assert blocker.result(timeout=10) == 0.0
    assert victims[0].result(timeout=10) == 1.0
    assert vip.result(timeout=10) == 3.0
    mb.close()


def _admitted_engine(**quota):
    from photon_tpu.serve import AdmissionConfig

    model = make_model()
    eng = ServingEngine(
        model,
        entity_indexes={"userId": make_entity_index()},
        config=ServeConfig(
            max_batch_size=8, max_delay_ms=1.0, hot_bytes=1,
            admission=AdmissionConfig(**quota),
        ),
    )
    return eng, model


def test_engine_quota_429_recovery_and_tenant_stats():
    from photon_tpu.serve.admission import QuotaExceededError

    eng, model = _admitted_engine(tenant_qps={"t1": 2.0})
    xa = rng.normal(size=D_FIX).astype(np.float32)
    xb = rng.normal(size=D_RE).astype(np.float32)
    req = {"features": {"shardA": xa.tolist(), "shardB": xb.tolist()},
           "entityIds": {"userId": "user3"}}
    from photon_tpu.serve.frontend import request_from_json

    ok = [eng.submit(request_from_json(req), tenant="t1") for _ in range(2)]
    with pytest.raises(QuotaExceededError):
        eng.submit(request_from_json(req), tenant="t1")
    expected = batch_scores(model, xa[None], xb[None], [3])[0]
    for f in ok:
        assert np.float32(f.result(timeout=30)) == expected
    time.sleep(0.6)  # 2 qps → >1 token back: the tenant recovers
    assert np.float32(
        eng.submit(request_from_json(req), tenant="t1").result(timeout=30)
    ) == expected
    t = eng.stats()["tenants"]["t1"]
    assert t["admitted"] == 3 and t["shed"] == 1 and t["qps_limit"] == 2.0
    eng.close()


# ---------------------------------------------------------------------------
# Per-line error mapping in /v1/score-batch (PR 7 satellite)
# ---------------------------------------------------------------------------


def test_http_score_batch_maps_per_line_errors(http_server):
    port, model = http_server
    xa = rng.normal(size=(2, D_FIX)).astype(np.float32)
    xb = rng.normal(size=(2, D_RE)).astype(np.float32)
    good = [json.dumps({
        "features": {"shardA": xa[i].tolist(), "shardB": xb[i].tolist()},
        "entityIds": {"userId": i},
    }) for i in range(2)]
    body = "\n".join([good[0], "{not json", '{"no": "features"}', good[1]])
    raw = _post(port, "/v1/score-batch", body.encode()).decode()
    lines = [json.loads(s) for s in raw.splitlines()]
    assert len(lines) == 4  # one result per input line, in order
    expected = batch_scores(model, xa, xb, [0, 1])
    assert np.float32(lines[0]["score"]) == expected[0]
    assert np.float32(lines[3]["score"]) == expected[1]
    # Malformed lines are per-line 400s, NOT backpressure and NOT fatal.
    for bad in (lines[1], lines[2]):
        assert bad["code"] == 400 and bad["kind"] == "bad_request"


def test_http_tenant_quota_is_429_with_kind(http_server_quota):
    port, _ = http_server_quota
    body = json.dumps({
        "features": {
            "shardA": [0.0] * D_FIX, "shardB": [0.0] * D_RE
        },
        "entityIds": {"userId": "user1"},
    }).encode()

    def post(tenant):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/score", data=body, method="POST",
            headers={"X-Tenant": tenant},
        )
        return urllib.request.urlopen(req, timeout=10)

    post("t1").read()
    with pytest.raises(urllib.error.HTTPError) as err:
        post("t1")
    assert err.value.code == 429
    payload = json.loads(err.value.read())
    assert payload["kind"] == "quota" and payload["tenant"] == "t1"
    post("t2").read()  # other tenants unaffected by t1's quota


@pytest.fixture()
def http_server_quota():
    from http.server import ThreadingHTTPServer

    from photon_tpu.cli.game_serving import make_handler

    eng, model = _admitted_engine(tenant_qps={"t1": 1.0})
    server = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(eng, None))
    server.daemon_threads = True
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield server.server_address[1], model
    server.shutdown()
    server.server_close()
    eng.close()


# ---------------------------------------------------------------------------
# Multi-process front end: IPC channel, LATEST-pointer reload, e2e (PR 7)
# ---------------------------------------------------------------------------


def test_scorer_ipc_parity_stats_and_error_mapping(tmp_path):
    from photon_tpu.serve.frontend import (
        RemoteBackend,
        ScorerClient,
        ScorerServer,
        classify_exception,
        request_from_json,
    )

    eng, model = make_engine(max_batch_size=4)
    srv = ScorerServer(eng, str(tmp_path / "scorer.sock"))
    srv.start()
    cli = ScorerClient(str(tmp_path / "scorer.sock"))
    try:
        n = 6
        xa = rng.normal(size=(n, D_FIX)).astype(np.float32)
        xb = rng.normal(size=(n, D_RE)).astype(np.float32)
        futs = [cli.submit_score({
            "features": {"shardA": xa[i].tolist(), "shardB": xb[i].tolist()},
            "entityIds": {"userId": i},
        }, None, "interactive") for i in range(n)]
        got = np.asarray(
            [np.float32(f.result(timeout=30)["score"]) for f in futs]
        )
        # Same engine, same jitted program: the IPC hop changes nothing.
        np.testing.assert_array_equal(
            got, batch_scores(model, xa, xb, list(range(n)))
        )
        # Errors cross the socket as (code, kind) and rebuild client-side.
        with pytest.raises(ValueError):
            cli.submit_score({"no": "features"}, None, "interactive").result(
                timeout=30
            )
        try:
            cli.submit_score({"no": "features"}, None, "interactive").result(
                timeout=30
            )
        except ValueError as exc:
            assert classify_exception(exc) == (400, "bad_request")
        stats = RemoteBackend(cli, worker_index=3).stats()
        assert stats["worker"] == 3 and stats["retraces_since_warmup"] == 0
    finally:
        cli.close()
        srv.close()
        eng.close()


def _publish_generation(root, gen, scale):
    """Training-side publication: save a generation + flip the fsync'd
    LATEST pointer (what train_glm/game_training do on final checkpoint)."""
    import os

    from photon_tpu.io.model_io import publish_latest_pointer, save_game_model

    model = make_model(scale)
    imaps = {
        "shardA": IndexMap.build([f"a{j}" for j in range(D_FIX)]),
        "shardB": IndexMap.build([f"b{j}" for j in range(D_RE)]),
    }
    eidx = make_entity_index()
    for shard, imap in imaps.items():
        imap.save(os.path.join(root, f"index-map-{shard}.json"))
    eidx.save(os.path.join(root, "entity-index-userId.json"))
    # sparsity_threshold=0: keep all nonzero coefficients → exact round trip.
    save_game_model(model, os.path.join(root, gen), imaps, {"userId": eidx},
                    sparsity_threshold=0.0)
    publish_latest_pointer(root, gen)
    return model


def test_latest_pointer_resolution_and_reload_watcher(tmp_path):
    from photon_tpu.cli.game_serving import _reload_watcher, resolve_model_dir
    from photon_tpu.serve.engine import load_engine

    root = str(tmp_path)
    m1 = _publish_generation(root, "gen-1", 1.0)
    assert resolve_model_dir(root).endswith("gen-1")
    eng = load_engine(
        resolve_model_dir(root), artifacts_dir=root,
        config=ServeConfig(max_batch_size=4, hot_bytes=1),
    )
    stop = threading.Event()
    t = threading.Thread(
        target=_reload_watcher, args=(eng, root, 0.05, stop), daemon=True
    )
    t.start()
    try:
        xa = rng.normal(size=D_FIX).astype(np.float32)
        xb = rng.normal(size=D_RE).astype(np.float32)
        feats = {"shardA": xa, "shardB": xb}
        ids = {"userId": "user7"}
        assert np.float32(eng.score(feats, ids)) == batch_scores(
            m1, xa[None], xb[None], [7]
        )[0]
        m2 = _publish_generation(root, "gen-2", 3.0)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if eng.model_version.endswith("gen-2"):
                break
            time.sleep(0.05)
        else:
            raise AssertionError(f"watcher never swapped: {eng.model_version}")
        # The swapped-in generation scores EXACTLY like its source model:
        # publish → LATEST → watcher → reload is lossless end to end.
        assert np.float32(eng.score(feats, ids)) == batch_scores(
            m2, xa[None], xb[None], [7]
        )[0]
        assert eng.retraces_since_warmup == 0  # reload never retraces
    finally:
        stop.set()
        t.join(timeout=5)
        eng.close()


def test_multiprocess_front_end_end_to_end(tmp_path):
    """Forked-worker deployment shape, as a real subprocess (forking with
    jax initialized in THIS process is unsafe): banner → parity → healthz
    → SIGTERM drain exits 0."""
    import signal
    import subprocess
    import sys

    root = str(tmp_path)
    model = _publish_generation(root, "gen-1", 1.0)
    proc = subprocess.Popen(
        [sys.executable, "-m", "photon_tpu.cli.game_serving",
         "--model-input-dir", root, "--port", "0", "--workers", "1",
         "--max-batch-size", "4", "--queue-cap", "64"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
    )
    try:
        banner = {}

        def _read():
            banner["line"] = proc.stdout.readline()

        rt = threading.Thread(target=_read, daemon=True)
        rt.start()
        rt.join(timeout=300)
        assert banner.get("line"), "no startup banner within 300s"
        up = json.loads(banner["line"])
        assert up["workers"] == 1
        port = up["port"]
        n = 4
        xa = rng.normal(size=(n, D_FIX)).astype(np.float32)
        xb = rng.normal(size=(n, D_RE)).astype(np.float32)
        got = np.asarray([np.float32(json.loads(_post(port, "/v1/score", json.dumps({
            "features": {"shardA": xa[i].tolist(), "shardB": xb[i].tolist()},
            "entityIds": {"userId": i},
        }).encode()))["score"]) for i in range(n)])
        # Worker process → unix socket → scorer process scores EXACTLY what
        # the in-process batch path scores from the same published model.
        np.testing.assert_array_equal(
            got, batch_scores(model, xa, xb, list(range(n)))
        )
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10
        ) as resp:
            health = json.loads(resp.read())
        assert health["retraces_since_warmup"] == 0
        assert "worker" in health and health["model_version"].endswith("gen-1")
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=60) == 0  # graceful drain, clean exit
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
