#!/usr/bin/env bash
# CI entry point (role of the reference's Travis matrix, .travis.yml:30-34:
# rat | unit | integration). Everything runs on a virtual 8-device CPU mesh
# (tests/conftest.py forces it), so no accelerator is needed for correctness.
#
# Usage: ./ci.sh [static|unit|dryrun|telemetry|active-set|ooc|serve|faults|soak|fleet|rollout|streaming|exhaustion|obs|quality|experiments|install|kernels|all]   (default: all)
set -euo pipefail
cd "$(dirname "$0")"

stage="${1:-all}"

run_static() {
    # Fast fail-first pass: import-time breakage (syntax errors, bad
    # top-level references) surfaces in seconds instead of after the
    # 800s pytest stage.
    echo "== static: compileall + pyflakes =="
    python -m compileall -q photon_tpu bench.py bench_configs.py
    if python -c "import pyflakes" 2>/dev/null; then
        python -m pyflakes photon_tpu bench.py bench_configs.py
        echo "   pyflakes OK"
    else
        echo "   pyflakes not installed; compileall only"
    fi
}

run_native() {
    # Source-only native dir (no committed binaries, VERDICT r3 #9): a fresh
    # clone compiles both libraries here; runtime mtime-recompile remains a
    # dev convenience only.
    echo "== native: g++ build of avro_decode + index_store =="
    for lib in avro_decode index_store; do
        g++ -O2 -std=c++17 -shared -fPIC \
            -o "photon_tpu/native/lib${lib}.so" \
            "photon_tpu/native/${lib}.cpp"
        echo "   lib${lib}.so built"
    done
}

run_unit() {
    echo "== unit + integration tests (virtual 8-device CPU mesh) =="
    python -m pytest tests/ -x -q
}

run_dryrun() {
    echo "== multichip dryrun (8-device mesh compile + run + parity) =="
    python __graft_entry__.py
    # Device-sharded coordinate gate: sharded-at-8 vs sharded-at-1 RE
    # coefficients must be bit-identical, with zero post-warmup solve-cache
    # retraces at both device counts (subprocess per count — the virtual
    # mesh width must be fixed before the first jax touch).
    echo "== multichip gate (sharded-vs-single parity + zero retrace) =="
    tmp="$(mktemp -d)"
    for n in 1 8; do
        python bench.py --multichip-worker "$n" "$tmp/rung$n"
    done
    python - "$tmp" <<'EOF'
import json, sys
import numpy as np

tmp = sys.argv[1]
c1 = np.load(f"{tmp}/rung1.npy")
c8 = np.load(f"{tmp}/rung8.npy")
assert np.array_equal(c1, c8), "sharded-at-8 != sharded-at-1 (bit parity)"
for n in (1, 8):
    with open(f"{tmp}/rung{n}.json") as f:
        r = json.load(f)
    assert r["post_warmup_retraces"] == 0, (n, r["retraces_per_pass"])
f1 = np.load(f"{tmp}/rung1.fused.npy")
f8 = np.load(f"{tmp}/rung8.fused.npy")
drift = float(np.abs(f1 - f8).max())
assert drift <= 1e-3, f"fused-step cross-mesh drift {drift}"
print(f"   parity OK, retraces 0, fused drift {drift:.2e}")
EOF
    rm -rf "$tmp"
}

run_telemetry() {
    # End-to-end smoke of the unified run report: train a tiny GLM with
    # --telemetry-out and assert the JSONL parses, carries at least one span
    # per CD iteration (the λ sweep), the solve-cache counters, and no
    # NaN/Inf anywhere in the artifact.
    echo "== telemetry: train_glm --telemetry-out smoke =="
    tmp="$(mktemp -d)"
    python - "$tmp" <<'EOF'
import sys, os, json, collections
import numpy as np

tmp = sys.argv[1]
rng = np.random.default_rng(3)
lines = []
for _ in range(200):
    x = rng.normal(size=5)
    y = 1 if rng.uniform() < 1 / (1 + np.exp(-(x[0] - x[1]))) else -1
    feats = " ".join(f"{j + 1}:{x[j]:.4f}" for j in range(5))
    lines.append(f"{y:+d} {feats}")
data = os.path.join(tmp, "train.txt")
with open(data, "w") as f:
    f.write("\n".join(lines))

from photon_tpu.cli import train_glm

tele = os.path.join(tmp, "run.jsonl")
args = train_glm.build_parser().parse_args([
    "--training-data", data, "--format", "libsvm",
    "--output-dir", os.path.join(tmp, "out"),
    "--regularization-weights", "0.1,1",
    "--max-iterations", "10",
    "--telemetry-out", tele,
])
train_glm.run(args)

text = open(tele).read()
assert "NaN" not in text and "Infinity" not in text, "non-finite leaked"
from photon_tpu.obs import validate_record
records = [json.loads(line) for line in text.splitlines()]
for rec in records:
    validate_record(rec)
kinds = collections.Counter(r["record"] for r in records)
assert kinds["meta"] == 1 and kinds["env"] == 1, kinds
cd_rows = [r for r in records if r["record"] == "coordinate_descent"]
spans = [r for r in records if r["record"] == "span"]
solve_spans = [s for s in spans if s["name"].startswith("glm/lambda")
               and s["name"].endswith("/solve")]
assert len(cd_rows) == 2, cd_rows
# ≥1 span per CD iteration (train_glm's λ sweep is its coordinate sequence)
assert len(solve_spans) >= len(cd_rows), (solve_spans, cd_rows)
cache = {r["metric"]: r["value"] for r in records
         if r["record"] == "metric" and r["metric"].startswith("solve_cache_")}
assert cache.get("solve_cache_calls") == 2, cache
assert "solve_cache_hits" in cache and "solve_cache_traces" in cache, cache
print(f"   {len(records)} records, {len(spans)} spans, "
      f"solve_cache={ {k: v for k, v in sorted(cache.items())} } OK")
EOF
    rm -rf "$tmp"
}

run_active_set() {
    # Gated-vs-full smoke for the convergence-gated active-set RE passes:
    # a 3-pass synthetic GAME workload run twice must reach the SAME final
    # objective (rtol 1e-5), skip entities from pass 2 on, and keep the
    # solve-cache trace counter identical to the full run. Timing is NOT
    # asserted here (CI machines vary); bench.py --active-set-ab measures
    # the wall-clock side.
    echo "== active-set: 3-pass gated-vs-full parity smoke =="
    python - <<'EOF'
import numpy as np
import jax.numpy as jnp

from photon_tpu.algorithm.coordinate_descent import CoordinateDescent
from photon_tpu.algorithm.fixed_effect import FixedEffectCoordinate
from photon_tpu.algorithm.random_effect import RandomEffectCoordinate
from photon_tpu.algorithm.solve_cache import SolveCache
from photon_tpu.data.game_data import GameBatch
from photon_tpu.data.random_effect import (
    RandomEffectDataConfig, build_random_effect_dataset,
)
from photon_tpu.ops.losses import LogisticLoss
from photon_tpu.ops.objective import GLMObjective
from photon_tpu.optim.factory import OptimizerSpec
from photon_tpu.types import OptimizerType, TaskType
from photon_tpu.utils.events import EventEmitter

rng = np.random.default_rng(7)
E, d_re, d_fe = 96, 6, 5
counts = rng.integers(37, 47, size=E)
eids = np.repeat(np.arange(E, dtype=np.int32), counts)
n = eids.size
Xr = rng.normal(size=(n, d_re)).astype(np.float32)
Xr[eids % 3 != 0] = 0.0  # cold cohort: retires from pass 2 deterministically
Xf = rng.normal(size=(n, d_fe)).astype(np.float32)
Xf[:, 0] = 1.0
y = (rng.uniform(size=n) < 0.5).astype(np.float32)
w = np.ones(n, np.float32)
batch = GameBatch(
    label=jnp.asarray(y), offset=jnp.zeros(n, jnp.float32),
    weight=jnp.asarray(w),
    features={"global": jnp.asarray(Xf), "re": jnp.asarray(Xr)},
    entity_ids={"userId": jnp.asarray(eids)},
)
ds = build_random_effect_dataset(
    eids, Xr, y, w, E,
    RandomEffectDataConfig(re_type="userId", feature_shard="re", n_buckets=4,
                           shape_bucketing=True, subspace_projection=False),
)

def run(active):
    cache = SolveCache(donate=True)
    fe = FixedEffectCoordinate(
        coordinate_id="global", feature_shard="global",
        task=TaskType.LOGISTIC_REGRESSION,
        objective=GLMObjective(loss=LogisticLoss, l2_weight=1.0,
                               intercept_index=0),
        optimizer_spec=OptimizerSpec(optimizer=OptimizerType.LBFGS,
                                     max_iter=50, tol=1e-9),
        solve_cache=cache,
    )
    re = RandomEffectCoordinate(
        coordinate_id="per_user", dataset=ds,
        task=TaskType.LOGISTIC_REGRESSION,
        objective=GLMObjective(loss=LogisticLoss, l2_weight=0.5),
        optimizer_spec=OptimizerSpec(optimizer=OptimizerType.NEWTON,
                                     max_iter=25, tol=1e-9),
        solve_cache=cache, active_set=active, convergence_tol=1e-4,
    )
    events = []
    em = EventEmitter(); em.register(events.append)
    cd = CoordinateDescent(coordinates={"global": fe, "per_user": re},
                           update_sequence=["global", "per_user"],
                           num_iterations=3)
    res = cd.run(batch, profile=True, emitter=em)
    total = np.asarray(res.model.get("global").score(batch)
                       + res.model.get("per_user").score(batch))
    obj = float(np.mean(w * np.logaddexp(0.0, -(2 * y - 1) * total)))
    stats = [e.payload["active_set"] for e in events
             if e.name == "PhotonOptimizationLogEvent"
             and e.payload.get("coordinate") == "per_user"]
    return obj, cache.stats.traces, stats

obj_f, traces_f, _ = run(False)
obj_g, traces_g, stats = run(True)
rel = abs(obj_g - obj_f) / max(abs(obj_f), 1e-30)
assert rel <= 1e-5, f"parity violated: {obj_f} vs {obj_g} (rel {rel:.3g})"
assert traces_f == traces_g, f"trace counters differ: {traces_f} vs {traces_g}"
skipped = [s["entities_skipped"] for s in stats]
assert skipped[0] == 0 and all(s > 0 for s in skipped[1:]), skipped
print(f"   objective {obj_g:.6f} (rel {rel:.1e}), traces {traces_g}, "
      f"skipped/pass {skipped} OK")
EOF
}

run_ooc() {
    # Out-of-core residency smoke: the same RE coordinate trained twice —
    # fully resident and under a quarter-footprint device budget — must
    # produce BIT-identical coefficients (objective rel ≤ 1e-6 follows),
    # see at least 2 eviction waves, and compile nothing after the warm-up
    # pass. Timing is NOT asserted here; bench.py --out-of-core-ab
    # measures the throughput-retention and overlap side.
    echo "== ooc: quarter-budget residency parity smoke =="
    python - <<'EOF'
import numpy as np
import jax.numpy as jnp

from photon_tpu.algorithm.random_effect import RandomEffectCoordinate
from photon_tpu.algorithm.re_store import block_device_cost
from photon_tpu.algorithm.solve_cache import SolveCache
from photon_tpu.data.game_data import GameBatch
from photon_tpu.data.random_effect import (
    RandomEffectDataConfig, build_random_effect_dataset,
)
from photon_tpu.ops.losses import LogisticLoss
from photon_tpu.ops.objective import GLMObjective
from photon_tpu.optim.factory import OptimizerSpec
from photon_tpu.types import OptimizerType, TaskType

rng = np.random.default_rng(7)
E, d_re = 96, 6
counts = rng.integers(37, 47, size=E)
eids = np.repeat(np.arange(E, dtype=np.int32), counts)
n = eids.size
Xr = rng.normal(size=(n, d_re)).astype(np.float32)
y = (rng.uniform(size=n) < 0.5).astype(np.float32)
w = np.ones(n, np.float32)
batch = GameBatch(
    label=jnp.asarray(y), offset=jnp.zeros(n, jnp.float32),
    weight=jnp.asarray(w), features={"re": jnp.asarray(Xr)},
    entity_ids={"userId": jnp.asarray(eids)},
)
cfg = RandomEffectDataConfig(re_type="userId", feature_shard="re",
                             n_buckets=4, shape_bucketing=True,
                             subspace_projection=False)

def run(budget, passes=4):
    cache = SolveCache(donate=True)
    coord = RandomEffectCoordinate(
        coordinate_id="per_user",
        dataset=build_random_effect_dataset(eids, Xr, y, w, E, cfg),
        task=TaskType.LOGISTIC_REGRESSION,
        objective=GLMObjective(loss=LogisticLoss, l2_weight=0.5),
        optimizer_spec=OptimizerSpec(optimizer=OptimizerType.NEWTON,
                                     max_iter=25, tol=1e-9),
        solve_cache=cache, device_budget_bytes=budget,
    )
    model, warm_mark = None, None
    for it in range(passes):
        coord.begin_cd_pass(it)
        model, _ = coord.train(batch, None, model)
        if it == 0:
            warm_mark = cache.trace_mark()
    return model, coord, cache.traces_since(warm_mark)

footprint = sum(block_device_cost(b) for b in
                build_random_effect_dataset(eids, Xr, y, w, E, cfg).blocks)
ref, _, ref_post = run(None)
ooc, coord, ooc_post = run(footprint // 4)
st = coord.last_residency_stats
assert np.array_equal(np.asarray(ref.coefficients),
                      np.asarray(ooc.coefficients)), "coefficients diverged"
s_ref, s_ooc = np.asarray(ref.score(batch)), np.asarray(ooc.score(batch))
obj = lambda s: float(np.mean(w * np.logaddexp(0.0, -(2 * y - 1) * s)))
rel = abs(obj(s_ooc) - obj(s_ref)) / max(abs(obj(s_ref)), 1e-30)
assert rel <= 1e-6, f"objective parity violated: rel={rel:.3g}"
waves = sum(1 for e in st["pass_evictions"] if e > 0)
assert waves >= 2, f"expected >=2 eviction waves, got {st['pass_evictions']}"
assert ooc_post == 0, f"post-warmup retraces: {ooc_post}"
assert st["peak_bytes"] <= st["effective_budget_bytes"], st
print(f"   footprint {footprint} B @ budget {footprint // 4} B: "
      f"bit-identical coefs, rel {rel:.1e}, evictions/pass "
      f"{st['pass_evictions']}, post-warmup traces {ooc_post} OK")
EOF
}

run_serve() {
    # Online-serving smoke: train a tiny GAME model, batch-score it with the
    # game_scoring driver, then push the SAME rows through the in-process
    # serving engine from many threads. Asserts (1) bit-parity — every
    # micro-batched score equals the batch driver's, atol=0; (2) the
    # in-trace retrace counter stays 0 after warm-up; (3) backpressure
    # sheds with the explicit error.
    echo "== serve: concurrent micro-batch parity + zero-retrace smoke =="
    tmp="$(mktemp -d)"
    python - "$tmp" <<'EOF'
import os, sys, threading
import numpy as np

tmp = sys.argv[1]
rng = np.random.default_rng(23)

from photon_tpu.io.avro import write_avro_records
from photon_tpu.io.schemas import TRAINING_EXAMPLE_SCHEMA

def write_fixture(path, n, d=6, n_users=8):
    w = np.linspace(-1, 1, d)
    bias = np.linspace(-2, 2, n_users)
    records = []
    for i in range(n):
        x = rng.normal(size=d)
        u = i % n_users
        y = float(rng.uniform() < 1 / (1 + np.exp(-(x @ w + bias[u]))))
        records.append(dict(
            uid=str(i), label=y,
            features=[{"name": f"x{j}", "term": "", "value": float(x[j])}
                      for j in range(d)],
            metadataMap={"userId": f"u{u}"}, weight=1.0, offset=0.0))
    write_avro_records(path, TRAINING_EXAMPLE_SCHEMA, records)

train, valid = os.path.join(tmp, "train.avro"), os.path.join(tmp, "valid.avro")
# 48 users > the engine's 32-row hot floor, so the hot store actually runs
# its LRU promote/demote path (8 users would pin the whole table).
write_fixture(train, 600, n_users=48)
write_fixture(valid, 256, n_users=48)

from photon_tpu.cli import game_scoring, game_training

out = os.path.join(tmp, "out")
game_training.run(game_training.build_parser().parse_args([
    "--input-paths", train, "--output-dir", out,
    "--feature-shard-configurations", "name=globalShard",
    "--coordinate-configurations",
    "name=global,feature.shard=globalShard,optimizer=LBFGS,reg.weights=1",
    "name=perUser,feature.shard=globalShard,random.effect.type=userId,reg.weights=1",
    "--update-sequence", "global,perUser",
]))
score_out = os.path.join(tmp, "scores")
game_scoring.run(game_scoring.build_parser().parse_args([
    "--input-paths", valid, "--output-dir", score_out,
    "--feature-shard-configurations", "name=globalShard",
    "--model-input-dir", os.path.join(out, "best"),
    "--model-artifacts-dir", out,
]))
from photon_tpu.io.scores import load_scores
batch_score = {r["uid"]: np.float32(r["predictionScore"])
               for r in load_scores(os.path.join(score_out, "scores.avro"))}

# Same rows, served: dense feature vectors from the same reader + index maps.
from photon_tpu.cli.common import parse_feature_shard_config
from photon_tpu.data.index_map import EntityIndex, IndexMap
from photon_tpu.io.data_reader import read_merged
from photon_tpu.serve import ScoreRequest, ServeConfig, load_engine

imap = IndexMap.load(os.path.join(out, "index-map-globalShard.json"))
eidx = EntityIndex.load(os.path.join(out, "entity-index-userId.json"))
batch, _, _ = read_merged(
    [valid], parse_feature_shard_config("name=globalShard"),
    index_maps={"globalShard": imap},
    entity_id_columns={"userId": "userId"},
    entity_indexes={"userId": eidx}, intern_new_entities=False,
)
X = np.asarray(batch.features["globalShard"])
eids = np.asarray(batch.entity_ids["userId"])
uids = [str(int(u)) for u in np.asarray(batch.uid)]
n = X.shape[0]

engine = load_engine(
    os.path.join(out, "best"), artifacts_dir=out,
    config=ServeConfig(max_batch_size=32, max_delay_ms=5.0,
                       # force the LRU path: budget far below the full table
                       hot_bytes=1),
)
assert not engine.stats()["store"]["userId"]["pinned"], engine.stats()

results = [None] * n
def worker(lo, hi):
    futs = [(i, engine.submit(ScoreRequest(
        {"globalShard": X[i]}, {"userId": int(eids[i])})))
        for i in range(lo, hi)]
    for i, f in futs:
        results[i] = np.float32(f.result(timeout=60))
threads = [threading.Thread(target=worker, args=(lo, min(lo + 16, n)))
           for lo in range(0, n, 16)]
for t in threads: t.start()
for t in threads: t.join()

exact = sum(results[i] == batch_score[uids[i]] for i in range(n))
assert exact == n, f"bit-parity: only {exact}/{n} scores exact"
assert engine.retraces_since_warmup == 0, engine.stats()

# Backpressure sheds with the explicit error (cap 1, pile on a 2nd+3rd).
from photon_tpu.serve import BackpressureError
from photon_tpu.serve.engine import ServingEngine  # noqa: F401 (doc pointer)
shed_engine = load_engine(
    os.path.join(out, "best"), artifacts_dir=out,
    config=ServeConfig(max_batch_size=1, max_delay_ms=200.0, queue_cap=1))
shed = 0
for _ in range(50):
    try:
        shed_engine.submit(ScoreRequest({"globalShard": X[0]},
                                        {"userId": int(eids[0])}))
    except BackpressureError:
        shed += 1
assert shed > 0, "queue_cap=1 under a 50-request burst must shed"
shed_engine.close()
engine.close()
print(f"   {n}/{n} scores bit-exact vs batch driver, retraces=0, "
      f"shed={shed}/50 OK")
EOF
    rm -rf "$tmp"
}

run_faults() {
    # Crash-safe resume smoke: SIGKILL the trainer mid-sweep via the
    # fault-injection harness (kill fires right after the first checkpoint
    # publish), then rerun with --resume and assert the final artifacts
    # match an uninterrupted baseline run to rel 1e-6 per λ.
    echo "== faults: SIGKILL mid-train + --resume objective parity =="
    tmp="$(mktemp -d)"
    python - "$tmp" <<'EOF'
import json, os, signal, subprocess, sys
import numpy as np

tmp = sys.argv[1]
rng = np.random.default_rng(11)
lines = []
for _ in range(120):
    x = rng.normal(size=4)
    y = 1 if rng.uniform() < 1 / (1 + np.exp(-(x[0] - x[2]))) else -1
    feats = " ".join(f"{j + 1}:{x[j]:.4f}" for j in range(4))
    lines.append(f"{y:+d} {feats}")
data = os.path.join(tmp, "train.txt")
with open(data, "w") as f:
    f.write("\n".join(lines))

def run(outdir, resume=False, plan=None):
    env = dict(os.environ)
    env.pop("PHOTON_TPU_FAULT_PLAN", None)
    if plan is not None:
        env["PHOTON_TPU_FAULT_PLAN"] = json.dumps(plan)
    cmd = [sys.executable, "-m", "photon_tpu.cli.train_glm",
           "--training-data", data, "--format", "libsvm",
           "--output-dir", outdir,
           "--checkpoint-dir", os.path.join(outdir, "ckpt"),
           "--regularization-weights", "10,1,0.1",
           "--max-iterations", "15"]
    if resume:
        cmd.append("--resume")
    return subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=600)

base = os.path.join(tmp, "base")
r = run(base)
assert r.returncode == 0, r.stderr

faulted = os.path.join(tmp, "faulted")
kill_plan = {"rules": [{"site": "checkpoint.after_save", "kind": "kill",
                        "at": [0]}]}
r = run(faulted, plan=kill_plan)
assert r.returncode == -signal.SIGKILL, (r.returncode, r.stderr)

r = run(faulted, resume=True)
assert r.returncode == 0, r.stderr
assert "resuming" in (r.stdout + r.stderr)

def summary(outdir):
    with open(os.path.join(outdir, "training-summary.json")) as f:
        return json.load(f)

a, b = summary(base), summary(faulted)
assert a["best_lambda"] == b["best_lambda"], (a, b)
assert len(b["models"]) == len(a["models"]) == 3, b
worst = 0.0
for ma, mb in zip(a["models"], b["models"]):
    assert ma["lambda"] == mb["lambda"]
    rel = abs(mb["loss"] - ma["loss"]) / max(abs(ma["loss"]), 1e-30)
    worst = max(worst, rel)
    assert rel <= 1e-6, (ma, mb, rel)
print(f"   kill @ first checkpoint, resume parity: "
      f"worst per-λ loss rel {worst:.2e} (≤ 1e-6) OK")
EOF
    rm -rf "$tmp"
}

run_soak() {
    # Multi-process serving smoke: forked HTTP workers + scorer process
    # under mixed-tenant load with LATEST-pointer reload churn and an
    # abusive tenant. run_serve_soak asserts the PR-7 acceptance bar
    # itself: zero caller-visible errors, per-tenant fairness under abuse
    # (abuser sheds 429s, others hold p99), HTTP-vs-batch bit parity,
    # zero retraces after warm-up, and a clean SIGTERM drain (exit 0).
    echo "== soak: multi-process serve under quota + reload churn =="
    JAX_PLATFORMS=cpu python bench.py --serve-soak \
        --soak-duration 8 --soak-workers 2
    echo "   serve-soak smoke OK"
}

run_fleet() {
    # Scorer-fleet smoke: 3 consistent-hash replicas over disjoint ring
    # shards of the entity store, driven through the routing front end.
    # run_fleet_soak --fleet-smoke asserts the ISSUE 13 drill: bit parity
    # vs an in-process engine, a serve.replica_kill fault-plan SIGKILL
    # surviving with zero caller errors (shard degrades FE-only, re-homes
    # on revive), a live join + drain/leave, disjoint per-replica hit
    # rates, and fleet-global admission charging ONE token bucket. The
    # 2.2x QPS scaling bar runs in the full (non-smoke) soak only.
    echo "== fleet: 3-replica parity + kill/rejoin + fleet admission =="
    JAX_PLATFORMS=cpu python bench.py --fleet-soak --fleet-smoke
    echo "   fleet-soak smoke OK"
    # Cross-host transport drill: the same frame protocol over TCP
    # loopback with the HMAC handshake, warm shard handoff through a
    # live join AND drain (per-replica hit rate holds — no cold dip, no
    # FE-only window), a SIGKILL+revive with zero caller errors, zero
    # post-warmup retraces, and the probe set bit-identical over TCP,
    # Unix sockets, and the batch engine.
    echo "== fleet: TCP transport parity + warm shard handoff =="
    JAX_PLATFORMS=cpu python bench.py --fleet-handoff --fleet-smoke
    echo "   fleet-handoff smoke OK"
}

run_rollout() {
    # Continuous-rollout smoke: the full generation lifecycle in one
    # process — train gen-1, serve it, incremental-retrain gen-2, shadow
    # it on live traffic and promote, REFUSE a checksum-corrupted
    # generation at the validation gate, then trip the circuit breaker on
    # a promoted generation and auto-roll back to its parent (poisoned,
    # never re-promoted). run_rollout_soak asserts the ISSUE 8 bar
    # itself: zero caller-visible errors, zero retraces after warm-up,
    # and post-rollback bit parity with direct pinned scoring.
    echo "== rollout: train -> shadow -> promote -> gate-refuse -> rollback =="
    JAX_PLATFORMS=cpu python bench.py --rollout-soak
    echo "   rollout-soak smoke OK"
}

run_streaming() {
    # Streaming-freshness smoke: the full feedback -> micro-generation
    # loop live — serving lands scored requests + labels in the spool,
    # the continuous updater turns sealed segments into per-entity DELTA
    # micro-generations, and the rollout watcher shadows + promotes each
    # one under uninterrupted load. run_streaming_soak asserts the
    # ISSUE 11 bar itself: >=3 promotions, zero caller errors, zero
    # retraces, staleness p95 < 60 s, <=1% entities and <5% bytes per
    # delta, shadow bit-parity, and SIGKILL crash-resume bit-equivalence.
    echo "== streaming: feedback spool -> delta micro-generations -> promote =="
    JAX_PLATFORMS=cpu python bench.py --streaming-soak
    echo "   streaming-soak smoke OK"
    # Sharded freshness plane (ISSUE 17): 2 entity-hash-routed shard
    # workers over live-spooled traffic — composed model bit-identical to
    # the single updater, zero post-warmup retraces per shard, concurrent
    # flock'd publishes rebasing to one linear lineage. (The >=3x scaling
    # bar is asserted by the full `bench.py --updater-shard-ab`, not in
    # CI — shared boxes are too noisy to gate on a throughput ratio.)
    echo "== streaming: 2-shard updater A/B (parity + retrace + lineage) =="
    JAX_PLATFORMS=cpu python bench.py --updater-shard-ab --shard-smoke
    echo "   updater-shard-ab smoke OK"
}

run_exhaustion() {
    # Resource-exhaustion smoke: device OOM, disk-full, and host memory
    # pressure injected through training, spill, checkpoint, telemetry,
    # and serving. run_exhaustion_soak asserts the ISSUE 10 bar itself:
    # the run completes with zero caller-visible errors, coefficients and
    # scores stay bit-identical to the unconstrained fault-free run, the
    # checkpoint writer prunes-and-retries under ENOSPC, and no partial
    # artifact (*.tmp, spool-*.pkl) survives on disk.
    echo "== exhaustion: OOM + ENOSPC + RSS-pressure containment =="
    JAX_PLATFORMS=cpu python bench.py --exhaustion-soak
    echo "   exhaustion-soak smoke OK"
}

run_obs() {
    # Observability plane: W3C-style trace context propagated worker →
    # relay → replica (ONE trace, spans from ≥3 pids, correct nesting),
    # the tail-based flight recorder + /v1/traces, fleet-merged /metrics
    # with per-replica labels, metric-name aliases, and the SLO burn-rate
    # state machine (tests/test_obs_plane.py asserts the ISSUE 14 bar
    # itself). tests/test_obs_export.py covers the ISSUE 15 export loop:
    # OTLP-shaped span/metric batches vs a mock collector, retry/backoff
    # + drop-and-count on a dead collector, deterministic histogram
    # exemplars, ring-overflow accounting, and the SLO gate's
    # freeze/rollback/unfreeze cycle. Then the tracing-on vs tracing-off
    # serve A/B (now WITH the exporter shipping every traced span to a
    # live mock collector): median per-pass p99 overhead <= 5%, zero
    # post-warmup retraces, sync-free telemetry pin re-asserted. Finally
    # the SLO-breach actuation drill: injected latency burn aborts a
    # shadow candidate and rolls back a settling promotion with zero
    # caller errors, and a /metrics exemplar resolves through the CLI.
    echo "== obs: tracing + export + fleet /metrics + SLO actuation =="
    JAX_PLATFORMS=cpu python -m pytest -q -p no:cacheprovider \
        tests/test_obs_plane.py tests/test_obs_export.py
    echo "   obs plane + export tests OK"
    JAX_PLATFORMS=cpu python bench.py --obs-overhead-ab
    echo "   obs overhead A/B OK"
    JAX_PLATFORMS=cpu python bench.py --slo-rollback-drill
    echo "   SLO rollback drill OK"
}

run_quality() {
    # Model-quality plane (ISSUE 18): the streaming evaluator's invariants
    # (histogram AUC within its tie bound of the exact auc_roc incl. ties
    # and single-class windows, merge == accumulate associativity, window
    # rotation monotone under clock skew), then the freshness-lift smoke:
    # live drifting traffic against fresh-delta serving vs a frozen pinned
    # baseline — measured online AUC lift must be positive, zero caller
    # errors, zero post-warmup retraces — and the quality-burn drill: an
    # injected label shift pages auc_drop and actuates a counted rollback
    # + promotion freeze through the unchanged SLO gate.
    echo "== quality: streaming evaluator unit suite =="
    JAX_PLATFORMS=cpu python -m pytest -q -p no:cacheprovider \
        tests/test_quality.py
    echo "   quality evaluator tests OK"
    echo "== quality: freshness-lift smoke (lift + burn drill) =="
    JAX_PLATFORMS=cpu python bench.py --freshness-lift --smoke
    echo "   freshness-lift smoke OK"
}

run_experiments() {
    # Continuous online experiment plane (ISSUE 20): GP proposal
    # determinism + search-history serialization round-trip + crash-resume
    # from durable manifest records (tests/test_experiment.py), and the
    # GLM family audit — EVERY task type (linear, logistic, Poisson,
    # smoothed hinge) through train → serve → stream → rollout with the
    # family's own quality-plane loss (tests/test_glm_family.py). Then the
    # live smokes: the GLM-family traffic drill across all four task
    # types, and the experiment soak — a GP-driven sweep holding 4
    # concurrent shadow candidates under live traffic, quality-burn
    # poisoning of an injected regression, SIGKILL of the manager
    # mid-round resuming without re-training, and the GP winner landing
    # within tolerance of an offline exhaustive λ sweep.
    echo "== experiments: GP determinism + resume + GLM family tests =="
    JAX_PLATFORMS=cpu python -m pytest -q -p no:cacheprovider \
        tests/test_experiment.py tests/test_glm_family.py
    echo "   experiment + GLM family tests OK"
    echo "== experiments: GLM family traffic smoke (all task types) =="
    JAX_PLATFORMS=cpu python bench.py --glm-family --smoke
    echo "   glm-family smoke OK"
    echo "== experiments: GP live-sweep soak smoke =="
    JAX_PLATFORMS=cpu python bench.py --experiment-soak --smoke
    echo "   experiment-soak smoke OK"
}

run_kernels() {
    # Kernel-surface smoke: interpret-mode parity for both Pallas kernel
    # families (FE fused value+grad/HVP, RE batched Newton system), and a
    # dead-code gate — the round-4 FE A/B DELETED the losing lowerings, so
    # their per-call tile_n override must stay gone from the public
    # signatures (no quietly resurrected code paths in ops/pallas_glm.py).
    echo "== kernels: FE/RE Pallas parity smokes + deleted-lowering gate =="
    JAX_PLATFORMS=cpu python - <<'EOF'
import inspect

from photon_tpu.ops.pallas_glm import (
    fused_data_hvp,
    fused_data_value_and_grad,
)
from photon_tpu.ops.pallas_newton import fused_newton_system

for fn in (fused_data_value_and_grad, fused_data_hvp):
    params = inspect.signature(fn).parameters
    assert "tile_n" not in params, (
        f"{fn.__name__} grew a tile_n override back — the losing FE "
        "lowerings were deleted in the round-4 A/B (BENCH_FULL.md)"
    )
print("   deleted-lowering gate OK (no tile_n in public signatures)")
EOF
    JAX_PLATFORMS=cpu python -m pytest -q -p no:cacheprovider \
        tests/test_pallas_glm.py \
        tests/test_re_kernel.py::test_fused_newton_system_bitexact_unbatched_and_vmapped \
        "tests/test_re_kernel.py::test_solve_block_pallas_bitexact_mixed_geometries[False]" \
        tests/test_re_kernel.py::test_solve_block_bf16x_pinned_tolerance \
        tests/test_re_kernel.py::test_zero_post_warmup_retraces
    echo "   kernels smoke OK"
}

run_install() {
    echo "== packaging: editable install + console entry points =="
    tmp="$(mktemp -d)"
    python -m venv "$tmp/venv"
    # Air-gapped CI: no index access, and the base interpreter may itself be
    # a venv (so --system-site-packages wouldn't see its packages). Bridge
    # the parent environment's site-packages (setuptools for the build,
    # jax/numpy for runtime) via PYTHONPATH instead.
    parent_site="$(python -c 'import site; print(site.getsitepackages()[0])')"
    PYTHONPATH="$parent_site" "$tmp/venv/bin/pip" install -q --no-deps \
        --no-build-isolation -e .
    # Entry points must resolve and print usage without touching a backend.
    for cmd in photon-tpu-game-training photon-tpu-game-scoring \
               photon-tpu-train-glm photon-tpu-feature-indexing \
               photon-tpu-name-and-term-bags photon-tpu-game-serving \
               photon-tpu-game-incremental photon-tpu-game-streaming \
               photon-tpu-game-experiment photon-tpu-obs; do
        PYTHONPATH="$parent_site" "$tmp/venv/bin/$cmd" --help > /dev/null
        echo "   $cmd --help OK"
    done
    # The sharded freshness plane must be reachable from the installed
    # entry point, not just the module: --updater-shards (and the
    # materializing router switch) are part of the CLI contract.
    PYTHONPATH="$parent_site" "$tmp/venv/bin/photon-tpu-game-streaming" \
        --help | grep -q -- "--updater-shards"
    PYTHONPATH="$parent_site" "$tmp/venv/bin/photon-tpu-game-streaming" \
        --help | grep -q -- "--route-spool"
    echo "   photon-tpu-game-streaming exposes --updater-shards/--route-spool OK"
    # Quality-plane surfaces (ISSUE 18): late-label replay + FE-retrain
    # actuation flags on the streaming driver, and the quality subcommand
    # on the obs CLI.
    PYTHONPATH="$parent_site" "$tmp/venv/bin/photon-tpu-game-streaming" \
        --help | grep -q -- "--late-replay-cadence"
    PYTHONPATH="$parent_site" "$tmp/venv/bin/photon-tpu-game-streaming" \
        --help | grep -q -- "--fe-retrain"
    PYTHONPATH="$parent_site" "$tmp/venv/bin/photon-tpu-obs" \
        quality --help > /dev/null
    echo "   quality-plane CLI surfaces OK (--late-replay-cadence/--fe-retrain/quality)"
    # Experiment-plane surfaces (ISSUE 20): the sweep driver's core flags
    # and the experiments rollup on the obs CLI.
    PYTHONPATH="$parent_site" "$tmp/venv/bin/photon-tpu-game-experiment" \
        --help | grep -q -- "--rounds"
    PYTHONPATH="$parent_site" "$tmp/venv/bin/photon-tpu-obs" \
        experiments --help > /dev/null
    echo "   experiment-plane CLI surfaces OK (--rounds/experiments)"
    rm -rf "$tmp"
}

case "$stage" in
    static) run_static ;;
    native) run_native ;;
    unit) run_unit ;;
    dryrun) run_dryrun ;;
    telemetry) run_telemetry ;;
    active-set) run_active_set ;;
    ooc) run_ooc ;;
    serve) run_serve ;;
    faults) run_faults ;;
    soak) run_soak ;;
    fleet) run_fleet ;;
    rollout) run_rollout ;;
    streaming) run_streaming ;;
    exhaustion) run_exhaustion ;;
    install) run_install ;;
    kernels) run_kernels ;;
    obs) run_obs ;;
    quality) run_quality ;;
    experiments) run_experiments ;;
    all) run_static; run_native; run_install; run_dryrun; run_telemetry; run_active_set; run_ooc; run_serve; run_faults; run_soak; run_fleet; run_rollout; run_streaming; run_exhaustion; run_obs; run_quality; run_experiments; run_kernels; run_unit ;;
    *) echo "unknown stage: $stage" >&2; exit 2 ;;
esac
echo "CI ($stage) PASSED"
