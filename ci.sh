#!/usr/bin/env bash
# CI entry point (role of the reference's Travis matrix, .travis.yml:30-34:
# rat | unit | integration). Everything runs on a virtual 8-device CPU mesh
# (tests/conftest.py forces it), so no accelerator is needed for correctness.
#
# Usage: ./ci.sh [static|unit|dryrun|install|all]   (default: all)
set -euo pipefail
cd "$(dirname "$0")"

stage="${1:-all}"

run_static() {
    # Fast fail-first pass: import-time breakage (syntax errors, bad
    # top-level references) surfaces in seconds instead of after the
    # 800s pytest stage.
    echo "== static: compileall + pyflakes =="
    python -m compileall -q photon_tpu bench.py bench_configs.py
    if python -c "import pyflakes" 2>/dev/null; then
        python -m pyflakes photon_tpu bench.py bench_configs.py
        echo "   pyflakes OK"
    else
        echo "   pyflakes not installed; compileall only"
    fi
}

run_native() {
    # Source-only native dir (no committed binaries, VERDICT r3 #9): a fresh
    # clone compiles both libraries here; runtime mtime-recompile remains a
    # dev convenience only.
    echo "== native: g++ build of avro_decode + index_store =="
    for lib in avro_decode index_store; do
        g++ -O2 -std=c++17 -shared -fPIC \
            -o "photon_tpu/native/lib${lib}.so" \
            "photon_tpu/native/${lib}.cpp"
        echo "   lib${lib}.so built"
    done
}

run_unit() {
    echo "== unit + integration tests (virtual 8-device CPU mesh) =="
    python -m pytest tests/ -x -q
}

run_dryrun() {
    echo "== multichip dryrun (8-device mesh compile + run + parity) =="
    python __graft_entry__.py
}

run_install() {
    echo "== packaging: editable install + console entry points =="
    tmp="$(mktemp -d)"
    python -m venv "$tmp/venv"
    # Air-gapped CI: no index access, and the base interpreter may itself be
    # a venv (so --system-site-packages wouldn't see its packages). Bridge
    # the parent environment's site-packages (setuptools for the build,
    # jax/numpy for runtime) via PYTHONPATH instead.
    parent_site="$(python -c 'import site; print(site.getsitepackages()[0])')"
    PYTHONPATH="$parent_site" "$tmp/venv/bin/pip" install -q --no-deps \
        --no-build-isolation -e .
    # Entry points must resolve and print usage without touching a backend.
    for cmd in photon-tpu-game-training photon-tpu-game-scoring \
               photon-tpu-train-glm photon-tpu-feature-indexing \
               photon-tpu-name-and-term-bags; do
        PYTHONPATH="$parent_site" "$tmp/venv/bin/$cmd" --help > /dev/null
        echo "   $cmd --help OK"
    done
    rm -rf "$tmp"
}

case "$stage" in
    static) run_static ;;
    native) run_native ;;
    unit) run_unit ;;
    dryrun) run_dryrun ;;
    install) run_install ;;
    all) run_static; run_native; run_install; run_dryrun; run_unit ;;
    *) echo "unknown stage: $stage" >&2; exit 2 ;;
esac
echo "CI ($stage) PASSED"
