"""GAME model persistence: Avro-compatible save/load with warm-start support.

Parity target: reference ``ModelProcessingUtils`` (photon-client
data/avro/ModelProcessingUtils.scala:59-700): directory layout
``fixed-effect/<name>/coefficients/`` + ``random-effect/<name>/``
with per-entity ``BayesianLinearModelAvro`` records, ``id-info`` files naming
the RE type, JSON ``model-metadata``, and sparsity-thresholded coefficient
output. Models saved here can warm-start later runs (loadGameModelFromHDFS
role) and are structured for interop with reference tooling.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import hashlib
import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from photon_tpu.data.index_map import EntityIndex, IndexMap
from photon_tpu.io.avro import read_avro_records, write_avro_records
from photon_tpu.io.schemas import BAYESIAN_LINEAR_MODEL_SCHEMA
from photon_tpu.models.coefficients import Coefficients
from photon_tpu.models.game import (
    FixedEffectModel,
    GameModel,
    ProjectedRandomEffectModel,
    RandomEffectModel,
)
from photon_tpu.models.glm import GeneralizedLinearModel
from photon_tpu.ops.losses import loss_for_task
from photon_tpu.types import TaskType

FIXED_DIR = "fixed-effect"
RANDOM_DIR = "random-effect"
METADATA_FILE = "model-metadata.json"
ID_INFO_FILE = "id-info"
COEFF_DIR = "coefficients"
MANIFEST_FILE = "generation-manifest.json"
POISON_FILE = "poisoned-generations.json"

logger = logging.getLogger(__name__)

# Fully-qualified class names: the reference loader instantiates models via
# Class.forName(modelClass) (AvroUtils.scala:390), so models this framework
# writes must carry the reference's FQCNs to be loadable there. (The
# smoothed-hinge task has no model class in the reference tree; the logistic
# classifier is the closest loadable stand-in.)
_MODEL_CLASS = {
    TaskType.LOGISTIC_REGRESSION:
        "com.linkedin.photon.ml.supervised.classification.LogisticRegressionModel",
    TaskType.LINEAR_REGRESSION:
        "com.linkedin.photon.ml.supervised.regression.LinearRegressionModel",
    TaskType.POISSON_REGRESSION:
        "com.linkedin.photon.ml.supervised.regression.PoissonRegressionModel",
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM:
        "com.linkedin.photon.ml.supervised.classification.LogisticRegressionModel",
}
# Reader accepts both FQCN and bare class name (this repo's rounds <= 3
# wrote bare names). Hinge aliases to logistic in _MODEL_CLASS, so the
# reverse map is spelled out — and the record's lossFunction field is
# consulted FIRST (it distinguishes hinge from logistic where the
# class name cannot).
_CLASS_MODEL = {
    "LogisticRegressionModel": TaskType.LOGISTIC_REGRESSION,
    "LinearRegressionModel": TaskType.LINEAR_REGRESSION,
    "PoissonRegressionModel": TaskType.POISSON_REGRESSION,
    "SmoothedHingeLossLinearSVMModel": TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
}
_LOSS_TASK = {
    loss_for_task(t).name: t for t in TaskType
}


def _split_key(key: str) -> Tuple[str, str]:
    if IndexMap.DELIM in key:
        name, term = key.split(IndexMap.DELIM, 1)
        return name, term
    return key, ""


def _coeffs_to_avro(
    model_id: str,
    means: np.ndarray,
    variances: Optional[np.ndarray],
    index_map: IndexMap,
    task: TaskType,
    sparsity_threshold: float,
) -> dict:
    rows = []
    var_rows = [] if variances is not None else None
    for j in np.flatnonzero(np.abs(means) > sparsity_threshold):
        key = index_map.get_feature_name(int(j))
        if key is None:
            continue
        name, term = _split_key(key)
        rows.append({"name": name, "term": term, "value": float(means[j])})
        if var_rows is not None:
            var_rows.append({"name": name, "term": term, "value": float(variances[j])})
    return {
        "modelId": model_id,
        "modelClass": _MODEL_CLASS[task],
        "means": rows,
        "variances": var_rows,
        "lossFunction": loss_for_task(task).name,
    }


def _avro_to_coeffs(rec: dict, index_map: IndexMap, dim: int):
    means = np.zeros(dim, np.float32)
    for ntv in rec["means"]:
        key = IndexMap.key(ntv["name"], ntv["term"])
        j = index_map.get_index(key)
        if j >= 0:
            means[j] = ntv["value"]
    variances = None
    if rec.get("variances"):
        variances = np.zeros(dim, np.float32)
        for ntv in rec["variances"]:
            j = index_map.get_index(IndexMap.key(ntv["name"], ntv["term"]))
            if j >= 0:
                variances[j] = ntv["value"]
    task = _LOSS_TASK.get(rec.get("lossFunction") or "")
    if task is None:
        cls_name = (rec.get("modelClass") or "").rsplit(".", 1)[-1]
        task = _CLASS_MODEL.get(cls_name)  # None when unrecognized
    return means, variances, task


def save_game_model(
    model: GameModel,
    output_dir: str,
    index_maps: Dict[str, IndexMap],  # feature-shard -> IndexMap
    entity_indexes: Optional[Dict[str, EntityIndex]] = None,  # RE type -> index
    sparsity_threshold: float = 1e-4,
    extra_metadata: Optional[dict] = None,
) -> None:
    """saveGameModelToHDFS role (ModelProcessingUtils.scala:77-131)."""
    entity_indexes = entity_indexes or {}
    os.makedirs(output_dir, exist_ok=True)
    meta: dict = {"coordinates": {}, **(extra_metadata or {})}

    for cid, sub in model.models.items():
        if isinstance(sub, FixedEffectModel):
            cdir = os.path.join(output_dir, FIXED_DIR, cid, COEFF_DIR)
            os.makedirs(cdir, exist_ok=True)
            # Reference layout: fixed-effect/<name>/id-info holds the feature
            # shard id (ModelProcessingUtils.scala:99,173).
            with open(
                os.path.join(output_dir, FIXED_DIR, cid, ID_INFO_FILE), "w"
            ) as f:
                f.write(sub.feature_shard + "\n")
            imap = index_maps[sub.feature_shard]
            rec = _coeffs_to_avro(
                cid,
                np.asarray(sub.model.coefficients.means),
                None
                if sub.model.coefficients.variances is None
                else np.asarray(sub.model.coefficients.variances),
                imap,
                sub.model.task,
                sparsity_threshold,
            )
            write_avro_records(
                os.path.join(cdir, "part-00000.avro"),
                BAYESIAN_LINEAR_MODEL_SCHEMA,
                [rec],
            )
            meta["coordinates"][cid] = {
                "type": "fixed",
                "featureShard": sub.feature_shard,
                "task": sub.model.task.value,
                "dim": int(sub.model.coefficients.dim),
            }
        elif isinstance(sub, RandomEffectModel):
            cdir = os.path.join(output_dir, RANDOM_DIR, cid)
            os.makedirs(os.path.join(cdir, COEFF_DIR), exist_ok=True)
            # Reference layout: random-effect/<name>/id-info holds TWO lines,
            # (randomEffectType, featureShardId)
            # (ModelProcessingUtils.scala:116,216).
            with open(os.path.join(cdir, ID_INFO_FILE), "w") as f:
                f.write(sub.re_type + "\n" + sub.feature_shard + "\n")
            imap = index_maps[sub.feature_shard]
            eidx = entity_indexes.get(sub.re_type)
            coefs = np.asarray(sub.coefficients)
            variances = None if sub.variances is None else np.asarray(sub.variances)
            records = []
            for e in range(coefs.shape[0]):
                model_id = eidx.entity_id(e) if eidx is not None else str(e)
                records.append(
                    _coeffs_to_avro(
                        model_id,
                        coefs[e],
                        None if variances is None else variances[e],
                        imap,
                        sub.task,
                        sparsity_threshold,
                    )
                )
            write_avro_records(
                os.path.join(cdir, COEFF_DIR, "part-00000.avro"),
                BAYESIAN_LINEAR_MODEL_SCHEMA,
                records,
            )
            meta["coordinates"][cid] = {
                "type": "random",
                "reType": sub.re_type,
                "featureShard": sub.feature_shard,
                "task": sub.task.value,
                "dim": int(coefs.shape[1]),
                "numEntities": int(coefs.shape[0]),
            }
        elif isinstance(sub, ProjectedRandomEffectModel):
            # Wide-shard path: iterate blocks, translate block-local columns
            # to global names through col_map — the (E, d_full) matrix is
            # never materialized (ModelProjection.projectBackward role,
            # performed per nonzero coefficient at write time).
            cdir = os.path.join(output_dir, RANDOM_DIR, cid)
            os.makedirs(os.path.join(cdir, COEFF_DIR), exist_ok=True)
            with open(os.path.join(cdir, ID_INFO_FILE), "w") as f:
                f.write(sub.re_type + "\n" + sub.feature_shard + "\n")
            imap = index_maps[sub.feature_shard]
            eidx = entity_indexes.get(sub.re_type)
            entity_block = np.asarray(sub.entity_block)
            entity_row = np.asarray(sub.entity_row)
            records = []
            for e in range(sub.num_entities):
                b = int(entity_block[e])
                if b < 0:
                    continue  # entity never seen: no model row
                cmap = np.asarray(sub.col_maps[b])
                w = np.asarray(sub.block_coefs[b][int(entity_row[e])])
                v = (
                    None
                    if sub.block_variances is None
                    else np.asarray(sub.block_variances[b][int(entity_row[e])])
                )
                model_id = eidx.entity_id(e) if eidx is not None else str(e)
                rows, var_rows = [], [] if v is not None else None
                for j in np.flatnonzero(np.abs(w) > sparsity_threshold):
                    key = imap.get_feature_name(int(cmap[j]))
                    if key is None:
                        continue
                    name, term = _split_key(key)
                    rows.append({"name": name, "term": term, "value": float(w[j])})
                    if var_rows is not None:
                        var_rows.append(
                            {"name": name, "term": term, "value": float(v[j])}
                        )
                records.append(
                    {
                        "modelId": model_id,
                        "modelClass": _MODEL_CLASS[sub.task],
                        "means": rows,
                        "variances": var_rows,
                        "lossFunction": loss_for_task(sub.task).name,
                    }
                )
            write_avro_records(
                os.path.join(cdir, COEFF_DIR, "part-00000.avro"),
                BAYESIAN_LINEAR_MODEL_SCHEMA,
                records,
            )
            meta["coordinates"][cid] = {
                "type": "random",
                "reType": sub.re_type,
                "featureShard": sub.feature_shard,
                "task": sub.task.value,
                "dim": int(sub.d_full),
                "numEntities": int(sub.num_entities),
            }
        else:
            raise TypeError(f"unknown submodel type {type(sub)}")

    tasks = [c["task"] for c in meta["coordinates"].values()]
    if tasks:
        meta.setdefault("modelType", tasks[0])  # reference metadata key
    with open(os.path.join(output_dir, METADATA_FILE), "w") as f:
        json.dump(meta, f, indent=2)


def publish_latest_pointer(publish_root: str, generation: str) -> str:
    """Atomically publish ``generation`` (a subdirectory of
    ``publish_root``, or an absolute path) as the CURRENT model: write a
    fsync'd ``LATEST`` pointer file via tmp+rename, same torn-write
    discipline as checkpoint publication (utils/checkpoint.py).

    This is the training half of the train→serve loop:
    ``game_serving --reload-poll-interval`` follows the pointer
    (``resolve_model_dir``) and hot-swaps each new generation with zero
    downtime. A crash mid-publish leaves either the old pointer or the new
    one — never a torn file — and the pointed-to directory is always fully
    written (callers publish AFTER ``save_game_model`` returns)."""
    os.makedirs(publish_root, exist_ok=True)
    path = os.path.join(publish_root, "LATEST")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(generation.strip() + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    try:  # best-effort directory fsync: make the rename itself durable
        dfd = os.open(publish_root, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass
    return path


# ---------------------------------------------------------------------------
# Generation manifests + validation gate (the safe-rollout contract).
#
# A *generation* is one fully written model directory under a publish root.
# Its manifest records per-file sha256 checksums, the parent generation id,
# and the holdout-metric record of the training run that produced it. The
# gate (verify_generation / gate_and_publish) re-derives everything the
# manifest claims BEFORE the LATEST pointer may move: checksums, coefficient
# sanity (finite values, norm drift bounded vs the parent), and a holdout
# regression bound. A failing generation stays on disk — written, inspectable,
# never pointed to — with the refusal reason recorded in its own manifest.
# ---------------------------------------------------------------------------


def _file_sha256(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def generation_checksums(model_dir: str) -> Dict[str, str]:
    """relpath → sha256 over every payload file of a generation (the
    manifest itself is excluded — it cannot checksum its own content)."""
    out: Dict[str, str] = {}
    for root, _dirs, files in os.walk(model_dir):
        for fn in sorted(files):
            rel = os.path.relpath(os.path.join(root, fn), model_dir)
            if rel == MANIFEST_FILE:
                continue
            out[rel] = _file_sha256(os.path.join(root, fn))
    return out


def _write_json_durable(path: str, obj: dict) -> None:
    """tmp + fsync + rename: the same torn-write discipline as LATEST."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def write_generation_manifest(
    model_dir: str,
    parent: Optional[str] = None,
    holdout_metrics: Optional[Dict[str, float]] = None,
    extra: Optional[dict] = None,
) -> dict:
    """Record the generation's identity: per-file checksums, parent
    generation id, holdout metrics. Written AFTER save_game_model, BEFORE
    the gate — the gate verifies this record against the files.

    Fault site ``model.corrupt_manifest`` simulates bit-rot that still
    parses: one recorded checksum is flipped, so the directory deserializes
    fine everywhere but the gate's checksum pass must refuse it."""
    from photon_tpu.utils import faults

    checksums = generation_checksums(model_dir)
    sizes = {
        rel: os.path.getsize(os.path.join(model_dir, rel)) for rel in checksums
    }
    manifest = {
        "generation": os.path.basename(model_dir.rstrip("/")),
        "parent": parent,
        "createdAt": time.time(),
        "holdoutMetrics": dict(holdout_metrics or {}),
        "files": checksums,
        # Byte accounting feeds the delta-vs-full publish assertion in the
        # streaming soak: a delta layer's totalBytes must be a small fraction
        # of its base generation's.
        "fileBytes": sizes,
        "totalBytes": int(sum(sizes.values())),
        "gate": {"status": "candidate", "reason": None},
        **(extra or {}),
    }
    rule = faults.injector().fire("model.corrupt_manifest")
    if rule is not None and manifest["files"]:
        rel = sorted(manifest["files"])[0]
        manifest["files"][rel] = "0" * 64
        logger.warning(
            "fault model.corrupt_manifest: flipped checksum of %r in %s",
            rel, model_dir,
        )
    _write_json_durable(os.path.join(model_dir, MANIFEST_FILE), manifest)
    return manifest


def load_generation_manifest(model_dir: str) -> Optional[dict]:
    path = os.path.join(model_dir, MANIFEST_FILE)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def update_generation_manifest(model_dir: str, patch: dict) -> Optional[dict]:
    """Durably merge top-level keys into an existing generation manifest.
    The manifest is excluded from its own checksum record, so a metadata
    patch (e.g. the experiment plane stamping an online observation into
    its ``experiment`` tag) never invalidates the gate's checksum pass.
    Returns the merged manifest, or None when the directory has none."""
    manifest = load_generation_manifest(model_dir)
    if manifest is None:
        return None
    for key, val in patch.items():
        if (isinstance(val, dict) and isinstance(manifest.get(key), dict)):
            manifest[key] = {**manifest[key], **val}
        else:
            manifest[key] = val
    _write_json_durable(os.path.join(model_dir, MANIFEST_FILE), manifest)
    return manifest


def experiment_generations(
    publish_root: str, experiment_id: Optional[str] = None
) -> List[dict]:
    """Every generation manifest under ``publish_root`` carrying an
    ``experiment`` tag (optionally filtered to one experiment id), sorted
    by (round, generation). Each entry is the manifest's experiment block
    plus ``generation`` / ``gate`` / ``createdAt`` — the crash-safe record
    a resuming ExperimentManager (and the obs rollup) reconstructs rounds
    from; the manifests ARE the experiment store, there is no side file to
    lose."""
    out: List[dict] = []
    try:
        names = sorted(os.listdir(publish_root))
    except OSError:
        return out
    for name in names:
        model_dir = os.path.join(publish_root, name)
        if not os.path.isdir(model_dir):
            continue
        manifest = load_generation_manifest(model_dir)
        if not manifest:
            continue
        exp = manifest.get("experiment")
        if not isinstance(exp, dict):
            continue
        if experiment_id is not None and exp.get("id") != experiment_id:
            continue
        out.append(dict(
            exp,
            generation=manifest.get("generation", name),
            gate=manifest.get("gate"),
            createdAt=manifest.get("createdAt"),
        ))
    out.sort(key=lambda e: (int(e.get("round", 0)), str(e["generation"])))
    return out


def delta_info(model_dir: str) -> Optional[dict]:
    """The ``delta`` block of a generation's metadata ({"base": <generation>,
    "changedEntities": {...}}), or None for a full self-contained generation.
    Reads the raw metadata JSON — a delta layer always carries the metadata
    this repo writes (there is no reference-layout fallback for deltas)."""
    path = os.path.join(model_dir, METADATA_FILE)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f).get("delta")
    except (OSError, ValueError):
        return None


def resolve_delta_chain(
    model_dir: str,
    publish_root: Optional[str] = None,
    max_depth: int = 128,
) -> list:
    """Resolution chain for a generation, base-first: ``[full_base, delta_1,
    ..., model_dir]``. A full generation resolves to ``[model_dir]``. Bases
    are looked up as siblings under ``publish_root`` (default: the
    generation's own parent directory). Raises FileNotFoundError when a
    referenced base is missing, ValueError on a cycle or over-deep chain —
    the gate turns either into a refusal, never a published generation.

    Interleaved shard publishes need no special casing here: each sharded
    streaming publish rebases onto the ``LATEST`` of its moment under
    :func:`publish_lock`, so the lineage stays a single base chain whose
    consecutive layers may come from DIFFERENT shards. Because shard layers
    are row-disjoint (:func:`layers_commute`), the row-overwrite resolve is
    order-independent across them — the composed model is bit-identical to
    the single-updater ordering of the same cycles."""
    publish_root = publish_root or os.path.dirname(
        os.path.abspath(model_dir.rstrip("/"))
    )
    chain: list = []
    seen = set()
    cur = model_dir
    while True:
        name = os.path.basename(cur.rstrip("/"))
        if name in seen:
            raise ValueError(f"delta chain cycle at {name!r}")
        seen.add(name)
        chain.append(cur)
        if len(chain) > max_depth:
            raise ValueError(
                f"delta chain deeper than {max_depth} from {model_dir!r}"
            )
        info = delta_info(cur)
        if not info:
            chain.reverse()
            return chain
        base = info.get("base")
        if not base:
            raise ValueError(f"delta generation {name!r} names no base")
        cand = base if os.path.isabs(base) else os.path.join(publish_root, base)
        if not os.path.isdir(cand):
            raise FileNotFoundError(
                f"delta base {base!r} of {name!r} missing under "
                f"{publish_root!r}"
            )
        cur = cand


def delta_row_ids(model_dir: str) -> Dict[str, set]:
    """Per-coordinate model ids (entity id strings) carried by one DELTA
    layer: ``{cid: {modelId, ...}}``. The ids are exactly the strings the
    routing ring hashes (``serve/store._owned_mask`` hashes the same ones),
    so two shard workers' layers are row-disjoint iff these sets are
    disjoint per coordinate. A full generation returns {} — it is not a
    layer and participates in no commutation question."""
    if delta_info(model_dir) is None:
        return {}
    meta = read_model_metadata(model_dir)
    out: Dict[str, set] = {}
    for cid, info in meta["coordinates"].items():
        if info.get("type") == "fixed":
            # A layer carrying a retrained FE never commutes with anything.
            out[cid] = {"__fixed__"}
            continue
        cdir = os.path.join(model_dir, RANDOM_DIR, cid)
        ids = set()
        for path in _coefficient_files(cdir):
            for rec in _coefficient_records(path):
                ids.add(rec["modelId"])
        out[cid] = ids
    return out


def layers_commute(dir_a: str, dir_b: str) -> bool:
    """True iff two delta layers touch row-disjoint entity sets in every
    coordinate (and neither retrains the fixed effect). Row-overwrite
    application (:func:`_apply_delta_layer`) of disjoint row sets is
    order-independent, so any interleaving of such layers over a common
    ancestry resolves to the same composed model — the invariant that lets
    N entity-hash-routed updater shards publish concurrently without a
    total order on their training cycles."""
    rows_a, rows_b = delta_row_ids(dir_a), delta_row_ids(dir_b)
    for cid in set(rows_a) & set(rows_b):
        if rows_a[cid] & rows_b[cid]:
            return False
    return True


def _resolved_coordinate_records(
    model_dir: str, publish_root: Optional[str] = None
) -> Tuple[Dict[str, dict], Dict[str, dict]]:
    """Resolve a generation's delta chain into per-coordinate record maps:
    ``(coordinates, {cid: {modelId: record}})`` where later layers overwrite
    earlier records row-by-row (an entity's record in a delta replaces the
    base's record for that entity; everything else rides through verbatim)."""
    chain = resolve_delta_chain(model_dir, publish_root)
    coordinates: Dict[str, dict] = {}
    records: Dict[str, dict] = {}
    for layer in chain:
        meta = read_model_metadata(layer)
        for cid, info in meta["coordinates"].items():
            coordinates.setdefault(cid, dict(info))
            sub = FIXED_DIR if info.get("type") == "fixed" else RANDOM_DIR
            cdir = os.path.join(layer, sub, cid)
            per = records.setdefault(cid, {})
            for path in _coefficient_files(cdir):
                for rec in _coefficient_records(path):
                    per[rec["modelId"]] = rec
    return coordinates, records


def _norms_over_records(recs) -> dict:
    import math

    sq = 0.0
    n = 0
    finite = True
    for rec in recs:
        n += 1
        for ntv in rec.get("means") or ():
            v = float(ntv["value"])
            if not math.isfinite(v):
                finite = False
            else:
                sq += v * v
        for ntv in rec.get("variances") or ():
            if not math.isfinite(float(ntv["value"])):
                finite = False
    return {"l2": math.sqrt(sq), "records": n, "finite": finite}


def coordinate_norms(model_dir: str, resolve_deltas: bool = True) -> Dict[str, dict]:
    """Per-coordinate coefficient summary straight off the Avro part files
    (no index maps needed): L2 norm over all recorded means, record count,
    and whether every value (means + variances) is finite. This is what the
    gate's coefficient-sanity pass runs on — it must not depend on loading
    artifacts that could themselves be the corrupted thing.

    A delta generation is summarized over its RESOLVED chain (base rows
    overwritten by each layer in order): a micro-generation that touched 10
    of a million entities should show near-zero norm drift vs its parent,
    not the norm of 10 rows vs a million."""
    if resolve_deltas and delta_info(model_dir) is not None:
        _coords, records = _resolved_coordinate_records(model_dir)
        return {cid: _norms_over_records(per.values())
                for cid, per in records.items()}
    out: Dict[str, dict] = {}
    meta = read_model_metadata(model_dir)
    for cid, info in meta.get("coordinates", {}).items():
        sub = FIXED_DIR if info.get("type") == "fixed" else RANDOM_DIR
        cdir = os.path.join(model_dir, sub, cid)

        def _iter(cdir=cdir):
            for path in _coefficient_files(cdir):
                yield from _coefficient_records(path)

        out[cid] = _norms_over_records(_iter())
    return out


@dataclasses.dataclass
class GateResult:
    """Verdict of the validation gate for one candidate generation."""

    ok: bool
    reason: Optional[str]
    checks: Dict[str, object] = dataclasses.field(default_factory=dict)


def _metric_regressed(name: str, new: float, old: float, tol: float) -> bool:
    """True when ``new`` is worse than ``old`` by more than ``tol``, with
    the metric's own direction (AUC up-is-better, RMSE down-is-better —
    EvaluatorSpec grammar). Unknown metric names are not judged."""
    import math

    if not (math.isfinite(new) and math.isfinite(old)):
        return not math.isfinite(new)  # a non-finite NEW metric always fails
    try:
        from photon_tpu.evaluation.suite import EvaluatorSpec

        better = EvaluatorSpec.parse(name).better()
    except Exception:  # noqa: BLE001 — unknown metric: no regression verdict
        return False
    if better(1.0, 0.0):  # higher is better
        return new < old - tol
    return new > old + tol


def verify_generation(
    model_dir: str,
    parent_dir: Optional[str] = None,
    metric_tolerance: float = 0.02,
    norm_drift_bound: float = 10.0,
) -> GateResult:
    """The validation gate. Three passes, all against re-derived facts:

    1. **Checksums** — every file the manifest lists must exist and hash to
       the recorded sha256 (catches torn copies AND bit-rot that still
       deserializes).
    2. **Coefficient sanity** — every persisted coefficient finite; each
       coordinate's L2 norm within ``norm_drift_bound`` relative drift of
       the parent's (a re-train that exploded the weights is wrong even if
       its own holdout number looks fine).
    3. **Holdout regression** — each metric recorded in both manifests must
       not be worse than the parent's by more than ``metric_tolerance``,
       judged in the metric's own direction.

    Never raises on bad content — returns ``GateResult(ok=False, reason)``;
    the caller decides whether that blocks publication."""
    checks: Dict[str, object] = {}
    manifest = None
    try:
        manifest = load_generation_manifest(model_dir)
    except (OSError, ValueError) as exc:
        return GateResult(False, f"manifest_unreadable: {exc}", checks)
    if manifest is None:
        return GateResult(False, "manifest_missing", checks)

    # 1. checksums
    recorded = manifest.get("files") or {}
    for rel, digest in sorted(recorded.items()):
        path = os.path.join(model_dir, rel)
        if not os.path.exists(path):
            return GateResult(False, f"missing_file: {rel}", checks)
        actual = _file_sha256(path)
        if actual != digest:
            return GateResult(False, f"checksum_mismatch: {rel}", checks)
    checks["files_verified"] = len(recorded)

    # 1b. delta chain — a delta layer is only as good as the bases it
    # resolves through: a missing/cyclic chain or a poisoned base refuses
    # the candidate outright (the resolved model would embed bad rows).
    if delta_info(model_dir) is not None:
        publish_root = os.path.dirname(os.path.abspath(model_dir.rstrip("/")))
        try:
            chain = resolve_delta_chain(model_dir, publish_root)
        except (OSError, ValueError) as exc:
            return GateResult(False, f"delta_chain_unresolvable: {exc}", checks)
        checks["delta_chain"] = [
            os.path.basename(p.rstrip("/")) for p in chain
        ]
        for layer in chain[:-1]:
            if is_poisoned(publish_root, layer):
                return GateResult(
                    False,
                    "delta_base_poisoned: "
                    f"{os.path.basename(layer.rstrip('/'))}",
                    checks,
                )

    # 2. coefficient sanity (+ norm drift vs parent)
    try:
        norms = coordinate_norms(model_dir)
    except Exception as exc:  # noqa: BLE001 — unreadable coefficients fail the gate
        return GateResult(False, f"coefficients_unreadable: {exc}", checks)
    checks["coordinate_norms"] = {c: round(v["l2"], 6) for c, v in norms.items()}
    for cid, info in norms.items():
        if not info["finite"]:
            return GateResult(False, f"non_finite_coefficients: {cid}", checks)
    parent_manifest = None
    if parent_dir:
        try:
            parent_manifest = load_generation_manifest(parent_dir)
            parent_norms = coordinate_norms(parent_dir)
        except Exception:  # noqa: BLE001 — an unreadable parent cannot bound us
            parent_norms = {}
        for cid, info in norms.items():
            old = parent_norms.get(cid, {}).get("l2")
            if old is None or old <= 1e-9:
                continue
            drift = abs(info["l2"] - old) / old
            if drift > norm_drift_bound:
                return GateResult(
                    False,
                    f"norm_drift: {cid} drifted {drift:.2f}x "
                    f"(bound {norm_drift_bound})",
                    checks,
                )

    # 3. holdout regression vs parent
    new_metrics = manifest.get("holdoutMetrics") or {}
    old_metrics = (parent_manifest or {}).get("holdoutMetrics") or {}
    compared = {}
    for name, new_v in new_metrics.items():
        old_v = old_metrics.get(name)
        if old_v is None:
            continue
        compared[name] = {"new": new_v, "parent": old_v}
        if _metric_regressed(name, float(new_v), float(old_v), metric_tolerance):
            checks["holdout_compared"] = compared
            return GateResult(
                False,
                f"holdout_regression: {name} {new_v:.6g} vs parent "
                f"{old_v:.6g} (tolerance {metric_tolerance})",
                checks,
            )
    checks["holdout_compared"] = compared
    return GateResult(True, None, checks)


def gate_and_publish(
    publish_root: str,
    generation: str,
    metric_tolerance: float = 0.02,
    norm_drift_bound: float = 10.0,
) -> GateResult:
    """Run the validation gate on ``generation`` (a subdir of
    ``publish_root``) against the CURRENT ``LATEST`` generation, then flip
    the pointer only on a pass. A failing generation is left on disk with
    the refusal reason written into its own manifest's gate record and a
    ``model_gate_failures_total`` count — candidate forever, published
    never."""
    from photon_tpu.obs.metrics import registry

    model_dir = os.path.join(publish_root, generation)
    parent_dir = None
    latest = os.path.join(publish_root, "LATEST")
    if os.path.isfile(latest):
        with open(latest) as f:
            name = f.read().strip()
        if name and name != generation:
            cand = name if os.path.isabs(name) else os.path.join(publish_root, name)
            if os.path.isdir(cand):
                parent_dir = cand
    result = verify_generation(
        model_dir, parent_dir,
        metric_tolerance=metric_tolerance,
        norm_drift_bound=norm_drift_bound,
    )
    manifest = load_generation_manifest(model_dir)
    if manifest is not None:
        manifest["gate"] = {
            "status": "published" if result.ok else "rejected",
            "reason": result.reason,
            "checkedAt": time.time(),
        }
        _write_json_durable(os.path.join(model_dir, MANIFEST_FILE), manifest)
    if result.ok:
        publish_latest_pointer(publish_root, generation)
        registry().counter("model_generations_published_total").inc()
        logger.info("generation %s passed the gate; LATEST -> %s",
                    generation, generation)
    else:
        registry().counter("model_gate_failures_total").inc()
        logger.warning(
            "generation %s REFUSED by the validation gate (%s); LATEST "
            "unchanged", generation, result.reason,
        )
    return result


# ---------------------------------------------------------------------------
# Poison list: generations that must never be (re-)promoted. Lives beside
# the manifests in the publish root, one durable JSON object
# {generation: reason}; the serving watcher both writes it (rollback, reload
# exhaustion) and consults it before loading anything.
# ---------------------------------------------------------------------------


def load_poison_list(publish_root: str) -> Dict[str, str]:
    path = os.path.join(publish_root, POISON_FILE)
    if not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            obj = json.load(f)
        return {str(k): str(v) for k, v in obj.items()}
    except (OSError, ValueError):
        return {}


def mark_poisoned(publish_root: str, generation: str, reason: str) -> None:
    """Durably add ``generation`` to the publish root's poison list.

    The read-modify-write runs under an exclusive flock on a sidecar lock
    file: a publish root is shared state (the watcher's rollback path can
    race the gate/driver, or another server process entirely), and a lost
    update here would let a bad generation be re-adopted."""
    generation = os.path.basename(generation.rstrip("/"))
    with open(os.path.join(publish_root, POISON_FILE + ".lock"), "a") as lockf:
        try:
            import fcntl

            fcntl.flock(lockf.fileno(), fcntl.LOCK_EX)
        except ImportError:  # non-POSIX: best-effort, single-writer only
            pass
        poisoned = load_poison_list(publish_root)
        poisoned[generation] = reason
        _write_json_durable(os.path.join(publish_root, POISON_FILE), poisoned)
    logger.warning("generation %s marked POISONED: %s", generation, reason)


def is_poisoned(publish_root: str, generation: str) -> bool:
    return os.path.basename(generation.rstrip("/")) in load_poison_list(
        publish_root
    )


def next_generation_name(publish_root: str, prefix: str = "gen-") -> str:
    """First unused ``<prefix><N>`` under the publish root (N counts up from
    the numerically largest existing generation, poisoned ones included)."""
    best = 0
    if os.path.isdir(publish_root):
        for name in os.listdir(publish_root):
            if name.startswith(prefix):
                try:
                    best = max(best, int(name[len(prefix):]))
                except ValueError:
                    continue
    return f"{prefix}{best + 1}"


def allocate_generation(publish_root: str, prefix: str = "gen-") -> str:
    """Claim the next unused generation name under the publish root.

    ``next_generation_name`` alone is a racy listdir scan: two concurrent
    updaters (batch incremental + streaming, or two streaming workers) can
    both see ``gen-4`` free and clobber each other's artifacts. Allocation
    runs under an exclusive flock on a sidecar lock file — same discipline
    ``mark_poisoned`` uses for the poison list — and the directory is
    created INSIDE the lock, so the claim is visible to the next scanner
    the moment the lock drops. A claimant that crashes before publishing
    leaves an inert unpublished directory behind; the next allocation simply
    skips past it."""
    os.makedirs(publish_root, exist_ok=True)
    with open(os.path.join(publish_root, ".generation-allocate.lock"), "a") as lockf:
        try:
            import fcntl

            fcntl.flock(lockf.fileno(), fcntl.LOCK_EX)
        except ImportError:  # non-POSIX: best-effort, single-writer only
            pass
        name = next_generation_name(publish_root, prefix)
        os.makedirs(os.path.join(publish_root, name))
    return name


@contextlib.contextmanager
def publish_lock(publish_root: str):
    """Exclusive flock serializing the save→manifest→gate→flip tail of a
    publish against every other holder of the same publish root.

    ``allocate_generation`` already makes generation NAMES race-safe; this
    lock makes generation LINEAGE race-safe. Concurrent shard workers that
    each resolved the same parent at cycle start would otherwise both flip
    ``LATEST`` with a delta based on that stale parent, dropping the other
    worker's rows from the resolved chain. Under the lock each publisher
    re-reads ``LATEST`` and rebases its (row-disjoint, therefore commuting)
    layer onto the true predecessor — chains stay linear no matter how
    cycles interleave. Held for file IO only, never for a solve."""
    os.makedirs(publish_root, exist_ok=True)
    with open(os.path.join(publish_root, ".streaming-publish.lock"), "a") as lockf:
        try:
            import fcntl

            fcntl.flock(lockf.fileno(), fcntl.LOCK_EX)
        except ImportError:  # non-POSIX: best-effort, single-writer only
            pass
        yield


def save_delta_model(
    model: GameModel,
    changed_entities: Dict[str, np.ndarray],
    output_dir: str,
    index_maps: Dict[str, IndexMap],
    entity_indexes: Dict[str, EntityIndex],
    base: str,
    sparsity_threshold: float = 0.0,
    include_fixed: bool = False,
    extra_metadata: Optional[dict] = None,
) -> Dict[str, int]:
    """Write a per-entity DELTA generation: only the rows named by
    ``changed_entities`` (``{re_type: bool mask or int index array}``) are
    persisted, in the exact same per-coordinate Avro layout as a full
    generation, plus metadata carrying ``{"delta": {"base": <generation>}}``.
    Resolving the layer over its base (``load_resolved_game_model``) must be
    bit-identical to publishing the whole model, which is why the default
    sparsity threshold here is 0.0 — a micro-generation exists to move
    freshness, not to shrink records it doesn't own.

    Fixed effects are omitted unless ``include_fixed`` — the streaming
    updater locks them, so the base's FE rides through the resolve verbatim.
    Returns per-coordinate written record counts."""
    os.makedirs(output_dir, exist_ok=True)
    base = os.path.basename(base.rstrip("/"))
    written: Dict[str, int] = {}
    meta: dict = {"coordinates": {}, **(extra_metadata or {})}
    changed_counts: Dict[str, int] = {}

    for cid, sub in model.models.items():
        if isinstance(sub, FixedEffectModel):
            if not include_fixed:
                continue
            cdir = os.path.join(output_dir, FIXED_DIR, cid, COEFF_DIR)
            os.makedirs(cdir, exist_ok=True)
            with open(
                os.path.join(output_dir, FIXED_DIR, cid, ID_INFO_FILE), "w"
            ) as f:
                f.write(sub.feature_shard + "\n")
            rec = _coeffs_to_avro(
                cid,
                np.asarray(sub.model.coefficients.means),
                None
                if sub.model.coefficients.variances is None
                else np.asarray(sub.model.coefficients.variances),
                index_maps[sub.feature_shard],
                sub.model.task,
                sparsity_threshold,
            )
            write_avro_records(
                os.path.join(cdir, "part-00000.avro"),
                BAYESIAN_LINEAR_MODEL_SCHEMA,
                [rec],
            )
            meta["coordinates"][cid] = {
                "type": "fixed",
                "featureShard": sub.feature_shard,
                "task": sub.model.task.value,
                "dim": int(sub.model.coefficients.dim),
            }
            written[cid] = 1
        elif isinstance(sub, RandomEffectModel):
            mask = changed_entities.get(sub.re_type)
            if mask is None:
                continue
            coefs = np.asarray(sub.coefficients)
            mask = np.asarray(mask)
            if mask.dtype == bool:
                idx = np.flatnonzero(mask)
            else:
                idx = np.unique(mask.astype(np.int64))
            idx = idx[idx < coefs.shape[0]]
            if idx.size == 0:
                continue
            cdir = os.path.join(output_dir, RANDOM_DIR, cid)
            os.makedirs(os.path.join(cdir, COEFF_DIR), exist_ok=True)
            with open(os.path.join(cdir, ID_INFO_FILE), "w") as f:
                f.write(sub.re_type + "\n" + sub.feature_shard + "\n")
            imap = index_maps[sub.feature_shard]
            eidx = entity_indexes.get(sub.re_type)
            variances = None if sub.variances is None else np.asarray(sub.variances)
            records = []
            for e in idx:
                e = int(e)
                model_id = eidx.entity_id(e) if eidx is not None else str(e)
                records.append(
                    _coeffs_to_avro(
                        model_id,
                        coefs[e],
                        None if variances is None else variances[e],
                        imap,
                        sub.task,
                        sparsity_threshold,
                    )
                )
            write_avro_records(
                os.path.join(cdir, COEFF_DIR, "part-00000.avro"),
                BAYESIAN_LINEAR_MODEL_SCHEMA,
                records,
            )
            meta["coordinates"][cid] = {
                "type": "random",
                "reType": sub.re_type,
                "featureShard": sub.feature_shard,
                "task": sub.task.value,
                "dim": int(coefs.shape[1]),
                "numEntities": int(idx.size),
            }
            written[cid] = int(idx.size)
            changed_counts[sub.re_type] = changed_counts.get(
                sub.re_type, 0
            ) + int(idx.size)
        elif isinstance(sub, ProjectedRandomEffectModel):
            raise ValueError(
                f"coordinate {cid!r}: projected random effects do not "
                "support delta layers — publish a full generation"
            )
    if not written:
        raise ValueError(
            "delta generation would be empty: no changed entities named "
            "and fixed effects excluded"
        )
    tasks = [c["task"] for c in meta["coordinates"].values()]
    if tasks:
        meta.setdefault("modelType", tasks[0])
    meta["delta"] = {"base": base, "changedEntities": changed_counts}
    with open(os.path.join(output_dir, METADATA_FILE), "w") as f:
        json.dump(meta, f, indent=2)
    return written


def read_delta_rows(
    model_dir: str,
    index_maps: Dict[str, IndexMap],
    entity_indexes: Dict[str, EntityIndex],
) -> dict:
    """Decode one delta layer into the serving fast-apply payload:
    ``{"base": <generation>, "re_rows": {cid: (entity_idx int64[m],
    rows float32[m, d])}, "fixed": {cid: means float32[d]}}``. Entity ids
    must already exist in ``entity_indexes`` (the publisher persists grown
    indexes before the manifest); an unknown id raises ValueError and the
    caller falls back to a full resolved load."""
    info = delta_info(model_dir)
    if info is None:
        raise ValueError(f"{model_dir!r} is not a delta generation")
    meta = read_model_metadata(model_dir)
    out: dict = {"base": info.get("base"), "re_rows": {}, "fixed": {}}
    for cid, cinfo in meta["coordinates"].items():
        imap = index_maps[cinfo["featureShard"]]
        dim = cinfo.get("dim", len(imap))
        if cinfo["type"] == "fixed":
            cdir = os.path.join(model_dir, FIXED_DIR, cid)
            recs = []
            for path in _coefficient_files(cdir):
                recs.extend(_coefficient_records(path))
            if len(recs) != 1:
                raise ValueError(
                    f"delta fixed-effect {cid!r}: expected one record, "
                    f"got {len(recs)}"
                )
            means, _variances, _task = _avro_to_coeffs(recs[0], imap, dim)
            out["fixed"][cid] = means
        else:
            cdir = os.path.join(model_dir, RANDOM_DIR, cid)
            with open(os.path.join(cdir, ID_INFO_FILE)) as f:
                re_type = f.read().split()[0]
            eidx = entity_indexes.get(re_type)
            if eidx is None:
                raise ValueError(
                    f"delta coordinate {cid!r}: no entity index for "
                    f"{re_type!r}"
                )
            idx, rows = [], []
            for path in _coefficient_files(cdir):
                for rec in _coefficient_records(path):
                    e = eidx.lookup(rec["modelId"])
                    if e < 0:
                        raise ValueError(
                            f"delta coordinate {cid!r}: entity "
                            f"{rec['modelId']!r} unknown to the serving "
                            "entity index"
                        )
                    means, _variances, _task = _avro_to_coeffs(rec, imap, dim)
                    idx.append(e)
                    rows.append(means)
            if idx:
                out["re_rows"][cid] = (
                    np.asarray(idx, np.int64),
                    np.stack(rows).astype(np.float32),
                )
    return out


def load_resolved_game_model(
    model_dir: str,
    index_maps: Dict[str, IndexMap],
    entity_indexes: Optional[Dict[str, EntityIndex]] = None,
    to_device: bool = True,
    publish_root: Optional[str] = None,
) -> GameModel:
    """Load a generation with its delta chain applied: the full base loads
    host-side, then each layer's records overwrite the matching entity rows
    (interning may grow the entity space — a streaming layer can introduce
    entities the base never saw). The result is bit-identical to loading an
    equivalent whole-model publish. A full generation degrades to plain
    ``load_game_model``."""
    chain = resolve_delta_chain(model_dir, publish_root)
    entity_indexes = entity_indexes if entity_indexes is not None else {}
    model = load_game_model(
        chain[0], index_maps, entity_indexes, to_device=False
    )
    for layer in chain[1:]:
        model = _apply_delta_layer(model, layer, index_maps, entity_indexes)
    if not to_device:
        return model
    return GameModel({
        cid: _submodel_to_device(sub) for cid, sub in model.models.items()
    })


def _submodel_to_device(sub):
    if isinstance(sub, FixedEffectModel):
        c = sub.model.coefficients
        return FixedEffectModel(
            GeneralizedLinearModel(
                Coefficients(
                    jnp.asarray(c.means),
                    None if c.variances is None else jnp.asarray(c.variances),
                ),
                sub.model.task,
            ),
            sub.feature_shard,
        )
    if isinstance(sub, RandomEffectModel):
        return RandomEffectModel(
            jnp.asarray(sub.coefficients),
            sub.re_type,
            sub.feature_shard,
            sub.task,
            None if sub.variances is None else jnp.asarray(sub.variances),
            present_entities=None
            if sub.present_entities is None
            else jnp.asarray(sub.present_entities),
        )
    return sub


def _apply_delta_layer(
    model: GameModel,
    layer_dir: str,
    index_maps: Dict[str, IndexMap],
    entity_indexes: Dict[str, EntityIndex],
) -> GameModel:
    """Overwrite ``model``'s rows with one delta layer's records, growing
    per-type entity spaces when the layer introduces new ids. Host-side
    numpy only — callers device-put once, after the last layer."""
    meta = read_model_metadata(layer_dir)
    models = dict(model.models)
    for cid, info in meta["coordinates"].items():
        imap = index_maps[info["featureShard"]]
        dim = info.get("dim", len(imap))
        if info["type"] == "fixed":
            cdir = os.path.join(layer_dir, FIXED_DIR, cid)
            recs = []
            for path in _coefficient_files(cdir):
                recs.extend(_coefficient_records(path))
            if len(recs) != 1:
                raise ValueError(
                    f"delta fixed-effect {cid!r}: expected one record, "
                    f"got {len(recs)}"
                )
            means, variances, _task = _avro_to_coeffs(recs[0], imap, dim)
            old = models.get(cid)
            if not isinstance(old, FixedEffectModel):
                raise ValueError(
                    f"delta fixed-effect {cid!r} has no fixed base coordinate"
                )
            oldc = old.model.coefficients
            models[cid] = FixedEffectModel(
                GeneralizedLinearModel(
                    Coefficients(
                        means,
                        variances
                        if variances is not None
                        else (
                            None
                            if oldc.variances is None
                            else np.asarray(oldc.variances)
                        ),
                    ),
                    old.model.task,
                ),
                old.feature_shard,
            )
        else:
            cdir = os.path.join(layer_dir, RANDOM_DIR, cid)
            with open(os.path.join(cdir, ID_INFO_FILE)) as f:
                re_type = f.read().split()[0]
            old = models.get(cid)
            if not isinstance(old, RandomEffectModel):
                raise ValueError(
                    f"delta coordinate {cid!r} has no random-effect base "
                    "coordinate"
                )
            eidx = entity_indexes.setdefault(re_type, EntityIndex())
            recs = []
            for path in _coefficient_files(cdir):
                recs.extend(_coefficient_records(path))
            for rec in recs:
                eidx.intern(rec["modelId"])
            E = len(eidx)
            coefs = np.asarray(old.coefficients)
            present = (
                np.zeros((coefs.shape[0],), bool)
                if old.present_entities is None
                else np.asarray(old.present_entities).copy()
            )
            variances_arr = (
                None if old.variances is None else np.asarray(old.variances)
            )
            if E > coefs.shape[0]:  # layer introduced new entities
                grow = E - coefs.shape[0]
                coefs = np.vstack(
                    [coefs, np.zeros((grow, coefs.shape[1]), np.float32)]
                )
                present = np.concatenate([present, np.zeros((grow,), bool)])
                if variances_arr is not None:
                    variances_arr = np.vstack([
                        variances_arr,
                        np.zeros((grow, variances_arr.shape[1]), np.float32),
                    ])
            else:
                coefs = coefs.copy()
            for rec in recs:
                e = eidx.lookup(rec["modelId"])
                means, variances, _task = _avro_to_coeffs(rec, imap, dim)
                coefs[e] = means
                present[e] = True
                if variances is not None and variances_arr is not None:
                    variances_arr[e] = variances
            models[cid] = RandomEffectModel(
                coefs,
                re_type,
                old.feature_shard,
                old.task,
                variances_arr,
                present_entities=present,
            )
    return GameModel(models)


def _scan_model_dir(model_dir: str, meta: dict) -> Dict[str, dict]:
    """Reconstruct per-coordinate info by scanning a reference-written model
    directory (the reference stores NO coordinate table in its metadata —
    loadGameModelFromHDFS lists fixed-effect/ and random-effect/ and reads
    each coordinate's id-info, ModelProcessingUtils.scala:160-220)."""
    task = meta.get("modelType", TaskType.LOGISTIC_REGRESSION.value)
    coords: Dict[str, dict] = {}
    fdir = os.path.join(model_dir, FIXED_DIR)
    if os.path.isdir(fdir):
        for cid in sorted(os.listdir(fdir)):
            with open(os.path.join(fdir, cid, ID_INFO_FILE)) as f:
                (shard,) = f.read().split()
            coords[cid] = {
                "type": "fixed", "featureShard": shard, "task": task,
                # metadata carried no per-coordinate task: the coefficient
                # records' modelClass may refine it at load time.
                "task_inferred": True,
            }
    rdir = os.path.join(model_dir, RANDOM_DIR)
    if os.path.isdir(rdir):
        for cid in sorted(os.listdir(rdir)):
            with open(os.path.join(rdir, cid, ID_INFO_FILE)) as f:
                re_type, shard = f.read().split()
            coords[cid] = {
                "type": "random", "reType": re_type, "featureShard": shard,
                "task": task, "task_inferred": True,
            }
    return coords


def _coefficient_files(cdir: str) -> list:
    """Coefficient part files for one coordinate: the reference layout puts
    them under <coordinate>/coefficients/part-*.avro; rounds ≤3 of this repo
    wrote RE parts directly in <coordinate>/."""
    out = []
    coeff_dir = os.path.join(cdir, COEFF_DIR)
    for d in (coeff_dir, cdir):
        if os.path.isdir(d):
            out = [
                os.path.join(d, fn)
                for fn in sorted(os.listdir(d))
                if fn.endswith(".avro")
            ]
            if out:
                return out
    return out


# Decoded-record cache for coefficient part files. Generation directories are
# immutable once published (every publish allocates a fresh flock'd name), yet
# the streaming plane re-decodes the same chain every cycle: the gate's
# coordinate_norms resolves the parent chain, the warm start loads it again,
# and with N shard workers in one process each re-reads the shared ancestry.
# Python-side Avro decode dominates those walks, so cache per FILE keyed on
# (mtime_ns, size, inode) — a rewritten or corrupted-in-place file (the gate
# refusal tests do this) misses and is re-read. Callers treat the returned
# records as read-only; nothing in this module mutates them.
_COEFF_CACHE_MAX = 512
_coeff_cache: "collections.OrderedDict" = collections.OrderedDict()
_coeff_cache_lock = threading.Lock()


def _coefficient_records(path: str) -> list:
    try:
        st = os.stat(path)
        sig = (st.st_mtime_ns, st.st_size, st.st_ino)
    except OSError:
        return read_avro_records(path)
    with _coeff_cache_lock:
        hit = _coeff_cache.get(path)
        if hit is not None and hit[0] == sig:
            _coeff_cache.move_to_end(path)
            return hit[1]
    recs = read_avro_records(path)
    with _coeff_cache_lock:
        _coeff_cache[path] = (sig, recs)
        _coeff_cache.move_to_end(path)
        while len(_coeff_cache) > _COEFF_CACHE_MAX:
            _coeff_cache.popitem(last=False)
    return recs


def read_model_metadata(model_dir: str) -> dict:
    """Model metadata with a guaranteed ``coordinates`` table: reads the
    JSON this repo writes, falling back to the reference-layout directory
    scan (fixed-effect/ + random-effect/ + id-info) when the table is
    absent. The scoring and serving drivers both key entity-index loading
    off this — one reader, not two drifting copies."""
    meta: dict = {}
    meta_path = os.path.join(model_dir, METADATA_FILE)
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    if not meta.get("coordinates"):
        meta["coordinates"] = _scan_model_dir(model_dir, meta)
    if not meta["coordinates"]:
        raise FileNotFoundError(
            f"no GAME model at {model_dir!r}: neither a metadata coordinate "
            "table nor fixed-effect/ / random-effect/ directories found"
        )
    return meta


def model_re_types(meta: dict) -> list:
    """Random-effect types named by a metadata coordinate table, stable
    order, deduplicated (two coordinates may share one entity space)."""
    out = []
    for info in meta.get("coordinates", {}).values():
        if info.get("type") == "random" and info["reType"] not in out:
            out.append(info["reType"])
    return out


def load_game_model(
    model_dir: str,
    index_maps: Dict[str, IndexMap],
    entity_indexes: Optional[Dict[str, EntityIndex]] = None,
    to_device: bool = True,
) -> GameModel:
    """loadGameModelFromHDFS role (ModelProcessingUtils.scala:143+). Entity
    ids are re-interned against the provided EntityIndex (or a fresh one),
    so warm starts align with the new run's interning. Reads both this
    repo's metadata-driven layout and reference-written directories
    (directory scan + id-info, proven against the reference's checked-in
    GameIntegTest fixtures).

    ``to_device=False`` keeps coefficient leaves as host numpy — the
    serving store's master copy, which gathers cold rows host-side and
    uploads only the hot working set; shipping the full (E, d) matrix to
    the device just to pull rows back would defeat its byte budget."""
    entity_indexes = entity_indexes if entity_indexes is not None else {}
    coordinates = read_model_metadata(model_dir)["coordinates"]
    arr = jnp.asarray if to_device else np.asarray

    models: Dict[str, object] = {}
    for cid, info in coordinates.items():
        task = TaskType(info["task"])
        shard = info["featureShard"]
        imap = index_maps[shard]
        dim = info.get("dim", len(imap))
        if info["type"] == "fixed":
            cdir = os.path.join(model_dir, FIXED_DIR, cid)
            recs = []
            for path in _coefficient_files(cdir):
                recs.extend(_coefficient_records(path))
            if len(recs) != 1:  # Spark may write empty extra part files
                raise ValueError(
                    f"fixed-effect coordinate {cid!r}: expected exactly one "
                    f"coefficient record across part files, got {len(recs)}"
                )
            means, variances, rec_task = _avro_to_coeffs(recs[0], imap, dim)
            if info.get("task_inferred") and rec_task is not None:
                task = rec_task  # modelClass beats the modelType guess
            models[cid] = FixedEffectModel(
                GeneralizedLinearModel(
                    Coefficients(
                        arr(means),
                        None if variances is None else arr(variances),
                    ),
                    task,
                ),
                shard,
            )
        else:
            cdir = os.path.join(model_dir, RANDOM_DIR, cid)
            with open(os.path.join(cdir, ID_INFO_FILE)) as f:
                re_type = f.read().split()[0]
            eidx = entity_indexes.setdefault(re_type, EntityIndex())
            recs = []
            for path in _coefficient_files(cdir):
                recs.extend(_coefficient_records(path))
            # First pass: intern all entity ids.
            for rec in recs:
                eidx.intern(rec["modelId"])
            E = len(eidx)
            coefs = np.zeros((E, dim), np.float32)
            present = np.zeros((E,), bool)
            variances_arr = None
            for rec in recs:
                e = eidx.lookup(rec["modelId"])
                means, variances, rec_task = _avro_to_coeffs(rec, imap, dim)
                if info.get("task_inferred") and rec_task is not None:
                    task = rec_task  # modelClass beats the modelType guess
                coefs[e] = means
                present[e] = True
                if variances is not None:
                    if variances_arr is None:
                        variances_arr = np.zeros((E, dim), np.float32)
                    variances_arr[e] = variances
            models[cid] = RandomEffectModel(
                arr(coefs),
                re_type,
                shard,
                task,
                None if variances_arr is None else arr(variances_arr),
                present_entities=arr(present),
            )
    return GameModel(models)


def write_basic_statistics(stats, index_map: IndexMap, path: str) -> None:
    """Per-feature summary statistics as FeatureSummarizationResultAvro
    (reference ModelProcessingUtils.writeBasicStatistics,
    ModelProcessingUtils.scala:516): one record per feature with a
    metric-name → value map."""
    from photon_tpu.io.schemas import FEATURE_SUMMARIZATION_SCHEMA

    records = []
    d = int(np.asarray(stats.mean).shape[0])
    mean = np.asarray(stats.mean, np.float64)
    var = np.asarray(stats.variance, np.float64)
    mn = np.asarray(stats.min, np.float64)
    mx = np.asarray(stats.max, np.float64)
    l1 = np.asarray(stats.norm_l1, np.float64)
    l2 = np.asarray(stats.norm_l2, np.float64)
    nnz = np.asarray(stats.num_nonzeros, np.float64)
    for j in range(d):
        key = index_map.get_feature_name(j)
        if key is None:
            continue
        name, term = _split_key(key)
        records.append(
            {
                "featureName": name,
                "featureTerm": term,
                "metrics": {
                    "mean": float(mean[j]),
                    "variance": float(var[j]),
                    "min": float(mn[j]),
                    "max": float(mx[j]),
                    "normL1": float(l1[j]),
                    "normL2": float(l2[j]),
                    "numNonzeros": float(nnz[j]),
                },
            }
        )
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    write_avro_records(path, FEATURE_SUMMARIZATION_SCHEMA, records)
