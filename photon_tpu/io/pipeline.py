"""Pipelined ingest→device data path: overlap decode, assembly, and H2D
transfer with device compute.

SURVEY §7 hard part 4 ("keep the mesh fed"): the streaming reader
(io/columnar.py::stream_avro_columnar) already decodes container blocks
concurrently, but the per-chunk tail — GameBatch assembly (IndexMap lookups,
CSR scatters) and host→device placement — ran strictly serially with device
compute: the device idled during host work and the host idled during device
work. This module runs the three host stages on worker threads with bounded
queues (backpressure), so a jitted consumer overlaps all of them via JAX's
async dispatch — the Snap-ML-style hierarchical pipelining of data loading
against compute (PAPERS.md), host-side counterpart of PR 1's compile-once
device hot loop.

Stages (each its own thread when ``overlap=True``):

    decode    stream_avro_columnar: container blocks → ColumnarRows chunks
              (itself block-parallel; the stage thread additionally moves the
              file-order merge off the consumer)
    assemble  ColumnarRows → HOST GameBatch (numpy: vectorized IndexMap
              lookups + CSR scatters; cumulative entity interning keeps this
              stage strictly in chunk order)
    h2d       bucket-pad (numpy, so the jitted consumer never retraces after
              warmup) → jax.device_put

Backpressure: every inter-stage queue is bounded at ``depth`` chunks, so host
memory holds at most ``3·depth + in-flight`` chunks regardless of file size.
Telemetry: per-stage busy/starved/backpressured wall, items, bytes, and
queue-depth samples land in utils/timed.py ``PipelineStats`` — surfaced by
driver summaries and ``bench.py --pipeline-ab``.

``overlap=False`` runs the identical stage functions inline (the serial
per-chunk path the drivers used before this module) — the A/B control, and
the zero-thread-overhead path for 1-core hosts. Outputs are bit-identical
either way: threads change WHEN work happens, never what it computes.

Defaults (``DEFAULT_QUEUE_DEPTH``, ``default_decode_workers``) come from the
measured ``bench.py --pipeline-ab`` sweep on the bench host, not taste — see
BENCH_FULL.md's stage-timing section.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import pickle
import queue
import tempfile
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

from photon_tpu.obs.metrics import registry
from photon_tpu.obs.trace import current_span_path, record_span, tracer
from photon_tpu.utils import faults, resources
from photon_tpu.utils.timed import PipelineStats, StageStats, record_pipeline

logger = logging.getLogger("photon_tpu")

# Queue bound between stages, in chunks. Measured on the bench host
# (bench.py --pipeline-ab sweeps {1, 2, 4}): depth 2 is double-buffering —
# one chunk in flight downstream, one buffered — and deeper queues bought
# nothing while holding more chunk memory. See BENCH_FULL.md.
DEFAULT_QUEUE_DEPTH = 2

_DONE = object()
_SKIP = object()  # _retry_or_skip verdict: drop this chunk, keep streaming

# Errors worth retrying: filesystem/network hiccups and injected transients
# (faults.TransientInjectedFault subclasses OSError on purpose). Everything
# else — decode logic errors, assembly bugs — fails fast into the skip
# budget or the consumer.
TRANSIENT_ERRORS = (OSError, TimeoutError)

MAX_RETRIES_ENV = "PHOTON_TPU_PIPELINE_MAX_RETRIES"
SKIP_BUDGET_ENV = "PHOTON_TPU_PIPELINE_SKIP_BUDGET"
DEAD_LETTER_ENV = "PHOTON_TPU_PIPELINE_DEAD_LETTER"


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Transient-failure handling for pipeline stages: exponential backoff
    with deterministic seeded jitter on ``TRANSIENT_ERRORS``, then a bounded
    poisoned-chunk skip budget SHARED across all stages of one pipeline run.
    ``skip_budget=0`` (default) keeps the historical fail-fast behavior."""

    max_retries: int = 2
    dead_letter_path: Optional[str] = None  # JSONL sidecar for skipped chunks
    backoff_s: float = 0.05
    backoff_max_s: float = 2.0
    jitter: float = 0.25
    skip_budget: int = 0
    seed: int = 0


def default_retry_policy() -> RetryPolicy:
    """Env-tunable default (drivers that expose no retry flags still get
    operational knobs): PHOTON_TPU_PIPELINE_MAX_RETRIES / _SKIP_BUDGET."""
    p = RetryPolicy()
    mr = os.environ.get(MAX_RETRIES_ENV, "").strip()
    sb = os.environ.get(SKIP_BUDGET_ENV, "").strip()
    dl = os.environ.get(DEAD_LETTER_ENV, "").strip()
    if mr:
        p = dataclasses.replace(p, max_retries=int(mr))
    if sb:
        p = dataclasses.replace(p, skip_budget=int(sb))
    if dl:
        p = dataclasses.replace(p, dead_letter_path=dl)
    return p


class _SkipBudget:
    """Pipeline-wide poisoned-chunk budget (thread-safe). With a
    ``dead_letter_path``, every consumed skip appends one JSONL record
    naming the dropped chunk — skipped data becomes targetable by the
    incremental driver's next refresh (``--dead-letter-in``) instead of
    silently lost."""

    def __init__(self, limit: int, dead_letter_path: Optional[str] = None):
        self.limit = int(limit)
        self.used = 0
        self.dead_letter_path = dead_letter_path
        self._lock = threading.Lock()

    def try_consume(self) -> bool:
        with self._lock:
            if self.used >= self.limit:
                return False
            self.used += 1
            return True

    def dead_letter(self, stage: str, item, exc: BaseException) -> None:
        if not self.dead_letter_path:
            return
        record = dict(
            stage=stage,
            chunk=getattr(item, "index", None),
            rows=getattr(item, "n", None),
            error=f"{type(exc).__name__}: {exc}",
            ts=time.time(),
        )
        # A failing sidecar append must never mask the ORIGINAL chunk error
        # the caller is handling — dead letters are observability, the
        # bottom of the degradation priority. Degrade to a counted drop.
        guard = resources.DiskBudgetGuard("deadletter.write")
        try:
            with self._lock:
                with open(self.dead_letter_path, "a") as f:
                    guard.check()  # ``enospc``/error rules for the sidecar
                    f.write(json.dumps(record) + "\n")
        except OSError as exc2:
            guard.record(exc2)
            try:
                registry().counter("dead_letter_write_failures_total").inc()
            except Exception:
                pass
            logger.exception(
                "could not append dead-letter record to %s",
                self.dead_letter_path,
            )


def _with_retries(
    fn: Callable,
    item,
    policy: RetryPolicy,
    name: str,
    stop: Optional[threading.Event],
    rng: np.random.Generator,
):
    """Call ``fn(item)`` retrying TRANSIENT_ERRORS with jittered exponential
    backoff. The backoff wait respects the stop event so a shutting-down
    pipeline never sits out a sleep (the no-hang guarantee)."""
    delay = policy.backoff_s
    attempt = 0
    while True:
        try:
            return fn(item)
        except TRANSIENT_ERRORS as exc:
            attempt += 1
            if attempt > policy.max_retries:
                raise
            sleep = delay * (1.0 + policy.jitter * float(rng.random()))
            registry().counter("pipeline_retries_total", stage=name).inc()
            logger.warning(
                "pipeline stage %s: transient failure (attempt %d/%d), "
                "retrying in %.3fs: %s",
                name, attempt, policy.max_retries, sleep, exc,
            )
            if stop is not None:
                if stop.wait(sleep):
                    raise  # shutting down — abandon remaining retries
            else:
                time.sleep(sleep)
            delay = min(delay * 2.0, policy.backoff_max_s)


def _retry_or_skip(
    fn: Callable,
    item,
    policy: RetryPolicy,
    name: str,
    stop: Optional[threading.Event],
    rng: np.random.Generator,
    skips: _SkipBudget,
):
    """Retry layer + skip budget: a chunk whose processing keeps failing is
    DROPPED (returning ``_SKIP``) while budget remains, else the error
    propagates (→ ``_Failure`` → the consumer raises)."""
    try:
        return _with_retries(fn, item, policy, name, stop, rng)
    except Exception as exc:  # noqa: BLE001 — budget decision, then re-raise
        if skips.try_consume():
            registry().counter("pipeline_chunks_skipped_total", stage=name).inc()
            skips.dead_letter(name, item, exc)
            logger.warning(
                "pipeline stage %s: skipping poisoned chunk after retries "
                "(%s); skip budget %d/%d used",
                name, exc, skips.used, skips.limit,
            )
            return _SKIP
        raise


def default_decode_workers() -> int:
    """Decode-stage block parallelism: one worker per available core
    (affinity/cgroup-quota aware, PHOTON_TPU_DECODE_WORKERS overrides —
    io/columnar.py::_available_cores), capped like stream_avro_columnar."""
    from photon_tpu.io.columnar import _available_cores

    return min(16, _available_cores())


@dataclasses.dataclass
class BatchChunk:
    """One pipeline chunk: ``batch`` is numpy-leaved after assemble, device-
    resident after h2d. ``n`` is the valid row count (pre-padding); ``uid``
    inside the batch is already renumbered globally."""

    batch: object  # GameBatch
    n: int
    index: int


def chunk_nbytes(chunk: BatchChunk) -> int:
    """Host bytes of a chunk's arrays (replay-cache budget accounting)."""
    import jax

    return sum(
        int(getattr(leaf, "nbytes", 0))
        for leaf in jax.tree_util.tree_leaves(chunk.batch)
    )


def columnar_nbytes(cols) -> int:
    total = 0
    for group in (cols.numeric, cols.longs, cols.strings):
        total += sum(a.nbytes for a in group.values())
    for b in cols.bags.values():
        total += b.offsets.nbytes + b.key_ids.nbytes + b.values.nbytes
    total += cols.meta_rows.nbytes + cols.meta_keys.nbytes + cols.meta_vals.nbytes
    return total


# ---------------------------------------------------------------------------
# Thread plumbing: bounded queues + stop event + error forwarding.
# ---------------------------------------------------------------------------


class _Failure:
    def __init__(self, exc: BaseException):
        self.exc = exc


def _put(q: "queue.Queue", item, stop: threading.Event) -> bool:
    """Put respecting shutdown; returns False when the pipeline stopped."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.05)
            return True
        except queue.Full:
            continue
    return False


def _get(q: "queue.Queue", stop: threading.Event):
    """Get respecting shutdown; returns _DONE when the pipeline stopped."""
    while not stop.is_set():
        try:
            return q.get(timeout=0.05)
        except queue.Empty:
            continue
    return _DONE


def _source_thread(
    make_iter: Callable[[], Iterator],
    out_q: "queue.Queue",
    stage: StageStats,
    stop: threading.Event,
    nbytes_of: Callable,
    name: str,
    source_hook: Optional[Callable],
    policy: RetryPolicy,
    rng: np.random.Generator,
    skips: _SkipBudget,
) -> None:
    gen = None
    try:
        gen = make_iter()
        while True:
            t0 = time.perf_counter()
            try:
                item = next(gen)
            except StopIteration:
                break
            if source_hook is not None:
                # Per-chunk hook (fault injection / validation) runs OUTSIDE
                # next(): a retry re-runs only the hook — a generator that
                # raised cannot be resumed, so errors inside the source
                # itself stay permanent (forwarded below).
                item = _retry_or_skip(
                    source_hook, item, policy, name, stop, rng, skips
                )
                if item is _SKIP:
                    continue
            stage.add_busy(time.perf_counter() - t0, nbytes_of(item))
            t1 = time.perf_counter()
            if not _put(out_q, item, stop):
                return
            stage.add_wait_out(time.perf_counter() - t1)
            stage.sample_depth(out_q.qsize())
        _put(out_q, _DONE, stop)
    except BaseException as exc:  # noqa: BLE001 — forwarded to the consumer
        _put(out_q, _Failure(exc), stop)
    finally:
        # Shuts the decode block pool on abandonment; plain (non-generator)
        # iterators have nothing to close.
        close = getattr(gen, "close", None)
        if close is not None:
            close()


def _stage_thread(
    fn: Callable,
    in_q: "queue.Queue",
    out_q: "queue.Queue",
    stage: StageStats,
    stop: threading.Event,
    nbytes_of: Callable,
    name: str,
    policy: RetryPolicy,
    rng: np.random.Generator,
    skips: _SkipBudget,
) -> None:
    try:
        while True:
            t0 = time.perf_counter()
            item = _get(in_q, stop)
            stage.add_wait_in(time.perf_counter() - t0)
            if item is _DONE:
                _put(out_q, _DONE, stop)
                return
            if isinstance(item, _Failure):
                _put(out_q, item, stop)
                return
            t1 = time.perf_counter()
            out = _retry_or_skip(fn, item, policy, name, stop, rng, skips)
            if out is _SKIP:
                continue
            stage.add_busy(time.perf_counter() - t1, nbytes_of(out))
            t2 = time.perf_counter()
            if not _put(out_q, out, stop):
                return
            stage.add_wait_out(time.perf_counter() - t2)
            stage.sample_depth(out_q.qsize())
    except BaseException as exc:  # noqa: BLE001 — forwarded to the consumer
        _put(out_q, _Failure(exc), stop)


def _run_staged(
    make_source: Callable[[], Iterator],
    source_nbytes: Callable,
    stages: List,  # [(name, fn, nbytes_of)]
    stats: PipelineStats,
    depth: int,
    overlap: bool,
    source_name: str = "decode",
    retry: Optional[RetryPolicy] = None,
    source_hook: Optional[Callable] = None,
) -> Iterator:
    """Compose source + transform stages into one output iterator, threaded
    (bounded queues) or inline — same functions, same order, same results.
    ``retry`` adds transient-error backoff and a shared poisoned-chunk skip
    budget to every stage (and ``source_hook``, run per item after the
    source yields it); both paths apply identical retry/skip semantics."""
    policy = retry if retry is not None else default_retry_policy()
    skips = _SkipBudget(policy.skip_budget, policy.dead_letter_path)
    # Per-stage RNGs so jitter streams are independent yet deterministic
    # for a fixed policy.seed regardless of thread interleaving.
    src_rng = np.random.default_rng(policy.seed)
    stage_rngs = [np.random.default_rng(policy.seed + i + 1) for i in range(len(stages))]

    if not overlap:
        src_stage = stats.stage(source_name)
        stage_objs = [
            (stats.stage(name), fn, nb, stage_rngs[i])
            for i, (name, fn, nb) in enumerate(stages)
        ]
        gen = make_source()
        try:
            for item in gen:
                if source_hook is not None:
                    item = _retry_or_skip(
                        source_hook, item, policy, source_name, None, src_rng, skips
                    )
                    if item is _SKIP:
                        continue
                src_stage.add_busy(0.0, source_nbytes(item))
                # busy time for the source is folded into the consumer's
                # iteration in serial mode; per-stage transform walls are
                # still measured so the A/B can compare stage costs.
                skipped = False
                for stage, fn, nb, rng in stage_objs:
                    t0 = time.perf_counter()
                    item = _retry_or_skip(
                        fn, item, policy, stage.name, None, rng, skips
                    )
                    if item is _SKIP:
                        skipped = True
                        break
                    stage.add_busy(time.perf_counter() - t0, nb(item))
                if not skipped:
                    yield item
        finally:
            close = getattr(gen, "close", None)
            if close is not None:
                close()
        return

    stop = threading.Event()
    # Parent span path captured HERE — the generator body first runs on the
    # consumer thread's first next(), so this is the consumer's innermost
    # open span. Stage threads carry it explicitly (thread-local nesting
    # cannot cross threads), keeping the trace tree connected.
    parent = current_span_path()

    def spanned(target):
        def run(*args):
            with tracer().span(
                f"pipeline-stage/{threading.current_thread().name}",
                parent=parent,
            ):
                target(*args)

        return run

    # Each queue slot pins one decoded host chunk, so under host memory
    # pressure the depth is the cheapest RSS to give back: drop to
    # single-buffering for this pipeline run (trade overlap for survival).
    depth = resources.tightened_depth(depth)
    queues = [queue.Queue(maxsize=depth) for _ in range(len(stages) + 1)]
    threads = [
        threading.Thread(
            target=spanned(_source_thread),
            args=(make_source, queues[0], stats.stage(source_name), stop,
                  source_nbytes, source_name, source_hook, policy, src_rng, skips),
            name=f"photon-pipe-{source_name}",
            daemon=True,
        )
    ]
    for i, (name, fn, nbytes_of) in enumerate(stages):
        threads.append(
            threading.Thread(
                target=spanned(_stage_thread),
                args=(fn, queues[i], queues[i + 1], stats.stage(name), stop,
                      nbytes_of, name, policy, stage_rngs[i], skips),
                name=f"photon-pipe-{name}",
                daemon=True,
            )
        )
    for t in threads:
        t.start()
    out_q = queues[-1]
    try:
        while True:
            # No-hang guarantee: a manual timed get so the consumer can
            # notice every stage thread dying without a _DONE/_Failure
            # reaching this queue (e.g. a forwarding _put raced shutdown).
            try:
                item = out_q.get(timeout=0.05)
            except queue.Empty:
                if stop.is_set():
                    return
                if not any(t.is_alive() for t in threads) and out_q.empty():
                    raise RuntimeError(
                        "pipeline stage threads exited without completing "
                        "the stream"
                    )
                continue
            if item is _DONE:
                return
            if isinstance(item, _Failure):
                raise item.exc
            yield item
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10.0)


class StageWorker:
    """One pipeline stage OUTSIDE a source→consumer chain: bounded input
    queue, a single worker thread, StageStats accounting, and failure
    propagation back to the submitting thread.

    ``_run_staged`` composes stages that flow source → consumer; the
    out-of-core RE store's d2h download stage flows the OPPOSITE way (the
    dispatching consumer produces work for a draining worker), so it gets
    its own primitive with the same queue discipline: ``submit`` blocks when
    the worker is ``depth`` items behind (backpressure — the time shows up
    as the stage's backpressured wall), and a worker failure surfaces at the
    next ``submit`` or at ``close``. Items are processed strictly in
    submission order."""

    def __init__(
        self,
        name: str,
        fn: Callable,
        stage: StageStats,
        depth: int = DEFAULT_QUEUE_DEPTH,
        nbytes_of: Callable = lambda item, out: 0,
    ):
        self.name = name
        self._fn = fn
        self._stage = stage
        self._nbytes = nbytes_of
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._failure: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._run, name=f"photon-pipe-{name}", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while True:
            t0 = time.perf_counter()
            item = _get(self._q, self._stop)
            self._stage.add_wait_in(time.perf_counter() - t0)
            if item is _DONE:
                return
            t1 = time.perf_counter()
            try:
                out = self._fn(item)
            except BaseException as exc:  # noqa: BLE001 — forwarded to submitter
                self._failure = exc
                self._stop.set()
                return
            self._stage.add_busy(time.perf_counter() - t1, self._nbytes(item, out))

    def submit(self, item) -> None:
        """Enqueue one item (blocking under backpressure). Raises the
        worker's failure if it already died."""
        if self._failure is not None:
            raise self._failure
        t0 = time.perf_counter()
        if not _put(self._q, item, self._stop):
            if self._failure is not None:
                raise self._failure
            raise RuntimeError(f"stage worker {self.name!r} stopped")
        self._stage.add_wait_out(time.perf_counter() - t0)
        self._stage.sample_depth(self._q.qsize())

    def close(self, timeout: float = 600.0) -> None:
        """Drain the queue, stop the worker, and re-raise any failure."""
        _put(self._q, _DONE, self._stop)
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            self._stop.set()
            raise RuntimeError(
                f"stage worker {self.name!r} did not drain within {timeout}s"
            )
        if self._failure is not None:
            raise self._failure

    def abort(self) -> None:
        """Stop without draining (error-path cleanup); never raises."""
        self._stop.set()


# ---------------------------------------------------------------------------
# Concrete stages: decode → assemble → h2d over GameBatch chunks.
# ---------------------------------------------------------------------------


def _bucket_pad_host(chunk: BatchChunk, pad_rows_to: int) -> BatchChunk:
    """Numpy-side bucket padding: rows pad to the next ``pad_rows_to``
    multiple with weight-0 samples and -1 entity ids; padded-sparse nnz
    widths bucket to the next power of two. Applied to EVERY chunk (a chunk
    landing exactly on the multiple still buckets its nnz width) so the
    jitted consumer compiles once per bucket shape. The padding rules live
    in data/padding.py — shared with the serving batcher, which must land
    on the SAME program shapes."""
    from photon_tpu.data.padding import pad_game_batch

    n = chunk.n
    target = int(np.ceil(n / pad_rows_to) * pad_rows_to) if n else pad_rows_to
    batch = pad_game_batch(chunk.batch, target, xp=np)
    if batch is chunk.batch:
        return chunk
    return BatchChunk(batch, n, chunk.index)


def _h2d(chunk: BatchChunk, pad_rows_to: Optional[int]) -> BatchChunk:
    import jax

    if pad_rows_to:
        chunk = _bucket_pad_host(chunk, pad_rows_to)
    return BatchChunk(jax.device_put(chunk.batch), chunk.n, chunk.index)


def _faulted(site: str, fn: Callable) -> Callable:
    """Prefix a stage function with a fault-injection checkpoint. The check
    runs BEFORE fn, so an injected transient retries the whole stage call
    on the same (unconsumed) item."""

    def wrapped(item):
        faults.check(site)
        return fn(item)

    return wrapped


def _source_fault_hook(item):
    faults.check("ingest.source")
    return item


def _make_assembler(
    shard_configs,
    index_maps,
    entity_id_columns,
    entity_indexes,
    intern_new_entities,
    column_names,
):
    """ColumnarRows → host BatchChunk closure. Stateful: entity interning is
    cumulative and uids renumber globally, so exactly ONE assembler consumes
    the chunk stream, in order."""
    from photon_tpu.io.data_reader import _columnar_to_game_batch

    state = {"uid_base": 0, "index": 0, "eidx": entity_indexes}

    def assemble(cols) -> BatchChunk:
        batch, state["eidx"] = _columnar_to_game_batch(
            cols,
            shard_configs,
            index_maps,
            entity_id_columns,
            state["eidx"],
            intern_new_entities,
            column_names,
            to_device=False,
        )
        batch = dataclasses.replace(
            batch,
            uid=np.arange(state["uid_base"], state["uid_base"] + cols.n, dtype=np.int64),
        )
        out = BatchChunk(batch, cols.n, state["index"])
        state["uid_base"] += cols.n
        state["index"] += 1
        return out

    return assemble


def assemble_host_batches(
    cols_iter: Iterator,
    shard_configs: Dict,
    index_maps: Dict,
    entity_id_columns: Optional[Dict[str, str]] = None,
    entity_indexes: Optional[Dict] = None,
    intern_new_entities: bool = True,
    column_names=None,
) -> Iterator[BatchChunk]:
    """Assemble an existing ColumnarRows iterator (e.g. a ChunkReplayCache
    replay of decoded chunks) into host (numpy) GameBatch chunks with
    globally-renumbered uids. Strictly in-order, single consumer (entity
    interning is cumulative)."""
    assemble = _make_assembler(
        shard_configs, index_maps, entity_id_columns,
        entity_indexes if entity_indexes is not None else {},
        intern_new_entities, column_names,
    )
    for cols in cols_iter:
        yield assemble(cols)


def stream_host_batches(
    paths: Sequence[str],
    shard_configs: Dict,
    index_maps: Dict,
    entity_id_columns: Optional[Dict[str, str]] = None,
    entity_indexes: Optional[Dict] = None,
    intern_new_entities: bool = True,
    chunk_rows: int = 1 << 16,
    column_names=None,
    decode_workers: Optional[int] = None,
) -> Iterator[BatchChunk]:
    """Decode + assemble inline (no threads): host (numpy) GameBatch chunks
    with globally-renumbered uids — the replay-cache fill path and the
    serial control's host half."""
    from photon_tpu.io.columnar import stream_avro_columnar
    from photon_tpu.io.data_reader import _expand_paths

    yield from assemble_host_batches(
        stream_avro_columnar(_expand_paths(paths), chunk_rows, workers=decode_workers),
        shard_configs, index_maps, entity_id_columns, entity_indexes,
        intern_new_entities, column_names,
    )


def stream_device_batches(
    paths: Sequence[str],
    shard_configs: Dict,
    index_maps: Dict,
    entity_id_columns: Optional[Dict[str, str]] = None,
    entity_indexes: Optional[Dict] = None,
    intern_new_entities: bool = True,
    chunk_rows: int = 1 << 16,
    column_names=None,
    decode_workers: Optional[int] = None,
    depth: int = DEFAULT_QUEUE_DEPTH,
    pad_rows_to: Optional[int] = None,
    overlap: bool = True,
    telemetry_label: str = "ingest",
    stats: Optional[PipelineStats] = None,
    retry: Optional[RetryPolicy] = None,
) -> Iterator[BatchChunk]:
    """The full pipeline: decode → assemble → h2d, yielding device-resident
    GameBatch chunks the consumer's jitted compute overlaps with.

    ``pad_rows_to`` pads every chunk to a row-count multiple (weight-0 rows,
    -1 entity ids) and buckets sparse nnz widths to powers of two — the
    retrace-free scoring contract. Leave None for exact-shape chunks (e.g.
    when chunks will be concatenated into one batch).

    ``overlap=False`` is the serial per-chunk control: identical stage
    functions run inline on the consumer thread — bit-identical chunks,
    no threads. Telemetry lands in utils/timed.py under
    ``telemetry_label`` either way.

    ``retry`` (default :func:`default_retry_policy`) governs transient-error
    backoff per stage plus a shared poisoned-chunk skip budget. Assemble
    retries are safe: the assembler mutates its interning/uid state only
    after a chunk fully assembles.
    """
    from photon_tpu.io.columnar import stream_avro_columnar
    from photon_tpu.io.data_reader import _expand_paths

    if stats is None:
        stats = PipelineStats(overlapped=overlap)
    else:
        stats.overlapped = overlap
    record_pipeline(telemetry_label, stats)
    expanded = _expand_paths(paths)
    assemble = _make_assembler(
        shard_configs, index_maps, entity_id_columns,
        entity_indexes if entity_indexes is not None else {},
        intern_new_entities, column_names,
    )

    def source():
        return stream_avro_columnar(expanded, chunk_rows, workers=decode_workers)

    stages = [
        ("assemble", _faulted("ingest.assemble", assemble), chunk_nbytes),
        ("h2d", _faulted("ingest.h2d", lambda c: _h2d(c, pad_rows_to)), lambda c: 0),
    ]
    source_hook = _source_fault_hook if faults.active("ingest.source") else None
    t0 = time.perf_counter()
    try:
        yield from _run_staged(
            source, columnar_nbytes, stages, stats, depth, overlap,
            retry=retry, source_hook=source_hook,
        )
    finally:
        stats.wall_s = time.perf_counter() - t0
        stats.log(telemetry_label)
        _finalize_pipeline_telemetry(telemetry_label, stats)


def _finalize_pipeline_telemetry(label: str, stats: PipelineStats) -> None:
    """Flush one pipeline run into the run report: stage metrics into the
    registry plus one externally-timed span covering the whole stream.
    Guarded — this runs in a ``finally`` while a pipeline failure may be
    propagating, and telemetry must never mask that exception."""
    try:
        stats.publish(label)
        record_span(f"pipeline/{label}", stats.wall_s)
    except Exception:
        logger.exception("pipeline telemetry publish failed for %s", label)


def device_chunks_from(
    host_chunks: Callable[[], Iterator[BatchChunk]],
    depth: int = DEFAULT_QUEUE_DEPTH,
    pad_rows_to: Optional[int] = None,
    overlap: bool = True,
    telemetry_label: str = "replay",
    stats: Optional[PipelineStats] = None,
    retry: Optional[RetryPolicy] = None,
) -> Iterator[BatchChunk]:
    """Run only the h2d stage over an existing host-chunk source (a replay
    cache pass): placement overlaps compute, decode/assembly already paid."""
    if stats is None:
        stats = PipelineStats(overlapped=overlap)
    else:
        stats.overlapped = overlap
    record_pipeline(telemetry_label, stats)
    stages = [("h2d", _faulted("ingest.h2d", lambda c: _h2d(c, pad_rows_to)), lambda c: 0)]
    t0 = time.perf_counter()
    try:
        yield from _run_staged(
            host_chunks, chunk_nbytes, stages, stats, depth, overlap,
            source_name="assemble", retry=retry,
        )
    finally:
        stats.wall_s = time.perf_counter() - t0
        stats.log(telemetry_label)
        _finalize_pipeline_telemetry(telemetry_label, stats)


def materialize_game_batch(chunks: Iterator[BatchChunk]):
    """Concatenate device chunks (use pad_rows_to=None sources) into one
    GameBatch: each chunk's H2D overlaps the previous chunks' device concat
    via async dispatch — the pipelined replacement for slurp-then-put."""
    from photon_tpu.io.data_reader import concat_game_batches

    batches = [c.batch for c in chunks]
    if not batches:
        raise ValueError("streaming ingest read zero data blocks")
    return concat_game_batches(batches)


class ChunkReplayCache:
    """Host-side chunk cache for multi-pass streaming training: decode once,
    replay many.

    Pass 1 pulls from ``source_factory()`` (typically
    :func:`stream_host_batches` — decode + assembly) and tees each chunk
    into memory while the running total stays within ``byte_budget``. Later
    passes replay from memory — decode and assembly are never paid again.
    If the stream outgrows the budget, the overflow SPILLS TO DISK: the
    in-memory prefix stays put and every later chunk is pickled to a spool
    file under ``spill_dir``, so replay passes read memory + disk in the
    original order and the decode is still paid exactly once. Host memory
    stays bounded by the budget plus one in-flight chunk. ``spill_dir`` of
    ``"auto"`` (the default) lazily creates a temp directory on first
    spill; ``None`` restores the legacy fallback — drop the cache and
    re-stream every pass from the source.

    Single-consumer: passes must not interleave. A pass abandoned mid-way
    leaves the cache incomplete (and deletes its spool); the next pass
    re-streams.
    """

    def __init__(
        self,
        source_factory: Callable[[], Iterator[BatchChunk]],
        byte_budget: int = 1 << 30,
        nbytes: Callable = chunk_nbytes,
        spill_dir: Optional[str] = "auto",
    ):
        self._factory = source_factory
        self.byte_budget = int(byte_budget)
        self._nbytes = nbytes
        self._spill_dir = spill_dir
        self._guard = resources.DiskBudgetGuard("spool.write")
        self._spool_path: Optional[str] = None
        self._spool_count = 0
        self._spool_seq = 0
        self._chunks: List[BatchChunk] = []
        self._complete = False
        self.spilled = False
        self.cached_bytes = 0
        self.spilled_bytes = 0
        self.source_passes = 0
        self.replay_passes = 0

    def _reset_cache(self) -> None:
        self._chunks, self.cached_bytes = [], 0
        self.spilled_bytes = 0
        self._spool_count = 0
        if self._spool_path is not None:
            try:
                os.unlink(self._spool_path)
            except OSError:
                pass
            self._spool_path = None

    def _open_spool(self):
        if self._spill_dir == "auto":
            self._spill_dir = tempfile.mkdtemp(prefix="photon-replay-")
        os.makedirs(self._spill_dir, exist_ok=True)
        self._spool_path = os.path.join(
            self._spill_dir, f"spool-{self._spool_seq:04d}.pkl"
        )
        self._spool_seq += 1
        return open(self._spool_path, "wb")

    def _read_spool(self) -> Iterator[BatchChunk]:
        with open(self._spool_path, "rb") as fh:
            for _ in range(self._spool_count):
                yield pickle.load(fh)

    def _spill_failed(self, exc: OSError, spool) -> None:
        """ENOSPC (or any OSError) on the spool mid-spill: fall back to the
        legacy re-stream path instead of propagating into the training loop.
        Closes and deletes the partial spool file, stops caching, and
        disables disk spill for this cache's lifetime — the current pass
        keeps yielding straight from the source, and later passes re-stream
        (decode is re-paid, training is not interrupted)."""
        if spool is not None:
            try:
                spool.close()
            except OSError:
                pass
        self._guard.record(exc)
        try:
            registry().counter("replay_spill_fallbacks_total").inc()
        except Exception:
            pass
        logger.warning(
            "replay-cache spool write failed under %s; falling back to "
            "re-streaming from source (decode re-paid each pass): %s",
            self._spill_dir, exc,
        )
        self._reset_cache()
        self._spill_dir = None
        self.spilled = True

    def close(self) -> None:
        """Drop the cache and delete any spool file."""
        self._complete = False
        self._reset_cache()

    def _recover_torn_spool(self, exc: Exception, already_yielded: int):
        """A replay pass hit a torn/partial spool file (truncated pickle,
        deleted file, bit rot). Decode order is deterministic, so recovery
        is exact: drop the cache (deleting the bad spool), re-stream the
        SOURCE, and skip the chunks this pass already yielded from the
        memory prefix + intact spool head — the consumer sees the same
        chunk sequence it would have without the tear."""
        reg = registry()
        reg.counter("replay_spool_torn_total").inc()
        logger.warning(
            "torn replay spool %s after %d chunk(s); re-streaming this pass "
            "from source: %s", self._spool_path, already_yielded, exc,
        )
        self._complete = False
        self._reset_cache()
        self.source_passes += 1
        reg.counter("replay_cache_source_passes_total").inc()
        for i, chunk in enumerate(self._factory()):
            if i >= already_yielded:
                yield chunk

    def __iter__(self) -> Iterator[BatchChunk]:
        reg = registry()
        if self._complete:
            self.replay_passes += 1
            reg.counter("replay_cache_replay_passes_total").inc()
            yield from self._chunks
            if self._spool_count:
                yielded = len(self._chunks)
                spool_iter = self._read_spool()
                while True:
                    try:
                        chunk = next(spool_iter)
                    except StopIteration:
                        break
                    except (OSError, EOFError, pickle.UnpicklingError,
                            ValueError) as exc:
                        yield from self._recover_torn_spool(exc, yielded)
                        return
                    yield chunk
                    yielded += 1
            return
        self.source_passes += 1
        reg.counter("replay_cache_source_passes_total").inc()
        self._reset_cache()
        # A memory-only cache that overflowed once never tries again (the
        # stream is known not to fit); a disk-backed cache retries, since a
        # fresh pass rebuilds both the memory prefix and the spool.
        caching = not self.spilled or self._spill_dir is not None
        spool = None
        finished = False
        try:
            for chunk in self._factory():
                if caching:
                    cost = self._nbytes(chunk)
                    # Host memory pressure tightens the replay budget: stop
                    # growing the in-RAM prefix early and spill (or fall
                    # back) even though the nominal byte budget has room.
                    over = (self.cached_bytes + cost > self.byte_budget
                            or resources.memory_pressure())
                    if spool is None and over:
                        if self._spill_dir is None:
                            self.spilled, caching = True, False
                            self._reset_cache()
                            reg.counter("replay_cache_spills_total").inc()
                        else:
                            try:
                                self._guard.check()
                                spool = self._open_spool()
                            except OSError as exc:
                                self._spill_failed(exc, spool)
                                spool, caching = None, False
                            else:
                                self.spilled = True
                                reg.counter("replay_cache_spills_total").inc()
                    if caching:
                        if spool is None:
                            self._chunks.append(chunk)
                            self.cached_bytes += cost
                        else:
                            try:
                                self._guard.check()
                                pickle.dump(
                                    chunk, spool,
                                    protocol=pickle.HIGHEST_PROTOCOL,
                                )
                                spool.flush()
                            except OSError as exc:
                                self._spill_failed(exc, spool)
                                spool, caching = None, False
                            else:
                                self._spool_count += 1
                                self.spilled_bytes += cost
                                reg.counter(
                                    "replay_cache_spilled_bytes_total"
                                ).inc(cost)
                yield chunk
            finished = True
        finally:
            if spool is not None:
                spool.close()
            if finished and caching:
                self._complete = True
            elif not finished:
                self._reset_cache()
            reg.gauge("replay_cache_cached_bytes").set(self.cached_bytes)
            reg.gauge("replay_cache_spilled_bytes").set(self.spilled_bytes)
            reg.gauge("replay_cache_spilled").set(int(self.spilled))
