"""Columnar Avro ingest: native block decode + vectorized batch assembly.

The row-oriented reader (io/avro.py + io/data_reader.py) walks every record
field-by-field in Python — fine for model files, too slow to keep a TPU fed
(SURVEY.md §7 hard part #4: ingest throughput). This module decodes container
blocks into COLUMNS in one C++ pass (photon_tpu/native/avro_decode.cpp):
numeric columns, interned string columns, feature bags as CSR
(offsets/key-ids/values) and metadata triplets, with all string interning
done natively. Python's remaining work is vectorized numpy: one IndexMap
lookup per DISTINCT feature key, one scatter per shard.

Falls back to the pure-Python codec whenever the native library is missing
or the writer schema doesn't fit the supported program (the caller sees
identical results either way — parity-tested).
"""

from __future__ import annotations

import ctypes
import dataclasses
import os
import subprocess
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_tpu.io.avro import MAGIC, SYNC_SIZE, _Codec, _META_SCHEMA, _Reader

# Program opcodes (avro_decode.cpp header).
_OP_DOUBLE, _OP_OPT_DOUBLE, _OP_STR, _OP_OPT_STR = 0, 1, 2, 3
_OP_BAG, _OP_OPT_MAP, _OP_MAP, _OP_FLOAT, _OP_LONG = 4, 5, 6, 7, 8


@dataclasses.dataclass
class FeatureBagColumn:
    offsets: np.ndarray  # (n+1,) int64 CSR row offsets
    key_ids: np.ndarray  # (nnz,) int32 interned feature keys
    values: np.ndarray  # (nnz,) float64


@dataclasses.dataclass
class ColumnarRows:
    """Struct-of-arrays view of a training-row file set."""

    n: int
    numeric: Dict[str, np.ndarray]  # field -> float64, NaN where null
    longs: Dict[str, np.ndarray]  # long fields -> exact int64 (ids > 2^53)
    strings: Dict[str, np.ndarray]  # field -> int32 intern ids, -1 null
    bags: Dict[str, FeatureBagColumn]
    meta_rows: np.ndarray  # (m,) int32 record index
    meta_keys: np.ndarray  # (m,) int32 intern ids (metadata key)
    meta_vals: np.ndarray  # (m,) int32 intern ids (metadata value)
    intern: List[str]  # id -> string

    def meta_column(self, name: str) -> np.ndarray:
        """Per-record intern id of metadataMap[name] (-1 where absent)."""
        out = np.full(self.n, -1, np.int32)
        try:
            key_id = self.intern.index(name)
        except ValueError:
            return out
        sel = self.meta_keys == key_id
        out[self.meta_rows[sel]] = self.meta_vals[sel]
        return out


def _lib_path() -> str:
    return os.path.join(
        os.path.dirname(__file__), "..", "native", "libavro_decode.so"
    )


_lib = None
_lib_failed = False


def _load_lib():
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    so = os.path.abspath(_lib_path())
    src = os.path.join(os.path.dirname(so), "avro_decode.cpp")
    # Build when the library is missing (source-only distribution; ci.sh
    # `native` is the sanctioned build). Rebuild-on-source-mtime is a dev
    # convenience only — writes into an installed package dir, so opt-in
    # (ADVICE r3).
    src_newer = (
        os.path.exists(src)
        and os.path.exists(so)
        and os.path.getmtime(src) > os.path.getmtime(so)
    )
    rebuild_enabled = os.environ.get("PHOTON_TPU_NATIVE_REBUILD") == "1"
    if src_newer and not rebuild_enabled:
        import warnings

        warnings.warn(
            "photon_tpu/native/avro_decode.cpp is newer than the built "
            "libavro_decode.so — run `./ci.sh native` or set "
            "PHOTON_TPU_NATIVE_REBUILD=1 to rebuild; loading the stale "
            "binary",
            RuntimeWarning,
            stacklevel=2,
        )
    if not os.path.exists(so) or (src_newer and rebuild_enabled):
        try:
            subprocess.run(
                ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", so, src],
                check=True, capture_output=True,
            )
        except (subprocess.CalledProcessError, FileNotFoundError):
            _lib_failed = True
            return None
    try:
        lib = ctypes.CDLL(so)
    except OSError:
        _lib_failed = True
        return None
    lib.avro_dec_new.restype = ctypes.c_void_p
    lib.avro_dec_new.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.avro_dec_block.restype = ctypes.c_int
    lib.avro_dec_block.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
    ]
    for name, res in [
        ("avro_dec_num_records", ctypes.c_int64),
        ("avro_dec_numeric", ctypes.POINTER(ctypes.c_double)),
        ("avro_dec_longcol", ctypes.POINTER(ctypes.c_int64)),
        ("avro_dec_strcol", ctypes.POINTER(ctypes.c_int32)),
        ("avro_dec_bag_len", ctypes.c_int64),
        ("avro_dec_bag_offsets", ctypes.POINTER(ctypes.c_int64)),
        ("avro_dec_bag_keys", ctypes.POINTER(ctypes.c_int32)),
        ("avro_dec_bag_values", ctypes.POINTER(ctypes.c_double)),
        ("avro_dec_meta_len", ctypes.c_int64),
        ("avro_dec_meta_rows", ctypes.POINTER(ctypes.c_int32)),
        ("avro_dec_meta_keys", ctypes.POINTER(ctypes.c_int32)),
        ("avro_dec_meta_vals", ctypes.POINTER(ctypes.c_int32)),
        ("avro_dec_intern_count", ctypes.c_int64),
        ("avro_dec_intern_blob_len", ctypes.c_int64),
        ("avro_dec_intern_blob", ctypes.POINTER(ctypes.c_char)),
        ("avro_dec_intern_offsets", ctypes.POINTER(ctypes.c_int64)),
    ]:
        fn = getattr(lib, name)
        fn.restype = res
        fn.argtypes = (
            [ctypes.c_void_p, ctypes.c_int]
            if name in ("avro_dec_numeric", "avro_dec_longcol", "avro_dec_strcol",
                        "avro_dec_bag_len", "avro_dec_bag_offsets",
                        "avro_dec_bag_keys", "avro_dec_bag_values")
            else [ctypes.c_void_p]
        )
    lib.avro_dec_free.argtypes = [ctypes.c_void_p]
    lib.avro_dec_free.restype = None
    _lib = lib
    return lib


def _type_name(t) -> Optional[str]:
    if isinstance(t, str):
        return t
    if isinstance(t, dict):
        return t.get("type")
    return None


def _is_feature_bag(t) -> bool:
    if not (isinstance(t, dict) and t.get("type") == "array"):
        return False
    items = t.get("items")
    if isinstance(items, str):  # by-name reference to a prior record def
        return items.split(".")[-1] in ("FeatureAvro", "NameTermValueAvro")
    if not (isinstance(items, dict) and items.get("type") == "record"):
        return False
    fields = items.get("fields", [])
    return (
        len(fields) == 3
        and [f["name"] for f in fields] == ["name", "term", "value"]
        and [_type_name(f["type"]) for f in fields] == ["string", "string", "double"]
    )


def compile_program(schema) -> Optional[Tuple[bytes, List[str]]]:
    """Writer schema → (opcode bytes, field names), or None if unsupported."""
    if not (isinstance(schema, dict) and schema.get("type") == "record"):
        return None
    ops: List[int] = []
    names: List[str] = []
    for f in schema.get("fields", []):
        t = f["type"]
        if t == "double":
            ops.append(_OP_DOUBLE)
        elif t == "float":
            ops.append(_OP_FLOAT)
        elif t in ("int", "long"):
            ops.append(_OP_LONG)
        elif t == "string":
            ops.append(_OP_STR)
        elif isinstance(t, list) and t == ["null", "double"]:
            ops.append(_OP_OPT_DOUBLE)
        elif isinstance(t, list) and t == ["null", "string"]:
            ops.append(_OP_OPT_STR)
        elif _is_feature_bag(t):
            ops.append(_OP_BAG)
        elif (
            isinstance(t, list)
            and len(t) == 2
            and t[0] == "null"
            and isinstance(t[1], dict)
            and t[1].get("type") == "map"
            and t[1].get("values") == "string"
        ):
            ops.append(_OP_OPT_MAP)
        elif isinstance(t, dict) and t.get("type") == "map" and t.get("values") == "string":
            ops.append(_OP_MAP)
        else:
            return None
        names.append(f["name"])
    return bytes(ops), names


_HEADER_PROBE = 1 << 16  # initial read: magic + metadata map + sync


def _read_header(f):
    """Parse an object-container header from an open file. Returns
    (schema, codec, sync, byte offset of the first block)."""
    buf = f.read(_HEADER_PROBE)
    if buf[:4] != MAGIC:
        raise ValueError("not an Avro object container file")
    while True:  # metadata map can exceed the probe; grow geometrically
        try:
            r = _Reader(buf)
            r.pos = 4
            meta = _Codec(_META_SCHEMA).decode(r)
            sync = r.read_fixed(SYNC_SIZE)
            if len(sync) != SYNC_SIZE:  # silently-short slice = truncated
                raise IndexError("truncated header")
            break
        except (IndexError, ValueError):
            more = f.read(len(buf))
            if not more:
                raise
            buf += more
    f.seek(r.pos)  # rewind to the first block (probe over-read)
    import json

    schema = json.loads(meta["avro.schema"].decode())
    codec = meta.get("avro.codec", b"null").decode()
    if codec not in ("null", "deflate"):
        raise ValueError(f"unsupported avro codec {codec}")
    return schema, codec, sync, r.pos


def _read_block_varint(f) -> Optional[int]:
    """Read one zigzag varint directly from a file (None at clean EOF)."""
    shift = 0
    acc = 0
    first = f.read(1)
    if not first:
        return None
    b = first[0]
    while True:
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
        nxt = f.read(1)
        if not nxt:
            raise ValueError("truncated varint in container block header")
        b = nxt[0]
    return (acc >> 1) ^ -(acc & 1)


def stream_raw_blocks(path: str):
    """(schema, codec, generator of (count, COMPRESSED bytes)) — reads the
    file incrementally so host memory stays bounded by ONE block, not the
    file (the round-3 reader slurped the whole container and materialized
    every decompressed block; reference streams per-partition,
    AvroDataReader.scala:165-209). Decompression is left to the consumer
    so parallel decoders can decompress off the reader's thread.

    The header parse opens/closes the file immediately; the generator
    reopens it lazily on first consumption — an UNSTARTED generator holds
    no file descriptor, so compiling schemas for thousands of paths never
    exhausts the FD limit."""
    with open(path, "rb") as f:
        schema, codec, sync, _pos = _read_header(f)
        start = f.tell()

    def gen():
        with open(path, "rb") as f:
            f.seek(start)
            while True:
                count = _read_block_varint(f)
                if count is None:
                    return
                size = _read_block_varint(f)
                data = f.read(size)
                if len(data) != size:
                    raise ValueError("truncated container block")
                if f.read(SYNC_SIZE) != sync:
                    raise ValueError("bad sync marker (corrupt file)")
                yield count, data

    return schema, codec, gen()


def _inflate(codec: str, data: bytes) -> bytes:
    """Undo a container block's codec (the avro writer's inverse)."""
    return zlib.decompress(data, -15) if codec == "deflate" else data


def stream_blocks(path: str):
    """(schema, generator of (count, decompressed bytes)): the
    decompressed-block view of ``stream_raw_blocks`` (same laziness)."""
    schema, codec, raw = stream_raw_blocks(path)

    def gen():
        for count, data in raw:
            yield count, _inflate(codec, data)

    return schema, gen()


def _extract_columns(lib, ctx, program, names) -> ColumnarRows:
    """Copy a decode context's accumulated columns out into numpy arrays."""
    n = int(lib.avro_dec_num_records(ctx))

    def arr(ptr, count, dtype):
        if count == 0:
            return np.empty(0, dtype)
        return np.ctypeslib.as_array(ptr, shape=(count,)).astype(dtype, copy=True)

    numeric: Dict[str, np.ndarray] = {}
    longs: Dict[str, np.ndarray] = {}
    strings: Dict[str, np.ndarray] = {}
    bags: Dict[str, FeatureBagColumn] = {}
    for i, op in enumerate(program):
        fname = names[i]
        if op in (_OP_DOUBLE, _OP_OPT_DOUBLE, _OP_FLOAT, _OP_LONG):
            numeric[fname] = arr(lib.avro_dec_numeric(ctx, i), n, np.float64)
            if op == _OP_LONG:
                longs[fname] = arr(lib.avro_dec_longcol(ctx, i), n, np.int64)
        elif op in (_OP_STR, _OP_OPT_STR):
            strings[fname] = arr(lib.avro_dec_strcol(ctx, i), n, np.int32)
        elif op == _OP_BAG:
            nnz = int(lib.avro_dec_bag_len(ctx, i))
            bags[fname] = FeatureBagColumn(
                offsets=arr(lib.avro_dec_bag_offsets(ctx, i), n + 1, np.int64),
                key_ids=arr(lib.avro_dec_bag_keys(ctx, i), nnz, np.int32),
                values=arr(lib.avro_dec_bag_values(ctx, i), nnz, np.float64),
            )
    m = int(lib.avro_dec_meta_len(ctx))
    meta_rows = arr(lib.avro_dec_meta_rows(ctx), m, np.int32)
    meta_keys = arr(lib.avro_dec_meta_keys(ctx), m, np.int32)
    meta_vals = arr(lib.avro_dec_meta_vals(ctx), m, np.int32)

    n_intern = int(lib.avro_dec_intern_count(ctx))
    blob_len = int(lib.avro_dec_intern_blob_len(ctx))
    blob = ctypes.string_at(lib.avro_dec_intern_blob(ctx), blob_len)
    offs = arr(lib.avro_dec_intern_offsets(ctx), n_intern + 1, np.int64)
    intern = [
        blob[offs[i]:offs[i + 1]].decode("utf-8") for i in range(n_intern)
    ]
    return ColumnarRows(
        n=n, numeric=numeric, longs=longs, strings=strings, bags=bags,
        meta_rows=meta_rows, meta_keys=meta_keys, meta_vals=meta_vals,
        intern=intern,
    )


def _compile_for_paths(paths: Sequence[str]):
    """(program, names, list of per-path (codec, raw-block generator)) or
    None when any schema falls outside the supported program / schemas
    differ."""
    program = names = None
    gens = []
    for path in paths:
        schema, codec, gen = stream_raw_blocks(path)
        compiled = compile_program(schema)
        if compiled is None or (
            program is not None
            and (compiled[0] != program or compiled[1] != names)
        ):
            gen.close()
            for _c, g in gens:
                g.close()
            return None
        if program is None:
            program, names = compiled
        gens.append((codec, gen))
    return program, names, gens


def read_avro_columnar(paths: Sequence[str]) -> Optional[ColumnarRows]:
    """Decode container files into columns via the native decoder. Blocks
    stream through one at a time (bounded by a single decompressed block,
    not the file). Returns None when the native path is unavailable or the
    schema is outside the supported program (callers fall back to rows)."""
    lib = _load_lib()
    if lib is None:
        return None
    compiled = _compile_for_paths(paths)
    if compiled is None:
        return None
    program, names, gens = compiled

    ctx = lib.avro_dec_new(program, len(program))
    try:
        for codec, gen in gens:
            for count, data in gen:
                data = _inflate(codec, data)
                rc = lib.avro_dec_block(ctx, data, len(data), count)
                if rc != 0:
                    return None  # malformed vs program: Python-codec fallback
        return _extract_columns(lib, ctx, program, names)
    finally:
        lib.avro_dec_free(ctx)
        for _c, g in gens:
            g.close()


def _cgroup_quota_cores() -> Optional[int]:
    """Cores granted by the cgroup CPU controller, or None when unlimited.

    sched_getaffinity over-reports in quota-limited containers (a pod
    pinned to 2 CPUs of quota still sees every host core in its mask), so
    the decode pool would oversubscribe and thrash. v2 reads
    ``cpu.max`` ("<quota> <period>" or "max ..."); v1 reads
    ``cpu.cfs_quota_us`` / ``cpu.cfs_period_us`` (-1 = unlimited).
    Fractional quotas round UP: 1.5 CPUs of quota decodes with 2 workers.
    """
    for quota_path, period_path in (
        ("/sys/fs/cgroup/cpu.max", None),  # v2: one file, "quota period"
        (
            "/sys/fs/cgroup/cpu/cpu.cfs_quota_us",  # v1 pair
            "/sys/fs/cgroup/cpu/cpu.cfs_period_us",
        ),
    ):
        try:
            with open(quota_path) as f:
                first = f.read().split()
            if period_path is None:
                quota_s, period_s = first[0], first[1]
            else:
                quota_s = first[0]
                with open(period_path) as f:
                    period_s = f.read().split()[0]
            if quota_s in ("max", "-1"):
                return None
            quota, period = int(quota_s), int(period_s)
            if quota <= 0 or period <= 0:
                return None
            return max(1, -(-quota // period))  # ceil division
        except (OSError, ValueError, IndexError):
            continue
    return None


def _available_cores() -> int:
    """Cores available to THIS process: PHOTON_TPU_DECODE_WORKERS env
    override first, else min(affinity mask, cgroup CPU quota) — the quota
    bound because sched_getaffinity over-reports in quota-limited
    containers (sched_getaffinity is Linux-only; cpu_count is the
    portable fallback)."""
    env = os.environ.get("PHOTON_TPU_DECODE_WORKERS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass  # malformed override: fall through to detection
    cores = None
    getaff = getattr(os, "sched_getaffinity", None)
    if getaff is not None:
        try:
            cores = max(1, len(getaff(0)))
        except OSError:  # pragma: no cover - exotic platforms
            pass
    if cores is None:
        cores = max(1, os.cpu_count() or 1)
    quota = _cgroup_quota_cores()
    if quota is not None:
        cores = min(cores, quota)
    return max(1, cores)


def merge_columnar(parts: Sequence[ColumnarRows]) -> ColumnarRows:
    """Concatenate per-block/per-chunk ColumnarRows into one, re-interning
    strings into a single table (first-occurrence order over parts, which
    matches what a serial decode of the same blocks would produce)."""
    if len(parts) == 1:
        return parts[0]
    table: Dict[str, int] = {}
    intern: List[str] = []
    luts = []
    for p in parts:
        lut = np.empty(len(p.intern) + 1, np.int32)  # [-1] slot for nulls
        lut[-1] = -1
        for i, s in enumerate(p.intern):
            idx = table.get(s)
            if idx is None:
                idx = len(intern)
                table[s] = idx
                intern.append(s)
            lut[i] = idx
        luts.append(lut)

    n = sum(p.n for p in parts)
    row_off = np.cumsum([0] + [p.n for p in parts])
    numeric = {
        k: np.concatenate([p.numeric[k] for p in parts])
        for k in parts[0].numeric
    }
    longs = {
        k: np.concatenate([p.longs[k] for p in parts]) for k in parts[0].longs
    }
    strings = {
        k: np.concatenate([lut[p.strings[k]] for p, lut in zip(parts, luts)])
        for k in parts[0].strings
    }
    bags = {}
    for k in parts[0].bags:
        offs_parts, keys_parts, vals_parts = [], [], []
        nnz_off = 0
        for p, lut in zip(parts, luts):
            b = p.bags[k]
            offs_parts.append(
                (b.offsets if nnz_off == 0 else b.offsets[1:]) + nnz_off
            )
            keys_parts.append(lut[b.key_ids])
            vals_parts.append(b.values)
            nnz_off += int(b.offsets[-1])
        bags[k] = FeatureBagColumn(
            offsets=np.concatenate(offs_parts),
            key_ids=np.concatenate(keys_parts),
            values=np.concatenate(vals_parts),
        )
    meta_rows = np.concatenate([
        p.meta_rows + np.int32(row_off[i]) for i, p in enumerate(parts)
    ])
    meta_keys = np.concatenate([
        lut[p.meta_keys] for p, lut in zip(parts, luts)
    ])
    meta_vals = np.concatenate([
        lut[p.meta_vals] for p, lut in zip(parts, luts)
    ])
    return ColumnarRows(
        n=n, numeric=numeric, longs=longs, strings=strings, bags=bags,
        meta_rows=meta_rows, meta_keys=meta_keys, meta_vals=meta_vals,
        intern=intern,
    )


def stream_avro_columnar(
    paths: Sequence[str],
    chunk_rows: int = 1 << 16,
    workers: Optional[int] = None,
):
    """Yield ColumnarRows chunks of >= chunk_rows rows (block-aligned):
    the streaming ingest path (SURVEY §7 hard part 4, VERDICT r3 #5). Host
    memory is bounded by one chunk + a bounded window of in-flight blocks,
    never the file. Raises (rather than returning None) when the native
    decoder or schema can't serve the stream — streaming callers need a
    hard error, not a silent slurp.

    ``workers`` > 1 decodes container blocks CONCURRENTLY — zlib and the
    native decoder both release the GIL, and blocks are independent (the
    Spark-partition analogue, AvroDataReader.scala:165-209), so decode
    scales with cores while results are merged back in file order
    (bit-identical to the serial path, parity-tested). Default: one worker
    per available core."""
    lib = _load_lib()
    if lib is None:
        raise RuntimeError("native decoder unavailable for streaming ingest")
    compiled = _compile_for_paths(paths)
    if compiled is None:
        raise ValueError(
            "schema outside the native columnar program (or heterogeneous "
            "schemas); streaming ingest unavailable"
        )
    program, names, gens = compiled
    if workers is None:
        workers = min(16, _available_cores())

    def decode_one(codec: str, count: int, data: bytes) -> ColumnarRows:
        data = _inflate(codec, data)
        ctx = lib.avro_dec_new(program, len(program))
        try:
            rc = lib.avro_dec_block(ctx, data, len(data), count)
            if rc != 0:
                raise ValueError("malformed container block")
            return _extract_columns(lib, ctx, program, names)
        finally:
            lib.avro_dec_free(ctx)

    def blocks():
        for codec, gen in gens:
            for count, data in gen:
                yield codec, count, data

    try:
        if workers <= 1:
            # Serial: one long-lived ctx accumulates blocks per chunk (no
            # merge cost, identical output).
            ctx = lib.avro_dec_new(program, len(program))
            try:
                for codec, count, data in blocks():
                    data = _inflate(codec, data)
                    rc = lib.avro_dec_block(ctx, data, len(data), count)
                    if rc != 0:
                        raise ValueError("malformed container block")
                    if int(lib.avro_dec_num_records(ctx)) >= chunk_rows:
                        yield _extract_columns(lib, ctx, program, names)
                        lib.avro_dec_free(ctx)
                        ctx = lib.avro_dec_new(program, len(program))
                if int(lib.avro_dec_num_records(ctx)) > 0:
                    yield _extract_columns(lib, ctx, program, names)
            finally:
                lib.avro_dec_free(ctx)
            return

        import collections
        from concurrent.futures import ThreadPoolExecutor

        pool = ThreadPoolExecutor(max_workers=workers)
        try:
            pending = collections.deque()  # futures in FILE ORDER
            buffered: List[ColumnarRows] = []
            buffered_rows = 0
            source = blocks()

            def drain(fut):
                nonlocal buffered_rows
                part = fut.result()
                buffered.append(part)
                buffered_rows += part.n

            exhausted = False
            while not exhausted or pending:
                while not exhausted and len(pending) < 2 * workers:
                    try:
                        codec, count, data = next(source)
                    except StopIteration:
                        exhausted = True
                        break
                    pending.append(pool.submit(decode_one, codec, count, data))
                if pending:
                    drain(pending.popleft())
                if buffered_rows >= chunk_rows:
                    yield merge_columnar(buffered)
                    buffered, buffered_rows = [], 0
            if buffered:
                yield merge_columnar(buffered)
        finally:
            # An abandoned generator or a decode error must not block on
            # (or waste) the ~2*workers queued read-ahead blocks.
            pool.shutdown(wait=True, cancel_futures=True)
    finally:
        for _c, g in gens:
            g.close()
