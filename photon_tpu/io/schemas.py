"""Avro schemas compatible with the reference's data formats.

Field names/types mirror the reference's 12 .avsc files
(photon-avro-schemas/src/main/avro/, inventory SURVEY.md §2.4) so that data
and model files interoperate; the schemas are declared here as Python dicts
consumed by photon_tpu.io.avro. Namespaces are preserved so Java readers
resolve the records.
"""

NAME_TERM_VALUE_SCHEMA = {
    "type": "record",
    "name": "NameTermValueAvro",
    "namespace": "com.linkedin.photon.avro.generated",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string"},
        {"name": "value", "type": "double"},
    ],
}

FEATURE_SCHEMA = {
    "type": "record",
    "name": "FeatureAvro",
    "namespace": "com.linkedin.photon.avro.generated",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string"},
        {"name": "value", "type": "double"},
    ],
}

TRAINING_EXAMPLE_SCHEMA = {
    "type": "record",
    "name": "TrainingExampleAvro",
    "namespace": "com.linkedin.photon.avro.generated",
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "label", "type": "double"},
        {"name": "features", "type": {"type": "array", "items": FEATURE_SCHEMA}},
        {
            "name": "metadataMap",
            "type": ["null", {"type": "map", "values": "string"}],
            "default": None,
        },
        {"name": "weight", "type": ["null", "double"], "default": None},
        {"name": "offset", "type": ["null", "double"], "default": None},
    ],
}

RESPONSE_PREDICTION_SCHEMA = {
    "type": "record",
    "name": "SimplifiedResponsePrediction",
    "namespace": "com.linkedin.photon.avro.generated",
    "fields": [
        {"name": "response", "type": "double"},
        {"name": "features", "type": {"type": "array", "items": "FeatureAvro"}},
        {"name": "weight", "type": "double", "default": 1.0},
        {"name": "offset", "type": "double", "default": 0.0},
    ],
}
# FeatureAvro must be defined inline on first use for self-contained files:
RESPONSE_PREDICTION_SCHEMA["fields"][1]["type"]["items"] = FEATURE_SCHEMA

BAYESIAN_LINEAR_MODEL_SCHEMA = {
    "type": "record",
    "name": "BayesianLinearModelAvro",
    "namespace": "com.linkedin.photon.avro.generated",
    "fields": [
        {"name": "modelId", "type": "string"},
        {"name": "modelClass", "type": ["null", "string"], "default": None},
        {"name": "means", "type": {"type": "array", "items": NAME_TERM_VALUE_SCHEMA}},
        {
            "name": "variances",
            "type": ["null", {"type": "array", "items": "NameTermValueAvro"}],
            "default": None,
        },
        {"name": "lossFunction", "type": ["null", "string"], "default": None},
    ],
}

SCORING_RESULT_SCHEMA = {
    "type": "record",
    "name": "ScoringResultAvro",
    "namespace": "com.linkedin.photon.avro.generated",
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "label", "type": ["null", "double"], "default": None},
        {"name": "modelId", "type": "string"},
        {"name": "predictionScore", "type": "double"},
        {"name": "weight", "type": ["null", "double"], "default": None},
        {
            "name": "metadataMap",
            "type": ["null", {"type": "map", "values": "string"}],
            "default": None,
        },
    ],
}

FEATURE_SUMMARIZATION_SCHEMA = {
    "type": "record",
    "name": "FeatureSummarizationResultAvro",
    "namespace": "com.linkedin.photon.avro.generated",
    "fields": [
        {"name": "featureName", "type": "string"},
        {"name": "featureTerm", "type": "string"},
        {"name": "metrics", "type": {"type": "map", "values": "double"}},
    ],
}

LATENT_FACTOR_SCHEMA = {
    "type": "record",
    "name": "LatentFactorAvro",
    "namespace": "com.linkedin.photon.avro.generated",
    "fields": [
        {"name": "effectId", "type": "string"},
        {"name": "latentFactor", "type": {"type": "array", "items": "double"}},
    ],
}
