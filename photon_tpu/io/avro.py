"""Minimal Avro object-container codec (pure Python, stdlib only).

Role parity: the reference's data plane is Avro-on-HDFS via the Java Avro
library (photon-client data/avro/AvroUtils.scala, AvroDataReader.scala). This
image has no Avro package, so the framework ships its own schema-driven
binary codec implementing the public Avro 1.x spec subset the reference's
schemas need: records, unions, arrays, maps, strings/bytes, all primitive
types, null/deflate block codecs, object container files with sync markers.

Not a copy of any implementation — written from the published format spec.
A C++ accelerated decode path can replace the inner loop later (SURVEY.md
§2.9 optional Avro decode acceleration).
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, BinaryIO, Dict, Iterable, Iterator, List, Optional, Union

MAGIC = b"Obj\x01"
SYNC_SIZE = 16

Schema = Union[str, dict, list]


def parse_schema(schema: Union[str, dict, list]) -> Schema:
    if isinstance(schema, str) and schema.strip().startswith(("{", "[")):
        return json.loads(schema)
    return schema


def _named_types(schema: Schema, acc: Dict[str, dict]) -> None:
    """Collect named record/enum/fixed definitions for by-name references."""
    if isinstance(schema, dict):
        t = schema.get("type")
        if t in ("record", "enum", "fixed") and "name" in schema:
            acc[schema["name"]] = schema
            ns = schema.get("namespace")
            if ns:
                acc[f"{ns}.{schema['name']}"] = schema
        if t == "record":
            for f in schema.get("fields", []):
                _named_types(f["type"], acc)
        elif t == "array":
            _named_types(schema["items"], acc)
        elif t == "map":
            _named_types(schema["values"], acc)
    elif isinstance(schema, list):
        for s in schema:
            _named_types(s, acc)


# ---------------------------------------------------------------------------
# Binary encoding primitives (Avro spec: zigzag varints, little-endian IEEE)
# ---------------------------------------------------------------------------


def _write_long(out: io.BytesIO, n: int) -> None:
    n = (n << 1) ^ (n >> 63)  # zigzag
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.write(bytes((b | 0x80,)))
        else:
            out.write(bytes((b,)))
            return


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def read_long(self) -> int:
        b = self.buf
        pos = self.pos
        shift = 0
        acc = 0
        while True:
            byte = b[pos]
            pos += 1
            acc |= (byte & 0x7F) << shift
            if not byte & 0x80:
                break
            shift += 7
        self.pos = pos
        return (acc >> 1) ^ -(acc & 1)  # un-zigzag

    def read_bytes(self) -> bytes:
        n = self.read_long()
        out = self.buf[self.pos : self.pos + n]
        self.pos += n
        return out

    def read_fixed(self, n: int) -> bytes:
        out = self.buf[self.pos : self.pos + n]
        self.pos += n
        return out


# ---------------------------------------------------------------------------
# Schema-driven encode/decode
# ---------------------------------------------------------------------------


class _Codec:
    def __init__(self, schema: Schema):
        self.schema = parse_schema(schema)
        self.named: Dict[str, dict] = {}
        _named_types(self.schema, self.named)

    # --- decode ---

    def decode(self, r: _Reader, schema: Optional[Schema] = None) -> Any:
        s = self.schema if schema is None else schema
        if isinstance(s, str):
            if s in self.named:
                return self.decode(r, self.named[s])
            return self._decode_primitive(r, s)
        if isinstance(s, list):  # union
            idx = r.read_long()
            return self.decode(r, s[idx])
        t = s["type"]
        if t == "record":
            return {f["name"]: self.decode(r, f["type"]) for f in s["fields"]}
        if t == "array":
            out: List[Any] = []
            while True:
                n = r.read_long()
                if n == 0:
                    break
                if n < 0:
                    r.read_long()  # block byte size, unused
                    n = -n
                for _ in range(n):
                    out.append(self.decode(r, s["items"]))
            return out
        if t == "map":
            m: Dict[str, Any] = {}
            while True:
                n = r.read_long()
                if n == 0:
                    break
                if n < 0:
                    r.read_long()
                    n = -n
                for _ in range(n):
                    k = r.read_bytes().decode("utf-8")
                    m[k] = self.decode(r, s["values"])
            return m
        if t == "enum":
            return s["symbols"][r.read_long()]
        if t == "fixed":
            return r.read_fixed(s["size"])
        if isinstance(t, (dict, list)):
            return self.decode(r, t)
        return self._decode_primitive(r, t)

    def _decode_primitive(self, r: _Reader, t: str) -> Any:
        if t == "null":
            return None
        if t == "boolean":
            v = r.buf[r.pos]
            r.pos += 1
            return bool(v)
        if t in ("int", "long"):
            return r.read_long()
        if t == "float":
            (v,) = struct.unpack_from("<f", r.buf, r.pos)
            r.pos += 4
            return v
        if t == "double":
            (v,) = struct.unpack_from("<d", r.buf, r.pos)
            r.pos += 8
            return v
        if t == "bytes":
            return r.read_bytes()
        if t == "string":
            return r.read_bytes().decode("utf-8")
        raise ValueError(f"unknown avro type {t!r}")

    # --- encode ---

    def encode(self, out: io.BytesIO, datum: Any, schema: Optional[Schema] = None) -> None:
        s = self.schema if schema is None else schema
        if isinstance(s, str):
            if s in self.named:
                return self.encode(out, datum, self.named[s])
            return self._encode_primitive(out, datum, s)
        if isinstance(s, list):  # union: pick first matching branch
            idx = self._union_index(datum, s)
            _write_long(out, idx)
            return self.encode(out, datum, s[idx])
        t = s["type"]
        if t == "record":
            for f in s["fields"]:
                try:
                    self.encode(out, datum[f["name"]], f["type"])
                except KeyError:
                    if "default" in f:
                        self.encode(out, f["default"], f["type"])
                    else:
                        raise
            return
        if t == "array":
            items = list(datum)
            if items:
                _write_long(out, len(items))
                for it in items:
                    self.encode(out, it, s["items"])
            _write_long(out, 0)
            return
        if t == "map":
            if datum:
                _write_long(out, len(datum))
                for k, v in datum.items():
                    self._encode_primitive(out, k, "string")
                    self.encode(out, v, s["values"])
            _write_long(out, 0)
            return
        if t == "enum":
            _write_long(out, s["symbols"].index(datum))
            return
        if t == "fixed":
            out.write(datum)
            return
        if isinstance(t, (dict, list)):
            return self.encode(out, datum, t)
        return self._encode_primitive(out, datum, t)

    def _union_index(self, datum: Any, union: list) -> int:
        for i, s in enumerate(union):
            name = s if isinstance(s, str) else s.get("type")
            if datum is None and name == "null":
                return i
            if datum is not None and name != "null":
                return i
        raise ValueError(f"no union branch for {datum!r} in {union!r}")

    def _encode_primitive(self, out: io.BytesIO, datum: Any, t: str) -> None:
        if t == "null":
            return
        if t == "boolean":
            out.write(b"\x01" if datum else b"\x00")
        elif t in ("int", "long"):
            _write_long(out, int(datum))
        elif t == "float":
            out.write(struct.pack("<f", float(datum)))
        elif t == "double":
            out.write(struct.pack("<d", float(datum)))
        elif t == "bytes":
            _write_long(out, len(datum))
            out.write(datum)
        elif t == "string":
            b = datum.encode("utf-8")
            _write_long(out, len(b))
            out.write(b)
        else:
            raise ValueError(f"unknown avro type {t!r}")


# ---------------------------------------------------------------------------
# Object container files
# ---------------------------------------------------------------------------

_META_SCHEMA = {"type": "map", "values": "bytes"}


class AvroWriter:
    """Writes an Avro object-container file (codec: null or deflate)."""

    def __init__(self, path_or_file, schema: Schema, codec: str = "deflate",
                 block_records: int = 4096):
        self._own = isinstance(path_or_file, (str, os.PathLike))
        self.f: BinaryIO = open(path_or_file, "wb") if self._own else path_or_file
        self.codec = codec
        self.block_records = block_records
        self._codec = _Codec(schema)
        self.sync = os.urandom(SYNC_SIZE)
        self._buf = io.BytesIO()
        self._count = 0
        self._write_header(schema)

    def _write_header(self, schema: Schema) -> None:
        self.f.write(MAGIC)
        meta = io.BytesIO()
        mc = _Codec(_META_SCHEMA)
        mc.encode(
            meta,
            {
                "avro.schema": json.dumps(parse_schema(schema)).encode(),
                "avro.codec": self.codec.encode(),
            },
        )
        self.f.write(meta.getvalue())
        self.f.write(self.sync)

    def append(self, datum: Any) -> None:
        self._codec.encode(self._buf, datum)
        self._count += 1
        if self._count >= self.block_records:
            self._flush_block()

    def _flush_block(self) -> None:
        if self._count == 0:
            return
        data = self._buf.getvalue()
        if self.codec == "deflate":
            data = zlib.compress(data)[2:-1]  # raw deflate (no zlib header)
        head = io.BytesIO()
        _write_long(head, self._count)
        _write_long(head, len(data))
        self.f.write(head.getvalue())
        self.f.write(data)
        self.f.write(self.sync)
        self._buf = io.BytesIO()
        self._count = 0

    def close(self) -> None:
        self._flush_block()
        if self._own:
            self.f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class AvroReader:
    """Reads an Avro object-container file; iterates decoded records."""

    def __init__(self, path_or_file):
        self._own = isinstance(path_or_file, (str, os.PathLike))
        self.f: BinaryIO = open(path_or_file, "rb") if self._own else path_or_file
        raw = self.f.read()
        if raw[:4] != MAGIC:
            raise ValueError("not an Avro object container file")
        r = _Reader(raw)
        r.pos = 4
        meta = _Codec(_META_SCHEMA).decode(r)
        self.schema = json.loads(meta["avro.schema"].decode())
        self.codec = meta.get("avro.codec", b"null").decode()
        if self.codec not in ("null", "deflate"):
            raise ValueError(f"unsupported avro codec {self.codec}")
        self.sync = r.read_fixed(SYNC_SIZE)
        self._r = r
        self._codec = _Codec(self.schema)

    def __iter__(self) -> Iterator[Any]:
        r = self._r
        n_total = len(r.buf)
        while r.pos < n_total:
            count = r.read_long()
            size = r.read_long()
            data = r.read_fixed(size)
            if self.codec == "deflate":
                data = zlib.decompress(data, -15)
            br = _Reader(data)
            for _ in range(count):
                yield self._codec.decode(br)
            sync = r.read_fixed(SYNC_SIZE)
            if sync != self.sync:
                raise ValueError("bad sync marker (corrupt file)")

    def close(self) -> None:
        if self._own:
            self.f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_avro_records(path: str) -> List[Any]:
    with AvroReader(path) as r:
        return list(r)


def write_avro_records(path: str, schema: Schema, records: Iterable[Any],
                       codec: str = "deflate",
                       block_records: int = 4096) -> None:
    with AvroWriter(path, schema, codec, block_records) as w:
        for rec in records:
            w.append(rec)
