from photon_tpu.io.avro import AvroReader, AvroWriter, parse_schema  # noqa: F401
from photon_tpu.io.schemas import (  # noqa: F401
    BAYESIAN_LINEAR_MODEL_SCHEMA,
    FEATURE_SUMMARIZATION_SCHEMA,
    RESPONSE_PREDICTION_SCHEMA,
    SCORING_RESULT_SCHEMA,
    TRAINING_EXAMPLE_SCHEMA,
)
