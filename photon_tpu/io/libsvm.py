"""LIBSVM text format support.

Parity targets: reference ``LibSVMInputDataFormat``
(photon-client io/deprecated/LibSVMInputDataFormat.scala) and the dev script
``libsvm_text_to_trainingexample_avro.py`` (dev-scripts/) used by the README's
a1a demo workload (README.md:240-304).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from photon_tpu.io.avro import write_avro_records
from photon_tpu.io.schemas import TRAINING_EXAMPLE_SCHEMA


def read_libsvm(
    path: str, dim: Optional[int] = None, zero_based: bool = False
) -> Tuple[np.ndarray, np.ndarray]:
    """Parse a LIBSVM file → (dense X (n, d), y (n,)). Labels -1/+1 map to
    0/1; multi-label values pass through."""
    rows: List[List[Tuple[int, float]]] = []
    labels: List[float] = []
    max_idx = -1
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            y = float(parts[0])
            labels.append(1.0 if y > 0 else 0.0 if y in (-1.0, 0.0) else y)
            feats = []
            for tok in parts[1:]:
                if tok.startswith("#"):
                    break
                k, v = tok.split(":")
                j = int(k) - (0 if zero_based else 1)
                feats.append((j, float(v)))
                max_idx = max(max_idx, j)
            rows.append(feats)
    d = dim if dim is not None else max_idx + 1
    X = np.zeros((len(rows), d), np.float32)
    for i, feats in enumerate(rows):
        for j, v in feats:
            if j < d:
                X[i, j] = v
    return X, np.asarray(labels, np.float32)


def libsvm_to_training_example_avro(
    libsvm_path: str, avro_path: str, zero_based: bool = False
) -> int:
    """LIBSVM text → TrainingExampleAvro container (dev-script parity).
    Feature names are the 1-based libsvm indices as strings, matching the
    converter's convention. Returns the number of records written."""
    records = []
    with open(libsvm_path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            y = float(parts[0])
            feats = []
            for tok in parts[1:]:
                if tok.startswith("#"):
                    break
                k, v = tok.split(":")
                feats.append({"name": k, "term": "", "value": float(v)})
            records.append(
                {
                    "uid": str(i),
                    "label": 1.0 if y > 0 else 0.0,
                    "features": feats,
                    "metadataMap": None,
                    "weight": 1.0,
                    "offset": 0.0,
                }
            )
    write_avro_records(avro_path, TRAINING_EXAMPLE_SCHEMA, records)
    return len(records)
