"""Score output (ScoringResultAvro writer/reader).

Parity target: reference ``ScoreProcessingUtils``
(photon-client data/avro/ScoreProcessingUtils.scala).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from photon_tpu.io.avro import read_avro_records, write_avro_records
from photon_tpu.io.schemas import SCORING_RESULT_SCHEMA


def save_scores(
    path: str,
    scores: np.ndarray,
    model_id: str,
    uids: Optional[Sequence[str]] = None,
    labels: Optional[np.ndarray] = None,
    weights: Optional[np.ndarray] = None,
) -> None:
    scores = np.asarray(scores)

    def records():
        # Generator: the block writer consumes rows as produced, so the
        # per-row record dicts never materialize all at once (a 10M-row
        # scoring output would otherwise hold ~GBs of dicts transiently).
        for i, s in enumerate(scores):
            yield {
                "uid": None if uids is None else str(uids[i]),
                "label": None if labels is None else float(labels[i]),
                "modelId": model_id,
                "predictionScore": float(s),
                "weight": None if weights is None else float(weights[i]),
                "metadataMap": None,
            }

    write_avro_records(path, SCORING_RESULT_SCHEMA, records())


def load_scores(path: str) -> List[dict]:
    return read_avro_records(path)
