"""Avro training data → GameBatch, with feature-bag merging per shard.

Parity target: reference ``AvroDataReader`` (photon-client
data/avro/AvroDataReader.scala:54-500): N source feature bags merged into one
vector column per feature shard, index maps created by a distinct scan or
supplied, intercept injection, and id-tag extraction (uid / metadataMap) for
random-effect grouping; plus ``DataReader.readMerged`` overloads
(data/DataReader.scala:27-324).

TPU-first: output is a single struct-of-arrays GameBatch (dense per-shard
matrices when the shard is narrow, padded-sparse otherwise) with dense
interned entity indices — ready for device placement; no row objects.
"""

from __future__ import annotations

import dataclasses
import glob as globlib
import os
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from photon_tpu.data.batch import SparseFeatures
from photon_tpu.data.game_data import GameBatch
from photon_tpu.data.index_map import EntityIndex, IndexMap
from photon_tpu.io.avro import AvroReader

INTERCEPT_KEY = IndexMap.INTERCEPT

# Reserved columns (reference InputColumnsNames)
RESPONSE, OFFSET, WEIGHT, UID, META = "response", "offset", "weight", "uid", "metadataMap"


@dataclasses.dataclass
class FeatureShardConfig:
    """Bags merged into one shard + intercept flag (reference
    FeatureShardConfiguration, photon-client io/FeatureShardConfiguration.scala)."""

    feature_bags: Sequence[str] = ("features",)
    has_intercept: bool = True
    # Densify when the shard dimension is at most this; padded-sparse above.
    dense_dim_limit: int = 4096


def _feature_key(f: dict) -> str:
    return IndexMap.key(f["name"], f.get("term") or "")


def _expand_paths(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(globlib.glob(os.path.join(p, "*.avro"))))
        else:
            out.extend(sorted(globlib.glob(p)) or [p])
    return out


def read_avro_rows(paths: Sequence[str]) -> List[dict]:
    rows: List[dict] = []
    for path in _expand_paths(paths):
        with AvroReader(path) as r:
            rows.extend(r)
    return rows


def _row_label(row: dict) -> float:
    if "label" in row:
        return float(row["label"])
    return float(row.get("response", 0.0))


def build_index_maps(
    rows: List[dict],
    shard_configs: Dict[str, FeatureShardConfig],
) -> Dict[str, IndexMap]:
    """Distinct-scan index map creation (generateIndexMapLoaders role,
    AvroDataReader.scala:223-243)."""
    maps: Dict[str, IndexMap] = {}
    for shard, cfg in shard_configs.items():
        keys = set()
        for row in rows:
            for bag in cfg.feature_bags:
                for f in row.get(bag) or []:
                    keys.add(_feature_key(f))
        maps[shard] = IndexMap.build(keys, add_intercept=cfg.has_intercept)
    return maps


def rows_to_game_batch(
    rows: List[dict],
    shard_configs: Dict[str, FeatureShardConfig],
    index_maps: Dict[str, IndexMap],
    entity_id_columns: Optional[Dict[str, str]] = None,  # RE type -> id column
    entity_indexes: Optional[Dict[str, EntityIndex]] = None,
    intern_new_entities: bool = True,
) -> Tuple[GameBatch, Dict[str, EntityIndex]]:
    """Merge feature bags per shard, inject intercepts, intern entity ids.

    entity id columns resolve from the row's metadataMap first, then a
    top-level field (reference GameConverters id-tag extraction).
    """
    n = len(rows)
    entity_id_columns = entity_id_columns or {}
    entity_indexes = entity_indexes or {}

    label = np.array([_row_label(r) for r in rows], np.float32)
    offset = np.array([float(r.get("offset") or 0.0) for r in rows], np.float32)
    weight = np.array(
        [float(r["weight"]) if r.get("weight") is not None else 1.0 for r in rows],
        np.float32,
    )
    uid = np.arange(n, dtype=np.int64)

    features: Dict[str, object] = {}
    for shard, cfg in shard_configs.items():
        imap = index_maps[shard]
        d = len(imap)
        icpt = imap.get_index(INTERCEPT_KEY) if cfg.has_intercept else -1
        sparse_rows = []
        max_nnz = 1
        for row in rows:
            ix: List[int] = []
            vs: List[float] = []
            for bag in cfg.feature_bags:
                for f in row.get(bag) or []:
                    j = imap.get_index(_feature_key(f))
                    if j >= 0:
                        ix.append(j)
                        vs.append(float(f["value"]))
            if icpt >= 0:
                ix.append(icpt)
                vs.append(1.0)
            sparse_rows.append((ix, vs))
            max_nnz = max(max_nnz, len(ix))
        if d <= cfg.dense_dim_limit:
            X = np.zeros((n, d), np.float32)
            for i, (ix, vs) in enumerate(sparse_rows):
                X[i, ix] = vs
            features[shard] = jnp.asarray(X)
        else:
            features[shard] = SparseFeatures.from_rows(sparse_rows, d)

    entity_ids: Dict[str, np.ndarray] = {}
    for re_type, col in entity_id_columns.items():
        eidx = entity_indexes.setdefault(re_type, EntityIndex())
        ids = np.empty(n, np.int32)
        for i, row in enumerate(rows):
            meta = row.get(META) or {}
            raw = meta.get(col, row.get(col))
            if raw is None:
                ids[i] = -1
            elif intern_new_entities:
                ids[i] = eidx.intern(str(raw))
            else:
                ids[i] = eidx.lookup(str(raw))
        entity_ids[re_type] = ids

    batch = GameBatch(
        label=jnp.asarray(label),
        offset=jnp.asarray(offset),
        weight=jnp.asarray(weight),
        features=features,
        entity_ids={k: jnp.asarray(v) for k, v in entity_ids.items()},
        uid=jnp.asarray(uid),
    )
    return batch, entity_indexes


def read_merged(
    paths: Sequence[str],
    shard_configs: Dict[str, FeatureShardConfig],
    index_maps: Optional[Dict[str, IndexMap]] = None,
    entity_id_columns: Optional[Dict[str, str]] = None,
    entity_indexes: Optional[Dict[str, EntityIndex]] = None,
    intern_new_entities: bool = True,
) -> Tuple[GameBatch, Dict[str, IndexMap], Dict[str, EntityIndex]]:
    """DataReader.readMerged role: read Avro files → GameBatch (+ created
    index maps when not supplied)."""
    rows = read_avro_rows(paths)
    if index_maps is None:
        index_maps = build_index_maps(rows, shard_configs)
    batch, entity_indexes = rows_to_game_batch(
        rows, shard_configs, index_maps, entity_id_columns, entity_indexes,
        intern_new_entities,
    )
    return batch, index_maps, entity_indexes
