"""Avro training data → GameBatch, with feature-bag merging per shard.

Parity target: reference ``AvroDataReader`` (photon-client
data/avro/AvroDataReader.scala:54-500): N source feature bags merged into one
vector column per feature shard, index maps created by a distinct scan or
supplied, intercept injection, and id-tag extraction (uid / metadataMap) for
random-effect grouping; plus ``DataReader.readMerged`` overloads
(data/DataReader.scala:27-324).

TPU-first: output is a single struct-of-arrays GameBatch (dense per-shard
matrices when the shard is narrow, padded-sparse otherwise) with dense
interned entity indices — ready for device placement; no row objects.
"""

from __future__ import annotations

import dataclasses
import glob as globlib
import os
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from photon_tpu.data.batch import SparseFeatures
from photon_tpu.data.game_data import GameBatch
from photon_tpu.data.index_map import EntityIndex, IndexMap
from photon_tpu.io.avro import AvroReader

INTERCEPT_KEY = IndexMap.INTERCEPT

# Reserved columns (reference InputColumnsNames)
RESPONSE, OFFSET, WEIGHT, UID, META = "response", "offset", "weight", "uid", "metadataMap"


@dataclasses.dataclass(frozen=True)
class InputColumnsNames:
    """Reserved-column indirection (reference InputColumnsNames,
    photon-api data/InputColumnsNames.scala): lets input files use custom
    names for the reserved columns (the reference's
    different-column-names fixture exercises exactly this)."""

    response: str = RESPONSE
    offset: str = OFFSET
    weight: str = WEIGHT
    uid: str = UID
    metadata: str = META


@dataclasses.dataclass
class FeatureShardConfig:
    """Bags merged into one shard + intercept flag (reference
    FeatureShardConfiguration, photon-client io/FeatureShardConfiguration.scala)."""

    feature_bags: Sequence[str] = ("features",)
    has_intercept: bool = True
    # Densify when the shard dimension is at most this; padded-sparse above.
    dense_dim_limit: int = 4096
    # rmatvec lowering for padded-sparse shards: True attaches the
    # column-sorted transpose plan (segment_sum), False keeps the
    # duplicate-index scatter-add, None takes the backend-aware measured
    # default (data/batch.py::default_transpose_plan — scatter on CPU per
    # bench.py --rmatvec-cpu-ab, segment-sum on TPU where XLA serializes
    # colliding scatter updates).
    transpose_plan: Optional[bool] = None

    @property
    def resolved_transpose_plan(self) -> bool:
        from photon_tpu.data.batch import default_transpose_plan

        if self.transpose_plan is None:
            return default_transpose_plan()
        return bool(self.transpose_plan)


def _feature_key(f: dict) -> str:
    return IndexMap.key(f["name"], f.get("term") or "")


def _expand_paths(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(globlib.glob(os.path.join(p, "*.avro"))))
        else:
            out.extend(sorted(globlib.glob(p)) or [p])
    return out


def read_avro_rows(paths: Sequence[str]) -> List[dict]:
    rows: List[dict] = []
    for path in _expand_paths(paths):
        with AvroReader(path) as r:
            rows.extend(r)
    return rows


def _row_get(row: dict, names: Sequence[str]):
    """First present (non-None) value among candidate column names."""
    for name in names:
        v = row.get(name)
        if v is not None:
            return v
    return None


def _label_columns(response_col: str) -> Tuple[str, ...]:
    """Label resolution order. With default column names this preserves the
    historical label-then-response precedence (rows carrying BOTH train on
    'label'); a custom response column always wins."""
    if response_col != RESPONSE:
        return (response_col, "label", RESPONSE)
    return ("label", RESPONSE)


def _row_label(row: dict, response_col: str = RESPONSE) -> float:
    v = _row_get(row, _label_columns(response_col))
    return float(v) if v is not None else 0.0


def build_index_maps(
    rows: List[dict],
    shard_configs: Dict[str, FeatureShardConfig],
) -> Dict[str, IndexMap]:
    """Distinct-scan index map creation (generateIndexMapLoaders role,
    AvroDataReader.scala:223-243)."""
    maps: Dict[str, IndexMap] = {}
    for shard, cfg in shard_configs.items():
        keys = set()
        for row in rows:
            for bag in cfg.feature_bags:
                for f in row.get(bag) or []:
                    keys.add(_feature_key(f))
        maps[shard] = IndexMap.build(keys, add_intercept=cfg.has_intercept)
    return maps


def rows_to_game_batch(
    rows: List[dict],
    shard_configs: Dict[str, FeatureShardConfig],
    index_maps: Dict[str, IndexMap],
    entity_id_columns: Optional[Dict[str, str]] = None,  # RE type -> id column
    entity_indexes: Optional[Dict[str, EntityIndex]] = None,
    intern_new_entities: bool = True,
    column_names: Optional[InputColumnsNames] = None,
) -> Tuple[GameBatch, Dict[str, EntityIndex]]:
    """Merge feature bags per shard, inject intercepts, intern entity ids.

    entity id columns resolve from the row's metadataMap first, then a
    top-level field (reference GameConverters id-tag extraction).
    """
    n = len(rows)
    entity_id_columns = entity_id_columns or {}
    entity_indexes = entity_indexes if entity_indexes is not None else {}
    cn = column_names or InputColumnsNames()

    label = np.array([_row_label(r, cn.response) for r in rows], np.float32)
    offset = np.array(
        [float(_row_get(r, (cn.offset, OFFSET)) or 0.0) for r in rows], np.float32
    )
    weight = np.array(
        [
            float(v) if (v := _row_get(r, (cn.weight, WEIGHT))) is not None else 1.0
            for r in rows
        ],
        np.float32,
    )
    uid = np.arange(n, dtype=np.int64)

    features: Dict[str, object] = {}
    for shard, cfg in shard_configs.items():
        imap = index_maps[shard]
        d = len(imap)
        icpt = imap.get_index(INTERCEPT_KEY) if cfg.has_intercept else -1
        sparse_rows = []
        max_nnz = 1
        for row in rows:
            ix: List[int] = []
            vs: List[float] = []
            for bag in cfg.feature_bags:
                for f in row.get(bag) or []:
                    j = imap.get_index(_feature_key(f))
                    if j >= 0:
                        ix.append(j)
                        vs.append(float(f["value"]))
            if icpt >= 0:
                ix.append(icpt)
                vs.append(1.0)
            sparse_rows.append((ix, vs))
            max_nnz = max(max_nnz, len(ix))
        if d <= cfg.dense_dim_limit:
            X = np.zeros((n, d), np.float32)
            for i, (ix, vs) in enumerate(sparse_rows):
                X[i, ix] = vs
            features[shard] = jnp.asarray(X)
        else:
            sf = SparseFeatures.from_rows(sparse_rows, d)
            if cfg.resolved_transpose_plan:
                sf = sf.with_transpose_plan()
            features[shard] = sf

    entity_ids: Dict[str, np.ndarray] = {}
    for re_type, col in entity_id_columns.items():
        eidx = entity_indexes.setdefault(re_type, EntityIndex())
        ids = np.empty(n, np.int32)
        for i, row in enumerate(rows):
            meta = _row_get(row, (cn.metadata, META)) or {}
            raw = meta.get(col, row.get(col))
            if raw is None:
                ids[i] = -1
            elif intern_new_entities:
                ids[i] = eidx.intern(str(raw))
            else:
                ids[i] = eidx.lookup(str(raw))
        entity_ids[re_type] = ids

    batch = GameBatch(
        label=jnp.asarray(label),
        offset=jnp.asarray(offset),
        weight=jnp.asarray(weight),
        features=features,
        entity_ids={k: jnp.asarray(v) for k, v in entity_ids.items()},
        uid=jnp.asarray(uid),
    )
    return batch, entity_indexes


def _columnar_index_maps(
    cols, shard_configs: Dict[str, FeatureShardConfig]
) -> Dict[str, IndexMap]:
    maps: Dict[str, IndexMap] = {}
    for shard, cfg in shard_configs.items():
        ids: List[np.ndarray] = [
            cols.bags[bag].key_ids
            for bag in cfg.feature_bags
            if bag in cols.bags
        ]
        uniq = (
            np.unique(np.concatenate(ids)) if ids else np.empty(0, np.int32)
        )
        maps[shard] = IndexMap.build(
            (cols.intern[i] for i in uniq), add_intercept=cfg.has_intercept
        )
    return maps


def _columnar_to_game_batch(
    cols,
    shard_configs: Dict[str, FeatureShardConfig],
    index_maps: Dict[str, IndexMap],
    entity_id_columns: Optional[Dict[str, str]] = None,
    entity_indexes: Optional[Dict[str, EntityIndex]] = None,
    intern_new_entities: bool = True,
    column_names: Optional[InputColumnsNames] = None,
    to_device: bool = True,
) -> Tuple[GameBatch, Dict[str, EntityIndex]]:
    """Vectorized rows_to_game_batch over native-decoded columns: one
    IndexMap lookup per DISTINCT key, numpy scatters for the matrices.

    ``to_device=False`` keeps every leaf numpy (GameBatch is leaf-agnostic):
    the pipeline's assemble stage runs concurrently with device compute, so
    placement is deferred to its h2d stage (io/pipeline.py) — implicit
    jnp.asarray here would serialize transfers into assembly.
    """
    n = cols.n
    entity_id_columns = entity_id_columns or {}
    entity_indexes = entity_indexes if entity_indexes is not None else {}
    cn = column_names or InputColumnsNames()
    as_arr = jnp.asarray if to_device else np.asarray

    def _num_col(names):
        for name in names:
            if name in cols.numeric:
                return cols.numeric[name]
        return None

    label_col = _num_col(_label_columns(cn.response))
    label = np.nan_to_num(
        np.zeros(n, np.float64) if label_col is None else label_col, nan=0.0
    ).astype(np.float32)
    off_col = _num_col((cn.offset, OFFSET))
    offset = (
        np.zeros(n, np.float32)
        if off_col is None
        else np.nan_to_num(off_col, nan=0.0).astype(np.float32)
    )
    wt_col = _num_col((cn.weight, WEIGHT))
    weight = (
        np.ones(n, np.float32)
        if wt_col is None
        else np.nan_to_num(wt_col, nan=1.0).astype(np.float32)
    )

    features: Dict[str, object] = {}
    for shard, cfg in shard_configs.items():
        imap = index_maps[shard]
        d = len(imap)
        icpt = imap.get_index(INTERCEPT_KEY) if cfg.has_intercept else -1
        # One lookup per distinct interned string (metadata strings resolve
        # to -1 and are masked out below).
        feat_of = np.fromiter(
            (imap.get_index(s) for s in cols.intern),
            np.int32,
            count=len(cols.intern),
        )
        row_idx_parts, col_idx_parts, val_parts = [], [], []
        for bag_name in cfg.feature_bags:
            bag = cols.bags.get(bag_name)
            if bag is None or bag.key_ids.size == 0:
                continue
            rows_of = np.repeat(
                np.arange(n, dtype=np.int64), np.diff(bag.offsets)
            )
            j = feat_of[bag.key_ids]
            ok = j >= 0
            row_idx_parts.append(rows_of[ok])
            col_idx_parts.append(j[ok])
            val_parts.append(bag.values[ok].astype(np.float32))
        rows_all = (
            np.concatenate(row_idx_parts) if row_idx_parts else np.empty(0, np.int64)
        )
        cols_all = (
            np.concatenate(col_idx_parts) if col_idx_parts else np.empty(0, np.int32)
        )
        vals_all = (
            np.concatenate(val_parts) if val_parts else np.empty(0, np.float32)
        )
        if d <= cfg.dense_dim_limit:
            X = np.zeros((n, d), np.float32)
            X[rows_all, cols_all] = vals_all  # duplicate keys: last wins,
            # matching the row path's overwrite semantics
            if icpt >= 0:
                X[:, icpt] = 1.0
            features[shard] = as_arr(X)
        else:
            # Padded-sparse, built without any per-row Python loop.
            counts = np.bincount(rows_all, minlength=n).astype(np.int64)
            if icpt >= 0:
                counts += 1
            max_nnz = max(int(counts.max()) if n else 1, 1)
            order = np.argsort(rows_all, kind="stable")
            r_s, c_s, v_s = rows_all[order], cols_all[order], vals_all[order]
            starts = np.zeros(n + 1, np.int64)
            np.cumsum(np.bincount(r_s, minlength=n), out=starts[1:])
            pos = np.arange(r_s.size, dtype=np.int64) - starts[r_s]
            indices = np.full((n, max_nnz), -1, np.int32)
            values = np.zeros((n, max_nnz), np.float32)
            indices[r_s, pos] = c_s
            values[r_s, pos] = v_s
            if icpt >= 0:
                slot = counts - 1
                indices[np.arange(n), slot] = icpt
                values[np.arange(n), slot] = 1.0
            sf = SparseFeatures(as_arr(indices), as_arr(values), d)
            if cfg.resolved_transpose_plan:
                sf = sf.with_transpose_plan()
            features[shard] = sf

    entity_ids: Dict[str, np.ndarray] = {}
    for re_type, col in entity_id_columns.items():
        eidx = entity_indexes.setdefault(re_type, EntityIndex())
        raw = cols.meta_column(col)
        if col in cols.strings:  # top-level field fallback (GameConverters)
            raw = np.where(raw >= 0, raw, cols.strings[col])
        ids = np.full(n, -1, np.int32)
        present = np.unique(raw[raw >= 0])
        lut = np.full(len(cols.intern), -1, np.int32)
        for iid in present:
            s = cols.intern[iid]
            lut[iid] = eidx.intern(s) if intern_new_entities else eidx.lookup(s)
        sel = raw >= 0
        ids[sel] = lut[raw[sel]]
        if col in cols.numeric:
            # Numeric (long/int) top-level id fields: the row path (and the
            # reference, GameConvertersIntegTest's Long id columns) interns
            # str(raw), so format integral values as integer strings. One
            # intern per DISTINCT value, vectorized scatter for the rest.
            # Long columns use the exact int64 store (doubles would collapse
            # distinct ids past 2^53).
            num = cols.longs.get(col, cols.numeric[col])
            exact = col in cols.longs
            fill = (ids < 0) & (
                np.ones(n, bool) if exact else np.isfinite(num)
            )
            if fill.any():
                uniq, inv = np.unique(num[fill], return_inverse=True)
                # Match the row path's str(raw) exactly: long columns decode
                # to python ints ("123"), double columns to floats ("123.0").
                mapped = np.fromiter(
                    (
                        eidx.intern(s) if intern_new_entities else eidx.lookup(s)
                        for s in (
                            str(int(v)) if exact else str(float(v))
                            for v in uniq
                        )
                    ),
                    np.int32,
                    count=len(uniq),
                )
                ids[fill] = mapped[inv]
        entity_ids[re_type] = ids

    batch = GameBatch(
        label=as_arr(label),
        offset=as_arr(offset),
        weight=as_arr(weight),
        features=features,
        entity_ids={k: as_arr(v) for k, v in entity_ids.items()},
        uid=as_arr(np.arange(n, dtype=np.int64)),
    )
    return batch, entity_indexes


def read_merged(
    paths: Sequence[str],
    shard_configs: Dict[str, FeatureShardConfig],
    index_maps: Optional[Dict[str, IndexMap]] = None,
    entity_id_columns: Optional[Dict[str, str]] = None,
    entity_indexes: Optional[Dict[str, EntityIndex]] = None,
    intern_new_entities: bool = True,
    use_columnar: bool = True,
    column_names: Optional[InputColumnsNames] = None,
) -> Tuple[GameBatch, Dict[str, IndexMap], Dict[str, EntityIndex]]:
    """DataReader.readMerged role: read Avro files → GameBatch (+ created
    index maps when not supplied). Prefers the native columnar decode path
    (io/columnar.py); row-oriented pure Python is the universal fallback."""
    if use_columnar:
        from photon_tpu.io.columnar import read_avro_columnar

        try:
            cols = read_avro_columnar(_expand_paths(paths))
        except (ValueError, OSError):
            cols = None
        if cols is not None:
            if index_maps is None:
                index_maps = _columnar_index_maps(cols, shard_configs)
            batch, entity_indexes = _columnar_to_game_batch(
                cols, shard_configs, index_maps, entity_id_columns,
                entity_indexes, intern_new_entities, column_names,
            )
            return batch, index_maps, entity_indexes
    rows = read_avro_rows(paths)
    if index_maps is None:
        index_maps = build_index_maps(rows, shard_configs)
    batch, entity_indexes = rows_to_game_batch(
        rows, shard_configs, index_maps, entity_id_columns, entity_indexes,
        intern_new_entities, column_names,
    )
    return batch, index_maps, entity_indexes


def stream_merged(
    paths: Sequence[str],
    shard_configs: Dict[str, FeatureShardConfig],
    index_maps: Dict[str, IndexMap],
    entity_id_columns: Optional[Dict[str, str]] = None,
    entity_indexes: Optional[Dict[str, EntityIndex]] = None,
    intern_new_entities: bool = True,
    chunk_rows: int = 1 << 16,
    column_names: Optional[InputColumnsNames] = None,
    workers: Optional[int] = None,
):
    """Chunked readMerged: yields GameBatch chunks with host memory bounded
    by one chunk (+ a bounded window of in-flight blocks), never the
    dataset — each chunk's arrays are device-put-able as soon as it is
    yielded, so ingest overlaps the host->device feed (SURVEY §7 hard part
    4; the reference streams per-partition, AvroDataReader.scala:165-209).

    ``index_maps`` must be supplied: a stream cannot be distinct-scanned
    first (use the feature-indexing driver or a prior read). Entity ids
    intern cumulatively across chunks through ``entity_indexes``.
    ``workers`` caps the concurrent block decode (default: one per
    available core; 1 forces the serial single-ctx path).
    """
    from photon_tpu.io.columnar import stream_avro_columnar

    entity_indexes = entity_indexes if entity_indexes is not None else {}
    for cols in stream_avro_columnar(
        _expand_paths(paths), chunk_rows, workers=workers
    ):
        batch, entity_indexes = _columnar_to_game_batch(
            cols, shard_configs, index_maps, entity_id_columns,
            entity_indexes, intern_new_entities, column_names,
        )
        yield batch


def concat_game_batches(batches: List[GameBatch]) -> GameBatch:
    """Concatenate chunk batches (e.g. from ``stream_merged`` after a
    per-chunk device put) into one GameBatch. Runs on whatever backend the
    chunks live on, so host RAM never holds the assembled arrays when the
    chunks were device-put first. Padded-sparse shards re-pad to the widest
    chunk; uids renumber globally."""
    if not batches:
        raise ValueError("no batches to concatenate")
    if len(batches) == 1:
        (b,) = batches
        return b
    label = jnp.concatenate([b.label for b in batches])
    offset = jnp.concatenate([b.offset for b in batches])
    weight = jnp.concatenate([b.weight for b in batches])
    n = label.shape[0]
    features: Dict[str, object] = {}
    for shard in batches[0].features:
        parts = [b.features[shard] for b in batches]
        if isinstance(parts[0], SparseFeatures):
            k = max(p.indices.shape[1] for p in parts)
            dim = parts[0].dim

            def pad(p):
                short = k - p.indices.shape[1]
                if short == 0:
                    return p
                return SparseFeatures(
                    jnp.pad(p.indices, ((0, 0), (0, short))),
                    jnp.pad(p.values, ((0, 0), (0, short))),
                    p.dim,
                )

            had_plan = all(p.csc_order is not None for p in parts)
            parts = [pad(p) for p in parts]
            sf = SparseFeatures(
                jnp.concatenate([p.indices for p in parts]),
                jnp.concatenate([p.values for p in parts]),
                dim,
            )
            if had_plan:  # one host argsort over the concatenated pattern
                sf = sf.with_transpose_plan()
            features[shard] = sf
        else:
            features[shard] = jnp.concatenate(parts)
    entity_ids = {
        k: jnp.concatenate([b.entity_ids[k] for b in batches])
        for k in batches[0].entity_ids
    }
    return GameBatch(
        label=label, offset=offset, weight=weight, features=features,
        entity_ids=entity_ids, uid=jnp.asarray(np.arange(n, dtype=np.int64)),
    )
