// Columnar Avro block decoder — the native ingest accelerator.
//
// Role: the reference's data plane decodes Avro rows on executor JVMs
// (photon-client data/avro/AvroDataReader.scala) with the Java Avro runtime;
// SURVEY.md §2.9 names "Avro column decode acceleration" as sanctioned
// native scope for the TPU rebuild. This module turns DECOMPRESSED Avro
// block bytes into columnar buffers (numeric columns, interned string
// columns, feature-bag CSR triples, metadata triplets) so the Python side
// never walks records field-by-field. String interning happens here, so the
// host work left in Python is a vectorized unique-key lookup.
//
// Written from the public Avro 1.x binary spec (zigzag varints,
// little-endian IEEE doubles, block-encoded arrays/maps). Not derived from
// any existing decoder.
//
// Schema support is a compact per-field program compiled by the Python
// caller (photon_tpu/io/columnar.py) from the container file's writer
// schema:
//   0 = double
//   1 = union [null, double]           (null → NaN)
//   2 = string                         (interned id)
//   3 = union [null, string]           (null → -1)
//   4 = array<record{string name, string term, double value}>  (feature bag)
//   5 = union [null, map<string>]      (metadata triplets)
//   6 = map<string>
//   7 = float
//   8 = int/long
// Anything else → the caller falls back to the pure-Python codec.
//
// Build: g++ -O2 -std=c++17 -shared -fPIC -o libavro_decode.so avro_decode.cpp

#include <cmath>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  int64_t read_long() {
    uint64_t acc = 0;
    int shift = 0;
    while (p < end) {
      uint8_t b = *p++;
      acc |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) {
        // zigzag decode
        return static_cast<int64_t>((acc >> 1) ^ (~(acc & 1) + 1));
      }
      shift += 7;
      if (shift > 63) break;
    }
    ok = false;
    return 0;
  }

  double read_double() {
    if (end - p < 8) { ok = false; return 0.0; }
    double v;
    std::memcpy(&v, p, 8);
    p += 8;
    return v;
  }

  float read_float() {
    if (end - p < 4) { ok = false; return 0.0f; }
    float v;
    std::memcpy(&v, p, 4);
    p += 4;
    return v;
  }

  bool read_str(const char** s, int64_t* len) {
    int64_t n = read_long();
    if (!ok || n < 0 || end - p < n) { ok = false; return false; }
    *s = reinterpret_cast<const char*>(p);
    *len = n;
    p += n;
    return true;
  }

  void skip_bytes(int64_t n) {
    if (n < 0 || end - p < n) { ok = false; return; }
    p += n;
  }
};

struct Interner {
  // id-by-string; blob keeps the bytes, offsets delimit them.
  std::unordered_map<std::string, int32_t> ids;
  std::vector<char> blob;
  std::vector<int64_t> offsets{0};

  int32_t intern(const char* s, int64_t len) {
    std::string key(s, static_cast<size_t>(len));
    auto it = ids.find(key);
    if (it != ids.end()) return it->second;
    int32_t id = static_cast<int32_t>(ids.size());
    ids.emplace(std::move(key), id);
    blob.insert(blob.end(), s, s + len);
    offsets.push_back(static_cast<int64_t>(blob.size()));
    return id;
  }

  int32_t intern_key(const char* name, int64_t nlen, const char* term,
                     int64_t tlen) {
    // Feature key = name when the term is empty, else name + '\x01' + term
    // (IndexMap.key convention).
    if (tlen == 0) return intern(name, nlen);
    std::string key;
    key.reserve(static_cast<size_t>(nlen + 1 + tlen));
    key.append(name, static_cast<size_t>(nlen));
    key.push_back('\x01');
    key.append(term, static_cast<size_t>(tlen));
    auto it = ids.find(key);
    if (it != ids.end()) return it->second;
    int32_t id = static_cast<int32_t>(ids.size());
    blob.insert(blob.end(), key.data(), key.data() + key.size());
    offsets.push_back(static_cast<int64_t>(blob.size()));
    ids.emplace(std::move(key), id);
    return id;
  }
};

struct Ctx {
  std::vector<uint8_t> program;
  int64_t n_records = 0;
  // Per-field outputs, indexed by field position (empty where unused).
  std::vector<std::vector<double>> numeric;
  // Exact int64 values for long fields (op 8) — doubles lose precision
  // past 2^53, which would corrupt 64-bit entity ids.
  std::vector<std::vector<int64_t>> longcol;
  std::vector<std::vector<int32_t>> strcol;
  std::vector<std::vector<int64_t>> bag_offsets;  // CSR, length n+1 per bag
  std::vector<std::vector<int32_t>> bag_keys;
  std::vector<std::vector<double>> bag_values;
  // metadata triplets across all map fields
  std::vector<int32_t> meta_rows, meta_keys, meta_vals;
  Interner intern;
};

bool decode_record(Ctx* c, Reader& r) {
  const int64_t row = c->n_records;
  for (size_t fi = 0; fi < c->program.size(); ++fi) {
    switch (c->program[fi]) {
      case 0:  // double
        c->numeric[fi].push_back(r.read_double());
        break;
      case 1: {  // union [null, double]
        int64_t tag = r.read_long();
        // A tag outside {0,1} means corrupt or schema-evolved input; treating
        // it as null would desync the stream — fail so the caller falls back
        // to the pure-Python codec.
        if (tag != 0 && tag != 1) return false;
        c->numeric[fi].push_back(tag == 1 ? r.read_double()
                                          : std::nan(""));
        break;
      }
      case 2: {  // string
        const char* s; int64_t n;
        if (!r.read_str(&s, &n)) return false;
        c->strcol[fi].push_back(c->intern.intern(s, n));
        break;
      }
      case 3: {  // union [null, string]
        int64_t tag = r.read_long();
        if (tag != 0 && tag != 1) return false;
        if (tag == 1) {
          const char* s; int64_t n;
          if (!r.read_str(&s, &n)) return false;
          c->strcol[fi].push_back(c->intern.intern(s, n));
        } else {
          c->strcol[fi].push_back(-1);
        }
        break;
      }
      case 4: {  // array<{string name, string term, double value}>
        for (;;) {
          int64_t cnt = r.read_long();
          if (!r.ok) return false;
          if (cnt == 0) break;
          if (cnt < 0) {  // block with byte size prefix
            cnt = -cnt;
            (void)r.read_long();  // block byte size — unused
          }
          for (int64_t i = 0; i < cnt; ++i) {
            const char *nm, *tm; int64_t nl, tl;
            if (!r.read_str(&nm, &nl)) return false;
            if (!r.read_str(&tm, &tl)) return false;
            double v = r.read_double();
            c->bag_keys[fi].push_back(c->intern.intern_key(nm, nl, tm, tl));
            c->bag_values[fi].push_back(v);
          }
        }
        c->bag_offsets[fi].push_back(
            static_cast<int64_t>(c->bag_keys[fi].size()));
        break;
      }
      case 5: {  // union [null, map<string>]
        int64_t tag = r.read_long();
        if (tag != 0 && tag != 1) return false;
        if (tag != 1) break;
        [[fallthrough]];
      }
      case 6: {  // map<string>
        for (;;) {
          int64_t cnt = r.read_long();
          if (!r.ok) return false;
          if (cnt == 0) break;
          if (cnt < 0) {
            cnt = -cnt;
            (void)r.read_long();
          }
          for (int64_t i = 0; i < cnt; ++i) {
            const char *k, *v; int64_t kl, vl;
            if (!r.read_str(&k, &kl)) return false;
            if (!r.read_str(&v, &vl)) return false;
            c->meta_rows.push_back(static_cast<int32_t>(row));
            c->meta_keys.push_back(c->intern.intern(k, kl));
            c->meta_vals.push_back(c->intern.intern(v, vl));
          }
        }
        break;
      }
      case 7:  // float
        c->numeric[fi].push_back(static_cast<double>(r.read_float()));
        break;
      case 8: {  // int/long
        int64_t v = r.read_long();
        c->numeric[fi].push_back(static_cast<double>(v));
        c->longcol[fi].push_back(v);
        break;
      }
      default:
        return false;
    }
    if (!r.ok) return false;
  }
  c->n_records++;
  return true;
}

}  // namespace

extern "C" {

Ctx* avro_dec_new(const uint8_t* program, int n_fields) {
  Ctx* c = new Ctx();
  c->program.assign(program, program + n_fields);
  c->numeric.resize(n_fields);
  c->longcol.resize(n_fields);
  c->strcol.resize(n_fields);
  c->bag_offsets.resize(n_fields);
  c->bag_keys.resize(n_fields);
  c->bag_values.resize(n_fields);
  for (int i = 0; i < n_fields; ++i) {
    if (c->program[i] == 4) c->bag_offsets[i].push_back(0);
  }
  return c;
}

// Decode `count` records from decompressed block bytes. Returns 0 on
// success, nonzero on malformed input (caller falls back to Python codec).
int avro_dec_block(Ctx* c, const uint8_t* data, int64_t size, int64_t count) {
  Reader r{data, data + size};
  for (int64_t i = 0; i < count; ++i) {
    if (!decode_record(c, r)) return 1;
  }
  return r.p == r.end ? 0 : 2;  // trailing bytes = schema mismatch
}

int64_t avro_dec_num_records(Ctx* c) { return c->n_records; }

const double* avro_dec_numeric(Ctx* c, int fi) { return c->numeric[fi].data(); }
const int64_t* avro_dec_longcol(Ctx* c, int fi) { return c->longcol[fi].data(); }
const int32_t* avro_dec_strcol(Ctx* c, int fi) { return c->strcol[fi].data(); }

int64_t avro_dec_bag_len(Ctx* c, int fi) {
  return static_cast<int64_t>(c->bag_keys[fi].size());
}
const int64_t* avro_dec_bag_offsets(Ctx* c, int fi) {
  return c->bag_offsets[fi].data();
}
const int32_t* avro_dec_bag_keys(Ctx* c, int fi) {
  return c->bag_keys[fi].data();
}
const double* avro_dec_bag_values(Ctx* c, int fi) {
  return c->bag_values[fi].data();
}

int64_t avro_dec_meta_len(Ctx* c) {
  return static_cast<int64_t>(c->meta_rows.size());
}
const int32_t* avro_dec_meta_rows(Ctx* c) { return c->meta_rows.data(); }
const int32_t* avro_dec_meta_keys(Ctx* c) { return c->meta_keys.data(); }
const int32_t* avro_dec_meta_vals(Ctx* c) { return c->meta_vals.data(); }

int64_t avro_dec_intern_count(Ctx* c) {
  return static_cast<int64_t>(c->intern.ids.size());
}
int64_t avro_dec_intern_blob_len(Ctx* c) {
  return static_cast<int64_t>(c->intern.blob.size());
}
const char* avro_dec_intern_blob(Ctx* c) { return c->intern.blob.data(); }
const int64_t* avro_dec_intern_offsets(Ctx* c) {
  return c->intern.offsets.data();
}

void avro_dec_free(Ctx* c) { delete c; }

}  // extern "C"
