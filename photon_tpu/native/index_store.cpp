// Native memory-mapped feature index store.
//
// Role parity: the reference's PalDB off-heap partitioned feature index
// (photon-api index/PalDBIndexMap.scala:43-240): hash-partitioned
// string→int and int→string stores, memory-mapped read-only so many
// processes share one page-cache copy and feature-name spaces too large
// for the host heap stay off-heap. This is an original format (not PalDB):
//
//   part-<i>.bin : [u32 magic][u32 n_entries]
//                  n × {u64 hash, u32 value, u32 key_off, u32 key_len}
//                  (sorted by hash)  ++  keys blob
//   reverse.bin  : [u32 magic][u32 total] total × {u32 part, u32 slot}
//
// Lookups: FNV-1a 64 hash → binary search in the partition given by
// hash % num_partitions → verify key bytes. C ABI for ctypes.

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0x50494458;  // "PIDX"

// Packed to match the builder's 20-byte on-disk layout exactly (no padding).
struct __attribute__((packed)) Entry {
  uint64_t hash;
  uint32_t value;
  uint32_t key_off;
  uint32_t key_len;
};
static_assert(sizeof(Entry) == 20, "on-disk entry layout");

struct Part {
  const uint8_t* base = nullptr;
  size_t size = 0;
  const Entry* entries = nullptr;
  uint32_t n = 0;
  const char* keys = nullptr;
};

struct RevEntry {
  uint32_t part;
  uint32_t slot;
};

struct Store {
  std::vector<Part> parts;
  const uint8_t* rev_base = nullptr;
  size_t rev_size = 0;
  const RevEntry* rev = nullptr;
  uint32_t total = 0;
};

uint64_t fnv1a64(const char* data, size_t len) {
  uint64_t h = 1469598103934665603ULL;
  for (size_t i = 0; i < len; ++i) {
    h ^= static_cast<uint8_t>(data[i]);
    h *= 1099511628211ULL;
  }
  return h;
}

const uint8_t* map_file(const std::string& path, size_t* size_out) {
  int fd = ::open(path.c_str(), O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    ::close(fd);
    return nullptr;
  }
  void* p = ::mmap(nullptr, st.st_size, PROT_READ, MAP_SHARED, fd, 0);
  ::close(fd);
  if (p == MAP_FAILED) return nullptr;
  *size_out = static_cast<size_t>(st.st_size);
  return static_cast<const uint8_t*>(p);
}

}  // namespace

extern "C" {

// Opens a store directory with n partitions. Returns an opaque handle or
// nullptr on failure.
void* pidx_open(const char* dir, int num_partitions) {
  auto* s = new Store();
  s->parts.resize(num_partitions);
  for (int i = 0; i < num_partitions; ++i) {
    std::string path = std::string(dir) + "/part-" + std::to_string(i) + ".bin";
    Part& p = s->parts[i];
    p.base = map_file(path, &p.size);
    if (!p.base || p.size < 8 ||
        *reinterpret_cast<const uint32_t*>(p.base) != kMagic) {
      delete s;
      return nullptr;
    }
    p.n = *reinterpret_cast<const uint32_t*>(p.base + 4);
    p.entries = reinterpret_cast<const Entry*>(p.base + 8);
    p.keys = reinterpret_cast<const char*>(p.base + 8 + p.n * sizeof(Entry));
  }
  std::string rev_path = std::string(dir) + "/reverse.bin";
  s->rev_base = map_file(rev_path, &s->rev_size);
  if (s->rev_base && s->rev_size >= 8 &&
      *reinterpret_cast<const uint32_t*>(s->rev_base) == kMagic) {
    s->total = *reinterpret_cast<const uint32_t*>(s->rev_base + 4);
    s->rev = reinterpret_cast<const RevEntry*>(s->rev_base + 8);
  }
  return s;
}

void pidx_close(void* handle) {
  auto* s = static_cast<Store*>(handle);
  if (!s) return;
  for (auto& p : s->parts) {
    if (p.base) ::munmap(const_cast<uint8_t*>(p.base), p.size);
  }
  if (s->rev_base) ::munmap(const_cast<uint8_t*>(s->rev_base), s->rev_size);
  delete s;
}

// name → index; -1 when absent (reference IndexMap.getIndex semantics).
int64_t pidx_get_index(void* handle, const char* key, int64_t key_len) {
  auto* s = static_cast<Store*>(handle);
  uint64_t h = fnv1a64(key, key_len);
  const Part& p = s->parts[h % s->parts.size()];
  uint32_t lo = 0, hi = p.n;
  while (lo < hi) {  // lower_bound on hash
    uint32_t mid = (lo + hi) / 2;
    if (p.entries[mid].hash < h) {
      lo = mid + 1;
    } else {
      hi = mid;
    }
  }
  for (uint32_t i = lo; i < p.n && p.entries[i].hash == h; ++i) {
    const Entry& e = p.entries[i];
    if (e.key_len == key_len && memcmp(p.keys + e.key_off, key, key_len) == 0) {
      return e.value;
    }
  }
  return -1;
}

// Batched lookup: keys given as a packed blob + offsets; writes values.
void pidx_get_indices(void* handle, const char* blob, const int64_t* offsets,
                      int64_t n, int64_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    out[i] = pidx_get_index(handle, blob + offsets[i],
                            offsets[i + 1] - offsets[i]);
  }
}

// index → name; returns length, writes pointer into *ptr. -1 when absent.
int64_t pidx_get_name(void* handle, int64_t index, const char** ptr) {
  auto* s = static_cast<Store*>(handle);
  if (!s->rev || index < 0 || index >= s->total) return -1;
  RevEntry r = s->rev[index];
  if (r.part >= s->parts.size()) return -1;
  const Part& p = s->parts[r.part];
  if (r.slot >= p.n) return -1;
  const Entry& e = p.entries[r.slot];
  *ptr = p.keys + e.key_off;
  return e.key_len;
}

int64_t pidx_size(void* handle) {
  auto* s = static_cast<Store*>(handle);
  if (s->rev) return s->total;
  int64_t n = 0;
  for (auto& p : s->parts) n += p.n;
  return n;
}

}  // extern "C"
