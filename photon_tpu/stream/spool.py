"""Serve-side feedback spool: scored requests joined with observed labels.

The write half of the streaming freshness loop. The serving engine lands
every (sampled) scored request here; when the caller later reports the
observed label for that request's ``uid``, the joined record is appended to
the active spool segment. Segments are JSONL, size/age-rotated, and sealed
with the replay-spool discipline from ``io/pipeline.py``: appends are
flushed line-by-line, sealing is flush + fsync + atomic rename from
``segment-N.part`` to ``segment-N.jsonl``. Consumers (the streaming
updater) read only sealed ``.jsonl`` segments, so a torn in-progress write
can never reach training; a crashed writer's orphaned ``.part`` is
recovered at exact record parity for every fully written line — the torn
tail (at most one record) is dropped and counted.

Failure containment mirrors the degradation policy of
``utils/resources``: label ingestion must never break serving. A full disk
(ENOSPC, via :class:`~photon_tpu.utils.resources.DiskBudgetGuard`) or any
other write failure drops the record with a counter, not an exception.

Fault site ``serve.feedback`` (fired per observed label):

- ``transient`` / ``permanent`` — the label join is dropped and counted,
  the caller sees a clean False;
- ``torn`` — the active segment is abandoned mid-record (half a line, no
  newline), simulating a writer crash: recovery must seal the complete
  prefix and drop exactly the torn tail;
- ``enospc`` — the append path behaves as if the disk filled;
- ``kill`` — SIGKILL, the full crash simulation.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

from photon_tpu.utils import faults
from photon_tpu.utils.resources import DiskBudgetGuard

logger = logging.getLogger(__name__)

SEGMENT_PREFIX = "segment-"
SEALED_SUFFIX = ".jsonl"
PART_SUFFIX = ".part"
WRITER_LOCK = "writer.lock"
# Sidecar reclaiming TTL-evicted joins: `evicted` lines carry the scored
# features, `late_label` lines the label that missed the window. Never
# listed as a segment (no SEGMENT_PREFIX) — the updater ignores it; a
# future backfill pass re-joins the pairs and publishes a corrective delta.
LATE_LABELS_FILE = "late-labels.jsonl"


@dataclasses.dataclass
class SpoolConfig:
    """Knobs for the spool's rotation, sampling, and join window."""

    # Rotation: seal the active segment after this many records or this age,
    # whichever first. Both bound label→consumable latency, which feeds
    # straight into model staleness.
    segment_max_records: int = 256
    segment_max_age_s: float = 5.0
    # Fraction of scored requests retained for the join (fractional
    # accumulator, deterministic). ``tenant_fractions`` overrides per tenant.
    sample_fraction: float = 1.0
    tenant_fractions: Dict[str, float] = dataclasses.field(default_factory=dict)
    # Pending-join buffer: scored requests wait here for their label. A
    # label that arrives after eviction is an unmatched drop, counted.
    join_capacity: int = 65536
    join_ttl_s: float = 300.0


def segment_seq(name: str) -> int:
    """Sequence number of a segment file name (sealed or part)."""
    stem = os.path.basename(name)
    for suffix in (SEALED_SUFFIX, PART_SUFFIX):
        if stem.endswith(suffix):
            stem = stem[: -len(suffix)]
            break
    return int(stem[len(SEGMENT_PREFIX):])


def _sealed_name(seq: int) -> str:
    return f"{SEGMENT_PREFIX}{seq:08d}{SEALED_SUFFIX}"


def _part_name(seq: int) -> str:
    return f"{SEGMENT_PREFIX}{seq:08d}{PART_SUFFIX}"


def sealed_segments(directory: str) -> List[str]:
    """Sorted sealed segment file names (consumable set)."""
    if not os.path.isdir(directory):
        return []
    return sorted(
        fn for fn in os.listdir(directory)
        if fn.startswith(SEGMENT_PREFIX) and fn.endswith(SEALED_SUFFIX)
    )


def read_segment(path: str) -> List[dict]:
    """Parse one sealed segment. Sealed segments are fully valid by
    construction; a bad line (bit-rot) is skipped and counted rather than
    poisoning the whole cycle."""
    from photon_tpu.obs.metrics import registry

    out: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                registry().counter("feedback_spool_bad_lines_total").inc()
                logger.warning("unparseable spool line in %s", path)
    return out


def recover_segments(directory: str) -> Dict[str, int]:
    """Seal every orphaned ``.part`` in ``directory`` at exact record
    parity: the complete newline-terminated JSON prefix is rewritten
    (tmp + fsync + rename) as a sealed segment; the torn tail — at most one
    partially written record — is dropped and counted. An all-torn part is
    unlinked. Returns ``{sealed_name: record_count}``.

    Callers must hold (or have verified the absence of) the writer lock:
    the live writer recovers its own predecessor's parts at open; the
    consumer only recovers when it can take the lock itself."""
    from photon_tpu.obs.metrics import registry

    out: Dict[str, int] = {}
    if not os.path.isdir(directory):
        return out
    for fn in sorted(os.listdir(directory)):
        if not (fn.startswith(SEGMENT_PREFIX) and fn.endswith(PART_SUFFIX)):
            continue
        path = os.path.join(directory, fn)
        good: List[str] = []
        torn = False
        with open(path, "rb") as f:
            for raw in f:
                if not raw.endswith(b"\n"):
                    torn = True  # crash mid-append: drop the tail record
                    break
                try:
                    json.loads(raw)
                except ValueError:
                    torn = True
                    break
                good.append(raw.decode())
        if not good:
            os.unlink(path)
            if torn:
                registry().counter("feedback_spool_torn_recovered_total").inc()
            continue
        sealed = os.path.join(directory, _sealed_name(segment_seq(fn)))
        tmp = sealed + ".tmp"
        with open(tmp, "w") as f:
            f.writelines(good)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, sealed)
        os.unlink(path)
        if torn:
            registry().counter("feedback_spool_torn_recovered_total").inc()
        out[os.path.basename(sealed)] = len(good)
        logger.info(
            "recovered orphaned spool part %s -> %s (%d records%s)",
            fn, os.path.basename(sealed), len(good),
            ", torn tail dropped" if torn else "",
        )
    return out


class FeedbackSpool:
    """Single-writer feedback spool over one directory.

    Thread-safe: the serving engine's batcher thread calls
    :meth:`observe_scored`, frontend worker threads call
    :meth:`observe_label`, and the auto-flush thread seals on age."""

    def __init__(self, directory: str, config: Optional[SpoolConfig] = None):
        self.directory = directory
        self.config = config or SpoolConfig()
        os.makedirs(directory, exist_ok=True)
        self._guard = DiskBudgetGuard("feedback.spool")
        self._lock = threading.Lock()
        # Writer exclusivity: one spool directory, one live writer. The lock
        # file is held for the spool's lifetime; a consumer that can take it
        # knows no writer is alive and may recover orphaned parts itself.
        self._lockf = open(os.path.join(directory, WRITER_LOCK), "a")
        try:
            import fcntl

            fcntl.flock(self._lockf.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
        except ImportError:  # non-POSIX: best-effort, single-writer only
            pass
        except OSError:
            self._lockf.close()
            raise RuntimeError(
                f"feedback spool {directory!r} already has a live writer"
            )
        recover_segments(directory)
        seqs = [segment_seq(fn) for fn in os.listdir(directory)
                if fn.startswith(SEGMENT_PREFIX)
                and (fn.endswith(SEALED_SUFFIX) or fn.endswith(PART_SUFFIX))]
        self._seq = max(seqs, default=0) + 1
        self._part = None  # open file object for the active segment
        self._part_records = 0
        self._part_opened_at = 0.0
        # uid -> (enqueue time, scored record) awaiting its label, FIFO.
        self._pending: "dict" = {}
        # uids evicted past the join TTL: a label arriving for one of these
        # is LATE (a measured backfill candidate), not never-seen. Bounded
        # FIFO so the memory cost mirrors the pending buffer's.
        self._expired: "OrderedDict[str, float]" = OrderedDict()
        self._late_logged_seq = -1  # once-per-segment late-label log guard
        self._late_f = None  # late-labels.jsonl sidecar, opened on first use
        self._acc: Dict[str, float] = {}  # per-tenant sampling accumulator
        # Optional join subscriber: called with each successfully appended
        # joined record (score + label + provenance), OUTSIDE the spool
        # lock — the serving engine points the model-quality plane here.
        # Containment matches everything else on this path: a subscriber
        # failure is counted, never raised to the label caller.
        self.on_join = None
        self._flusher: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._closed = False

    # -- write half -------------------------------------------------------

    def observe_scored(
        self,
        uid: Optional[str],
        features=None,
        entity_ids: Optional[dict] = None,
        offset: float = 0.0,
        score: float = 0.0,
        model_version: Optional[str] = None,
        tenant: Optional[str] = None,
        ts: Optional[float] = None,
        trace: Optional[dict] = None,
    ) -> bool:
        """Buffer one scored request for its label. Returns True when the
        request was retained (sampled in and buffered). ``trace`` is the
        request's cross-process trace context (``TraceContext.to_dict()``
        shape): stamped onto the record so the streaming updater's
        micro-generations can name the requests that fed them."""
        from photon_tpu.obs.metrics import registry

        if uid is None:
            return False  # no join key: nothing to wait for
        fraction = self.config.tenant_fractions.get(
            tenant, self.config.sample_fraction
        ) if tenant is not None else self.config.sample_fraction
        if fraction <= 0.0:
            return False
        key = tenant or ""
        with self._lock:
            acc = self._acc.get(key, 0.0) + fraction
            if acc < 1.0:
                self._acc[key] = acc
                registry().counter("feedback_sampled_out_total").inc()
                return False
            self._acc[key] = acc - 1.0
            now = time.time()
            rec = {
                "ts": ts if ts is not None else now,
                "uid": str(uid),
                "tenant": tenant,
                "features": _jsonable_features(features),
                "entityIds": {
                    k: (v if isinstance(v, str) else int(v))
                    for k, v in (entity_ids or {}).items()
                },
                "offset": float(offset),
                "score": float(score),
                "modelVersion": model_version,
            }
            if trace is not None:
                rec["trace"] = {
                    "traceId": trace.get("traceId"),
                    "parentSpanId": trace.get("parentSpanId"),
                }
            self._pending[str(uid)] = (now, rec)
            self._evict_pending_locked(now)
        return True

    def _evict_pending_locked(self, now: float) -> None:
        from photon_tpu.obs.metrics import registry

        cfg = self.config
        dropped = 0
        while self._pending:
            first_uid = next(iter(self._pending))
            t0, rec = self._pending[first_uid]
            over_capacity = len(self._pending) > cfg.join_capacity
            past_ttl = now - t0 > cfg.join_ttl_s
            if over_capacity or past_ttl:
                del self._pending[first_uid]
                dropped += 1
                if past_ttl:
                    self._expired[first_uid] = now
                    # Side-spool the scored half so the eviction is
                    # reclaimable: when its label eventually lands (the
                    # late path below writes the other half), a backfill
                    # pass can re-join the pair instead of losing the
                    # example.
                    self._spool_late_locked(
                        {"kind": "evicted", "evictedAt": now, "record": rec}
                    )
            else:
                break
        expired_cap = max(cfg.join_capacity, 1024)
        while len(self._expired) > expired_cap:
            self._expired.popitem(last=False)
        if dropped:
            registry().counter("feedback_join_dropped_total").inc(dropped)

    def late_labels_path(self) -> str:
        return os.path.join(self.directory, LATE_LABELS_FILE)

    def _spool_late_locked(self, obj: dict) -> bool:
        """Append one JSON line to the ``late-labels.jsonl`` sidecar.
        Best-effort by design: the sidecar reclaims data the join already
        gave up on, so a write failure drops with a counter and must never
        take down label ingestion (same containment contract as
        ``_append_locked``). Lines interleave two kinds keyed by uid —
        ``evicted`` (the scored features, written at TTL eviction) and
        ``late_label`` (the label, written when it finally arrives) — which
        is exactly the pair a future backfill pass re-joins."""
        from photon_tpu.obs.metrics import registry

        try:
            self._guard.check()
            if self._late_f is None:
                self._late_f = open(self.late_labels_path(), "a")
            self._late_f.write(json.dumps(obj) + "\n")
            self._late_f.flush()
        except Exception as exc:  # noqa: BLE001 — containment, not rethrow
            self._guard.record(exc)
            registry().counter("feedback_late_spool_errors_total").inc()
            return False
        registry().counter("feedback_late_spooled_total").inc()
        return True

    def observe_label(
        self, uid: str, label: float, ts: Optional[float] = None
    ) -> bool:
        """Join an observed label with its buffered scored request and
        append the joined record to the active segment. Never raises to the
        caller (label ingestion must not break serving) — every failure
        mode drops with a counter. Returns True when the record landed."""
        from photon_tpu.obs.metrics import registry

        rule = faults.injector().fire("serve.feedback", label=str(uid))
        if rule is not None:
            if rule.kind == "kill":
                import signal

                logger.error("fault serve.feedback: SIGKILL")
                os.kill(os.getpid(), signal.SIGKILL)
            if rule.kind == "torn":
                self._tear_active_segment()
                registry().counter("feedback_labels_dropped_total").inc()
                return False
            if rule.kind == "enospc":
                registry().counter("feedback_labels_dropped_total").inc()
                self._guard.record(faults.exception_for(rule, "serve.feedback"))
                return False
            # transient / permanent: the label-join drop
            registry().counter("feedback_labels_dropped_total").inc()
            logger.warning("fault serve.feedback: label join dropped (%s)",
                           rule.kind)
            return False
        with self._lock:
            entry = self._pending.pop(str(uid), None)
            if entry is None:
                if str(uid) in self._expired:
                    # The scored request WAS here; the label just missed the
                    # join window. Counted separately from never-seen uids so
                    # the planned backfill pass has a measured denominator —
                    # and side-spooled so that pass has the label itself,
                    # not just a count.
                    registry().counter("feedback_label_late_total").inc()
                    self._spool_late_locked({
                        "kind": "late_label",
                        "uid": str(uid),
                        "label": float(label),
                        "labelTs": ts if ts is not None else time.time(),
                    })
                    if self._late_logged_seq != self._seq:
                        self._late_logged_seq = self._seq
                        logger.warning(
                            "feedback: label for uid %s arrived after the "
                            "%.0fs join TTL; counting in "
                            "feedback_label_late_total (logged once per "
                            "segment)", uid, self.config.join_ttl_s,
                        )
                else:
                    registry().counter("feedback_labels_unmatched_total").inc()
                return False
            _t0, rec = entry
            rec = dict(rec)
            rec["label"] = float(label)
            rec["labelTs"] = ts if ts is not None else time.time()
            landed = self._append_locked(rec)
        if landed and self.on_join is not None:
            try:
                self.on_join(rec)
            except Exception:  # noqa: BLE001 — subscriber never hurts labels
                registry().counter("feedback_join_subscriber_errors_total").inc()
                logger.exception("feedback spool on_join subscriber failed")
        return landed

    def _append_locked(self, rec: dict) -> bool:
        from photon_tpu.obs.metrics import registry

        now = time.time()
        try:
            self._guard.check()
            if self._part is None:
                path = os.path.join(self.directory, _part_name(self._seq))
                self._part = open(path, "a")
                self._part_records = 0
                self._part_opened_at = now
            self._part.write(json.dumps(rec) + "\n")
            self._part.flush()
        except Exception as exc:  # noqa: BLE001 — containment, not rethrow
            self._guard.record(exc)
            registry().counter("feedback_records_dropped_total").inc()
            logger.warning("feedback spool append failed: %s", exc)
            return False
        self._part_records += 1
        registry().counter("feedback_records_total").inc()
        if (self._part_records >= self.config.segment_max_records
                or now - self._part_opened_at >= self.config.segment_max_age_s):
            self._seal_locked()
        return True

    def _seal_locked(self) -> None:
        if self._part is None or self._part_records == 0:
            return
        part_path = self._part.name
        self._part.flush()
        os.fsync(self._part.fileno())
        self._part.close()
        os.replace(
            part_path,
            os.path.join(self.directory, _sealed_name(self._seq)),
        )
        self._part = None
        self._seq += 1

    def _tear_active_segment(self) -> None:
        """``torn`` fault: abandon the active segment mid-record, as a crash
        between ``write`` syscalls would. The half line is visible on disk;
        the writer moves on to a fresh sequence number (a restarted process
        would), and recovery must drop exactly the torn tail."""
        with self._lock:
            if self._part is None:
                path = os.path.join(self.directory, _part_name(self._seq))
                self._part = open(path, "a")
                self._part_records = 0
                self._part_opened_at = time.time()
            self._part.write('{"torn": tru')  # no newline, invalid JSON
            self._part.flush()
            self._part.close()
            self._part = None
            self._seq += 1
            logger.warning("fault serve.feedback: active segment torn")

    # -- lifecycle --------------------------------------------------------

    def flush(self) -> None:
        """Seal the active segment if it holds any records (makes them
        visible to the consumer immediately)."""
        with self._lock:
            self._seal_locked()

    def tick(self) -> None:
        """Age-based seal — call periodically so a quiet tenant's records
        don't sit invisible in an unsealed part past the age bound."""
        with self._lock:
            if (self._part is not None and self._part_records > 0
                    and time.time() - self._part_opened_at
                    >= self.config.segment_max_age_s):
                self._seal_locked()
            self._evict_pending_locked(time.time())

    def start_auto_flush(self) -> None:
        if self._flusher is not None:
            return
        interval = max(0.05, min(1.0, self.config.segment_max_age_s / 2.0))

        def loop():
            while not self._stop.wait(interval):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — flusher must survive
                    logger.exception("feedback spool tick failed")

        self._flusher = threading.Thread(
            target=loop, name="feedback-spool-flush", daemon=True
        )
        self._flusher.start()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        if self._flusher is not None:
            self._flusher.join(timeout=5.0)
        with self._lock:
            self._seal_locked()
            if self._part is not None:  # empty part: discard
                path = self._part.name
                self._part.close()
                try:
                    os.unlink(path)
                except OSError:
                    pass
                self._part = None
            if self._late_f is not None:
                try:
                    self._late_f.close()
                except OSError:
                    pass
                self._late_f = None
        try:
            self._lockf.close()
        except OSError:
            pass

    def stats(self) -> dict:
        with self._lock:
            return {
                "pending_joins": len(self._pending),
                "expired_uids": len(self._expired),
                "active_records": self._part_records if self._part else 0,
                "next_seq": self._seq,
                "sealed": len(sealed_segments(self.directory)),
                "late_labels_path": self.late_labels_path(),
            }


def _jsonable_features(features):
    """Features as JSON: dict {key: value} and (indices, values) pairs pass
    through; dense per-shard vectors become lists."""
    import numpy as np

    if features is None:
        return None
    if isinstance(features, dict):
        out = {}
        for shard, val in features.items():
            if isinstance(val, dict):
                out[shard] = {str(k): float(v) for k, v in val.items()}
            elif (isinstance(val, tuple) and len(val) == 2):
                idx, vals = val
                out[shard] = [
                    [int(i) for i in np.asarray(idx).tolist()],
                    [float(v) for v in np.asarray(vals).tolist()],
                ]
            else:
                out[shard] = [float(v) for v in np.asarray(val).tolist()]
        return out
    return [float(v) for v in np.asarray(features).tolist()]


def read_late_pairs(path: str) -> List[dict]:
    """Re-join the late-labels sidecar: ``evicted`` lines carry the scored
    half (features, score, modelVersion), ``late_label`` lines the label
    that missed the join window. Matching halves (by uid) merge into full
    spool-shaped records — the same dict :meth:`FeedbackSpool.observe_label`
    would have appended had the label been on time. Unmatched halves are
    left in the file and pair up on a later pass. Ordering is deterministic
    — sorted by (labelTs, uid) — so a crashed-and-restarted replay pass
    rebuilds the identical training batch. Malformed lines skip with a
    counter (the sidecar is best-effort on the write side too)."""
    from photon_tpu.obs.metrics import registry

    if not os.path.exists(path):
        return []
    evicted: Dict[str, dict] = {}
    labels: Dict[str, dict] = {}
    bad = 0
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    bad += 1
                    continue
                kind = obj.get("kind")
                if kind == "evicted" and isinstance(obj.get("record"), dict):
                    rec = obj["record"]
                    uid = str(rec.get("uid"))
                    # Last write wins: a uid re-scored and re-evicted pairs
                    # with the freshest features (file order is arrival
                    # order, so this stays deterministic).
                    evicted[uid] = rec
                elif kind == "late_label" and obj.get("uid") is not None:
                    labels[str(obj["uid"])] = obj
                else:
                    bad += 1
    except OSError:
        return []
    if bad:
        registry().counter("feedback_late_malformed_total").inc(bad)
    out: List[dict] = []
    for uid, rec in evicted.items():
        lab = labels.get(uid)
        if lab is None:
            continue
        joined = dict(rec)
        joined["label"] = float(lab.get("label") or 0.0)
        joined["labelTs"] = float(lab.get("labelTs") or 0.0)
        out.append(joined)
    out.sort(key=lambda r: (float(r.get("labelTs") or 0.0), str(r.get("uid"))))
    return out


def recover_orphan_parts(directory: str) -> Dict[str, int]:
    """Consumer-side recovery: seal orphaned parts only when no live writer
    holds the lock (take it non-blocking, recover, release). With a live
    writer present this is a no-op — the writer owns its parts."""
    lock_path = os.path.join(directory, WRITER_LOCK)
    if not os.path.isdir(directory):
        return {}
    try:
        lockf = open(lock_path, "a")
    except OSError:
        return {}
    try:
        try:
            import fcntl

            fcntl.flock(lockf.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
        except ImportError:
            pass
        except OSError:
            return {}  # live writer: leave its parts alone
        return recover_segments(directory)
    finally:
        lockf.close()
