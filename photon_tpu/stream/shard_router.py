"""Entity-hash shard routing for the streaming freshness plane.

One ``StreamingUpdater`` process consumes every spool (PR 11/13), so
freshness throughput is flat while serving QPS scales with the fleet. The
fix is the same move that made fleet cache hit rate a routing property:
give each updater shard a DISJOINT entity subset via the consistent-hash
ring (``serve/routing.py``), so shards never contend on a model row and
their per-entity delta layers commute (``io/model_io.layers_commute``).

The routing key is load-bearing: a record routes on the SAME per-entity
string ``serve/store._owned_mask`` hashes — the raw entity id when the
record carries one (``entityIds[re_type]``), else the decimal index form a
pre-interned int key serializes to. ``serve/routing.route_key`` already
encodes exactly that contract, so this module reuses it verbatim; an
updater shard's working set is therefore literally a serving replica's
entity shard, just over a ring with ``updater:k`` members instead of
replica ids.

Two routing topologies share the same ring:

- READ-SIDE (:func:`read_owned_segment`): every shard worker lists the
  same sealed segments and keeps only the rows it owns, routing on the
  raw line without a full parse. Zero extra writes, works over multi-dir
  spool globs — but every shard still scans every line, so aggregate
  throughput plateaus at the scan cost.
- MATERIALIZING (:func:`route_segments`): a router splits each sealed
  segment ONCE into per-shard sub-spool segments (same sequence numbers,
  atomic tmp+rename, idempotent re-runs), and each worker consumes only
  its own sub-spool (``pre_routed=True``) — per-shard cost is then
  proportional to owned records, which is what lets aggregate throughput
  actually scale with shard count.

MIXED segments split at record level in both modes; whole-segment routing
falls out for free when a segment happens to be single-entity. Records
with no entity ids at all (FE-only feedback; nothing row-level to train)
deterministically home on shard 0 so exactly one worker counts them.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Dict, List, Optional, Sequence, Tuple

from photon_tpu.serve.routing import HashRing, route_key

logger = logging.getLogger(__name__)

MEMBER_PREFIX = "updater:"


def shard_members(num_shards: int) -> List[str]:
    """Ring member names for ``num_shards`` updater shards. Stable strings
    (``updater:k``) — the ring snapshot, the manifest shard block, and the
    per-shard metric labels all agree on the same identity."""
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    return [f"{MEMBER_PREFIX}{k}" for k in range(num_shards)]


def shard_ring(
    num_shards: int, vnodes: int = 64, seed: int = 0
) -> HashRing:
    """The updater plane's ring. Every shard worker builds this from the
    same ``(num_shards, vnodes, seed)`` — blake2b makes owner assignment
    identical across processes, so N independently started workers derive
    the same disjoint partition with no coordination traffic."""
    return HashRing(shard_members(num_shards), vnodes=vnodes, seed=seed)


def member_index(member: str) -> int:
    """``updater:k`` -> k."""
    return int(member.rsplit(":", 1)[1])


def shard_of_record(
    record: dict,
    ring: HashRing,
    route_re_type: Optional[str] = None,
) -> int:
    """Owning shard index for one joined spool record. Hashes the identical
    string serving routes and ``_owned_mask`` masks on; entity-less records
    home on shard 0."""
    key = route_key(record.get("entityIds"), route_re_type)
    if key is None:
        return 0
    return member_index(ring.owner(key))


def owned_records(
    records: Sequence[dict],
    ring: HashRing,
    shard_index: int,
    route_re_type: Optional[str] = None,
) -> List[dict]:
    """The subset of ``records`` shard ``shard_index`` owns — a shard
    worker's view of a (possibly mixed) sealed segment."""
    return [
        r for r in records
        if shard_of_record(r, ring, route_re_type) == shard_index
    ]


_ENTITY_IDS_TOKEN = '"entityIds":'
_DECODER = json.JSONDecoder()


def entity_ids_of_line(line: str) -> Tuple[bool, Optional[dict]]:
    """Cheap ``entityIds`` extraction from one raw spool JSON line —
    ``(ok, ids)``.

    Read-side routing's scaling ceiling is the parse: every shard lists
    every sealed segment, and ``json.loads`` on records it will throw away
    costs more than the routing hash itself. This decodes ONLY the (tiny)
    ``entityIds`` object and leaves the rest of the line untouched, so a
    non-owner spends ~a hash per foreign record instead of a full parse.

    The token search is sound, not heuristic: ``json.dumps`` escapes every
    quote inside a string value (``\\"``), so the unescaped byte sequence
    ``"entityIds":`` can only occur as a real object key. Absence therefore
    means an entity-less record (``ids=None``, routes to shard 0). Any
    decode surprise returns ``ok=False`` — callers must fall back to the
    full parse, never guess.
    """
    i = line.find(_ENTITY_IDS_TOKEN)
    if i < 0:
        return True, None
    j = i + len(_ENTITY_IDS_TOKEN)
    n = len(line)
    while j < n and line[j] in " \t":
        j += 1
    try:
        ids, _ = _DECODER.raw_decode(line, j)
    except ValueError:
        return False, None
    if ids is not None and not isinstance(ids, dict):
        return False, None
    return True, ids


def read_owned_segment(
    path: str,
    ring: HashRing,
    shard_index: int,
    route_re_type: Optional[str] = None,
) -> Tuple[List[dict], int]:
    """One shard worker's view of a sealed segment: ``(owned_records,
    total_records)``.

    Routes on the raw line via :func:`entity_ids_of_line` and fully parses
    ONLY owned rows (plus the rare ambiguous line). Mirrors
    ``spool.read_segment``'s bit-rot discipline — a corrupt line is skipped
    and counted, never poisons the cycle. ``total_records`` counts every
    routable line (the whole segment's record count, not just this shard's
    subset), so per-shard manifests can record how much traffic they
    routed past.
    """
    from photon_tpu.obs.metrics import registry

    owned: List[dict] = []
    total = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            ok, ids = entity_ids_of_line(line)
            if ok:
                key = route_key(ids, route_re_type)
                shard = 0 if key is None else member_index(ring.owner(key))
                total += 1
                if shard != shard_index:
                    continue
                try:
                    owned.append(json.loads(line))
                except ValueError:
                    total -= 1
                    registry().counter(
                        "feedback_spool_bad_lines_total").inc()
                    logger.warning("unparseable spool line in %s", path)
                continue
            # Ambiguous prefix: full parse decides (and validates) routing.
            try:
                record = json.loads(line)
            except ValueError:
                registry().counter("feedback_spool_bad_lines_total").inc()
                logger.warning("unparseable spool line in %s", path)
                continue
            total += 1
            if shard_of_record(record, ring, route_re_type) == shard_index:
                owned.append(record)
    return owned, total


def updater_spill_dir(spill_root: str, shard_index: int) -> str:
    """Shard ``k``'s host-owned spill partition — ``<spill_root>/host-k/``
    (re_store.partition_spill_dir over the ``updater:k`` member). An
    updater shard parks its out-of-core host masters here so a shard-count
    rebalance relocates them by file rename, not row re-stream."""
    from photon_tpu.algorithm.re_store import partition_spill_dir

    return partition_spill_dir(spill_root, f"{MEMBER_PREFIX}{shard_index}")


def rebalance_updater_spill(
    spill_root: str,
    old_num_shards: int,
    new_num_shards: int,
    vnodes: int = 64,
    seed: int = 0,
) -> Dict[str, Dict]:
    """Re-home spill partitions across a shard-count change: every
    ``updater:k`` partition departed by the resize is adopted by its
    deterministic successor on the new ring via ``os.replace`` (see
    re_store.rebalance_spill_layout — and its locality-hint caveat: the
    owned-record filter, not file placement, remains the correctness
    boundary). Shrinking from 4 to 2 shards moves ``host-2``/``host-3``
    files under the survivors; growing moves nothing (new shards start
    cold) — either way, zero rows are decoded."""
    from photon_tpu.algorithm.re_store import rebalance_spill_layout

    return rebalance_spill_layout(
        spill_root,
        shard_ring(old_num_shards, vnodes=vnodes, seed=seed),
        shard_ring(new_num_shards, vnodes=vnodes, seed=seed),
    )


def shard_spool_dir(out_root: str, shard_index: int) -> str:
    """Per-shard sub-spool directory the materializing router writes —
    ``out_root/shard-k/``. Shard worker k points its ``spool_dir`` here
    (with ``pre_routed=True``) to skip read-side filtering entirely."""
    return os.path.join(out_root, f"shard-{shard_index}")


def route_segments(
    src_dir: str,
    out_root: str,
    num_shards: int,
    vnodes: int = 64,
    seed: int = 0,
    route_re_type: Optional[str] = None,
    ring: Optional[HashRing] = None,
) -> int:
    """Materialize the shard partition: split every sealed segment in
    ``src_dir`` into per-shard sub-spool segments under
    ``out_root/shard-k/`` and return how many segments were routed this
    call.

    Read-side filtering (:func:`read_owned_segment`) keeps every shard
    scanning every line, so its aggregate throughput plateaus at the
    routing-scan cost no matter how many shards run. This router pays the
    scan ONCE, upstream — each raw line is appended verbatim to exactly one
    shard's copy of the segment, so a worker's parse cost is proportional
    to the records it actually owns. Routing hashes the identical
    per-entity string as serving (:func:`entity_ids_of_line` +
    ``route_key``); entity-less records land on shard 0; a corrupt line is
    counted and dropped for every shard alike.

    Crash-safe and idempotent by construction: each shard file is written
    to a dot-tmp sibling, fsync'd, then renamed, and a segment counts as
    routed only when ALL ``num_shards`` outputs exist — a re-run after a
    mid-split crash rewrites the incomplete segment byte-identically (the
    ring is deterministic) and never touches completed ones. Output
    segments keep the SOURCE sequence numbers, so the per-shard
    manifest-as-cursor chain (``stream.consumedThrough``) means the same
    thing against a routed sub-spool as against the raw spool.
    """
    from photon_tpu.obs.metrics import registry
    from photon_tpu.stream.spool import sealed_segments

    if ring is None:
        ring = shard_ring(num_shards, vnodes=vnodes, seed=seed)
    routed = 0
    shard_dirs = [shard_spool_dir(out_root, k) for k in range(num_shards)]
    for d in shard_dirs:
        os.makedirs(d, exist_ok=True)
    memo: Dict[str, int] = {}  # entity route-key -> shard
    for fn in sealed_segments(src_dir):
        finals = [os.path.join(d, fn) for d in shard_dirs]
        if all(os.path.exists(p) for p in finals):
            continue
        tmps = [p + ".routing" for p in finals]
        outs = [open(t, "w") for t in tmps]
        try:
            with open(os.path.join(src_dir, fn)) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    ok, ids = entity_ids_of_line(line)
                    if ok:
                        key = route_key(ids, route_re_type)
                    else:
                        try:
                            record = json.loads(line)
                        except ValueError:
                            registry().counter(
                                "feedback_spool_bad_lines_total").inc()
                            logger.warning(
                                "unparseable spool line in %s", fn)
                            continue
                        key = route_key(
                            record.get("entityIds"), route_re_type)
                    if key is None:
                        shard = 0
                    else:
                        shard = memo.get(key)
                        if shard is None:
                            shard = member_index(ring.owner(key))
                            memo[key] = shard
                    outs[shard].write(line + "\n")
            for out in outs:
                out.flush()
                os.fsync(out.fileno())
        finally:
            for out in outs:
                out.close()
        for tmp, final in zip(tmps, finals):
            os.replace(tmp, final)
        routed += 1
        registry().counter("stream_router_segments_total").inc()
    return routed


def split_records(
    records: Sequence[dict],
    ring: HashRing,
    num_shards: int,
    route_re_type: Optional[str] = None,
) -> Dict[int, List[dict]]:
    """Partition a segment's records across all shards in one pass —
    ``{shard_index: [records]}``, every input record in exactly one bucket.
    The routing smoke uses this to assert the partition is disjoint AND
    complete against per-shard ``owned_records`` views."""
    out: Dict[int, List[dict]] = {k: [] for k in range(num_shards)}
    for r in records:
        out[shard_of_record(r, ring, route_re_type)].append(r)
    return out
