"""Continuous micro-generation updater: spool segments → warm-started
per-entity solves → gated delta publishes.

The consume half of the streaming freshness loop. A long-running
:class:`StreamingUpdater` polls the feedback spool for sealed segments,
batches their joined (features, label) records into an incremental update
(``train/incremental.py`` — warm start from the parent generation, active-set
per-entity solves, row-level merge), and publishes the result as a
per-entity DELTA layer (``io/model_io.py:save_delta_model``) through the
SAME validation gate and ``LATEST`` pointer full generations use. Serving
picks micro-generations up through the unchanged rollout watcher.

Consume-cursor discipline — the generation manifest IS the cursor. Each
published micro-generation records ``stream.consumedThrough`` (the highest
segment sequence it trained on) in its manifest, written durably BEFORE the
gate can flip ``LATEST``. Crash-resume is therefore double-apply-free by
construction:

- killed before the flip → ``LATEST`` (and so the cursor) is unchanged; the
  restarted updater reprocesses the same segments from the same parent,
  deterministically producing the same model;
- killed after the flip → the segments are recorded consumed and skipped.

There is no second cursor file to drift out of sync with the model lineage.
A gate-refused generation never moves the cursor (it is not in the
``LATEST`` lineage), so its segments are retried next cycle.

Fault site ``stream.consume`` fires once per consumed segment (labelled with
the segment name) and once more labelled ``train`` before the solve — a
``kill`` rule at the right call index crashes the updater mid-generation,
which is exactly what the resume-equivalence tests exercise. The late-label
replay pass uses its own site, ``stream.replay``, so replay cadence can
never shift ``stream.consume`` call indices out from under those tests.
"""

from __future__ import annotations

import dataclasses
import glob as glob_mod
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_tpu.stream.spool import (
    LATE_LABELS_FILE,
    read_late_pairs,
    read_segment,
    recover_orphan_parts,
    sealed_segments,
    segment_seq,
)
from photon_tpu.utils import faults

logger = logging.getLogger(__name__)

_CURSOR_KEY = "consumedThrough"
_PER_SPOOL_KEY = "consumedPerSpool"


def discover_spool_dirs(spec: str) -> List[str]:
    """``spool_dir`` may be one directory or a GLOB over several (the fleet
    shape: each scorer replica spools into ``<base>/<replica-id>``, the
    updater polls ``<base>/*``). Sorted for deterministic cycle order; a
    replica joining mid-run is picked up on the next poll with no updater
    restart."""
    if is_spool_glob(spec):
        return sorted(d for d in glob_mod.glob(spec) if os.path.isdir(d))
    return [spec]


def is_spool_glob(spec: str) -> bool:
    return any(ch in spec for ch in "*?[")


def spool_dir_key(path: str) -> str:
    """Stable manifest key for one spool directory (its basename — the
    replica id in the fleet layout)."""
    return os.path.basename(os.path.normpath(path))


def merge_pending_segments(
    dirs: Sequence[str],
    cursors: Dict[str, int],
    max_segments: int,
) -> List[Tuple[str, str]]:
    """Unconsumed sealed segments across every spool dir, merged in mtime
    order (seal time ≈ label arrival time, so records from N replicas
    interleave roughly chronologically). Ties break on (dir key, seq).
    Within one dir mtimes are monotone in seq, so ANY prefix of the merged
    order contains a per-dir seq prefix — the per-dir cursors stay sound
    under the ``max_segments`` cap."""
    entries: List[Tuple[float, str, int, str, str]] = []
    for d in dirs:
        cursor = cursors.get(spool_dir_key(d), 0)
        for fn in sealed_segments(d):
            seq = segment_seq(fn)
            if seq <= cursor:
                continue
            try:
                mtime = os.path.getmtime(os.path.join(d, fn))
            except OSError:
                continue  # consumed and pruned between listdir and stat
            entries.append((mtime, spool_dir_key(d), seq, d, fn))
    entries.sort()
    return [(d, fn) for _, _, _, d, fn in entries[:max_segments]]


@dataclasses.dataclass
class StreamingUpdaterConfig:
    """Everything one streaming updater needs besides the loaded index
    artifacts. ``coordinate_configs`` / ``update_sequence`` / ``task`` are
    the same objects the batch drivers use — the updater runs the same
    estimator, just on spool-fed micro-batches."""

    publish_root: str
    spool_dir: str  # one directory, or a glob over per-replica spool dirs
    task: object
    coordinate_configs: Sequence
    update_sequence: Sequence[str]
    cadence_s: float = 5.0
    # Don't bother solving for fewer joined records than this; the segments
    # stay unconsumed and accumulate into the next cycle.
    min_records: int = 8
    max_segments_per_cycle: int = 64
    locked_coordinates: Sequence[str] = ()
    # Publish per-entity delta layers (full publish is the fallback when a
    # layer is not emittable). ``full_every=k`` forces every k-th publish to
    # be full, bounding delta-chain length; 0 never forces.
    delta_artifacts: bool = True
    full_every: int = 0
    # Every k-th record (deterministically) is held out for the gate's
    # regression bound instead of trained on; 0 disables holdout scoring.
    holdout_fraction: float = 0.0
    evaluators: Sequence[str] = ("AUC",)
    metric_tolerance: float = 0.02
    norm_drift_bound: float = 10.0
    num_iterations: int = 1
    re_convergence_tol: float = 1e-4
    # Out-of-core residency for the per-cycle fits (train/incremental.py
    # pass-through). Sharded workers spill under the host-owned layout
    # ``<re_spill_dir>/host-<shard_index>/`` so a shard-count rebalance is
    # a file move (shard_router.rebalance_updater_spill), not a re-stream.
    re_device_budget_mb: Optional[float] = None
    re_spill_dir: Optional[str] = None
    # Sharded freshness plane: this worker is shard ``shard_index`` of
    # ``num_shards``. Records route by hashing the SAME per-entity string
    # serving's ``_owned_mask`` hashes (stream/shard_router.py), so each
    # worker's working set is a disjoint entity subset and its delta layers
    # commute with every sibling's. (num_shards=1, shard_index=0) is the
    # PR 11 single-updater plane byte-for-byte.
    num_shards: int = 1
    shard_index: int = 0
    shard_vnodes: int = 64
    shard_seed: int = 0
    route_re_type: Optional[str] = None
    # ``spool_dir`` already holds ONLY this shard's records (a materializing
    # router — shard_router.route_segments — split the raw spool upstream),
    # so skip read-side ring filtering and consume segments whole. Cursor
    # manifests stay shard-tagged: routed sub-spools keep the source
    # sequence numbers, so consumedThrough means the same thing.
    pre_routed: bool = False
    # Serialize the publish tail (save→manifest→gate→flip) under the
    # publish root's flock and rebase onto the live LATEST. None = auto:
    # on whenever sibling shards exist. Forcing True on a single updater
    # is safe (and protects against a concurrent batch publisher).
    serialize_publish: Optional[bool] = None
    # FE-drift trigger: the streaming plane locks the fixed effect, so its
    # age only grows. Past this bar the ``fe_age_s`` SLO objective starts
    # burning and the ``stream_fe_retrain_wanted`` gauge raises; with
    # ``fe_retrain`` on, the updater actually acts on it — a cooldown-
    # guarded full-publish generation with the FE coordinate unlocked,
    # trained on a bounded window of recent records.
    fe_max_age_s: float = 3600.0
    fe_retrain: bool = False
    fe_retrain_cooldown_s: float = 600.0
    fe_retrain_min_records: int = 32
    fe_retrain_window: int = 4096
    # Late-label replay correction pass: every ``late_replay_cadence_s``
    # seconds the updater re-joins the spool sidecar's (evicted, late_label)
    # halves and, once at least ``late_replay_min_pairs`` fresh pairs exist,
    # retrains the affected entities into a corrective delta published
    # through the UNCHANGED gate. 0 disables (the default — replay is
    # opt-in, exactly like holdout).
    late_replay_cadence_s: float = 0.0
    late_replay_min_pairs: int = 8


@dataclasses.dataclass
class CycleResult:
    """One ``run_once`` outcome (None is returned instead when there was
    nothing to consume)."""

    generation: str
    published: bool
    is_delta: bool
    gate_reason: Optional[str]
    segments: List[str]
    records: int
    consumed_through: int
    staleness_s: Optional[float]


def records_to_batch(records: List[dict], index_maps: Dict,
                     entity_indexes: Dict, intern: bool = True):
    """Joined spool records → one training GameBatch. Features densify
    exactly like the serving engine's request assembly (string keys through
    the shard's index map, intercept column set when the map has one), so
    the updater trains on the same vectors serving scored. New entity ids
    intern append-only into ``entity_indexes`` — existing slots never move.
    """
    import jax.numpy as jnp

    from photon_tpu.data.game_data import GameBatch
    from photon_tpu.data.index_map import IndexMap

    n = len(records)
    shard_dims = {shard: len(imap) for shard, imap in index_maps.items()}
    icpt = {
        shard: imap.get_index(IndexMap.INTERCEPT)
        if IndexMap.INTERCEPT in imap else -1
        for shard, imap in index_maps.items()
    }
    feats = {
        shard: np.zeros((n, d), np.float32) for shard, d in shard_dims.items()
    }
    eids = {
        re_type: np.full(n, -1, np.int64) for re_type in entity_indexes
    }
    label = np.zeros(n, np.float32)
    offset = np.zeros(n, np.float32)
    for i, rec in enumerate(records):
        label[i] = float(rec.get("label") or 0.0)
        offset[i] = float(rec.get("offset") or 0.0)
        for shard, d in shard_dims.items():
            row = feats[shard][i]
            j = icpt[shard]
            if j >= 0:
                row[j] = 1.0
            val = (rec.get("features") or {}).get(shard)
            if val is None:
                continue
            if isinstance(val, dict):
                imap = index_maps[shard]
                for k, v in val.items():
                    col = imap.get_index(k) if k in imap else -1
                    if 0 <= col < d:
                        row[col] = float(v)
            elif (isinstance(val, (list, tuple)) and len(val) == 2
                  and isinstance(val[0], (list, tuple))):
                idx = np.asarray(val[0], np.int64)
                vals = np.asarray(val[1], np.float32)
                ok = (idx >= 0) & (idx < d)
                row[idx[ok]] = vals[ok]
            else:
                arr = np.asarray(val, np.float32)
                if arr.shape != (d,):
                    raise ValueError(
                        f"spool record {i}: shard {shard!r} expects ({d},), "
                        f"got {arr.shape}"
                    )
                row[:] = arr
        for re_type, eidx in entity_indexes.items():
            key = (rec.get("entityIds") or {}).get(re_type)
            if key is None:
                continue
            if isinstance(key, str):
                eids[re_type][i] = (
                    eidx.intern(key) if intern else eidx.lookup(key)
                )
            else:
                eids[re_type][i] = int(key)
    return GameBatch(
        label=jnp.asarray(label),
        offset=jnp.asarray(offset),
        weight=jnp.ones(n, jnp.float32),
        features={s: jnp.asarray(a) for s, a in feats.items()},
        entity_ids={t: jnp.asarray(a, jnp.int32) for t, a in eids.items()},
    )


class StreamingUpdater:
    """Spool-consuming micro-generation publisher over one publish root."""

    def __init__(
        self,
        config: StreamingUpdaterConfig,
        index_maps: Dict,
        entity_indexes: Dict,
    ):
        self.config = config
        self.index_maps = index_maps
        self.entity_indexes = entity_indexes
        self._cycles = 0
        self._publishes = 0
        self._stop = threading.Event()
        # Busy-time accounting for the shard-scaling bench: wall seconds
        # spent inside cycles (busy) and inside the train+publish step
        # (train), plus records actually trained on. Σ_shards(records /
        # busy) is the aggregate-throughput number `--updater-shard-ab`
        # reports, mirroring the multichip busy-time methodology.
        self._busy_s = 0.0
        self._train_s = 0.0
        self._records_trained = 0
        if not (0 <= config.shard_index < max(1, config.num_shards)):
            raise ValueError(
                f"shard_index {config.shard_index} out of range for "
                f"num_shards {config.num_shards}"
            )
        self._ring = None
        if config.num_shards > 1:
            from photon_tpu.stream.shard_router import shard_ring

            self._ring = shard_ring(
                config.num_shards,
                vnodes=config.shard_vnodes,
                seed=config.shard_seed,
            )
        # Updater-side SLO plane: cycle success ratio + published-model
        # freshness — the training half of the serve-side tracker, so
        # staleness is measurable when no server is running — plus the
        # locked-FE age objective feeding the retrain-wanted trigger.
        from photon_tpu.obs.slo import SLOTracker, streaming_objectives

        self.slo = SLOTracker(
            objectives=streaming_objectives(
                fe_age_threshold_s=config.fe_max_age_s
            )
        )
        # Updater-side quality plane: holdout records (and replayed late
        # pairs) are scored-and-labelled examples keyed by the model
        # version that actually served them, so the training half measures
        # online quality even with no serving engine in-process. The
        # updater's SLO tracker carries no quality objectives by default —
        # record_event on an unknown objective is a no-op — so this is
        # measurement until a drill wires the rings in.
        from photon_tpu.obs.quality import (
            QualityConfig,
            QualityPlane,
            task_name,
        )

        self.quality = QualityPlane(QualityConfig(task=task_name(config.task)))
        self._last_replay = 0.0
        self._replay_publishes = 0
        self._fe_retrains = 0
        self._last_fe_retrain: Optional[float] = None
        # Bounded window of recent train records feeding an FE retrain;
        # only populated when the actuation is enabled.
        from collections import deque

        self._fe_recent: "deque" = deque(maxlen=max(1, config.fe_retrain_window))

    # -- cursor ------------------------------------------------------------

    def _cursor_matches(self, stream: Dict) -> bool:
        """Whether a lineage ``stream`` block is THIS worker's cursor. A
        sharded worker's cursor chain is the subsequence of manifests
        tagged with its own ``shard`` identity — sibling shards' blocks are
        walked through exactly like batch publishes. Untagged blocks (the
        PR 11 single-updater plane) count for every shard: they record
        segments the pre-shard plane fully consumed, so adopting them as a
        floor is what makes a 1→N reshard resume without re-training old
        traffic. A block from a DIFFERENT topology (other ``of``) is
        skipped — resharding N→M needs a drained spool or a fresh full
        publish (see README runbook)."""
        if _CURSOR_KEY not in stream and _PER_SPOOL_KEY not in stream:
            return False
        shard = stream.get("shard")
        if not shard:
            return True
        return (
            int(shard.get("of", 0)) == self.config.num_shards
            and int(shard.get("index", -1)) == self.config.shard_index
        )

    def _stream_blocks(self):
        """Yield the ``stream`` manifest blocks of the published lineage,
        newest first, walking parent links from ``LATEST``. Shared by every
        cursor lookup (segment cursor, replay-pairs cursor) so they all see
        the same chain with the same hop bound."""
        from photon_tpu.cli.game_serving import resolve_model_dir
        from photon_tpu.io.model_io import load_generation_manifest

        root = self.config.publish_root
        cur = resolve_model_dir(root)
        if cur == root:
            return
        for _ in range(128):
            manifest = load_generation_manifest(cur) or {}
            yield manifest.get("stream") or {}
            parent = manifest.get("parent")
            if not parent:
                return
            cur = os.path.join(root, parent)
            if not os.path.isdir(cur):
                return

    def _cursor_stream_info(self) -> Dict:
        """The most recent ``stream`` manifest block in the published
        lineage that belongs to THIS worker: the first matching block on
        the parent walk. A full (batch) publish — or a sibling shard's
        micro-generation — carries no matching record and is walked
        through; its parent chain still reaches this worker's last
        cursor."""
        for stream in self._stream_blocks():
            if self._cursor_matches(stream):
                return stream
        return {}

    def _replayed_pairs(self) -> Dict[str, int]:
        """Late-replay cursor: per-spool-dir COUNT of joined sidecar pairs
        already folded into the lineage. Same manifest-as-cursor discipline
        as segments — the count lands in the corrective generation's
        ``stream.lateReplay.pairs`` block before the gate can flip LATEST,
        so a crash before the flip deterministically re-replays the same
        pairs and a crash after skips them. The sidecar is append-only, so
        a pair count IS a stable prefix cursor."""
        for stream in self._stream_blocks():
            if not self._cursor_matches(stream):
                continue
            replay = stream.get("lateReplay") or {}
            # Shard-granular replay cursor: the block carries its OWN shard
            # tag (independent of the outer block's), so a sibling shard's
            # replay chain is never adopted even if a future manifest merge
            # drops the outer tag — each shard's crash-resume point is its
            # own last replay, full stop. Untagged blocks (pre-shard plane)
            # still count for every shard, same 1→N adoption rule as
            # segment cursors.
            tag = replay.get("shard")
            if tag and not (
                int(tag.get("of", 0)) == self.config.num_shards
                and int(tag.get("index", -1)) == self.config.shard_index
            ):
                continue
            pairs = replay.get("pairs")
            if pairs is not None:
                return {str(k): int(v) for k, v in pairs.items()}
        return {}

    def _re_spill_kwargs(self) -> Dict:
        """Out-of-core residency pass-through for the per-cycle fits.

        Sharded workers resolve their spill root through the host-owned
        layout (``host-<shard_index>/``) so a shard-count rebalance moves
        files (shard_router.rebalance_updater_spill) instead of
        re-streaming rows; the single-updater plane spills flat.
        """
        cfg = self.config
        out: Dict = {}
        if cfg.re_device_budget_mb is not None:
            out["re_device_budget_mb"] = cfg.re_device_budget_mb
        if cfg.re_spill_dir is not None:
            if cfg.num_shards > 1:
                from photon_tpu.stream.shard_router import updater_spill_dir

                out["re_spill_dir"] = updater_spill_dir(
                    cfg.re_spill_dir, cfg.shard_index
                )
            else:
                out["re_spill_dir"] = cfg.re_spill_dir
        return out

    def consumed_through(self) -> int:
        """Highest spool segment sequence already folded into the published
        model lineage (max across spool dirs in the fleet layout — the
        legacy single-dir cursor reads identically)."""
        stream = self._cursor_stream_info()
        if _CURSOR_KEY in stream:
            return int(stream[_CURSOR_KEY])
        per_spool = stream.get(_PER_SPOOL_KEY) or {}
        return max((int(v) for v in per_spool.values()), default=0)

    def consumed_per_spool(self) -> Dict[str, int]:
        """Per-spool-dir cursors (keyed by dir basename = replica id). A
        legacy manifest carrying only the scalar cursor applies it to a
        single configured dir; against a multi-dir glob it contributes
        nothing (each dir starts from its own recorded cursor or 0)."""
        stream = self._cursor_stream_info()
        per_spool = {
            str(k): int(v)
            for k, v in (stream.get(_PER_SPOOL_KEY) or {}).items()
        }
        if (
            not per_spool and _CURSOR_KEY in stream
            and not is_spool_glob(self.config.spool_dir)
        ):
            per_spool[spool_dir_key(self.config.spool_dir)] = int(
                stream[_CURSOR_KEY]
            )
        return per_spool

    # -- one cycle ---------------------------------------------------------

    def run_once(self) -> Optional[CycleResult]:
        """Consume pending sealed segments into one gated micro-generation.
        Returns None when there is nothing (or not yet enough) to train on.

        With an OTLP exporter installed the whole cycle runs under a
        minted trace context (``stream/cycle`` root span), so the solve's
        span tree flows to the collector; a failed cycle finishes its
        trace with the error, making it a kept flight-recorder tree."""
        from photon_tpu.obs.export import active_exporter

        if active_exporter() is None:
            return self._run_cycle()
        from photon_tpu.obs.trace import (
            flight_recorder,
            mint_context,
            span,
            tracer,
        )

        ctx = mint_context()
        t0 = time.monotonic()
        err = None
        try:
            with tracer().attach_context(ctx), span("stream/cycle"):
                return self._run_cycle()
        except Exception as exc:
            err = f"{type(exc).__name__}: {exc}"
            raise
        finally:
            flight_recorder().finish(
                ctx.trace_id, time.monotonic() - t0, error=err
            )

    def _run_cycle(self) -> Optional[CycleResult]:
        t_cycle = time.monotonic()
        try:
            return self._run_cycle_inner()
        finally:
            self._busy_s += time.monotonic() - t_cycle

    def _run_cycle_inner(self) -> Optional[CycleResult]:
        from photon_tpu.evaluation.suite import EvaluationSuite, EvaluatorSpec
        from photon_tpu.obs.metrics import registry
        from photon_tpu.train.incremental import incremental_update

        cfg = self.config
        dirs = discover_spool_dirs(cfg.spool_dir)
        for d in dirs:
            recover_orphan_parts(d)
        cursors = self.consumed_per_spool()
        # A glob spec is "multi" even when it currently matches one dir —
        # more replica spools may appear later, so per-spool cursors (and
        # dir-qualified segment names) are needed from the first cycle.
        multi = len(dirs) > 1 or is_spool_glob(cfg.spool_dir)
        pending_pairs = merge_pending_segments(
            dirs, cursors, cfg.max_segments_per_cycle
        )
        pending = [
            f"{spool_dir_key(d)}/{fn}" if multi else fn
            for d, fn in pending_pairs
        ]
        if not pending_pairs:
            return None
        records: List[dict] = []
        records_routed = 0
        if self._ring is not None and not cfg.pre_routed:
            # Mixed segments split at record level: keep the rows this
            # shard's ring slice owns, siblings pick up the rest from the
            # same sealed files. Routing happens on the RAW lines
            # (entityIds-only decode), so a shard pays a hash — not a full
            # json parse — for every foreign record; that is what lets
            # aggregate throughput scale with shard count when every
            # worker lists the same sealed bytes. The cursor still
            # advances over the WHOLE segment span consumed this cycle —
            # ownership is a property of rows, not files.
            from photon_tpu.stream.shard_router import read_owned_segment

            for d, fn in pending_pairs:
                faults.check("stream.consume", label=fn)
                owned, total = read_owned_segment(
                    os.path.join(d, fn), self._ring, cfg.shard_index,
                    cfg.route_re_type,
                )
                records.extend(owned)
                records_routed += total
        else:
            for d, fn in pending_pairs:
                faults.check("stream.consume", label=fn)
                records.extend(read_segment(os.path.join(d, fn)))
            records_routed = len(records)
        if len(records) < cfg.min_records:
            return None
        self._cycles += 1
        reg = registry()
        reg.counter("stream_cycles_total").inc()
        if cfg.num_shards > 1:
            reg.counter(
                "stream_shard_cycles_total", shard=str(cfg.shard_index)
            ).inc()

        # Deterministic holdout split: every k-th record scores the gate's
        # regression bound instead of training. Determinism matters — a
        # crashed-and-restarted cycle must rebuild the identical split.
        train_recs, holdout_recs = records, []
        if cfg.holdout_fraction > 0.0:
            k = max(2, int(round(1.0 / cfg.holdout_fraction)))
            train_recs = [r for i, r in enumerate(records) if i % k != 0]
            holdout_recs = [r for i, r in enumerate(records) if i % k == 0]
            if not train_recs:
                train_recs, holdout_recs = records, []
        if holdout_recs:
            # Holdout records were scored by serving and never trained on —
            # an unbiased online-quality sample keyed by the version that
            # actually scored each one.
            self._observe_quality(holdout_recs)
        if cfg.fe_retrain:
            self._fe_recent.extend(train_recs)

        faults.check("stream.consume", label="train")
        t_train = time.monotonic()
        batch = records_to_batch(
            train_recs, self.index_maps, self.entity_indexes, intern=True
        )
        valid_batch = None
        suite = None
        if holdout_recs:
            valid_batch = records_to_batch(
                holdout_recs, self.index_maps, self.entity_indexes,
                intern=False,
            )
            suite = EvaluationSuite(
                [EvaluatorSpec.parse(e) for e in cfg.evaluators],
                {k: len(v) for k, v in self.entity_indexes.items()},
            )

        # Per-dir cursors advance to the max seq consumed THIS cycle; dirs
        # with nothing new carry their prior cursor forward (an idle
        # replica's cursor must never regress to 0).
        new_cursors = dict(cursors)
        for d, fn in pending_pairs:
            key = spool_dir_key(d)
            new_cursors[key] = max(new_cursors.get(key, 0), segment_seq(fn))
        consumed = max(new_cursors.values())
        label_ts = [
            float(r["labelTs"]) for r in records if r.get("labelTs")
        ]
        oldest_label_ts = min(label_ts) if label_ts else None
        emit_delta = bool(cfg.delta_artifacts)
        if emit_delta and cfg.full_every > 0:
            emit_delta = (self._publishes + 1) % cfg.full_every != 0
        stream_info = {
            _CURSOR_KEY: consumed,
            "segments": pending,
            "records": len(records),
        }
        if cfg.num_shards > 1:
            # The shard identity tags this manifest as one link of THIS
            # worker's cursor chain — siblings and restarts walk past
            # non-matching blocks (see _cursor_matches).
            stream_info["shard"] = {
                "index": cfg.shard_index,
                "of": cfg.num_shards,
            }
            stream_info["recordsRouted"] = records_routed
        if multi:
            # Only the multi-dir (fleet) layout needs per-spool cursors;
            # single-dir manifests keep the PR 11 shape byte-for-byte.
            stream_info[_PER_SPOOL_KEY] = new_cursors
        if oldest_label_ts is not None:
            stream_info["oldestLabelTs"] = oldest_label_ts
        # Trace linkage: the request traces that fed this micro-generation
        # (spool records carry the serve-side context). Bounded sample —
        # enough to jump from a published generation back into the flight
        # recorder / request logs, without growing manifests unboundedly.
        trace_ids = []
        seen_tids = set()
        for r in records:
            tid = (r.get("trace") or {}).get("traceId")
            if tid and tid not in seen_tids:
                seen_tids.add(tid)
                trace_ids.append(tid)
        if trace_ids:
            stream_info["traceCount"] = len(trace_ids)
            stream_info["traceIds"] = trace_ids[:32]

        serialize = cfg.serialize_publish
        if serialize is None:
            serialize = cfg.num_shards > 1
        result = incremental_update(
            cfg.publish_root,
            batch,
            self.index_maps,
            self.entity_indexes,
            cfg.task,
            cfg.coordinate_configs,
            cfg.update_sequence,
            valid_batch=valid_batch,
            evaluation_suite=suite,
            locked_coordinates=list(cfg.locked_coordinates),
            num_iterations=cfg.num_iterations,
            metric_tolerance=cfg.metric_tolerance,
            norm_drift_bound=cfg.norm_drift_bound,
            re_convergence_tol=cfg.re_convergence_tol,
            emit_delta=emit_delta,
            extra_manifest={"stream": stream_info},
            serialize_publish=bool(serialize),
            **self._re_spill_kwargs(),
        )
        self._train_s += time.monotonic() - t_train
        self._records_trained += len(records)
        reg.counter("stream_records_consumed_total").inc(len(records))
        shard_labels = (
            {"shard": str(cfg.shard_index)} if cfg.num_shards > 1 else None
        )
        if shard_labels:
            reg.counter(
                "stream_shard_records_total", **shard_labels
            ).inc(len(records))
        staleness = None
        if result.published:
            self._publishes += 1
            reg.counter("stream_publishes_total").inc()
            if shard_labels:
                reg.counter(
                    "stream_shard_publishes_total", **shard_labels
                ).inc()
                reg.gauge(
                    "stream_shard_consumed_through", **shard_labels
                ).set(consumed)
            if oldest_label_ts is not None:
                staleness = time.time() - oldest_label_ts
                reg.gauge("model_staleness_published_s").set(staleness)
                # Same metric name the serving side publishes, so one SLO
                # query covers both halves of the freshness loop — and the
                # updater's own staleness objective sees every publish.
                reg.gauge("model_staleness_s").set(staleness)
                reg.histogram("model_staleness_hist_s").observe(staleness)
                self.slo.record_staleness(staleness)
                if shard_labels:
                    # Per-shard freshness: one lagging shard is invisible
                    # in the fleet-wide staleness gauge (siblings keep it
                    # low) but pins its own label high.
                    reg.gauge(
                        "stream_shard_staleness_s", **shard_labels
                    ).set(staleness)
            self.slo.record_event("update_cycle", True)
        else:
            # A refused generation means the freshness loop made no
            # progress this cycle — that burns the cycle objective even
            # though containment worked as designed.
            self.slo.record_event("update_cycle", False)
            reg.counter("stream_gate_rejects_total").inc()
            logger.warning(
                "streaming generation %s refused by the gate (%s); segments "
                "through %d stay unconsumed and retry next cycle",
                result.generation, result.gate_reason, consumed,
            )
        self._observe_fe_age(reg)
        return CycleResult(
            generation=result.generation,
            published=result.published,
            is_delta=result.is_delta,
            gate_reason=result.gate_reason,
            segments=pending,
            records=len(records),
            consumed_through=consumed,
            staleness_s=staleness,
        )

    # -- model-quality plane (obs/quality.py) ------------------------------

    def _observe_quality(self, records: Sequence[dict]) -> None:
        """Feed scored-and-labelled spool records into the quality plane,
        each keyed by the model version that actually scored it (the
        serving engine stamped ``modelVersion`` at score time). Contained:
        quality measurement must never fail a training cycle."""
        try:
            for rec in records:
                ids = rec.get("entityIds") or {}
                self.quality.observe(
                    float(rec.get("score") or 0.0),
                    float(rec.get("label") or 0.0),
                    model_version=rec.get("modelVersion"),
                    tenant=rec.get("tenant"),
                    re_type=",".join(sorted(ids)) if ids else "",
                    ts=rec.get("ts"),
                    label_ts=rec.get("labelTs"),
                    trace_id=(rec.get("trace") or {}).get("traceId"),
                    slo=self.slo,
                )
            self.quality.publish()
        except Exception:  # noqa: BLE001 — measurement containment
            from photon_tpu.obs.metrics import registry

            registry().counter("quality_observe_errors_total").inc()
            logger.exception("quality-plane observe failed; cycle continues")

    # -- late-label replay correction pass ---------------------------------

    def maybe_replay_late_labels(self) -> Optional[CycleResult]:
        """Cadence + containment wrapper around :meth:`replay_late_labels`.
        Called from the driver loop every iteration; a failed replay is
        counted and retried after the next cadence interval."""
        cfg = self.config
        if cfg.late_replay_cadence_s <= 0:
            return None
        now = time.monotonic()
        if now - self._last_replay < cfg.late_replay_cadence_s:
            return None
        self._last_replay = now
        try:
            return self.replay_late_labels()
        except Exception:  # noqa: BLE001 — replay containment
            from photon_tpu.obs.metrics import registry

            registry().counter("stream_replay_failures_total").inc()
            logger.exception("late-label replay failed; will retry")
            return None

    def replay_late_labels(self) -> Optional[CycleResult]:
        """Re-join each spool dir's ``late-labels.jsonl`` sidecar, train the
        affected entities on the recovered (features, label) pairs, and
        publish the result as a corrective delta through the UNCHANGED
        gate. The per-dir count of joined pairs already consumed is the
        cursor, persisted in the generation's ``stream.lateReplay.pairs``
        manifest block alongside the carried-forward segment cursors — the
        same manifest-as-cursor crash-resume discipline as segments.
        Returns None when there are not yet enough fresh pairs."""
        from photon_tpu.obs.metrics import registry
        from photon_tpu.train.incremental import incremental_update

        cfg = self.config
        dirs = discover_spool_dirs(cfg.spool_dir)
        consumed_pairs = self._replayed_pairs()
        new_pairs = dict(consumed_pairs)
        fresh: List[dict] = []
        for d in dirs:
            pairs = read_late_pairs(os.path.join(d, LATE_LABELS_FILE))
            if not pairs:
                continue
            key = spool_dir_key(d)
            done = min(consumed_pairs.get(key, 0), len(pairs))
            new_pairs[key] = len(pairs)
            fresh.extend(pairs[done:])
        if self._ring is not None and not cfg.pre_routed and fresh:
            # Sharded plane: train only the rows this shard's ring slice
            # owns. The pair cursor still counts ALL pairs — each shard's
            # replay chain is shard-tagged, so siblings keep their own.
            from photon_tpu.stream.shard_router import owned_records

            fresh = owned_records(
                fresh, self._ring, cfg.shard_index, cfg.route_re_type
            )
        if len(fresh) < max(1, cfg.late_replay_min_pairs):
            return None
        faults.check("stream.replay", label="train")
        reg = registry()
        t_train = time.monotonic()
        batch = records_to_batch(
            fresh, self.index_maps, self.entity_indexes, intern=True
        )
        cursors = self.consumed_per_spool()
        multi = len(dirs) > 1 or is_spool_glob(cfg.spool_dir)
        late_block: Dict = {"pairs": new_pairs, "records": len(fresh)}
        if cfg.num_shards > 1:
            # Shard-granular cursor tag (see _replayed_pairs): the replay
            # block names its owner so sibling shards' cursor walks skip it
            # no matter how the outer block is interpreted.
            late_block["shard"] = {
                "index": cfg.shard_index,
                "of": cfg.num_shards,
            }
        stream_info: Dict = {
            _CURSOR_KEY: max(cursors.values(), default=0),
            "lateReplay": late_block,
        }
        if multi:
            stream_info[_PER_SPOOL_KEY] = cursors
        if cfg.num_shards > 1:
            stream_info["shard"] = {
                "index": cfg.shard_index,
                "of": cfg.num_shards,
            }
        serialize = cfg.serialize_publish
        if serialize is None:
            serialize = cfg.num_shards > 1
        result = incremental_update(
            cfg.publish_root,
            batch,
            self.index_maps,
            self.entity_indexes,
            cfg.task,
            cfg.coordinate_configs,
            cfg.update_sequence,
            locked_coordinates=list(cfg.locked_coordinates),
            num_iterations=cfg.num_iterations,
            metric_tolerance=cfg.metric_tolerance,
            norm_drift_bound=cfg.norm_drift_bound,
            re_convergence_tol=cfg.re_convergence_tol,
            emit_delta=bool(cfg.delta_artifacts),
            extra_manifest={"stream": stream_info},
            serialize_publish=bool(serialize),
            **self._re_spill_kwargs(),
        )
        self._train_s += time.monotonic() - t_train
        if result.published:
            self._publishes += 1
            self._replay_publishes += 1
            self._records_trained += len(fresh)
            reg.counter("stream_late_replays_total").inc()
            reg.counter("stream_late_replayed_pairs_total").inc(len(fresh))
            # The recovered cohort is scored-and-labelled — measure it, so
            # the correction's lift is attributable in the quality plane.
            self._observe_quality(fresh)
            logger.info(
                "late-label replay published %s: %d recovered pairs",
                result.generation, len(fresh),
            )
        else:
            reg.counter("stream_gate_rejects_total").inc()
            logger.warning(
                "late-label replay generation %s refused by the gate (%s); "
                "pairs stay unconsumed and retry next cadence",
                result.generation, result.gate_reason,
            )
        return CycleResult(
            generation=result.generation,
            published=result.published,
            is_delta=result.is_delta,
            gate_reason=result.gate_reason,
            segments=[],
            records=len(fresh),
            consumed_through=max(cursors.values(), default=0),
            staleness_s=None,
        )

    # -- FE-drift trigger scaffold ----------------------------------------

    def fe_age_s(self) -> Optional[float]:
        """Age of the locked fixed effect: seconds since the most recent
        lineage generation that actually persisted FE coefficients (a full
        publish, or a delta with ``include_fixed``). Delta layers from the
        streaming plane lock the FE, so under pure streaming this only
        grows — the drift signal the retrain trigger watches. None when
        there is no published lineage yet."""
        from photon_tpu.cli.game_serving import resolve_model_dir
        from photon_tpu.io.model_io import (
            FIXED_DIR,
            load_generation_manifest,
        )

        root = self.config.publish_root
        cur = resolve_model_dir(root)
        if cur == root:
            return None
        for _ in range(128):
            fe_dir = os.path.join(cur, FIXED_DIR)
            if os.path.isdir(fe_dir) and os.listdir(fe_dir):
                manifest = load_generation_manifest(cur) or {}
                born = manifest.get("createdAt")
                if born is None:
                    try:
                        born = os.path.getmtime(cur)
                    except OSError:
                        return None
                return max(0.0, time.time() - float(born))
            manifest = load_generation_manifest(cur) or {}
            parent = manifest.get("parent")
            if not parent:
                return None
            cur = os.path.join(root, parent)
            if not os.path.isdir(cur):
                return None
        return None

    def _observe_fe_age(self, reg) -> None:
        """Feed the ``fe_age_s`` objective (same multi-window burn
        machinery as staleness) and raise ``stream_fe_retrain_wanted``
        while the locked FE is past its age bar. With ``fe_retrain`` on the
        raised gauge actuates a cooldown-guarded FE full retrain instead of
        just asking for one."""
        age = self.fe_age_s()
        if age is None:
            return
        reg.gauge("stream_fe_age_s").set(age)
        self.slo.record_fe_age(age)
        wanted = 1.0 if age > float(self.config.fe_max_age_s) else 0.0
        reg.gauge("stream_fe_retrain_wanted").set(wanted)
        if wanted:
            logger.warning(
                "locked fixed effect is %.0fs old (bar %.0fs): "
                "stream_fe_retrain_wanted raised", age,
                self.config.fe_max_age_s,
            )
            self._maybe_fe_retrain(reg, age)

    def _maybe_fe_retrain(self, reg, age: float) -> None:
        """Actuate the raised retrain-wanted gauge: cooldown-guarded, floor
        on accumulated records, contained. The cooldown stamp is taken
        BEFORE the attempt so a failing retrain cannot hot-loop — it burns
        its cooldown like a successful one and the failure is counted."""
        cfg = self.config
        if not cfg.fe_retrain:
            return
        now = time.monotonic()
        if (
            self._last_fe_retrain is not None
            and now - self._last_fe_retrain < cfg.fe_retrain_cooldown_s
        ):
            return
        recs = list(self._fe_recent)
        if len(recs) < max(1, cfg.fe_retrain_min_records):
            return
        self._last_fe_retrain = now
        try:
            self._run_fe_retrain(reg, recs, age)
        except Exception:  # noqa: BLE001 — actuation containment
            reg.counter("stream_fe_retrain_failures_total").inc()
            logger.exception(
                "FE full retrain failed; cooldown %.0fs still applies",
                cfg.fe_retrain_cooldown_s,
            )

    def _run_fe_retrain(self, reg, recs: List[dict], age: float) -> None:
        """One FE full-retrain generation: the recent-record window trains
        with the fixed-effect coordinates UNLOCKED and publishes full
        (``emit_delta=False``) so the new generation persists FE
        coefficients — which is exactly what resets ``fe_age_s`` and drops
        the wanted gauge. Same gate, same manifest-as-cursor discipline
        (segment cursors carry forward; no segments are consumed here)."""
        from photon_tpu.train.incremental import incremental_update

        cfg = self.config
        fe_ids = {
            getattr(c, "coordinate_id", None)
            for c in cfg.coordinate_configs
            if getattr(c, "re_type", None) is None
        }
        locked = [c for c in cfg.locked_coordinates if c not in fe_ids]
        batch = records_to_batch(
            recs, self.index_maps, self.entity_indexes, intern=True
        )
        cursors = self.consumed_per_spool()
        multi = (
            len(discover_spool_dirs(cfg.spool_dir)) > 1
            or is_spool_glob(cfg.spool_dir)
        )
        stream_info: Dict = {
            _CURSOR_KEY: max(cursors.values(), default=0),
            "feRetrain": {"records": len(recs), "ageS": round(age, 3)},
        }
        if multi:
            stream_info[_PER_SPOOL_KEY] = cursors
        if cfg.num_shards > 1:
            stream_info["shard"] = {
                "index": cfg.shard_index,
                "of": cfg.num_shards,
            }
        serialize = cfg.serialize_publish
        if serialize is None:
            serialize = cfg.num_shards > 1
        t_train = time.monotonic()
        result = incremental_update(
            cfg.publish_root,
            batch,
            self.index_maps,
            self.entity_indexes,
            cfg.task,
            cfg.coordinate_configs,
            cfg.update_sequence,
            locked_coordinates=locked,
            num_iterations=cfg.num_iterations,
            metric_tolerance=cfg.metric_tolerance,
            norm_drift_bound=cfg.norm_drift_bound,
            re_convergence_tol=cfg.re_convergence_tol,
            emit_delta=False,
            extra_manifest={"stream": stream_info},
            serialize_publish=bool(serialize),
            **self._re_spill_kwargs(),
        )
        self._train_s += time.monotonic() - t_train
        if result.published:
            self._publishes += 1
            self._fe_retrains += 1
            reg.counter("stream_fe_retrains_total").inc()
            reg.gauge("stream_fe_retrain_wanted").set(0.0)
            logger.info(
                "FE full retrain published %s (%d records, FE was %.0fs "
                "old)", result.generation, len(recs), age,
            )
        else:
            reg.counter("stream_gate_rejects_total").inc()
            logger.warning(
                "FE retrain generation %s refused by the gate (%s)",
                result.generation, result.gate_reason,
            )

    # -- driver loop -------------------------------------------------------

    def run_forever(self, max_cycles: Optional[int] = None) -> int:
        """Poll-train-publish until :meth:`stop` (or ``max_cycles``
        publishes/attempts). Solver or IO failures inside one cycle are
        contained and counted — the loop survives to retry with the same
        unconsumed segments."""
        from photon_tpu.obs.metrics import registry

        done = 0
        while not self._stop.is_set():
            try:
                result = self.run_once()
            except Exception:  # noqa: BLE001 — cycle containment
                registry().counter("stream_cycle_failures_total").inc()
                self.slo.record_event("update_cycle", False)
                logger.exception("streaming update cycle failed; retrying")
                result = None
            self.maybe_replay_late_labels()
            self.slo.publish_metrics()
            if result is not None:
                done += 1
                if max_cycles is not None and done >= max_cycles:
                    break
            self._stop.wait(self.config.cadence_s)
        return done

    def stop(self) -> None:
        self._stop.set()

    def stats(self) -> dict:
        out = {
            "cycles": self._cycles,
            "publishes": self._publishes,
            "consumed_through": self.consumed_through(),
            "busy_s": self._busy_s,
            "train_s": self._train_s,
            "records_trained": self._records_trained,
            "late_replays": self._replay_publishes,
            "fe_retrains": self._fe_retrains,
            "slo": self.slo.snapshot(),
            "quality": self.quality.snapshot(),
        }
        if self.config.num_shards > 1:
            out["shard"] = {
                "index": self.config.shard_index,
                "of": self.config.num_shards,
            }
        return out
