"""Streaming freshness loop: serve-side feedback spool, continuous
micro-generation updater, per-entity delta model artifacts.

The three parts close the label → fresher-model-serving-traffic loop:

- :mod:`photon_tpu.stream.spool` — crash-safe segment-rotated JSONL spool
  where the serving engine lands scored requests joined with later-arriving
  labels;
- :mod:`photon_tpu.stream.updater` — long-running consumer that batches
  spool segments into warm-started per-entity solves and publishes
  micro-generations (delta artifacts, ``io/model_io.py``) through the
  existing validation gate and rollout watcher;
- the serving side applies delta layers in place
  (``serve/store.py:clone_with_delta`` + ``serve/engine.py:
  load_delta_version``) so multi-version residency and bit-exact shadow
  sampling keep working at micro-generation cadence.
"""

from photon_tpu.stream.spool import (  # noqa: F401
    FeedbackSpool,
    SpoolConfig,
    read_segment,
    recover_segments,
    sealed_segments,
    segment_seq,
)
from photon_tpu.stream.updater import (  # noqa: F401
    StreamingUpdater,
    StreamingUpdaterConfig,
)
