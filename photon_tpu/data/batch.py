"""Core data containers.

Parity target: the reference's ``LabeledPoint(label, features, offset, weight)``
with ``computeMargin = x·w + offset`` (photon-lib data/LabeledPoint.scala:30-62)
and ``RDD[LabeledPoint]`` datasets.

TPU-first design: instead of a distributed collection of per-sample records,
a ``LabeledBatch`` is a struct-of-arrays pytree — one fixed-shape batch that
jit/pjit shards across the device mesh on the sample axis. Features are either
a dense ``(n, d)`` matrix (margins are MXU matmuls) or a padded sparse
``SparseFeatures`` (fixed nnz-per-row gather form, so shapes stay static under
jit). Sample weights of 0 mark padding rows, which makes ragged data a
non-problem: every reduction is already weighted.
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

# Per-backend defaults for the padded-sparse rmatvec lowering at the ingest
# boundary (FeatureShardConfig.transpose_plan=None resolves through
# ``default_transpose_plan()``). CPU: measured head-to-head on this image's
# CPU mesh (bench.py --rmatvec-cpu-ab, BENCH_FULL.md) — the duplicate-index
# scatter-add beat the column-sorted segment_sum, so no plan is attached.
# Re-confirmed on the SHARDED path (bench.py --rmatvec-sharded-ab, batch
# rows over the 8-virtual-device mesh, 2026-08-06): scatter 0.384 s vs
# segsum 0.439 s — the scatter partitions trivially on the sample axis
# (per-device partial + psum) while the flat column-sorted (n·k,) plan
# arrays cut across the row partition and cost SPMD collectives.
# TPU: segment-sum is the native lowering (XLA:TPU serializes colliding
# scatter updates, so the scatter path degenerates under index collisions);
# pinned True pending the on-chip re-run of the A/B at full run_sparse_wide
# scale — the CPU number does not transfer, and per-device row partitions
# shrink the collision profile, so the sharded on-chip A/B may narrow the
# gap but is not expected to flip it.
_TRANSPOSE_PLAN_CPU = False
_TRANSPOSE_PLAN_TPU = True


def default_transpose_plan() -> bool:
    """Backend-aware rmatvec-plan default, resolved LAZILY at dataset build
    / read time (a module-level constant would bake in whichever backend
    imported first and silently ship the CPU-measured winner to TPU)."""
    return _TRANSPOSE_PLAN_TPU if jax.default_backend() == "tpu" \
        else _TRANSPOSE_PLAN_CPU


@jax.tree_util.register_pytree_node_class
class SparseFeatures:
    """Row-padded sparse feature matrix: each row holds up to k (index, value)
    pairs; unused slots have value 0 (index arbitrary, conventionally 0).

    This is the TPU replacement for Breeze SparseVector rows: static shapes
    (n, k) so the margin is a gather + rowwise dot and the gradient is a
    scatter-add, both of which XLA compiles to efficient TPU programs.
    """

    def __init__(
        self,
        indices: Array,
        values: Array,
        dim: int,
        csc_order: Optional[Array] = None,
        csc_segments: Optional[Array] = None,
    ):
        self.indices = indices  # (n, k) int32
        self.values = values  # (n, k) float
        self.dim = int(dim)
        # Optional precomputed transpose plan (see with_transpose_plan):
        # csc_order sorts the flattened nnz entries by column, csc_segments
        # are the sorted column ids. When present, rmatvec uses a gather +
        # segment_sum instead of a duplicate-index scatter-add — the sorted
        # form is the TPU-friendly lowering (XLA serializes colliding
        # scatter updates).
        self.csc_order = csc_order  # (n*k,) int32 or None
        self.csc_segments = csc_segments  # (n*k,) int32 or None

    @property
    def shape(self):
        return (self.values.shape[0], self.dim)

    def matvec(self, w: Array) -> Array:
        """X @ w for the padded-sparse layout: (n,)."""
        return jnp.sum(self.values * w[self.indices], axis=-1)

    def rmatvec(self, r: Array) -> Array:
        """X.T @ r: segment-sum over the precomputed column-sorted plan when
        available, duplicate-index scatter-add otherwise."""
        d = self.dim
        contrib = self.values * r[:, None]  # promotes bf16 values to r.dtype
        if self.csc_order is not None:
            sorted_contrib = contrib.reshape(-1)[self.csc_order]
            return jax.ops.segment_sum(
                sorted_contrib, self.csc_segments, num_segments=d,
                indices_are_sorted=True,
            )
        # Accumulate at the PROMOTED dtype — a bf16-storage matrix must not
        # sum its gradient in bf16.
        return jnp.zeros((d,), dtype=contrib.dtype).at[self.indices].add(contrib)

    def with_transpose_plan(self) -> "SparseFeatures":
        """Return a copy carrying the column-sorted transpose plan (one host
        argsort over the static index pattern; ~2 extra int32 nnz-sized
        arrays in device memory). Host (numpy) matrices get a host plan —
        the pipeline's h2d stage places all leaves together."""
        flat = np.asarray(self.indices).reshape(-1)
        order = np.argsort(flat, kind="stable")
        as_arr = (
            np.asarray if isinstance(self.indices, np.ndarray) else jnp.asarray
        )
        return SparseFeatures(
            self.indices, self.values, self.dim,
            csc_order=as_arr(order.astype(np.int32)),
            csc_segments=as_arr(flat[order].astype(np.int32)),
        )

    def to_dense(self) -> Array:
        n, k = self.values.shape
        out = jnp.zeros((n, self.dim), dtype=self.values.dtype)
        return out.at[jnp.arange(n)[:, None], self.indices].add(self.values)

    def tree_flatten(self):
        return (
            (self.indices, self.values, self.csc_order, self.csc_segments),
            (self.dim,),
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        indices, values, csc_order, csc_segments = children
        return cls(indices, values, aux[0], csc_order, csc_segments)

    @staticmethod
    def from_rows(rows, dim: int, dtype=np.float32) -> "SparseFeatures":
        """Build from a list of (indices, values) per-row pairs, padding to the
        max row nnz. Host-side (numpy) construction for ingest."""
        k = max((len(ix) for ix, _ in rows), default=1)
        k = max(k, 1)
        n = len(rows)
        indices = np.zeros((n, k), dtype=np.int32)
        values = np.zeros((n, k), dtype=dtype)
        for i, (ix, vs) in enumerate(rows):
            m = len(ix)
            indices[i, :m] = ix
            values[i, :m] = vs
        return SparseFeatures(jnp.asarray(indices), jnp.asarray(values), dim)


Features = Union[Array, SparseFeatures]


@jax.tree_util.register_pytree_node_class
class LabeledBatch:
    """A batch of labeled samples (struct-of-arrays LabeledPoint).

    Fields mirror LabeledPoint.scala:30: label, features, offset, weight.
    ``uid`` carries the reference's UniqueSampleId for score alignment
    (GameDatum.scala:37); padding rows have weight 0.
    """

    def __init__(
        self,
        label: Array,
        features: Features,
        offset: Optional[Array] = None,
        weight: Optional[Array] = None,
        uid: Optional[Array] = None,
    ):
        n = label.shape[0]
        self.label = label
        self.features = features
        self.offset = jnp.zeros((n,), label.dtype) if offset is None else offset
        self.weight = jnp.ones((n,), label.dtype) if weight is None else weight
        self.uid = uid

    @property
    def n(self) -> int:
        return self.label.shape[0]

    @property
    def dim(self) -> int:
        return self.features.shape[1]

    def margins(self, w: Array) -> Array:
        """x·w + offset for every sample (LabeledPoint.computeMargin)."""
        if isinstance(self.features, SparseFeatures):
            xw = self.features.matvec(w)
        else:
            xw = self.features @ w
        return xw + self.offset

    def with_offset(self, offset: Array) -> "LabeledBatch":
        return LabeledBatch(self.label, self.features, offset, self.weight, self.uid)

    def add_scores_to_offsets(self, scores: Array) -> "LabeledBatch":
        """Residual application (Dataset.addScoresToOffsets, reference
        data/Dataset.scala:23-31) — alignment by construction, no join."""
        return self.with_offset(self.offset + scores)

    def tree_flatten(self):
        return (self.label, self.features, self.offset, self.weight, self.uid), ()

    @classmethod
    def tree_unflatten(cls, aux, children):
        label, features, offset, weight, uid = children
        return cls(label, features, offset, weight, uid)

    @property
    def total_weight(self) -> Array:
        return jnp.sum(self.weight)
