"""GAME data containers: multi-shard batches with entity ids.

Parity target: reference ``GameDatum`` (response/offset/weight + per-shard
feature vectors + id-tag map, photon-api data/GameDatum.scala:37-68) and the
``RDD[(UniqueSampleId, GameDatum)]`` game dataset.

TPU-first design: one struct-of-arrays ``GameBatch`` holds every sample's
label/offset/weight, a feature matrix per feature shard, and a dense int32
entity index per random-effect type. Entity ids are interned to [0, E) at
ingest (see photon_tpu.data.index_map.EntityIndex); -1 marks entities unseen
at training time (cold start → that coordinate contributes score 0, matching
the reference's behavior of missing random-effect models). Residual exchange
between coordinates is pure array arithmetic on aligned score vectors — the
reference's outer-join score algebra (DataScores.scala:33-157) disappears.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from photon_tpu.data.batch import Features, LabeledBatch

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GameBatch:
    """All samples for training/scoring, aligned on a single sample axis."""

    label: Array
    offset: Array
    weight: Array
    features: Dict[str, Features]  # feature-shard name -> (n, d_shard)
    entity_ids: Dict[str, Array]  # RE type name -> (n,) int32 dense entity idx
    uid: Optional[Array] = None

    @property
    def n(self) -> int:
        return self.label.shape[0]

    def labeled_batch(self, shard: str, extra_offset: Optional[Array] = None) -> LabeledBatch:
        """Project to a single-shard LabeledBatch
        (GameDatum.generateLabeledPointWithFeatureShardId role)."""
        offset = self.offset if extra_offset is None else self.offset + extra_offset
        return LabeledBatch(self.label, self.features[shard], offset, self.weight, self.uid)

    def with_offset(self, offset: Array) -> "GameBatch":
        return dataclasses.replace(self, offset=offset)
