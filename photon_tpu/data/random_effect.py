"""Random-effect dataset: ragged per-entity data → fixed-shape vmap blocks.

Parity target: reference ``RandomEffectDataset`` (photon-api
data/RandomEffectDataset.scala:52-647) — the most intricate structure in the
reference: per-entity grouped active data (with reservoir sampling bounds,
lower-bound filtering, Pearson feature selection), passive data, and
per-entity subspace projectors, partitioned by a bin-packing partitioner.

TPU-first design: grouping happens once at ingest on the host (numpy), and
produces dense blocks:

  features (E, n_max, d), label/offset-slot/weight (E, n_max), mask via
  weight==0, sample_index (E, n_max) int32 → row in the flat GameBatch.

- The **bin-packing partitioner** (RandomEffectDatasetPartitioner.scala:44-96)
  is unnecessary: after padding, every entity row costs the same, so a plain
  entity-axis sharding over the mesh is perfectly balanced. Bucketing by
  sample count (multiple blocks with different n_max) bounds padding waste —
  the analogue of the reference's per-partition 2GB budget.
- **Reservoir sampling** to ``active_upper_bound`` uses the same
  deterministic-key trick as the reference (byteswapped hash of the uid,
  RandomEffectDataset.scala:517-524) so recomputation/reruns are reproducible.
- **Passive data** (samples beyond the active bound) stays in the flat
  GameBatch and is scored by the gather path — no separate structure needed.
- **Pearson feature selection** (featureSelectionOnActiveData:582-596) is a
  per-entity top-k mask computed batched on device.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.data.batch import LabeledBatch

Array = jax.Array


def bucket_dim(x: int) -> int:
    """Round a block dimension UP to the geometric shape-bucket grid
    {1, 2, 3, 4, 6, 8, 12, 16, 24, 32, ...} (powers of two and 1.5×).

    Grid ratio ≤ 4/3 bounds per-dim padding waste at ~33% while collapsing
    heterogeneous entity populations onto a handful of block shapes, so the
    compiled-solver cache (algorithm/solve_cache.py) traces once per bucket
    instead of once per exact shape. Padding carries zero weight (samples)
    and ``train_mask=False`` / ``entity_idx=-1`` (entities), so results are
    bit-for-bit decoupled from real rows up to reduction order."""
    x = int(x)
    if x <= 2:
        return max(x, 1)
    p = 1 << (x - 1).bit_length()  # next power of two ≥ x
    if 3 * (p // 4) >= x:
        return 3 * (p // 4)  # 1.5 × previous power of two
    return p


def _publish_pad_waste(re_type: str, **dims: Tuple[int, int]) -> None:
    """Shape-bucket pad-waste telemetry, one (used, allocated) pair per dim
    (entities / samples / features). Published at dataset build — a one-time
    host-side step — so reading it never touches the solve hot path."""
    from photon_tpu.obs.metrics import registry

    reg = registry()
    for dim, (used, alloc) in dims.items():
        kw = dict(re_type=str(re_type), dim=dim)
        reg.counter("bucket_alloc_total", **kw).inc(int(alloc))
        reg.counter("bucket_used_total", **kw).inc(int(used))
        reg.histogram("bucket_pad_waste_ratio", **kw).observe(
            1.0 - (used / alloc) if alloc else 0.0
        )


def _byteswap64(x: np.ndarray) -> np.ndarray:
    """Deterministic sampling key (role of Spark's byteswap64 hash,
    RandomEffectDataset.scala:517-524)."""
    x = x.astype(np.uint64)
    x = ((x & np.uint64(0x00000000FFFFFFFF)) << np.uint64(32)) | (x >> np.uint64(32))
    x = ((x & np.uint64(0x0000FFFF0000FFFF)) << np.uint64(16)) | (
        (x >> np.uint64(16)) & np.uint64(0x0000FFFF0000FFFF)
    )
    x = ((x & np.uint64(0x00FF00FF00FF00FF)) << np.uint64(8)) | (
        (x >> np.uint64(8)) & np.uint64(0x00FF00FF00FF00FF)
    )
    # Mix (splitmix64 finalizer) for uniform ordering keys.
    x = x ^ (x >> np.uint64(30))
    x = x * np.uint64(0xBF58476D1CE4E5B9)
    x = x ^ (x >> np.uint64(27))
    x = x * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


@dataclasses.dataclass
class RandomEffectDataConfig:
    """Reference RandomEffectDataConfiguration (CoordinateDataConfiguration
    .scala:22-76): REType, shard, active-data bounds, feature selection."""

    re_type: str
    feature_shard: str
    active_upper_bound: Optional[int] = None  # numActiveDataPointsUpperBound
    active_lower_bound: Optional[int] = None  # lower bound on #samples/entity
    features_to_samples_ratio: Optional[float] = None  # Pearson selection cap
    n_buckets: int = 4  # blocks with distinct n_max to bound padding waste
    # Round block shapes (E, n_max, d) UP to the geometric bucket grid (see
    # ``bucket_dim``) so heterogeneous entity populations collapse onto a
    # handful of cached solver executables (algorithm/solve_cache.py).
    # Padding rows carry zero weight; padded entities carry
    # ``train_mask=False`` and ``entity_idx=-1``. The feature dim is
    # bucketed for dense shards only — a projected block's col_map is
    # content-defined and must stay exact (model I/O maps its columns back
    # to global feature names).
    shape_bucketing: bool = True
    # Per-block feature-subspace compaction (reference
    # LinearSubspaceProjector.scala:36-88 / RandomEffectDataset.scala:383-432,
    # vmap-granularity: the union of a BLOCK's active columns instead of one
    # projector per entity). None = auto: on for sparse shard input, off for
    # dense. Blocks store a ``col_map`` back to the global feature space.
    subspace_projection: Optional[bool] = None
    # Collapse dense blocks sharing an (n_max, d) geometry into one block
    # each (``merge_same_geometry_blocks``) — fewer solver dispatches per CD
    # pass at identical convergence. Opt-in: merged lane counts change XLA's
    # whole-program fusion order inside the vmapped Newton solve, so results
    # match the unmerged layout to solver tolerance, not bit-for-bit (the
    # re_kernel pallas-vs-xla parity, which IS bit-exact, is a separate
    # axis — it holds on whichever layout is selected here).
    merge_same_geometry: bool = False


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class EntityBlock:
    """One fixed-shape block of per-entity problems (vmap unit).

    entity_idx: (E,) dense entity index of each row; -1 marks a shape-bucket
      padding row (no entity — excluded from tracker stats and dropped at
      scatter time).
    features:   (E, n_max, d)
    label/weight: (E, n_max); padding samples have weight 0.
    sample_index: (E, n_max) int32 row into the flat GameBatch (-1 padding);
      used to gather residual offsets and scatter scores.
    train_mask: (E,) bool — False for entities filtered by the lower bound
      (they keep a zero model; reference filterActiveData:550-570) and for
      shape-bucket padding rows.
    """

    entity_idx: Array
    features: Array
    label: Array
    weight: Array
    sample_index: Array
    train_mask: Array
    # Subspace projection (LinearSubspaceProjector role): block-local feature
    # column j corresponds to global column col_map[j]. None = identity
    # (block dim == shard dim).
    col_map: Optional[Array] = None

    @property
    def num_entities(self) -> int:
        return self.features.shape[0]

    @property
    def n_max(self) -> int:
        return self.features.shape[1]

    @property
    def dim(self) -> int:
        """Block-local feature dimension (≤ shard dim under projection)."""
        return self.features.shape[2]

    def project_backward(self, w_block: Array, d_full: int) -> Array:
        """Block-space coefficients (E, dim) → global space (E, d_full)
        (reference LinearSubspaceProjector.projectBackward)."""
        if self.col_map is None:
            return w_block
        out = jnp.zeros((w_block.shape[0], d_full), w_block.dtype)
        return out.at[:, self.col_map].set(w_block)

    def project_forward(self, w_global: Array) -> Array:
        """Global-space coefficients (E, d_full) → block space (E, dim)
        (reference LinearSubspaceProjector.projectForward)."""
        if self.col_map is None:
            return w_global
        return w_global[:, self.col_map]

    def gather_offsets(self, offsets: Array) -> Array:
        """(E, n_max) per-sample offsets from the flat (n,) offset/residual
        array (addScoresToOffsets role — a gather, not a join)."""
        safe = jnp.maximum(self.sample_index, 0)
        return jnp.where(self.sample_index >= 0, offsets[safe], 0.0)


@dataclasses.dataclass
class RandomEffectDataset:
    """All blocks for one random-effect coordinate + bookkeeping.

    ``dim`` is the GLOBAL shard dimension; under subspace projection each
    block's local dim (``block.dim``) may be far smaller."""

    config: RandomEffectDataConfig
    blocks: List[EntityBlock]
    num_entities: int  # total interned entities E for this RE type
    dim: int

    @property
    def num_active_samples(self) -> int:
        return int(sum(np.sum(np.asarray(b.weight) > 0) for b in self.blocks))

    @property
    def projected(self) -> bool:
        return any(b.col_map is not None for b in self.blocks)

    def projection_tables(self):
        """(entity_block, entity_row, inv_maps) for ProjectedRandomEffectModel:
        entity e's model lives at row entity_row[e] of block entity_block[e]
        (−1 = entity has no data); inv_maps[b] maps global→block columns."""
        entity_block = np.full((self.num_entities,), -1, np.int32)
        entity_row = np.zeros((self.num_entities,), np.int32)
        inv_maps = []
        for b, block in enumerate(self.blocks):
            eidx = np.asarray(block.entity_idx)
            real = eidx >= 0  # skip shape-bucket padding rows
            entity_block[eidx[real]] = b
            entity_row[eidx[real]] = np.arange(eidx.size, dtype=np.int32)[real]
            inv = np.full((self.dim,), -1, np.int32)
            if block.col_map is not None:
                inv[np.asarray(block.col_map)] = np.arange(block.dim, dtype=np.int32)
            else:
                inv = np.arange(self.dim, dtype=np.int32)
            inv_maps.append(jnp.asarray(inv))
        return jnp.asarray(entity_block), jnp.asarray(entity_row), inv_maps


def build_random_effect_dataset(
    entity_ids: np.ndarray,  # (n,) dense int32 entity index per sample
    features,  # (n, d) dense np array OR host sparse (indices, values, dim)
    label: np.ndarray,
    weight: np.ndarray,
    num_entities: int,
    config: RandomEffectDataConfig,
    uid: Optional[np.ndarray] = None,
    existing_model_mask: Optional[np.ndarray] = None,
) -> RandomEffectDataset:
    """Host-side grouping: the TPU analogue of RandomEffectDataset.apply
    (reference :260-349 build pipeline).

    Samples per entity beyond ``active_upper_bound`` are dropped from active
    training data via deterministic reservoir sampling (they remain passive:
    still scored through the flat batch).

    ``existing_model_mask`` ((num_entities,) bool, warm-start only):
    entities WITHOUT an existing model are exempt from
    ``active_lower_bound`` — the reference's ignoreThresholdForNewModels
    flag (GameTrainingDriver.scala:169-172, RandomEffectDataset.scala:
    550-570: keep entity if count >= bound OR id not in existing keys).

    ``features`` is either a dense (n, d) array or a host-side padded-sparse
    triple ``(indices (n,k) int, values (n,k) float, dim)`` — the wide-shard
    route. Sparse input implies per-block subspace projection (compacting
    each block to the union of its entities' active columns, reference
    RandomEffectDataset.scala:383-432); dense input opts in via
    ``config.subspace_projection=True``.
    """
    sp_indices = sp_values = None
    if isinstance(features, tuple):
        sp_indices, sp_values, d = features
        sp_indices = np.asarray(sp_indices)
        sp_values = np.asarray(sp_values)
        n = sp_indices.shape[0]
        project = True if config.subspace_projection is None else config.subspace_projection
        if not project:
            raise ValueError("sparse shard input requires subspace projection")
        feat_dtype = sp_values.dtype
    else:
        features = np.asarray(features)
        n, d = features.shape
        project = bool(config.subspace_projection)
        feat_dtype = features.dtype
    uid = np.arange(n, dtype=np.int64) if uid is None else uid.astype(np.int64)

    # Group sample rows by entity (sorted for determinism).
    order = np.argsort(entity_ids, kind="stable")
    sorted_eids = entity_ids[order]
    uniq, starts = np.unique(sorted_eids, return_index=True)
    groups = np.split(order, starts[1:])

    # Drop the group of negative (unknown) entity ids if present.
    entities: List[Tuple[int, np.ndarray]] = [
        (int(eid), rows) for eid, rows in zip(uniq, groups) if eid >= 0
    ]
    if not entities:
        return RandomEffectDataset(config, [], num_entities, d)

    # Reservoir-sample active data per entity (deterministic key on uid).
    ub = config.active_upper_bound
    if ub is not None:
        capped = []
        for eid, rows in entities:
            if len(rows) > ub:
                keys = _byteswap64(uid[rows])
                rows = rows[np.argsort(keys, kind="stable")[:ub]]
            capped.append((eid, rows))
        entities = capped

    lb = config.active_lower_bound or 0

    # Bucket entities by sample count to bound padding waste.
    counts = np.array([len(rows) for _, rows in entities])
    if counts.size == 0:
        return RandomEffectDataset(config, [], num_entities, d)
    n_buckets = max(1, min(config.n_buckets, len(np.unique(counts))))
    # Quantile cut points on counts → per-bucket n_max.
    qs = np.quantile(counts, np.linspace(0, 1, n_buckets + 1)[1:], method="higher")
    qs = np.unique(qs.astype(np.int64))

    blocks: List[EntityBlock] = []
    assigned = np.digitize(counts, qs, right=True)
    for b, n_max in enumerate(qs):
        sel = np.flatnonzero(assigned == b)
        if sel.size == 0:
            continue
        n_max = int(max(n_max, 1))
        E = sel.size
        block_rows = np.concatenate([entities[gi][1] for gi in sel])

        # Subspace compaction: block feature space = union of active columns
        # (LinearSubspaceProjector per vmap block instead of per entity).
        col_map = inv_map = None
        if project:
            if sp_indices is not None:
                active = sp_indices[block_rows][sp_values[block_rows] != 0]
                col_map = np.unique(active).astype(np.int64)
            else:
                col_map = np.flatnonzero(
                    np.any(features[block_rows] != 0, axis=0)
                ).astype(np.int64)
            if col_map.size == 0:
                col_map = np.zeros((1,), np.int64)  # degenerate all-zero block
            inv_map = np.full((d,), -1, dtype=np.int64)
            inv_map[col_map] = np.arange(col_map.size)
        d_block = int(col_map.size) if project else d

        # Shape bucketing: round (E, n_max, d) up to the geometric grid so
        # the solver cache keys collapse; padding is inert by construction
        # (weight 0, train_mask False, entity_idx −1). Projected blocks keep
        # their exact content-defined col_map width.
        E_alloc = E
        n_used = int(counts[sel].sum())
        d_used = d_block
        if config.shape_bucketing:
            n_max = bucket_dim(n_max)
            E_alloc = bucket_dim(E)
            if not project:
                d_block = bucket_dim(d_block)
        _publish_pad_waste(
            config.re_type,
            entities=(E, E_alloc),
            samples=(n_used, E_alloc * n_max),
            features=(d_used, d_block),
        )

        feat = np.zeros((E_alloc, n_max, d_block), dtype=feat_dtype)
        lab = np.zeros((E_alloc, n_max), dtype=label.dtype)
        wt = np.zeros((E_alloc, n_max), dtype=weight.dtype)
        sidx = np.full((E_alloc, n_max), -1, dtype=np.int32)
        eidx = np.full((E_alloc,), -1, dtype=np.int32)
        tmask = np.zeros((E_alloc,), dtype=bool)
        for j, gi in enumerate(sel):
            eid, rows = entities[gi]
            m = len(rows)
            if sp_indices is not None:
                # Scatter padded-sparse rows into the compact block space.
                loc = inv_map[sp_indices[rows]]  # (m, k), −1 only for 0-values
                vals = sp_values[rows]
                keep = vals != 0
                r_i, _k_i = np.nonzero(keep)
                np.add.at(feat[j], (r_i, loc[keep]), vals[keep])
            elif project:
                feat[j, :m] = features[rows][:, col_map]
            else:
                # d_block ≥ d under bucketing; padded columns stay zero.
                feat[j, :m, :d] = features[rows]
            lab[j, :m] = label[rows]
            wt[j, :m] = weight[rows]
            sidx[j, :m] = rows
            eidx[j] = eid
            tmask[j] = m >= lb or (
                existing_model_mask is not None
                and not bool(existing_model_mask[eid])
            )
        blocks.append(
            EntityBlock(
                entity_idx=jnp.asarray(eidx),
                features=jnp.asarray(feat),
                label=jnp.asarray(lab),
                weight=jnp.asarray(wt),
                sample_index=jnp.asarray(sidx),
                train_mask=jnp.asarray(tmask),
                col_map=None if col_map is None else jnp.asarray(col_map, jnp.int32),
            )
        )
    dataset = RandomEffectDataset(config, blocks, num_entities, d)
    if config.merge_same_geometry:
        dataset = merge_same_geometry_blocks(dataset)
    return dataset


def merge_same_geometry_blocks(
    dataset: RandomEffectDataset,
) -> RandomEffectDataset:
    """Collapse dense blocks that share an (n_max, dim) geometry into ONE
    block each — the dispatch-count collapse behind ``re_kernel`` batching.

    Shape bucketing rounds every block's n_max/dim onto the geometric grid
    (``bucket_dim``), so quantile n-buckets frequently COLLIDE on the same
    (n_max, dim): the builder still emits them as separate blocks (one per
    quantile), and each becomes one solver dispatch per CD pass. Entities
    are vmap lanes with no cross-entity math, so same-geometry blocks can
    concatenate along the entity axis with per-entity results unchanged —
    one dispatch solves them all, and the fused Pallas kernel
    (ops/pallas_newton) runs one grid instance per merged row.

    Invariants preserved:
    * Per-entity data layout: rows are concatenated in block order, padding
      rows stay inert (entity_idx −1, weight 0, train_mask False), and the
      drop-mode scatter keys on ``entity_idx`` — which rows share a block
      never enters the math. Results are NOT bit-identical to the unmerged
      layout, however: the vmapped Newton program compiles per lane count,
      and XLA's fusion/reduction order inside that whole program shifts
      with the batch dimension (measured ≤ 2.3e-4 coefficient drift with
      occasional ±1 iteration-count differences on the CPU smoke workload
      — both layouts converge to the same tolerance). That is why
      ``RandomEffectDataConfig.merge_same_geometry`` is opt-in and why the
      re_kernel bit-parity tests always compare on a FIXED layout.
    * Shape bucketing: the merged entity count re-buckets via
      ``bucket_dim`` (when the dataset was built with bucketing) so the
      merged allocation stays on the solver-cache shape grid.
    * Projected blocks (content-defined ``col_map``) pass through
      untouched — merging them would retrace on the union col_map.

    Host-side numpy concatenation, one-time at dataset build — never inside
    the dispatch loop.
    """
    groups: Dict[Tuple[int, int], List[int]] = {}
    for i, b in enumerate(dataset.blocks):
        if b.col_map is not None:
            continue
        groups.setdefault((b.n_max, b.dim), []).append(i)

    merged: List[EntityBlock] = []
    consumed = set()
    for i, b in enumerate(dataset.blocks):
        if i in consumed:
            continue
        key = (b.n_max, b.dim)
        idxs = groups.get(key) if b.col_map is None else None
        if not idxs or len(idxs) == 1:
            merged.append(b)
            continue
        consumed.update(idxs)
        parts = [dataset.blocks[j] for j in idxs]
        E = sum(p.num_entities for p in parts)
        E_alloc = bucket_dim(E) if dataset.config.shape_bucketing else E
        pad = E_alloc - E
        n_max, d = key

        def cat(field, pad_arr):
            arrs = [np.asarray(getattr(p, field)) for p in parts]
            if pad:
                arrs.append(pad_arr)
            return jnp.asarray(np.concatenate(arrs))

        merged.append(
            EntityBlock(
                entity_idx=cat("entity_idx", np.full((pad,), -1, np.int32)),
                features=cat(
                    "features",
                    np.zeros((pad, n_max, d), np.asarray(parts[0].features).dtype),
                ),
                label=cat(
                    "label", np.zeros((pad, n_max), np.asarray(parts[0].label).dtype)
                ),
                weight=cat(
                    "weight", np.zeros((pad, n_max), np.asarray(parts[0].weight).dtype)
                ),
                sample_index=cat(
                    "sample_index", np.full((pad, n_max), -1, np.int32)
                ),
                train_mask=cat("train_mask", np.zeros((pad,), bool)),
                col_map=None,
            )
        )
    return dataclasses.replace(dataset, blocks=merged)


def pack_into_sizes(total: int, allowed_sizes: Sequence[int]) -> List[int]:
    """Plan compacted block sizes for ``total`` active rows using ONLY sizes
    drawn from ``allowed_sizes`` — the entity allocations of the dataset's
    original blocks with the same (n_max, d) geometry. Every one of those
    allocations was compiled during the first full CD pass, so a plan drawn
    from this set lands exclusively on already-cached executables: the
    active-set path's zero-retrace guarantee holds by construction.

    Greedy: the smallest allowed size that holds the remainder, else the
    largest allowed size repeatedly.
    """
    sizes = sorted({int(s) for s in allowed_sizes})
    if not sizes:
        raise ValueError("pack_into_sizes needs at least one allowed size")
    plan: List[int] = []
    remaining = int(total)
    while remaining > 0:
        plan.append(next((s for s in sizes if s >= remaining), sizes[-1]))
        remaining -= plan[-1]
    return plan


def compact_entity_blocks(
    blocks: Sequence[EntityBlock],
    keep: Sequence[np.ndarray],
    allowed_sizes: Optional[Sequence[int]] = None,
    to_device: bool = True,
) -> List[Tuple[EntityBlock, np.ndarray, np.ndarray]]:
    """Repack the still-active rows of same-geometry dense blocks into the
    smallest already-compiled shapes (the active-set repack path).

    ``blocks`` must share (n_max, dim) and be dense (``col_map is None``) —
    projected blocks keep content-defined col_map widths that cannot merge
    without a retrace, so they use whole-block skipping instead. ``keep[i]``
    is a host bool array over block i's entity rows; shape-bucket padding
    rows (entity_idx == -1) must already be False there.

    Returns ``[(compacted_block, src_block, src_row), ...]``: the two int32
    arrays are the per-row entity_gather index map — for every row of the
    compacted block, the (source block index, source row) it was gathered
    from, (-1, -1) on the compacted block's own padding rows. The map routes
    the NEXT pass's per-row active masks back onto original blocks; merging
    coefficients back needs no map at all, because compacted rows carry
    their real ``entity_idx`` and the coordinate's single drop-mode scatter
    already lands them.

    ``to_device=False`` keeps the compacted block's leaves as host numpy —
    the out-of-core path's upload stage does the ``device_put`` itself, so
    compaction must not eagerly place blocks on device (that would double
    the device footprint outside the residency budget).
    """
    if not blocks:
        return []
    geom = {(b.n_max, b.dim, b.col_map is None) for b in blocks}
    if len(geom) != 1 or not next(iter(geom))[2]:
        raise ValueError(
            f"compact_entity_blocks needs same-geometry dense blocks, got {geom}"
        )
    src_block_parts, src_row_parts = [], []
    for i, k in enumerate(keep):
        rows = np.flatnonzero(np.asarray(k))
        src_block_parts.append(np.full(rows.shape, i, np.int32))
        src_row_parts.append(rows.astype(np.int32))
    src_block = np.concatenate(src_block_parts)
    src_row = np.concatenate(src_row_parts)
    total = int(src_block.size)
    if total == 0:
        return []
    if allowed_sizes is None:
        allowed_sizes = [b.num_entities for b in blocks]
    plan = pack_into_sizes(total, allowed_sizes)

    n_max, d = blocks[0].n_max, blocks[0].dim
    out: List[Tuple[EntityBlock, np.ndarray, np.ndarray]] = []
    start = 0
    for size in plan:
        sb = src_block[start:start + size]
        sr = src_row[start:start + size]
        start += sb.size
        pad = size - sb.size

        def gather(field, pad_arr, sb=sb, sr=sr, pad=pad):
            # Host-side numpy gather, deliberately: jnp advanced indexing
            # would eagerly compile one XLA gather kernel per distinct
            # selection shape — seconds of warmup landing in the first gated
            # pass. The repack is a pass-boundary host step by design, so
            # gather on host and ship only the compacted block to device.
            # src pairs are sorted (block asc, row asc), so concatenating
            # per-source gathers in block order preserves row order exactly.
            parts = [
                np.asarray(getattr(blocks[b], field))[sr[sb == b]]
                for b in np.unique(sb)
            ]
            if pad:
                parts.append(pad_arr)
            merged = parts[0] if len(parts) == 1 else np.concatenate(parts)
            if not to_device:
                return np.ascontiguousarray(merged)
            return jnp.asarray(merged)

        block_c = EntityBlock(
            entity_idx=gather("entity_idx", np.full((pad,), -1, np.int32)),
            features=gather(
                "features", np.zeros((pad, n_max, d), blocks[0].features.dtype)
            ),
            label=gather("label", np.zeros((pad, n_max), blocks[0].label.dtype)),
            weight=gather(
                "weight", np.zeros((pad, n_max), blocks[0].weight.dtype)
            ),
            sample_index=gather(
                "sample_index", np.full((pad, n_max), -1, np.int32)
            ),
            train_mask=gather("train_mask", np.zeros((pad,), bool)),
            col_map=None,
        )
        out.append(
            (
                block_c,
                np.concatenate([sb, np.full((pad,), -1, np.int32)]),
                np.concatenate([sr, np.full((pad,), -1, np.int32)]),
            )
        )
    return out


def pearson_feature_mask(
    block: EntityBlock,
    max_features: Array,
    always_keep: Optional[int] = None,
) -> Array:
    """Per-entity Pearson-correlation top-k feature mask (reference
    LocalDataset.filterFeaturesByPearsonCorrelationScore:103), batched on
    device: (E, d) 0/1 mask keeping each entity's top ``max_features[e]``
    most label-correlated features.

    Constant/absent columns (zero variance for that entity — including
    features the entity never touches) score 0 so they cannot crowd out
    informative features; the intercept column (``always_keep``) is exempt
    from the filter, matching the reference's interceptOpt convention.
    """
    w = block.weight  # (E, n_max) — 0 on padding
    tot = jnp.maximum(jnp.sum(w, axis=1, keepdims=True), 1e-12)
    X, y = block.features, block.label
    mx = jnp.sum(w[..., None] * X, axis=1) / tot  # (E, d)
    my = jnp.sum(w * y, axis=1, keepdims=True) / tot  # (E, 1)
    dx = X - mx[:, None, :]
    dy = (y - my)[..., None]
    cov = jnp.sum(w[..., None] * dx * dy, axis=1)
    vx = jnp.sum(w[..., None] * dx * dx, axis=1)
    vy = jnp.sum(w[..., None] * dy * dy, axis=1)
    corr = jnp.abs(cov / jnp.sqrt(jnp.maximum(vx * vy, 1e-24)))
    corr = jnp.where(vx < 1e-12, 0.0, corr)
    # Rank features per entity (0 = most correlated); keep rank < k_e.
    order = jnp.argsort(-corr, axis=1)
    ranks = jnp.argsort(order, axis=1)
    k_e = jnp.asarray(max_features).reshape(-1, 1)
    mask = (ranks < k_e).astype(X.dtype)
    if always_keep is not None:
        mask = mask.at[:, always_keep].set(1.0)
    return mask
