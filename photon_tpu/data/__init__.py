from photon_tpu.data.batch import LabeledBatch, SparseFeatures  # noqa: F401
