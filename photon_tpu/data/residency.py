"""Shared residency core: deterministic LRU policies for device working sets.

Serving (serve/store.py) and training (algorithm/re_store.py) manage the
same resource — a bounded device-resident subset of a host-resident master —
under the same policy: least-recently-used eviction with protection for
entries the caller is actively using. This module is the single home for
that policy so the two sides cannot drift.

Two shapes of the same idea:

``SlotLru``
    A fixed pool of SLOTS (serving hot tables): every resident key occupies
    exactly one row of a preallocated device table, so admission means
    assigning a slot and eviction means demoting some other key out of its
    slot. Used by the serving hot/cold store for both dense and projected
    random-effect tables.

``ByteBudgetLru``
    Variable BYTE costs under a budget (training working set): each key is a
    whole entity block whose device arrays differ in size, so admission
    evicts least-recently-used keys until the newcomer's bytes fit. Used by
    the out-of-core training store (algorithm/re_store.py).

Both are deliberately clock- and hash-free: iteration and eviction order
depend only on the call sequence (OrderedDict insertion/touch order), never
on wall time or hashing — the out-of-core determinism contract (same seed +
budget ⇒ identical eviction sequence) rests on this.

Neither class is thread-safe by itself; callers serialize access (the
serving engine under its batch lock, the training store under its budget
condition variable).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Hashable, List, Optional


class SlotLru:
    """Key → slot assignment over a fixed pool of ``capacity`` slots.

    Free slots are handed out in ascending order (0, 1, …); once the pool is
    exhausted, ``claim`` demotes the least-recently-used key that is not in
    the caller's ``protected`` set and reuses its slot. ``on_demote`` fires
    for every demotion (metric counters live with the caller, which knows
    its label space).

    ``base`` offsets every slot this pool hands out: a store whose hot
    table is split into per-device-shard segments runs one SlotLru per
    segment over the segment's global slot range [base, base + capacity) —
    segments stay disjoint by construction and the upload scatter keeps
    addressing one (sharded) table.
    """

    def __init__(
        self,
        capacity: int,
        on_demote: Optional[Callable[[Hashable, int], None]] = None,
        base: int = 0,
    ):
        self.capacity = int(capacity)
        self.base = int(base)
        self._slot_of: "OrderedDict[Hashable, int]" = OrderedDict()
        # Popped from the end: slots assign in ascending order.
        self._free: List[int] = list(
            range(self.base + self.capacity - 1, self.base - 1, -1)
        )
        self._on_demote = on_demote

    def __len__(self) -> int:
        return len(self._slot_of)

    def __contains__(self, key) -> bool:
        return key in self._slot_of

    @property
    def resident(self) -> List:
        """Resident keys, least- to most-recently used."""
        return list(self._slot_of)

    def get(self, key) -> Optional[int]:
        """Slot of ``key`` (touching it most-recently-used), None if cold."""
        slot = self._slot_of.get(key)
        if slot is not None:
            self._slot_of.move_to_end(key)
        return slot

    def peek(self, key) -> Optional[int]:
        """Slot of ``key`` WITHOUT touching recency (upload index lookups)."""
        return self._slot_of.get(key)

    def claim(self, key, protected=()) -> int:
        """Make ``key`` resident and return its slot, demoting the LRU
        victim outside ``protected`` when the pool is full. Raises
        RuntimeError (message contains "exhausted") when every resident key
        is protected — the caller's working set exceeds the pool."""
        if self._free:
            slot = self._free.pop()
        else:
            slot = None
            for victim in self._slot_of:
                if victim not in protected:
                    slot = self._slot_of.pop(victim)
                    if self._on_demote is not None:
                        self._on_demote(victim, slot)
                    break
            if slot is None:
                raise RuntimeError(
                    f"slot pool exhausted: all {self.capacity} resident "
                    "entries are protected by the current batch"
                )
        self._slot_of[key] = slot
        return slot


class ByteBudgetLru:
    """Byte-budgeted LRU over variable-cost keys (training working set).

    ``admit`` evicts least-recently-used unprotected keys until the new
    entry's cost fits under ``budget``, then marks it resident. A single
    entry larger than everything evictable is still admitted (floor
    semantics: refusing would deadlock the pipeline) — callers size budgets
    to at least their largest entry so the resident-bytes gauge stays under
    the configured value.

    ``eviction_log`` records every policy eviction in order; the out-of-core
    determinism tests compare these sequences across runs.
    """

    def __init__(
        self,
        budget_bytes: int,
        on_evict: Optional[Callable[[Hashable], None]] = None,
    ):
        self.budget = int(budget_bytes)
        self._cost: "OrderedDict[Hashable, int]" = OrderedDict()
        self.resident_bytes = 0
        self.peak_bytes = 0
        self.evictions = 0
        self.eviction_log: List = []
        self._on_evict = on_evict

    def __len__(self) -> int:
        return len(self._cost)

    def __contains__(self, key) -> bool:
        return key in self._cost

    @property
    def resident(self) -> List:
        """Resident keys, least- to most-recently used."""
        return list(self._cost)

    def touch(self, key) -> bool:
        """Mark ``key`` most-recently-used; False if not resident."""
        if key in self._cost:
            self._cost.move_to_end(key)
            return True
        return False

    def would_fit(self, cost: int, protected=()) -> bool:
        """True when admitting ``cost`` bytes can respect the budget after
        evicting every unprotected resident. False means only protected
        bytes stand in the way — the caller should wait for releases before
        admitting. (With zero protected bytes this is always True: there is
        nothing to wait for, so the floor-admission path applies.)"""
        protected_bytes = sum(
            c for k, c in self._cost.items() if k in protected
        )
        return protected_bytes + int(cost) <= self.budget or not protected_bytes

    def admit(self, key, cost: int, protected=()) -> List:
        """Make ``key`` resident at ``cost`` bytes, evicting unprotected LRU
        keys as needed. Returns the eviction victims in order. Re-admitting
        a resident key refreshes recency and evicts nothing."""
        cost = int(cost)
        if key in self._cost:
            self._cost.move_to_end(key)
            return []
        victims: List = []
        while self.resident_bytes + cost > self.budget:
            victim = next((k for k in self._cost if k not in protected), None)
            if victim is None:
                break  # floor admission: nothing evictable remains
            victims.append(victim)
            self._evict(victim)
        self._cost[key] = cost
        self.resident_bytes += cost
        self.peak_bytes = max(self.peak_bytes, self.resident_bytes)
        return victims

    def evict(self, key) -> bool:
        """Policy-initiated eviction (counted and logged) — e.g. dropping a
        block whose entities all converged. False if not resident."""
        if key not in self._cost:
            return False
        self._evict(key)
        return True

    def discard(self, key) -> bool:
        """Drop ``key`` without counting an eviction (caller-initiated
        release of a transient entry). False if not resident."""
        if key not in self._cost:
            return False
        self.resident_bytes -= self._cost.pop(key)
        return True

    def _evict(self, key) -> None:
        self.resident_bytes -= self._cost.pop(key)
        self.evictions += 1
        self.eviction_log.append(key)
        if self._on_evict is not None:
            self._on_evict(key)
