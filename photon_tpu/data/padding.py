"""Shared shape-bucketed GameBatch padding: ONE grid for every consumer.

Three code paths feed the jitted GAME scorer with padded batches — the
scoring driver's device-side chunk padding (cli/game_scoring.py), the ingest
pipeline's host-side h2d padding (io/pipeline.py::_bucket_pad_host), and the
online serving batcher (serve/batcher.py). Their padding rules MUST agree:
a row-count or nnz-width computed differently in any one of them lands on a
different XLA program shape, which is both a retrace (latency cliff) and a
parity bug (the serve/CI bit-parity checks compare across the paths). This
module is that single rule set.

Rules (identical to the pre-dedupe copies, pinned by tests):

- rows pad with weight-0 samples and ``entity_idx = -1`` (scored as zero and
  dropped by callers; -1 rows are remapped/dropped at scatter time);
- sparse nnz widths bucket UP to the next power of two on EVERY batch, even
  when the row count already fits — a batch landing exactly on the row
  target must still bucket its width or each distinct width retraces;
- uid/label/offset pad with zeros.

The helpers are array-namespace generic: pass ``xp=numpy`` for host-side
padding (pipeline h2d stage, serving batcher assembly — keeps padding off
the device and lets ``jax.device_put`` ship one contiguous buffer) or
``xp=jax.numpy`` for device-resident batches (scoring driver chunks that
are already on device). Both produce bit-identical batches.
"""

from __future__ import annotations

import dataclasses

import numpy as np


def bucket_pow2(k: int) -> int:
    """Next power of two ≥ k (k ≥ 0); the sparse nnz-width grid."""
    return 1 << max(0, (int(k) - 1)).bit_length()


def bucket_grid(max_n: int):
    """Every row-count bucket an online caller can dispatch on with batches
    of 1..max_n rows: the ``bucket_dim`` grid values up to and including
    ``bucket_dim(max_n)``. The serving engine warms exactly this set, so
    "zero retraces after warm-up" is a closed-world guarantee, not a hope."""
    from photon_tpu.data.random_effect import bucket_dim

    grid = []
    n = 1
    top = bucket_dim(int(max_n))
    while True:
        b = bucket_dim(n)
        grid.append(b)
        if b >= top:
            return grid
        n = b + 1


def pad_feature_matrix(v, pad: int, xp=np):
    """Pad one feature leaf by ``pad`` rows; bucket sparse nnz width to the
    next power of two regardless of ``pad``. Returns ``v`` unchanged when
    nothing needs padding (no no-op copies on the streaming hot path)."""
    from photon_tpu.data.batch import SparseFeatures

    if isinstance(v, SparseFeatures):
        # Rows: zero-valued padding pointing at index 0 contributes nothing.
        # Columns: the per-batch nnz width varies with the densest row seen,
        # so bucket it — otherwise every distinct width retraces the jitted
        # scorer (one XLA compile per batch).
        k = v.indices.shape[1]
        k_pad = bucket_pow2(k)
        if pad == 0 and k_pad == k:
            return v  # already bucketed: no eager copies
        indices = xp.pad(xp.asarray(v.indices), ((0, pad), (0, k_pad - k)))
        values = xp.pad(xp.asarray(v.values), ((0, pad), (0, k_pad - k)))
        out = SparseFeatures(indices, values, v.dim)
        if xp is np and v.csc_order is not None:
            out = out.with_transpose_plan()  # padding changed the pattern
        return out
    return v if pad == 0 else xp.pad(xp.asarray(v), ((0, pad), (0, 0)))


def pad_game_batch(b, target_n: int, xp=np):
    """Pad a GameBatch to ``target_n`` rows (weight-0 samples, -1 entity
    ids) and bucket every sparse shard's nnz width. Returns ``b`` itself
    when no array changes — callers use identity to skip downstream work."""
    from photon_tpu.data.game_data import GameBatch

    pad = max(int(target_n) - b.n, 0)
    features = {k: pad_feature_matrix(v, pad, xp) for k, v in b.features.items()}
    if pad == 0:
        if all(f is v for f, v in zip(features.values(), b.features.values())):
            return b
        return dataclasses.replace(b, features=features)
    padf = lambda a: xp.pad(xp.asarray(a), (0, pad))  # noqa: E731
    return GameBatch(
        label=padf(b.label),
        offset=padf(b.offset),
        weight=padf(b.weight),  # zeros: padding rows carry no weight
        features=features,
        entity_ids={
            k: xp.pad(xp.asarray(v), (0, pad), constant_values=-1)
            for k, v in b.entity_ids.items()
        },
        uid=None if b.uid is None else padf(b.uid),
    )
