"""Feature and entity index maps.

Parity targets: reference ``IndexMap`` trait (photon-api index/IndexMap.scala:
22-46), in-heap ``DefaultIndexMap``, and the PalDB off-heap partitioned store
(index/PalDBIndexMap.scala:43-240). The TPU rebuild's native mmap store
(C++ hash-partitioned string→int store) plugs in behind the same interface;
this module provides the in-memory implementation plus the interning logic
used at ingest.

``EntityIndex`` is the TPU-new piece: random-effect entity ids are interned
to dense [0, E) indices at ingest, which is what turns the reference's
RDD joins into XLA gathers.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, Iterator, List, Optional, Tuple


class IndexMap:
    """Bidirectional feature-name ↔ index map (DefaultIndexMap role).

    Feature identity follows the reference's NameAndTerm convention:
    a feature key is "name\x01term" (AvroDataReader feature-bag semantics);
    the intercept is the reserved key ``INTERCEPT``.
    """

    INTERCEPT = "(INTERCEPT)"
    DELIM = "\x01"

    def __init__(self, name_to_index: Optional[Dict[str, int]] = None):
        self._fwd: Dict[str, int] = dict(name_to_index or {})
        self._rev: Optional[List[str]] = None

    @staticmethod
    def key(name: str, term: str = "") -> str:
        return f"{name}{IndexMap.DELIM}{term}" if term else name

    def __len__(self) -> int:
        return len(self._fwd)

    def __contains__(self, key: str) -> bool:
        return key in self._fwd

    def get_index(self, key: str) -> int:
        """-1 for unknown features (reference IndexMap.getIndex semantics)."""
        return self._fwd.get(key, -1)

    def get_feature_name(self, index: int) -> Optional[str]:
        if self._rev is None:
            rev: List[str] = [""] * len(self._fwd)
            for k, i in self._fwd.items():
                rev[i] = k
            self._rev = rev
        if 0 <= index < len(self._rev):
            return self._rev[index]
        return None

    def items(self) -> Iterator[Tuple[str, int]]:
        return iter(self._fwd.items())

    @staticmethod
    def build(keys: Iterable[str], add_intercept: bool = False) -> "IndexMap":
        """Build from distinct feature keys (FeatureIndexingDriver /
        generateIndexMapLoaders distinct-scan role). Sorted for determinism."""
        distinct = sorted(set(keys))
        if add_intercept and IndexMap.INTERCEPT not in distinct:
            distinct.append(IndexMap.INTERCEPT)
        return IndexMap({k: i for i, k in enumerate(distinct)})

    # --- persistence (JSON; the C++ mmap store replaces this for huge maps) ---

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self._fwd, f)

    @staticmethod
    def load(path: str) -> "IndexMap":
        with open(path) as f:
            return IndexMap(json.load(f))


class EntityIndex:
    """Interns random-effect entity ids (strings) to dense [0, E) ints."""

    def __init__(self):
        self._fwd: Dict[str, int] = {}
        self._rev: List[str] = []

    def __len__(self) -> int:
        return len(self._rev)

    def intern(self, entity_id: str) -> int:
        idx = self._fwd.get(entity_id)
        if idx is None:
            idx = len(self._rev)
            self._fwd[entity_id] = idx
            self._rev.append(entity_id)
        return idx

    def lookup(self, entity_id: str) -> int:
        """-1 for entities unseen at training time (cold start)."""
        return self._fwd.get(entity_id, -1)

    def entity_id(self, index: int) -> str:
        return self._rev[index]

    def ids(self) -> List[str]:
        return list(self._rev)

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self._rev, f)

    @staticmethod
    def load(path: str) -> "EntityIndex":
        ei = EntityIndex()
        with open(path) as f:
            for eid in json.load(f):
                ei.intern(eid)
        return ei
