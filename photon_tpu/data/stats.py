"""Per-feature summary statistics.

Parity target: reference ``FeatureDataStatistics`` (photon-lib
stat/FeatureDataStatistics.scala:44-139 — per-feature count/mean/var/min/max/
L1/L2/numNonzeros via Spark MultivariateOnlineSummarizer treeAggregate).

TPU-first: one pass of weighted column reductions under jit; with the batch
sharded over the mesh's data axis XLA turns each column sum into a psum.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from photon_tpu.data.batch import LabeledBatch, SparseFeatures

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class FeatureDataStatistics:
    count: Array  # scalar: total sample count (unweighted, matching reference)
    mean: Array  # (d,)
    variance: Array  # (d,)
    min: Array  # (d,)
    max: Array  # (d,)
    norm_l1: Array  # (d,)
    norm_l2: Array  # (d,)
    num_nonzeros: Array  # (d,)
    intercept_index: Optional[int] = dataclasses.field(
        default=None, metadata=dict(static=True)
    )

    @property
    def abs_max(self) -> Array:
        return jnp.maximum(jnp.abs(self.min), jnp.abs(self.max))

    @property
    def std(self) -> Array:
        return jnp.sqrt(jnp.maximum(self.variance, 0.0))

    def summary_text(self) -> str:
        """writeBasicStatistics-style dump (ModelProcessingUtils.scala:516)."""
        import numpy as np

        lines = ["index\tmean\tvar\tmin\tmax\tl1\tl2\tnnz"]
        for j in range(self.mean.shape[0]):
            lines.append(
                f"{j}\t{float(self.mean[j]):.6g}\t{float(self.variance[j]):.6g}\t"
                f"{float(self.min[j]):.6g}\t{float(self.max[j]):.6g}\t"
                f"{float(self.norm_l1[j]):.6g}\t{float(self.norm_l2[j]):.6g}\t"
                f"{int(self.num_nonzeros[j])}"
            )
        return "\n".join(lines)


def compute_feature_stats(
    batch: LabeledBatch, intercept_index: Optional[int] = None
) -> FeatureDataStatistics:
    """Single fused pass over the (possibly sharded) batch. Padding rows
    (weight 0) are excluded from every statistic."""
    feats = batch.features
    X = feats.to_dense() if isinstance(feats, SparseFeatures) else feats
    present = (batch.weight > 0).astype(X.dtype)  # (n,)
    n = jnp.maximum(jnp.sum(present), 1.0)

    Xp = X * present[:, None]
    mean = jnp.sum(Xp, axis=0) / n
    var = jnp.sum(present[:, None] * (X - mean[None, :]) ** 2, axis=0) / jnp.maximum(n - 1.0, 1.0)
    big = jnp.asarray(jnp.finfo(X.dtype).max)
    mn = jnp.min(jnp.where(present[:, None] > 0, X, big), axis=0)
    mx = jnp.max(jnp.where(present[:, None] > 0, X, -big), axis=0)
    l1 = jnp.sum(jnp.abs(Xp), axis=0)
    l2 = jnp.sqrt(jnp.sum(Xp * Xp, axis=0))
    nnz = jnp.sum((Xp != 0).astype(jnp.int32), axis=0)
    return FeatureDataStatistics(
        count=n, mean=mean, variance=var, min=mn, max=mx,
        norm_l1=l1, norm_l2=l2, num_nonzeros=nnz,
        intercept_index=intercept_index,
    )
