"""Native mmap feature index store: builder + ctypes reader + pure fallback.

Parity target: reference PalDB off-heap partitioned index
(photon-api index/PalDBIndexMap.scala:43-240, loader
PalDBIndexMapLoader.scala:25-100, builder PalDBIndexMapBuilder): feature
name→index and index→name in N hash-partitioned store files, memory-mapped
per reader so huge feature spaces never enter the Python heap.

Store format: see photon_tpu/native/index_store.cpp. The builder writes the
binary files from Python (numpy); reads go through the C++ library when it
can be built (ctypes), else a pure-Python mmap reader of the same files.
"""

from __future__ import annotations

import ctypes
import json
import mmap
import os
import struct
import subprocess
from typing import Iterable, List, Optional, Tuple

import numpy as np

_MAGIC = 0x50494458
_ENTRY = struct.Struct("<QIII")  # hash, value, key_off, key_len
_REV = struct.Struct("<II")

_FNV_OFFSET = 1469598103934665603
_FNV_PRIME = 1099511628211
_MASK = (1 << 64) - 1


def _fnv1a64(data: bytes) -> int:
    h = _FNV_OFFSET
    for b in data:
        h = ((h ^ b) * _FNV_PRIME) & _MASK
    return h


def _lib_path() -> str:
    return os.path.join(os.path.dirname(__file__), "..", "native", "libindex_store.so")


def build_native_lib(force: bool = False) -> Optional[str]:
    """Compile the C++ store reader (g++ -O2 -shared). Returns the .so path
    or None when no toolchain is available."""
    so = os.path.abspath(_lib_path())
    src = os.path.join(os.path.dirname(so), "index_store.cpp")
    if os.path.exists(so) and not force:
        return so
    try:
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", so, src],
            check=True, capture_output=True,
        )
        return so
    except (subprocess.CalledProcessError, FileNotFoundError):
        return None


class NativeIndexMapBuilder:
    """Writes the partitioned store files (PalDBIndexMapBuilder role)."""

    def __init__(self, store_dir: str, num_partitions: int = 4):
        self.store_dir = store_dir
        self.num_partitions = num_partitions

    def build(self, index_map) -> None:
        os.makedirs(self.store_dir, exist_ok=True)
        parts: List[List[Tuple[int, int, bytes]]] = [
            [] for _ in range(self.num_partitions)
        ]
        total = 0
        for key, value in index_map.items():
            kb = key.encode("utf-8")
            h = _fnv1a64(kb)
            parts[h % self.num_partitions].append((h, value, kb))
            total = max(total, value + 1)

        rev = np.zeros((total, 2), np.uint32)
        for pi, entries in enumerate(parts):
            entries.sort(key=lambda e: e[0])
            blob = bytearray()
            packed = bytearray()
            for slot, (h, value, kb) in enumerate(entries):
                packed += _ENTRY.pack(h, value, len(blob), len(kb))
                rev[value] = (pi, slot)
                blob += kb
            with open(os.path.join(self.store_dir, f"part-{pi}.bin"), "wb") as f:
                f.write(struct.pack("<II", _MAGIC, len(entries)))
                f.write(bytes(packed))
                f.write(bytes(blob))
        with open(os.path.join(self.store_dir, "reverse.bin"), "wb") as f:
            f.write(struct.pack("<II", _MAGIC, total))
            f.write(rev.astype("<u4").tobytes())
        with open(os.path.join(self.store_dir, "meta.json"), "w") as f:
            json.dump({"numPartitions": self.num_partitions, "size": total}, f)


class _PurePart:
    def __init__(self, path: str):
        self._f = open(path, "rb")
        self.mm = mmap.mmap(self._f.fileno(), 0, access=mmap.ACCESS_READ)
        magic, self.n = struct.unpack_from("<II", self.mm, 0)
        assert magic == _MAGIC, f"bad store file {path}"
        self.entries_off = 8
        self.keys_off = 8 + self.n * _ENTRY.size
        # hashes as numpy view for vectorized binary search
        raw = np.frombuffer(self.mm, dtype=np.uint8,
                            count=self.n * _ENTRY.size, offset=8)
        self.table = raw.view(np.dtype([("hash", "<u8"), ("value", "<u4"),
                                        ("off", "<u4"), ("len", "<u4")]))

    def entry(self, slot: int):
        return self.table[slot]

    def key_bytes(self, off: int, length: int) -> bytes:
        start = self.keys_off + off
        return self.mm[start : start + length]

    def close(self):
        # Drop numpy views into the mmap before closing it.
        self.table = None
        self.mm.close()
        self._f.close()


class NativeIndexMap:
    """Reader over a partitioned store (PalDBIndexMap role). Uses the C++
    library when available; same files either way."""

    def __init__(self, store_dir: str, use_native: bool = True):
        with open(os.path.join(store_dir, "meta.json")) as f:
            meta = json.load(f)
        self.store_dir = store_dir
        self.num_partitions = meta["numPartitions"]
        self._size = meta["size"]
        self._lib = None
        self._handle = None
        if use_native:
            so = build_native_lib()
            if so is not None:
                lib = ctypes.CDLL(so)
                lib.pidx_open.restype = ctypes.c_void_p
                lib.pidx_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
                lib.pidx_get_index.restype = ctypes.c_int64
                lib.pidx_get_index.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
                lib.pidx_get_name.restype = ctypes.c_int64
                lib.pidx_get_name.argtypes = [
                    ctypes.c_void_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_char_p)
                ]
                lib.pidx_get_indices.restype = None
                lib.pidx_get_indices.argtypes = [
                    ctypes.c_void_p, ctypes.c_char_p,
                    np.ctypeslib.ndpointer(np.int64), ctypes.c_int64,
                    np.ctypeslib.ndpointer(np.int64),
                ]
                lib.pidx_close.argtypes = [ctypes.c_void_p]
                handle = lib.pidx_open(store_dir.encode(), self.num_partitions)
                if handle:
                    self._lib, self._handle = lib, handle
        if self._lib is None:
            self._parts = [
                _PurePart(os.path.join(store_dir, f"part-{i}.bin"))
                for i in range(self.num_partitions)
            ]
            with open(os.path.join(store_dir, "reverse.bin"), "rb") as f:
                raw = f.read()
            magic, total = struct.unpack_from("<II", raw, 0)
            assert magic == _MAGIC
            self._rev = np.frombuffer(raw, dtype="<u4", offset=8).reshape(total, 2)

    @property
    def is_native(self) -> bool:
        return self._lib is not None

    def __len__(self) -> int:
        return self._size

    def get_index(self, key: str) -> int:
        kb = key.encode("utf-8")
        if self._lib is not None:
            return int(self._lib.pidx_get_index(self._handle, kb, len(kb)))
        h = _fnv1a64(kb)
        part = self._parts[h % self.num_partitions]
        lo = int(np.searchsorted(part.table["hash"], np.uint64(h), side="left"))
        for i in range(lo, part.n):
            e = part.entry(i)
            if int(e["hash"]) != h:
                break
            if part.key_bytes(int(e["off"]), int(e["len"])) == kb:
                return int(e["value"])
        return -1

    def get_indices(self, keys: List[str]) -> np.ndarray:
        """Batched lookup (the ingest hot path)."""
        if self._lib is not None:
            blobs = [k.encode("utf-8") for k in keys]
            offsets = np.zeros(len(blobs) + 1, np.int64)
            np.cumsum([len(b) for b in blobs], out=offsets[1:])
            blob = b"".join(blobs)
            out = np.empty(len(blobs), np.int64)
            self._lib.pidx_get_indices(self._handle, blob, offsets, len(blobs), out)
            return out
        return np.array([self.get_index(k) for k in keys], np.int64)

    def get_feature_name(self, index: int) -> Optional[str]:
        if self._lib is not None:
            ptr = ctypes.c_char_p()
            n = self._lib.pidx_get_name(self._handle, index, ctypes.byref(ptr))
            if n < 0:
                return None
            return ctypes.string_at(ptr, n).decode("utf-8")
        if index < 0 or index >= self._rev.shape[0]:
            return None
        pi, slot = (int(x) for x in self._rev[index])
        part = self._parts[pi]
        e = part.entry(slot)
        return part.key_bytes(int(e["off"]), int(e["len"])).decode("utf-8")

    def close(self):
        if self._lib is not None:
            self._lib.pidx_close(self._handle)
            self._lib = None
        elif hasattr(self, "_parts"):
            for p in self._parts:
                p.close()
