"""Per-feature box-constraint maps.

Parity target: reference ``GLMSuite.createConstraintFeatureMap`` (photon-client
io/deprecated/GLMSuite.scala:49-126, 190-260): the constraint string is a JSON
array of ``{"name", "term", "lowerBound", "upperBound"}`` maps resolved
against the feature index map into per-index bounds. Reference rules kept:

1. ``name`` and ``term`` are required in every entry.
2. ``lowerBound`` / ``upperBound`` default to ∓∞; at least one must be
   finite, and lower < upper.
3. A wildcard name requires a wildcard term ("*"/"*" = all features except
   the intercept) and must be the only constraint.
4. A wildcard term applies to every feature whose key starts with
   ``name + DELIM``; overlapping constraints are an error.

TPU-first shape: instead of a sparse index→(lo, hi) map consumed by a
per-iteration projection loop, the result is a dense per-coordinate
``(lower, upper)`` vector pair fed straight into the box-constrained solvers
(L-BFGS-B / projected L-BFGS / TRON projection) as arrays.
"""

from __future__ import annotations

import json
import math
from typing import List, Optional, Tuple

import numpy as np

from photon_tpu.data.index_map import IndexMap

WILDCARD = "*"

_NAME, _TERM = "name", "term"
_LOWER, _UPPER = "lowerBound", "upperBound"


def parse_constraint_entries(constraint_string: str) -> List[dict]:
    parsed = json.loads(constraint_string)
    if not isinstance(parsed, list):
        raise ValueError(
            f"constraint string must be a JSON array of maps, got: "
            f"{type(parsed).__name__}"
        )
    return parsed


def constraint_bound_vectors(
    constraint_string: Optional[str],
    index_map: IndexMap,
    dim: int,
    intercept_index: Optional[int] = None,
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Resolve a constraint JSON string to dense (lower, upper) vectors of
    length ``dim`` (unconstrained coordinates get ∓∞), or None if empty."""
    if not constraint_string:
        return None
    entries = parse_constraint_entries(constraint_string)
    lower = np.full((dim,), -np.inf, np.float32)
    upper = np.full((dim,), np.inf, np.float32)
    constrained: set = set()

    def add(idx: int, lo: float, hi: float, what: str) -> None:
        if idx in constrained:
            raise ValueError(
                f"conflicting constraints: feature {what} (index {idx}) is "
                f"constrained more than once"
            )
        constrained.add(idx)
        lower[idx], upper[idx] = lo, hi

    for entry in entries:
        if _NAME not in entry or _TERM not in entry:
            raise ValueError(
                f"every constraint map must carry '{_NAME}' and '{_TERM}' "
                f"keys; malformed entry: {entry}"
            )
        name, term = str(entry[_NAME]), str(entry[_TERM])
        lo = float(entry.get(_LOWER, -math.inf))
        hi = float(entry.get(_UPPER, math.inf))
        if not (lo > -math.inf or hi < math.inf):
            raise ValueError(
                f"both bounds infinite for feature name [{name}] term "
                f"[{term}] — an empty constraint"
            )
        if lo >= hi:
            raise ValueError(
                f"lower bound [{lo}] must be below upper bound [{hi}] for "
                f"feature name [{name}] term [{term}]"
            )

        if name == WILDCARD:
            if term != WILDCARD:
                raise ValueError(
                    "a wildcard name requires a wildcard term (reference "
                    "GLMSuite constraint semantics)"
                )
            if constrained:
                raise ValueError(
                    "an all-feature wildcard constraint cannot be combined "
                    "with other constraints"
                )
            for key, idx in index_map.items():
                if key == IndexMap.INTERCEPT or idx == intercept_index:
                    continue
                add(idx, lo, hi, key)
        elif term == WILDCARD:
            prefix = name + IndexMap.DELIM
            hits = [
                (key, idx)
                for key, idx in index_map.items()
                if key.startswith(prefix) or key == name
            ]
            if not hits:
                continue  # constraints for absent features are ignored
            for key, idx in hits:
                add(idx, lo, hi, key)
        else:
            idx = index_map.get_index(IndexMap.key(name, term))
            if idx < 0:
                continue  # absent feature: nothing to constrain
            add(idx, lo, hi, f"{name}/{term}")

    if not constrained:
        return None
    return lower, upper
