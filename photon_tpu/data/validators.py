"""Row-level training-data sanity checks.

Parity target: reference ``DataValidators`` (photon-client
data/DataValidators.scala): per-row checks — finite features, label in the
task's domain, non-negative weights, finite offsets — with validation modes
VALIDATE_FULL / VALIDATE_SAMPLE / VALIDATE_DISABLED, raising on the first
violated predicate.

TPU-first design: the checks are whole-array reductions on the
struct-of-arrays batch (one vectorized pass instead of a per-row Spark
filter); VALIDATE_SAMPLE checks a deterministic stride subsample.
"""

from __future__ import annotations

import enum
from typing import List, Optional

import numpy as np

from photon_tpu.data.batch import LabeledBatch, SparseFeatures
from photon_tpu.data.game_data import GameBatch
from photon_tpu.types import TaskType


class DataValidationType(enum.Enum):
    """How much of the data to validate (reference DataValidators modes)."""

    VALIDATE_FULL = "VALIDATE_FULL"
    VALIDATE_SAMPLE = "VALIDATE_SAMPLE"
    VALIDATE_DISABLED = "VALIDATE_DISABLED"


class DataValidationError(ValueError):
    """Raised when training data fails a sanity check."""


_SAMPLE_TARGET = 10_000


def _subsample(a: np.ndarray, mode: DataValidationType) -> np.ndarray:
    if mode != DataValidationType.VALIDATE_SAMPLE or a.shape[0] <= _SAMPLE_TARGET:
        return a
    stride = max(1, a.shape[0] // _SAMPLE_TARGET)
    return a[::stride]

def _check_finite(name: str, a: np.ndarray, errors: List[str]) -> None:
    if not np.all(np.isfinite(a)):
        errors.append(f"{name} contains non-finite values")


def _check_labels(task: TaskType, y: np.ndarray, errors: List[str]) -> None:
    """Label-domain predicate per task (DataValidators label checks)."""
    if task == TaskType.LOGISTIC_REGRESSION or task == TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM:
        # Binary labels: 0/1 (the ±1 mapping happens inside the loss).
        ok = np.all((y == 0.0) | (y == 1.0))
        if not ok:
            errors.append(f"{task.value} requires binary labels in {{0, 1}}")
    elif task == TaskType.POISSON_REGRESSION:
        if not np.all(y >= 0.0):
            errors.append("POISSON_REGRESSION requires non-negative labels")
    else:  # LINEAR_REGRESSION: any finite label
        _check_finite("labels", y, errors)


def validate_labeled_batch(
    batch: LabeledBatch,
    task: TaskType,
    mode: DataValidationType = DataValidationType.VALIDATE_FULL,
) -> None:
    """Sanity-check one single-shard batch; raises DataValidationError.

    Mirrors DataValidators.sanityCheckData for the legacy driver path.
    """
    if mode == DataValidationType.VALIDATE_DISABLED:
        return
    errors: List[str] = []
    y = _subsample(np.asarray(batch.label), mode)
    _check_finite("labels", y, errors)
    if not errors:
        _check_labels(task, y, errors)
    if batch.weight is not None:
        w = _subsample(np.asarray(batch.weight), mode)
        _check_finite("weights", w, errors)
        if not np.all(np.asarray(w) >= 0.0):
            errors.append("weights must be non-negative")
    if batch.offset is not None:
        _check_finite("offsets", _subsample(np.asarray(batch.offset), mode), errors)
    feats = batch.features
    if isinstance(feats, SparseFeatures):
        _check_finite("features", _subsample(np.asarray(feats.values), mode), errors)
    else:
        _check_finite("features", _subsample(np.asarray(feats), mode), errors)
    if errors:
        raise DataValidationError("; ".join(errors))


def validate_game_batch(
    batch: GameBatch,
    task: TaskType,
    mode: DataValidationType = DataValidationType.VALIDATE_FULL,
    feature_shards: Optional[List[str]] = None,
) -> None:
    """Sanity-check a GAME batch across all (or the given) feature shards.

    Mirrors DataValidators.sanityCheckDataFrameForTraining
    (GameTrainingDriver.scala:415-432 call site).
    """
    if mode == DataValidationType.VALIDATE_DISABLED:
        return
    errors: List[str] = []
    y = _subsample(np.asarray(batch.label), mode)
    _check_finite("labels", y, errors)
    if not errors:
        _check_labels(task, y, errors)
    w = _subsample(np.asarray(batch.weight), mode)
    _check_finite("weights", w, errors)
    if not np.all(w >= 0.0):
        errors.append("weights must be non-negative")
    _check_finite("offsets", _subsample(np.asarray(batch.offset), mode), errors)
    for shard in feature_shards or list(batch.features):
        feats = batch.features[shard]
        if isinstance(feats, SparseFeatures):
            vals = np.asarray(feats.values)
        else:
            vals = np.asarray(feats)
        _check_finite(f"features[{shard}]", _subsample(vals, mode), errors)
    if errors:
        raise DataValidationError("; ".join(errors))
