"""Feature normalization, folded algebraically into the objective.

Parity target: ``NormalizationContext`` (reference photon-lib
normalization/NormalizationContext.scala:37-131) and the algebraic fold the
reference derives in ValueAndGradientAggregator.scala:41-148: features are
never materialized in normalized form. With per-feature factors ``f`` and
shifts ``s`` (intercept untouched), the normalized margin is

    x'·w = Σ_j (x_j - s_j) f_j w_j + w_int
         = x·(f∘w) + (w_int - Σ_j w_j f_j s_j)

so training only needs the *effective coefficients* ``ew = f∘w`` and a scalar
*total shift* ``es = -(s·ew)``. In JAX this fold is two fused elementwise ops
in front of the margin matmul — autodiff then yields the correctly-folded
gradient/Hessian for free.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from photon_tpu.types import NormalizationType

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class NormalizationContext:
    """factors/shifts for one feature shard. ``factors[j] == 1`` and
    ``shifts[j] == 0`` at the intercept (and for NONE normalization).

    ``intercept_index`` is static metadata (reference shiftsAndInterceptOpt).
    """

    factors: Optional[Array] = None
    shifts: Optional[Array] = None
    intercept_index: Optional[int] = dataclasses.field(
        default=None, metadata=dict(static=True)
    )

    @property
    def is_identity(self) -> bool:
        return self.factors is None and self.shifts is None

    def effective(self, w: Array) -> Tuple[Array, Array]:
        """(ew, es): effective coefficients and total scalar shift."""
        ew = w if self.factors is None else w * self.factors
        es = jnp.zeros((), w.dtype) if self.shifts is None else -jnp.dot(self.shifts, ew)
        return ew, es

    def transformed_to_model_space(self, w: Array) -> Array:
        """Map coefficients trained against normalized features back to the
        original feature space (NormalizationContext.scala model↔transformed
        conversions)."""
        ew, es = self.effective(w)
        if self.intercept_index is not None and self.shifts is not None:
            ew = ew.at[self.intercept_index].add(es)
        return ew

    def model_to_transformed_space(self, w: Array) -> Array:
        out = w
        if self.intercept_index is not None and self.shifts is not None:
            out = out.at[self.intercept_index].add(jnp.dot(self.shifts, w))
        if self.factors is not None:
            out = out / self.factors
        return out


def build_normalization_context(
    norm_type: NormalizationType,
    mean: Array,
    std: Array,
    max_magnitude: Array,
    intercept_index: Optional[int],
) -> NormalizationContext:
    """Build a context from feature statistics (reference
    NormalizationContextFactory semantics; stats from FeatureDataStatistics).

    - SCALE_WITH_STANDARD_DEVIATION: factor = 1/std
    - SCALE_WITH_MAX_MAGNITUDE:      factor = 1/max|x|
    - STANDARDIZATION:               factor = 1/std, shift = mean (requires intercept)
    """
    def _safe_inv(a: Array) -> Array:
        return jnp.where(a > 0, 1.0 / jnp.where(a > 0, a, 1.0), 1.0)

    if norm_type == NormalizationType.NONE:
        return NormalizationContext(None, None, intercept_index)

    if norm_type == NormalizationType.SCALE_WITH_STANDARD_DEVIATION:
        factors = _safe_inv(std)
    elif norm_type == NormalizationType.SCALE_WITH_MAX_MAGNITUDE:
        factors = _safe_inv(jnp.abs(max_magnitude))
    elif norm_type == NormalizationType.STANDARDIZATION:
        if intercept_index is None:
            raise ValueError("STANDARDIZATION requires an intercept feature")
        factors = _safe_inv(std)
    else:
        raise ValueError(f"unknown normalization type {norm_type}")

    shifts = None
    if norm_type == NormalizationType.STANDARDIZATION:
        shifts = mean
    if intercept_index is not None:
        factors = factors.at[intercept_index].set(1.0)
        if shifts is not None:
            shifts = shifts.at[intercept_index].set(0.0)
    return NormalizationContext(factors, shifts, intercept_index)
