"""TRON: trust-region Newton with truncated conjugate-gradient inner solves.

Parity target: reference photon-lib optimization/TRON.scala (a LIBLINEAR
port; notice TRON.scala:16-51): outer trust-region loop with (η, σ) update
constants (TRON.scala:93-94), inner truncated CG solving the TR subproblem
with Hessian-vector products (truncatedConjugateGradientMethod:272-329);
defaults maxIter=15, tol=1e-5, ≤20 CG iterations (TRON.scala:251-256).

TPU-first design: the Hessian-vector product is a forward-over-reverse JVP of
the (sharded) objective — one fused XLA pass per CG step, no Hessian ever
materialized. The whole outer/inner loop nest is ``lax.while_loop``s inside a
single jitted program, so the ≤20 H·v products per outer iteration that cost
the reference ≤20 treeAggregate rounds (TRON.scala:287-326) cost zero host
round-trips here.

Trust-region constants: acceptance/band thresholds eta0=1e-4, eta1=0.25,
eta2=0.75 and shrink/grow factors sigma1=0.25, sigma3=4 (standard published
values). Unlike LIBLINEAR's exact radius schedule, the middle band
(eta1 <= rho < eta2) keeps the radius unchanged — the textbook TR update —
which avoids the geometric shrink that stalls runs whose rho hovers there.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from photon_tpu.optim.common import (
    OptimizeResult,
    OptimizerConfig,
    REASON_MAX_ITERATIONS,
    REASON_NOT_CONVERGED,
    check_convergence,
    project_to_box,
)

Array = jax.Array
ValueAndGrad = Callable[[Array], Tuple[Array, Array]]
Hvp = Callable[[Array, Array], Array]

ETA0, ETA1, ETA2 = 1e-4, 0.25, 0.75
SIGMA1, SIGMA3 = 0.25, 4.0

TRON_DEFAULT_CONFIG = OptimizerConfig(max_iter=15, tol=1e-5)


def _truncated_cg(
    hvp: Callable[[Array], Array],
    g: Array,
    delta: Array,
    max_cg_iter: int,
    cg_tol: Array,
) -> Tuple[Array, Array, Array]:
    """Solve min_s g·s + ½ sᵀHs  s.t. ‖s‖ ≤ delta by truncated CG
    (Steihaug). Returns (step s, whether boundary was hit, #iterations —
    each iteration costs one H·v product, counted by the caller)."""
    d = g.shape[0]
    s0 = jnp.zeros((d,), g.dtype)
    r0 = -g
    p0 = r0

    def cond(carry):
        s, r, p, it, done = carry
        return (~done) & (it < max_cg_iter) & (jnp.linalg.norm(r) > cg_tol)

    def body(carry):
        s, r, p, it, _done = carry
        Hp = hvp(p)
        pHp = jnp.dot(p, Hp)
        rr = jnp.dot(r, r)
        # Negative curvature: follow p to the boundary.
        alpha = jnp.where(pHp > 0, rr / jnp.maximum(pHp, 1e-30), jnp.inf)
        s_next = s + alpha * p

        def to_boundary(s, p):
            # tau ≥ 0 with ‖s + tau p‖ = delta
            ss, sp, pp = jnp.dot(s, s), jnp.dot(s, p), jnp.dot(p, p)
            disc = jnp.sqrt(jnp.maximum(sp * sp + pp * (delta * delta - ss), 0.0))
            return (disc - sp) / jnp.maximum(pp, 1e-30)

        outside = (jnp.linalg.norm(s_next) >= delta) | (pHp <= 0)
        tau = to_boundary(s, p)
        s_bound = s + tau * p
        s_new = jnp.where(outside, s_bound, s_next)
        r_new = jnp.where(outside, r, r - alpha * Hp)
        beta = jnp.dot(r_new, r_new) / jnp.maximum(rr, 1e-30)
        p_new = r_new + beta * p
        return s_new, r_new, p_new, it + 1, outside

    s, r, _p, it, hit = jax.lax.while_loop(
        cond, body, (s0, r0, p0, jnp.int32(0), jnp.bool_(False))
    )
    return s, hit, it


def minimize_tron(
    value_and_grad: ValueAndGrad,
    hvp: Optional[Hvp],
    w0: Array,
    config: OptimizerConfig = TRON_DEFAULT_CONFIG,
    max_cg_iter: int = 20,
    box: Optional[Tuple[Array, Array]] = None,
    hvp_factory: Optional[Callable[[Array], Callable[[Array], Array]]] = None,
) -> OptimizeResult:
    """Trust-region Newton minimization.

    Args:
      value_and_grad: w -> (f, ∇f).
      hvp: (w, v) -> H(w)·v. May be None when ``hvp_factory`` is given.
      box: optional coefficient box, applied by projection per accepted step
        (reference applies OptimizationUtils projection each iteration).
      hvp_factory: w -> (v -> H(w)·v). Preferred over ``hvp``: built ONCE
        per outer iteration, so w-dependent state (margins, curvature
        multipliers) is shared across all ≤max_cg_iter CG products of that
        iteration instead of recomputed inside each one
        (GLMObjective.linearized_hvp halves the X traffic this way).
    """
    if hvp_factory is None:
        if hvp is None:
            raise ValueError("minimize_tron needs hvp or hvp_factory")
        hvp_factory = lambda w: (lambda v: hvp(w, v))  # noqa: E731
    max_iter, tol = config.max_iter, config.tol
    dtype = w0.dtype

    w0 = project_to_box(w0, box)
    f0, g0 = value_and_grad(w0)
    g0_norm = jnp.linalg.norm(g0)
    delta0 = g0_norm

    hist_len = config.history_len
    state0 = dict(
        w=w0, f=f0, g=g0, delta=delta0,
        it=jnp.int32(0), reason=jnp.int32(REASON_NOT_CONVERGED),
        evals=jnp.int32(1),
        loss_hist=jnp.full((hist_len,), f0, dtype),
        gnorm_hist=jnp.full((hist_len,), g0_norm, dtype),
    )

    def cond(st):
        return (st["reason"] == REASON_NOT_CONVERGED) & (st["it"] < max_iter)

    def body(st):
        w, f, g, delta = st["w"], st["f"], st["g"], st["delta"]
        gnorm = jnp.linalg.norm(g)
        cg_tol = 0.1 * gnorm
        hv = hvp_factory(w)  # one build per outer iteration
        s, _hit, cg_iters = _truncated_cg(hv, g, delta, max_cg_iter, cg_tol)

        w_trial = project_to_box(w + s, box)
        s_eff = w_trial - w
        f_trial, g_trial = value_and_grad(w_trial)

        # Predicted reduction from the quadratic model (on the effective step).
        Hs = hv(s_eff)
        pred = -(jnp.dot(g, s_eff) + 0.5 * jnp.dot(s_eff, Hs))
        actual = f - f_trial
        rho = actual / jnp.maximum(pred, 1e-30)

        snorm = jnp.linalg.norm(s_eff)
        accept = (rho > ETA0) & (pred > 0)

        # Standard trust-region radius update: shrink on poor agreement,
        # keep on moderate agreement, grow on strong agreement.
        delta_new = jnp.where(
            rho < ETA1,
            jnp.maximum(SIGMA1 * jnp.minimum(snorm, delta), 1e-12),
            jnp.where(
                rho < ETA2,
                delta,
                jnp.clip(SIGMA3 * snorm, delta, SIGMA3 * delta),
            ),
        )

        w_new = jnp.where(accept, w_trial, w)
        f_new = jnp.where(accept, f_trial, f)
        g_new = jnp.where(accept, g_trial, g)

        it = st["it"] + 1
        gn = jnp.linalg.norm(g_new)
        reason = jnp.where(
            accept,
            check_convergence(f_new, f, gn, g0_norm, tol, it, max_iter),
            # Rejected step: keep going unless the radius collapsed.
            jnp.where(
                delta_new <= 1e-10,
                jnp.int32(REASON_MAX_ITERATIONS),
                jnp.int32(REASON_NOT_CONVERGED),
            ),
        )
        # Work accounting: 1 value_and_grad at the trial point, plus one H·v
        # per CG iteration and one for the ρ denominator — an H·v (jvp of
        # grad) streams the data the same ~2 passes a value_and_grad does,
        # so both count as one "objective_evals" unit (TRON.scala:287-326:
        # each of these was a treeAggregate round).
        return dict(
            w=w_new, f=f_new, g=g_new, delta=delta_new, it=it, reason=reason,
            evals=st["evals"] + 2 + cg_iters,
            loss_hist=st["loss_hist"].at[jnp.minimum(it, config.history_len - 1)].set(f_new),
            gnorm_hist=st["gnorm_hist"].at[jnp.minimum(it, config.history_len - 1)].set(gn),
        )

    st = jax.lax.while_loop(cond, body, state0)
    idx = jnp.arange(config.history_len)
    loss_hist = jnp.where(idx <= st["it"], st["loss_hist"], st["f"])
    gnorm_hist = jnp.where(idx <= st["it"], st["gnorm_hist"], jnp.linalg.norm(st["g"]))
    reason = jnp.where(
        st["reason"] == REASON_NOT_CONVERGED, REASON_MAX_ITERATIONS, st["reason"]
    )
    return OptimizeResult(
        w=st["w"], value=st["f"], grad_norm=jnp.linalg.norm(st["g"]),
        iterations=st["it"], reason_code=reason,
        loss_history=loss_hist, grad_norm_history=gnorm_hist,
        evals=st["evals"],
    )
