"""Optimizer factory: config → solver closure over a GLMObjective.

Parity target: reference photon-api optimization/OptimizerFactory +
OptimizerConfig case classes; selection semantics from
ObjectiveFunctionHelper/GeneralizedLinearOptimizationProblem: OWL-QN when an
L1 weight is present, otherwise the configured solver.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple

import jax

from photon_tpu.data.batch import LabeledBatch
from photon_tpu.ops.objective import GLMObjective
from photon_tpu.optim.common import OptimizeResult, OptimizerConfig
from photon_tpu.optim.lbfgs import minimize_lbfgs, minimize_lbfgsb
from photon_tpu.optim.margin_lbfgs import minimize_lbfgs_margin
from photon_tpu.optim.owlqn import minimize_owlqn
from photon_tpu.optim.tron import TRON_DEFAULT_CONFIG, minimize_tron
from photon_tpu.types import OptimizerType

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class OptimizerSpec:
    """User-facing optimizer configuration (reference
    CoordinateOptimizationConfiguration optimizer fields)."""

    optimizer: OptimizerType = OptimizerType.LBFGS
    max_iter: Optional[int] = None
    tol: Optional[float] = None
    memory: int = 10
    max_cg_iter: int = 20
    box: Optional[Tuple[Array, Array]] = None
    # OPTIMIZATION_STATE_TRACKER_OPTION (PhotonMLCmdLineParser.scala:136-139)
    track_history: bool = True

    def config(self) -> OptimizerConfig:
        base = TRON_DEFAULT_CONFIG if self.optimizer == OptimizerType.TRON else OptimizerConfig()
        return OptimizerConfig(
            max_iter=self.max_iter if self.max_iter is not None else base.max_iter,
            tol=self.tol if self.tol is not None else base.tol,
            memory=self.memory,
            track_history=self.track_history,
        )


def make_optimizer(
    objective: GLMObjective, spec: OptimizerSpec
) -> Callable[[Array, object], OptimizeResult]:
    """Return solve(w0, batch) -> OptimizeResult for the given objective.

    OWL-QN is auto-selected when the objective carries an L1 weight
    (reference RegularizationContext L1/elastic-net routing via OWLQN.scala).
    """
    config = spec.config()

    def solve(w0: Array, batch) -> OptimizeResult:
        vg = lambda w: objective.value_and_grad(w, batch)
        # OWL-QN whenever an L1 term exists (auto-selected or explicit) —
        # with l1_weight == 0 OWL-QN degenerates below plain L-BFGS (orthant
        # projection still pins sign-crossing coordinates), so a smooth
        # objective always routes to L-BFGS regardless of the spec.
        if objective.l1_weight > 0.0:
            l1_mask = None
            if objective.intercept_index is not None:
                import jax.numpy as jnp

                l1_mask = jnp.ones_like(w0).at[objective.intercept_index].set(0.0)
            return minimize_owlqn(vg, w0, objective.l1_weight, config, l1_mask)
        if spec.optimizer == OptimizerType.TRON:
            # Factory form: margins/curvature built once per outer iteration,
            # shared across that iteration's CG products (2 X passes each).
            return minimize_tron(
                vg, None, w0, config, spec.max_cg_iter, spec.box,
                hvp_factory=lambda w: objective.linearized_hvp(w, batch),
            )
        if spec.optimizer == OptimizerType.LBFGSB:
            assert spec.box is not None, "LBFGSB requires a box"
            return minimize_lbfgsb(vg, w0, spec.box[0], spec.box[1], config)
        # Smooth unconstrained GLM over a LabeledBatch: margin-space L-BFGS
        # (photon_tpu.optim.margin_lbfgs) — ~2 X passes/iteration instead of
        # the black-box 2·(1+trials); measured ~3× per-solve on TPU.
        if spec.box is None and isinstance(batch, LabeledBatch):
            return minimize_lbfgs_margin(objective, batch, w0, config)
        return minimize_lbfgs(vg, w0, config, spec.box)

    return solve
