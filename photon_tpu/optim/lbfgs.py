"""L-BFGS (+ box-constrained variant), fully jittable.

Parity targets: reference photon-lib optimization/LBFGS.scala:38-154 (which
wraps breeze.optimize.LBFGS; defaults maxIter=100, m=10, tol=1e-7) and
LBFGSB.scala:39-90 (box-constrained variant). The reference also applies
per-iteration box projection of coefficients (OptimizationUtils.scala:56).

TPU-first design: the optimizer is one ``lax.while_loop`` whose carried state
holds the circular (m, d) curvature history — the entire optimize call
(including every objective evaluation over the sharded batch) compiles to a
single XLA program. With the batch sharded over the mesh's data axis, every
gradient evaluation's cross-device psum is inserted by XLA; there are no
per-iteration host round-trips (the reference pays one broadcast + one
treeAggregate per iteration, ValueAndGradientAggregator.scala:300-321).

Box constraints use projected line search (trial points are clipped to the
box before evaluation), which subsumes the reference's per-iteration
projection and is the standard projected-quasi-Newton approach on TPU-friendly
static shapes (no active-set bookkeeping).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from photon_tpu.optim.common import (
    OptimizeResult,
    OptimizerConfig,
    REASON_DIVERGED,
    REASON_MAX_ITERATIONS,
    REASON_NOT_CONVERGED,
    check_convergence,
)
from photon_tpu.optim.linesearch import strong_wolfe

Array = jax.Array
ValueAndGrad = Callable[[Array], Tuple[Array, Array]]


def two_loop_direction(
    grad: Array, s_hist: Array, y_hist: Array, rho_hist: Array, num_stored: Array, head: Array
) -> Array:
    """Classic two-loop recursion over a circular history buffer.

    s_hist/y_hist: (m, d); rho_hist: (m,). ``head`` points at the slot holding
    the MOST RECENT pair; iteration runs newest→oldest then oldest→newest with
    masking for unfilled slots (static shapes, no dynamic slicing).
    """
    m = s_hist.shape[0]

    def newest_to_oldest(i, carry):
        q, alphas = carry
        slot = (head - i) % m
        valid = i < num_stored
        alpha = rho_hist[slot] * jnp.dot(s_hist[slot], q)
        alpha = jnp.where(valid, alpha, 0.0)
        q = q - alpha * y_hist[slot]
        return q, alphas.at[slot].set(alpha)

    q, alphas = jax.lax.fori_loop(
        0, m, newest_to_oldest, (grad, jnp.zeros((m,), grad.dtype))
    )

    # Initial Hessian scaling gamma = s·y / y·y from the most recent pair.
    recent = head % m
    sy = jnp.dot(s_hist[recent], y_hist[recent])
    yy = jnp.dot(y_hist[recent], y_hist[recent])
    gamma = jnp.where(
        (num_stored > 0) & (yy > 0), sy / jnp.maximum(yy, 1e-30), 1.0
    )
    r = gamma * q

    def oldest_to_newest(i, r):
        slot = (head - (num_stored - 1 - i)) % m
        valid = i < num_stored
        beta = rho_hist[slot] * jnp.dot(y_hist[slot], r)
        upd = (alphas[slot] - beta) * s_hist[slot]
        return r + jnp.where(valid, 1.0, 0.0) * upd

    r = jax.lax.fori_loop(0, m, oldest_to_newest, r)
    return -r


def minimize_lbfgs(
    value_and_grad: ValueAndGrad,
    w0: Array,
    config: OptimizerConfig = OptimizerConfig(),
    box: Optional[Tuple[Array, Array]] = None,
) -> OptimizeResult:
    """Minimize a smooth function with L-BFGS (optionally box-constrained).

    Args:
      value_and_grad: w -> (f, ∇f). Jittable; typically GLMObjective.value_and_grad
        closed over a (possibly mesh-sharded) batch.
      w0: initial point (projected into the box if one is given).
      box: optional (lower, upper) arrays broadcastable to w's shape.
    """
    m, max_iter, tol = config.memory, config.max_iter, config.tol
    d = w0.shape[0]
    dtype = w0.dtype

    def proj(w):
        if box is None:
            return w
        return jnp.clip(w, box[0], box[1])

    def opt_gnorm(w, g):
        # Convergence measure: plain gradient norm, or the projected-gradient
        # norm ‖w − proj(w − g)‖ under box constraints (0 at a KKT point).
        if box is None:
            return jnp.linalg.norm(g)
        return jnp.linalg.norm(w - proj(w - g))

    w0 = proj(w0)
    f0, g0 = value_and_grad(w0)
    g0_norm = opt_gnorm(w0, g0)

    hist_len = config.history_len
    loss_hist0 = jnp.full((hist_len,), f0, dtype)
    gnorm_hist0 = jnp.full((hist_len,), g0_norm, dtype)

    state0 = dict(
        w=w0,
        f=f0,
        g=g0,
        it=jnp.int32(0),
        reason=jnp.int32(REASON_NOT_CONVERGED),
        s_hist=jnp.zeros((m, d), dtype),
        y_hist=jnp.zeros((m, d), dtype),
        rho_hist=jnp.zeros((m,), dtype),
        num_stored=jnp.int32(0),
        head=jnp.int32(0),
        evals=jnp.int32(1),
        loss_hist=loss_hist0,
        gnorm_hist=gnorm_hist0,
    )

    def cond(st):
        return (st["reason"] == REASON_NOT_CONVERGED) & (st["it"] < max_iter)

    def body(st):
        w, f, g = st["w"], st["f"], st["g"]
        if box is None:
            g_dir = g
        else:
            # Gradient-projection active set: freeze coordinates sitting on a
            # bound with the gradient pushing outward, so the quasi-Newton
            # direction moves only in the free subspace.
            eps = 1e-9
            active = ((w <= box[0] + eps) & (g > 0)) | ((w >= box[1] - eps) & (g < 0))
            g_dir = jnp.where(active, 0.0, g)
        p = two_loop_direction(
            g_dir, st["s_hist"], st["y_hist"], st["rho_hist"], st["num_stored"], st["head"]
        )
        if box is not None:
            p = jnp.where(active, 0.0, p)
        dg0 = jnp.dot(p, g)
        # Safeguard: fall back to (projected) steepest descent on a
        # non-descent direction.
        bad_dir = dg0 >= 0
        p = jnp.where(bad_dir, -g_dir, p)
        dg0 = jnp.where(bad_dir, -jnp.dot(g_dir, g_dir), dg0)

        if box is None:
            fg_alpha = lambda a: value_and_grad(w + a * p)
            ls_fg = lambda a: _with_dir_deriv(fg_alpha(a), p)
        else:
            def ls_fg(a):
                wt = proj(w + a * p)
                ft, gt = value_and_grad(wt)
                # Derivative along the *projected* path direction.
                return ft, jnp.dot(gt, (wt - w) / jnp.maximum(a, 1e-30))

        init_alpha = jnp.where(st["num_stored"] == 0, jnp.minimum(1.0, 1.0 / jnp.maximum(jnp.linalg.norm(g), 1e-12)), 1.0)
        ls = strong_wolfe(
            ls_fg, f, dg0, init_alpha.astype(dtype),
            max_evals=config.max_line_search_evals,
        )

        w_new = proj(w + ls.alpha * p)
        f_new, g_new = value_and_grad(w_new)

        # Divergence rollback: a non-finite trial state (NaN loss from corrupt
        # data, overflowing step) never replaces the last finite iterate —
        # keep (w, f, g) and terminate with REASON_DIVERGED. The rollback also
        # zeroes (s, y) below, so no poisoned curvature pair is stored.
        finite = (
            jnp.isfinite(f_new)
            & jnp.all(jnp.isfinite(w_new))
            & jnp.all(jnp.isfinite(g_new))
        )
        w_new = jnp.where(finite, w_new, w)
        f_new = jnp.where(finite, f_new, f)
        g_new = jnp.where(finite, g_new, g)

        s = w_new - w
        y = g_new - g
        sy = jnp.dot(s, y)
        # Curvature condition: only store pairs with s·y > eps (keeps H ≻ 0).
        store = sy > 1e-12
        slot = (st["head"] + 1) % m
        s_hist = jnp.where(store, st["s_hist"].at[slot].set(s), st["s_hist"])
        y_hist = jnp.where(store, st["y_hist"].at[slot].set(y), st["y_hist"])
        rho_hist = jnp.where(
            store, st["rho_hist"].at[slot].set(1.0 / jnp.maximum(sy, 1e-30)), st["rho_hist"]
        )
        head = jnp.where(store, slot, st["head"])
        num_stored = jnp.where(store, jnp.minimum(st["num_stored"] + 1, m), st["num_stored"])

        it = st["it"] + 1
        gn = opt_gnorm(w_new, g_new)
        reason = check_convergence(f_new, f, gn, g0_norm, tol, it, max_iter)
        reason = jnp.where(finite, reason, REASON_DIVERGED)
        # A step that made no progress at all terminates the loop
        # (OBJECTIVE_NOT_IMPROVING analogue handled by fn-values check since
        # |Δf|=0 ⇒ FUNCTION_VALUES_CONVERGED).
        return dict(
            w=w_new,
            f=f_new,
            g=g_new,
            it=it,
            reason=reason,
            s_hist=s_hist,
            y_hist=y_hist,
            rho_hist=rho_hist,
            num_stored=num_stored,
            head=head,
            evals=st["evals"] + ls.evals + 1,
            loss_hist=st["loss_hist"].at[jnp.minimum(it, config.history_len - 1)].set(f_new),
            gnorm_hist=st["gnorm_hist"].at[jnp.minimum(it, config.history_len - 1)].set(gn),
        )

    st = jax.lax.while_loop(cond, body, state0)
    # Pad histories past the last iteration with the final values (projected
    # norm under box constraints, consistent with in-loop entries).
    final_gnorm = opt_gnorm(st["w"], st["g"])
    idx = jnp.arange(config.history_len)
    loss_hist = jnp.where(idx <= st["it"], st["loss_hist"], st["f"])
    gnorm_hist = jnp.where(idx <= st["it"], st["gnorm_hist"], final_gnorm)
    reason = jnp.where(
        st["reason"] == REASON_NOT_CONVERGED, REASON_MAX_ITERATIONS, st["reason"]
    )
    return OptimizeResult(
        w=st["w"],
        value=st["f"],
        grad_norm=final_gnorm,
        iterations=st["it"],
        reason_code=reason,
        loss_history=loss_hist,
        grad_norm_history=gnorm_hist,
        evals=st["evals"],
    )


def _with_dir_deriv(fg: Tuple[Array, Array], p: Array) -> Tuple[Array, Array]:
    f, g = fg
    return f, jnp.dot(g, p)


def minimize_lbfgsb(
    value_and_grad: ValueAndGrad,
    w0: Array,
    lower: Array,
    upper: Array,
    config: OptimizerConfig = OptimizerConfig(),
) -> OptimizeResult:
    """Box-constrained L-BFGS (reference LBFGSB.scala:39-90 capability,
    implemented as projected-line-search L-BFGS rather than the full Byrd
    subspace algorithm — same constraint semantics, TPU-static shapes)."""
    return minimize_lbfgs(value_and_grad, w0, config, box=(lower, upper))
