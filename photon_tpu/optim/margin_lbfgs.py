"""Margin-space L-BFGS: GLM-structured solver with O(n) line-search trials.

The reference (Breeze LBFGS via photon-lib optimization/LBFGS.scala:38-79)
treats the objective as a black box: every line-search trial re-evaluates
value+gradient with a full pass over the data — the dominant cost
(ValueAndGradientAggregator broadcast+treeAggregate per trial, SURVEY.md
§3.1 hot loop).

A GLM objective is not a black box: the margin is affine in the step along a
fixed direction,

    z(w + α·p) = z0 + α·u,        u = X·p   (one pass, independent of α)

so an entire strong-Wolfe line search costs ONE feature-matrix pass (u),
with every trial an O(n) elementwise evaluation on (z0, u):

    φ(α)  = Σᵢ wtᵢ·loss(z0ᵢ + α·uᵢ, yᵢ) + L2(α)      (L2 analytic in α)
    φ'(α) = Σᵢ uᵢ·wtᵢ·loss'(z0ᵢ + α·uᵢ, yᵢ) + L2'(α)

and the accepted point updates the carried margins incrementally
(z0 += α·u — float32 drift over ≤100 iterations is ~1e-5 relative, well
under optimizer tolerances). One L-BFGS iteration therefore costs exactly
TWO X passes (u = X·p and the new gradient Xᵀ·dz) instead of the black-box
2·(1 + #trials). Normalization stays folded: with factors f and shifts s,
u = X·(f∘p) − (s·(f∘p)) is still affine in α (photon_tpu.data.normalization
algebra), and the gradient chain-rules back through f.

Smooth objectives only (no box constraints / L1 — projections break the
affinity; those route through optim.lbfgs / optim.owlqn).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from photon_tpu.data.batch import LabeledBatch, SparseFeatures
from photon_tpu.ops.objective import GLMObjective
from photon_tpu.optim.common import (
    OptimizeResult,
    OptimizerConfig,
    REASON_MAX_ITERATIONS,
    REASON_NOT_CONVERGED,
    check_convergence,
)
from photon_tpu.optim.lbfgs import two_loop_direction
from photon_tpu.optim.linesearch import strong_wolfe

Array = jax.Array


def minimize_lbfgs_margin(
    objective: GLMObjective,
    batch: LabeledBatch,
    w0: Array,
    config: OptimizerConfig = OptimizerConfig(),
    l2_override: Optional[Array] = None,
) -> OptimizeResult:
    """L-BFGS over a GLMObjective exploiting margin affinity.

    Semantically equivalent to ``minimize_lbfgs(objective.value_and_grad...)``
    on smooth GLMs, at ~2 X-passes per iteration. ``result.evals`` counts
    X passes (the full-data cost unit); O(n) margin-only line-search trials
    are not counted.

    ``l2_override`` replaces the objective's static L2 weight with a TRACED
    scalar — the hook that lets ``sweep_l2_lbfgs_margin`` vmap one program
    over a whole λ grid.
    """
    if objective.l1_weight > 0.0:
        raise ValueError("margin L-BFGS is for smooth objectives; use OWL-QN for L1")

    loss = objective.loss
    l2 = objective.l2_weight if l2_override is None else l2_override
    has_l2 = l2_override is not None or objective.l2_weight != 0.0
    norm = objective.normalization
    factors = None if norm is None or norm.is_identity else norm.factors
    shifts = None if norm is None or norm.is_identity else norm.shifts
    label, weight, offset = batch.label, batch.weight, batch.offset
    feats = batch.features
    # Fused Pallas gradient pass: one X read yields value + gradient + FRESH
    # margins (ops/pallas_glm), replacing the separate Xᵀ·dz pass and the
    # incremental z += α·u update. Same 2-X-passes/iter, but the carried
    # margins are exact every iteration — which is what makes bfloat16 X
    # safe (no accumulated drift), halving the bandwidth-bound HBM traffic.
    # Caller contract (ops/objective._can_fuse): dense unsharded features,
    # no shift normalization, d within the VMEM tile budget.
    use_fused = objective.use_pallas and objective._can_fuse(batch)

    def matvec(p: Array) -> Array:
        """u = d(margins)/dα along direction p (normalization folded)."""
        ep = p if factors is None else p * factors
        if isinstance(feats, SparseFeatures):
            u = feats.matvec(ep)
        elif feats.dtype == jnp.bfloat16:
            # bf16 X stream with f32 accumulation on the MXU; the bf16
            # rounding of the direction only perturbs the line-search
            # parametrization (the accepted w stays f32, and the fused
            # gradient pass refreshes margins exactly from it).
            u = jnp.dot(feats, ep.astype(jnp.bfloat16),
                        preferred_element_type=jnp.float32)
        else:
            u = feats @ ep
        if shifts is not None:
            u = u - jnp.dot(shifts, ep)
        return u

    def fused_value_grad_margins(w: Array):
        """One X pass: value, gradient, and fresh margins at w."""
        from photon_tpu.ops.pallas_glm import fused_data_value_and_grad

        ew = w if factors is None else w * factors
        val, g, z = fused_data_value_and_grad(
            loss, ew, feats, label, offset, weight, return_margins=True
        )
        if factors is not None:
            g = g * factors
        if has_l2:
            g = g + l2 * _l2_mask(w)
        return val + l2_value(w), g, z

    def grad_from_margins(z: Array, w: Array) -> Array:
        dz = weight * loss.dz(z, label)
        g = feats.rmatvec(dz) if isinstance(feats, SparseFeatures) else feats.T @ dz
        if shifts is not None:
            g = g - jnp.sum(dz) * shifts
        if factors is not None:
            g = g * factors
        if has_l2:
            g = g + l2 * _l2_mask(w)
        return g

    def _l2_mask(w: Array) -> Array:
        if objective.intercept_index is None:
            return w
        return w.at[objective.intercept_index].set(0.0)

    def data_value(z: Array) -> Array:
        return jnp.sum(weight * loss.value(z, label))

    def l2_value(w: Array) -> Array:
        if not has_l2:
            return jnp.zeros((), w0.dtype)
        wm = _l2_mask(w)
        return 0.5 * l2 * jnp.dot(wm, wm)

    m, max_iter, tol = config.memory, config.max_iter, config.tol
    d = w0.shape[0]
    dtype = w0.dtype

    if use_fused:
        f0, g0, z0 = fused_value_grad_margins(w0)
        init_evals = 1  # one fused pass
    else:
        z0 = objective.margins(w0, batch)
        f0 = data_value(z0) + l2_value(w0)
        g0 = grad_from_margins(z0, w0)
        init_evals = 2  # margins + gradient passes
    g0_norm = jnp.linalg.norm(g0)

    hist_len = config.history_len
    state0 = dict(
        w=w0,
        z=z0,
        f=f0,
        g=g0,
        it=jnp.int32(0),
        reason=jnp.int32(REASON_NOT_CONVERGED),
        s_hist=jnp.zeros((m, d), dtype),
        y_hist=jnp.zeros((m, d), dtype),
        rho_hist=jnp.zeros((m,), dtype),
        num_stored=jnp.int32(0),
        head=jnp.int32(0),
        evals=jnp.int32(init_evals),
        loss_hist=jnp.full((hist_len,), f0, dtype),
        gnorm_hist=jnp.full((hist_len,), g0_norm, dtype),
    )

    def cond(st):
        return (st["reason"] == REASON_NOT_CONVERGED) & (st["it"] < max_iter)

    def body(st):
        w, z, f, g = st["w"], st["z"], st["f"], st["g"]
        p = two_loop_direction(
            g, st["s_hist"], st["y_hist"], st["rho_hist"], st["num_stored"], st["head"]
        )
        dg0 = jnp.dot(p, g)
        bad_dir = dg0 >= 0
        p = jnp.where(bad_dir, -g, p)
        dg0 = jnp.where(bad_dir, -jnp.dot(g, g), dg0)

        u = matvec(p)  # the ONE X pass for this whole line search
        # L2 along the path: quadratic with analytic coefficients.
        if has_l2:
            wm, pm = _l2_mask(w), _l2_mask(p)
            l2_a = l2 * jnp.dot(wm, pm)
            l2_b = l2 * jnp.dot(pm, pm)
        else:
            l2_a = l2_b = jnp.zeros((), dtype)
        f_l2 = l2_value(w)

        def ls_fg(a):
            za = z + a * u
            dza = weight * loss.dz(za, label)
            val = data_value(za) + f_l2 + a * l2_a + 0.5 * a * a * l2_b
            deriv = jnp.dot(u, dza) + l2_a + a * l2_b
            return val, deriv

        init_alpha = jnp.where(
            st["num_stored"] == 0,
            jnp.minimum(1.0, 1.0 / jnp.maximum(jnp.linalg.norm(g), 1e-12)),
            1.0,
        ).astype(dtype)
        ls = strong_wolfe(
            ls_fg, f, dg0, init_alpha, max_evals=config.max_line_search_evals
        )

        w_new = w + ls.alpha * p
        if use_fused:
            # Second X pass: fused value+grad+margins at w_new — carried
            # margins refreshed exactly, no incremental drift.
            f_new, g_new, z_new = fused_value_grad_margins(w_new)
        else:
            z_new = z + ls.alpha * u  # incremental margin update — no X pass
            f_new = data_value(z_new) + l2_value(w_new)
            g_new = grad_from_margins(z_new, w_new)  # second X pass

        s = w_new - w
        y = g_new - g
        sy = jnp.dot(s, y)
        store = sy > 1e-12
        slot = (st["head"] + 1) % m
        s_hist = jnp.where(store, st["s_hist"].at[slot].set(s), st["s_hist"])
        y_hist = jnp.where(store, st["y_hist"].at[slot].set(y), st["y_hist"])
        rho_hist = jnp.where(
            store,
            st["rho_hist"].at[slot].set(1.0 / jnp.maximum(sy, 1e-30)),
            st["rho_hist"],
        )
        head = jnp.where(store, slot, st["head"])
        num_stored = jnp.where(store, jnp.minimum(st["num_stored"] + 1, m), st["num_stored"])

        it = st["it"] + 1
        gn = jnp.linalg.norm(g_new)
        reason = check_convergence(f_new, f, gn, jnp.linalg.norm(g0), tol, it, max_iter)
        return dict(
            w=w_new,
            z=z_new,
            f=f_new,
            g=g_new,
            it=it,
            reason=reason,
            s_hist=s_hist,
            y_hist=y_hist,
            rho_hist=rho_hist,
            num_stored=num_stored,
            head=head,
            evals=st["evals"] + 2,
            loss_hist=st["loss_hist"].at[jnp.minimum(it, hist_len - 1)].set(f_new),
            gnorm_hist=st["gnorm_hist"].at[jnp.minimum(it, hist_len - 1)].set(gn),
        )

    st = jax.lax.while_loop(cond, body, state0)
    final_gnorm = jnp.linalg.norm(st["g"])
    idx = jnp.arange(hist_len)
    loss_hist = jnp.where(idx <= st["it"], st["loss_hist"], st["f"])
    gnorm_hist = jnp.where(idx <= st["it"], st["gnorm_hist"], final_gnorm)
    reason = jnp.where(
        st["reason"] == REASON_NOT_CONVERGED, REASON_MAX_ITERATIONS, st["reason"]
    )
    return OptimizeResult(
        w=st["w"],
        value=st["f"],
        grad_norm=final_gnorm,
        iterations=st["it"],
        reason_code=reason,
        loss_history=loss_hist,
        grad_norm_history=gnorm_hist,
        evals=st["evals"],
        eval_unit="x_passes",
    )


def sweep_l2_lbfgs_margin(
    objective: GLMObjective,
    batch: LabeledBatch,
    w0s: Array,  # (k, d) initial points, one per λ
    l2_weights: Array,  # (k,)
    config: OptimizerConfig = OptimizerConfig(),
) -> OptimizeResult:
    """Solve the SAME data against k regularization weights as ONE vmapped
    program — the TPU replacement for the reference's sequential warm-started
    λ sweep (ModelTraining.scala:162-200) and the parallel-candidate hook for
    Bayesian tuning (SURVEY.md §2.7.5: hyperparameter parallelism, absent in
    the reference).

    Every lane streams the shared X through its own margin-space L-BFGS via
    ``l2_override`` (a traced per-lane scalar), so the k solves cost one
    X-bandwidth budget per iteration instead of k. Returns a batched
    OptimizeResult whose leaves carry a leading (k,) axis.
    """
    import dataclasses

    # The fused Pallas kernel doesn't batch under vmap the way the XLA path
    # does (a batched pallas_call adds a grid axis instead of widening the
    # matmul); the XLA path turns the k lane matvecs into ONE X·P matmul,
    # which is exactly the bandwidth sharing this sweep exists for.
    objective = dataclasses.replace(objective, use_pallas=False)

    def solve(w0, l2):
        return minimize_lbfgs_margin(objective, batch, w0, config, l2_override=l2)

    return jax.vmap(solve)(w0s, l2_weights)
