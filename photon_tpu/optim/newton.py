"""Damped Newton (Levenberg) solver for small-dimension GLMs.

Role parity: the per-entity random-effect solves — the reference runs one
Breeze L-BFGS per entity inside ``mapValues``
(photon-api algorithm/RandomEffectCoordinate.scala:228-283), and offers TRON
(truncated Newton, photon-lib optimization/TRON.scala:148-246) as the
second-order option.

TPU-first design: for the random-effect shape (d ≲ 64, thousands of
entities solved as ONE vmapped program) the right second-order method is
exact Newton with a batched Cholesky — H = XᵀDX + λI is a tiny (d, d)
matrix whose assembly is an MXU einsum and whose factorization is cheap,
while L-BFGS's nested line-search loops dominate wall time on deep
``lax.while_loop`` nests (each vmapped while iteration costs fixed overhead
regardless of lane width). Newton converges in 3-5 iterations where L-BFGS
needs 10+, and each iteration is exactly TWO passes over X (one gradient
+ Hessian assembly, one trial-point margin refresh) with no inner loops.

Damping follows the Levenberg accept/reject pattern (the scalar analogue of
TRON's trust-region radius update, TRON.scala:93-94): a rejected step keeps
the iterate and multiplies the damping by 10; an accepted step shrinks it.
A failed Cholesky (NaNs) lands in the reject branch by construction.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from photon_tpu.data.batch import LabeledBatch, SparseFeatures
from photon_tpu.ops.objective import GLMObjective
from photon_tpu.optim.common import (
    OptimizeResult,
    OptimizerConfig,
    REASON_DIVERGED,
    REASON_MAX_ITERATIONS,
    REASON_NOT_CONVERGED,
    check_convergence,
)

Array = jax.Array

_MU_INIT = 0.0  # start with pure Newton; L2'd GLM Hessians are PD
_MU_BOOST = 10.0
_MU_SHRINK = 0.25
_MU_MIN_ON_REJECT = 1e-4  # first reject jumps 0 → 1e-3 (×10 applied after)


def minimize_newton(
    objective: GLMObjective,
    batch: LabeledBatch,
    w0: Array,
    config: OptimizerConfig = OptimizerConfig(),
    l2_override: Optional[Array] = None,
    kernel: str = "xla",
) -> OptimizeResult:
    """Levenberg-damped exact Newton over a dense-feature GLM batch.

    ``result.evals`` counts X passes (2 per iteration), the same cost unit
    as ``minimize_lbfgs_margin``. Dense features only (the per-entity blocks
    are dense by construction); scale-type normalization is folded, shift
    normalization is not supported (the random-effect path never uses it).

    ``kernel`` selects the Newton-system assembly lowering
    (ops/pallas_newton.RE_KERNELS): ``"xla"`` reads X twice per iteration
    (einsum Hessian + transpose matvec); ``"pallas"`` fuses both reductions
    into one Pallas read of X with per-entity results bit-equal to the XLA
    formulations (so the whole solve — same while_loop, damping, and trial
    sweep — stays bit-exact); ``"pallas_bf16x"`` additionally reads a
    bfloat16 copy of X inside the fused kernel (f32 accumulation,
    pinned-tolerance parity). Margins always use the f32 X — the trial
    sweep's affine-margin update is precision-critical.
    """
    if isinstance(batch.features, SparseFeatures):
        raise ValueError("minimize_newton requires dense features")
    if objective.l1_weight > 0.0:
        raise ValueError("Newton solves smooth objectives; use OWL-QN for L1")
    if kernel not in ("xla", "pallas", "pallas_bf16x"):
        raise ValueError(
            "minimize_newton kernel must be resolved to 'xla', 'pallas', or "
            f"'pallas_bf16x' (got {kernel!r}; resolve 'auto' via "
            "ops.pallas_newton.resolve_re_kernel first)"
        )
    norm = objective.normalization
    if norm is not None and not norm.is_identity and norm.shifts is not None:
        raise ValueError("minimize_newton supports scale normalization only")

    loss = objective.loss
    l2 = objective.l2_weight if l2_override is None else l2_override
    has_l2 = l2_override is not None or objective.l2_weight != 0.0
    label, weight, offset = batch.label, batch.weight, batch.offset
    X = batch.features
    if norm is not None and not norm.is_identity and norm.factors is not None:
        X = X * norm.factors[None, :]  # margins/H/grad all use X·diag(f)

    d = w0.shape[0]
    dtype = w0.dtype
    m_iter, tol = config.max_iter, config.tol

    use_fused = kernel in ("pallas", "pallas_bf16x")
    if use_fused:
        from photon_tpu.ops.pallas_newton import fused_newton_system

        # The kernel's HBM read; margins below keep the f32 slab.
        X_sys = X.astype(jnp.bfloat16) if kernel == "pallas_bf16x" else X

    def _l2_mask(w: Array) -> Array:
        if objective.intercept_index is None:
            return w
        return w.at[objective.intercept_index].set(0.0)

    def l2_value(w: Array) -> Array:
        if not has_l2:
            return jnp.zeros((), dtype)
        wm = _l2_mask(w)
        return 0.5 * l2 * jnp.dot(wm, wm)

    def data_value(z: Array) -> Array:
        return jnp.sum(weight * loss.value(z, label))

    lam_diag = jnp.zeros((d,), dtype)
    if has_l2:
        lam_diag = jnp.full((d,), l2, dtype)
        if objective.intercept_index is not None:
            lam_diag = lam_diag.at[objective.intercept_index].set(0.0)

    z0 = X @ w0 + offset
    f0 = data_value(z0) + l2_value(w0)

    hist_len = config.history_len
    state0 = dict(
        w=w0,
        z=z0,
        f=f0,
        mu=jnp.asarray(_MU_INIT, dtype),
        gnorm=jnp.asarray(jnp.inf, dtype),
        it=jnp.int32(0),
        reason=jnp.int32(REASON_NOT_CONVERGED),
        evals=jnp.int32(1),  # initial margin pass
        g0_norm=jnp.asarray(0.0, dtype),
        loss_hist=jnp.full((hist_len,), f0, dtype),
        gnorm_hist=jnp.full((hist_len,), jnp.inf, dtype),
    )

    def cond(st):
        return (st["reason"] == REASON_NOT_CONVERGED) & (st["it"] < m_iter)

    def body(st):
        w, z, f = st["w"], st["z"], st["f"]
        # --- pass 1: gradient + Hessian from the carried margins ---
        dz = weight * loss.dz(z, label)
        d2 = weight * loss.dzz(z, label)
        if use_fused:
            # One fused X read for both reductions; vmapped callers batch
            # this into one grid instance per entity (ops/pallas_newton).
            H_data, g_data = fused_newton_system(X_sys, d2, dz)
            g = g_data + (l2 * _l2_mask(w) if has_l2 else 0.0)
            H = H_data + jnp.diag(lam_diag)
        else:
            g = X.T @ dz + (l2 * _l2_mask(w) if has_l2 else 0.0)
            H = jnp.einsum("nd,n,ne->de", X, d2, X) + jnp.diag(lam_diag)
        gnorm = jnp.linalg.norm(g)
        g0_norm = jnp.where(st["it"] == 0, gnorm, st["g0_norm"])

        # Levenberg system: (H + μ·diag(H)) p = -g. Scaling the damping by
        # diag(H) keeps μ unit-free across entities of very different sizes.
        # The diagonal is floored at a tiny fraction of its largest entry so
        # a feature column with no active samples (H_jj = 0, arises when
        # l2 = 0) still becomes positive-definite under damping instead of
        # failing Cholesky forever — the dead direction then gets step
        # p_j = −g_j/(μ·floor) = 0 since g_j = 0 too.
        diag_h = jnp.diagonal(H)
        floor = 1e-7 * jnp.maximum(jnp.max(diag_h), 1.0)
        Hd = H + st["mu"] * jnp.diag(jnp.maximum(diag_h, floor))
        chol, _ = jax.scipy.linalg.cho_factor(Hd, lower=True)
        p = -jax.scipy.linalg.cho_solve((chol, True), g)

        # --- pass 2: trial margins, then FREE backtracking on margins ---
        # Margins are affine in the step: z(w + t·p) = z + t·u with
        # u = z_try − z already in hand, so step-halving trials are O(n)
        # elementwise evaluations with no further X pass (the same margin
        # affinity minimize_lbfgs_margin's line search exploits). This is
        # what globalizes pure Newton on exp-like losses (Poisson) without
        # burning a full iteration per rejected step.
        w_try = w + p
        z_try = X @ w_try + offset
        u = z_try - z
        ts = jnp.asarray([1.0, 0.5, 0.25, 0.125, 1 / 16, 1 / 32, 1 / 64], dtype)

        def f_at(t):
            return data_value(z + t * u) + l2_value(w + t * p)

        fs = jax.vmap(f_at)(ts)
        fs = jnp.where(jnp.isnan(fs), jnp.inf, fs)  # failed Cholesky → reject
        ib = jnp.argmin(fs)
        f_best, t_best = fs[ib], ts[ib]
        # <= so ties at f32 resolution near the optimum still step (the
        # gradient keeps contracting).
        accept = f_best <= f

        w_new = jnp.where(accept, w + t_best * p, w)
        z_new = jnp.where(accept, z + t_best * u, z)
        f_new = jnp.where(accept, f_best, f)
        mu_new = jnp.where(
            accept & (t_best == 1.0),
            st["mu"] * _MU_SHRINK,
            jnp.where(
                accept,
                st["mu"],  # partial step: keep current damping
                jnp.maximum(st["mu"], _MU_MIN_ON_REJECT) * _MU_BOOST,
            ),
        )

        it = st["it"] + 1
        # Convergence: gradient test on the CURRENT iterate's exact gradient
        # (no lag); value test on the best-trial-vs-current change — at the
        # optimum even a rejected Newton step has |f_best − f| ≈ 0, which is
        # precisely "can't improve" (a genuinely bad rejected step has a
        # large |f_best − f| and keeps iterating with boosted damping).
        reason = check_convergence(f_best, f, gnorm, g0_norm, tol, it, m_iter)
        # Divergence guard: a non-finite carried objective can never be
        # improved (every trial compares False against NaN, so the reject
        # branch keeps the iterate forever). Flag DIVERGED and stop; the
        # iterate itself is still the last finite point (w0 when f0 was
        # already non-finite — e.g. corrupted offsets).
        reason = jnp.where(
            jnp.isfinite(f_new), reason, jnp.int32(REASON_DIVERGED)
        )
        return dict(
            w=w_new,
            z=z_new,
            f=f_new,
            mu=mu_new,
            gnorm=gnorm,
            it=it,
            reason=reason,
            evals=st["evals"] + 2,
            g0_norm=g0_norm,
            loss_hist=st["loss_hist"].at[jnp.minimum(it, hist_len - 1)].set(f_new),
            gnorm_hist=st["gnorm_hist"].at[jnp.minimum(it, hist_len - 1)].set(gnorm),
        )

    st = jax.lax.while_loop(cond, body, state0)
    idx = jnp.arange(hist_len)
    loss_hist = jnp.where(idx <= st["it"], st["loss_hist"], st["f"])
    gnorm_hist = jnp.where(idx <= st["it"], st["gnorm_hist"], st["gnorm"])
    # Entry 0 = |g| at the initial point (computed inside the first body
    # iteration; inf only if the loop never ran).
    gnorm_hist = gnorm_hist.at[0].set(
        jnp.where(st["it"] > 0, st["g0_norm"], st["gnorm"])
    )
    reason = jnp.where(
        st["reason"] == REASON_NOT_CONVERGED, REASON_MAX_ITERATIONS, st["reason"]
    )
    return OptimizeResult(
        w=st["w"],
        value=st["f"],
        grad_norm=st["gnorm"],
        iterations=st["it"],
        reason_code=reason,
        loss_history=loss_hist,
        grad_norm_history=gnorm_hist,
        evals=st["evals"],
        eval_unit="x_passes",
    )
