from photon_tpu.optim.common import OptimizeResult, OptimizerConfig  # noqa: F401
from photon_tpu.optim.lbfgs import minimize_lbfgs  # noqa: F401
from photon_tpu.optim.owlqn import minimize_owlqn  # noqa: F401
from photon_tpu.optim.tron import minimize_tron  # noqa: F401
from photon_tpu.optim.factory import make_optimizer  # noqa: F401
