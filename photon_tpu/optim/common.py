"""Shared optimizer plumbing: result/state containers and convergence logic.

Parity targets: ``Optimizer.optimize`` template loop + convergence reasons
(reference photon-lib optimization/Optimizer.scala:126-187) and
``OptimizationStatesTracker`` (OptimizationStatesTracker.scala:31-113).

TPU-first design: the optimize loop is a single ``lax.while_loop`` inside one
jitted program — per-iteration state (loss, gradient norm) is recorded into
fixed-size history arrays (the tracker), so observability survives jit without
host round-trips. Convergence reasons are int codes resolved to the
``ConvergenceReason`` enum on the host.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from photon_tpu.types import ConvergenceReason

Array = jax.Array

# Reason codes used inside jit (host maps them back to the enum).
REASON_NOT_CONVERGED = 0
REASON_MAX_ITERATIONS = 1
REASON_FUNCTION_VALUES_CONVERGED = 2
REASON_GRADIENT_CONVERGED = 3
REASON_OBJECTIVE_NOT_IMPROVING = 4
# The solve produced a non-finite iterate and was rolled back to the last
# finite point (in-trace divergence guard). Not a convergence state: callers
# treating DIVERGED results should keep the previous/warm-start coefficients.
REASON_DIVERGED = 5

_REASONS = {
    REASON_NOT_CONVERGED: ConvergenceReason.NOT_CONVERGED,
    REASON_MAX_ITERATIONS: ConvergenceReason.MAX_ITERATIONS,
    REASON_FUNCTION_VALUES_CONVERGED: ConvergenceReason.FUNCTION_VALUES_CONVERGED,
    REASON_GRADIENT_CONVERGED: ConvergenceReason.GRADIENT_CONVERGED,
    REASON_OBJECTIVE_NOT_IMPROVING: ConvergenceReason.OBJECTIVE_NOT_IMPROVING,
    REASON_DIVERGED: ConvergenceReason.DIVERGED,
}


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    """Static solver configuration. Defaults mirror the reference:
    L-BFGS maxIter=100, m=10, tol=1e-7 (LBFGS.scala:148-154);
    TRON overrides maxIter=15, tol=1e-5 (TRON.scala:251-256)."""

    max_iter: int = dataclasses.field(default=100, metadata=dict(static=True))
    tol: float = dataclasses.field(default=1e-7, metadata=dict(static=True))
    memory: int = dataclasses.field(default=10, metadata=dict(static=True))
    # Line-search evaluation budget per iteration.
    max_line_search_evals: int = dataclasses.field(default=20, metadata=dict(static=True))
    # Record per-iteration (loss, |grad|) histories. Disable for vmapped
    # per-entity solves where (E, max_iter) tracker arrays would dominate HBM
    # (the reference's RandomEffectOptimizationTracker keeps only aggregate
    # stats for the same reason).
    track_history: bool = dataclasses.field(default=True, metadata=dict(static=True))

    @property
    def history_len(self) -> int:
        return self.max_iter + 1 if self.track_history else 1


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class OptimizeResult:
    """Solution + tracker (OptimizationStatesTracker role).

    ``loss_history[i]`` / ``grad_norm_history[i]`` hold the state after i
    iterations; entries past ``iterations`` are padded with the final values.
    """

    w: Array
    value: Array
    grad_norm: Array
    iterations: Array
    reason_code: Array
    loss_history: Array
    grad_norm_history: Array
    # Work counter for throughput accounting. Its unit is ``eval_unit``:
    # black-box solvers (LBFGS/OWL-QN/LBFGS-B/TRON) count objective
    # evaluations including line-search trials ("objective_evals", each = 2
    # feature-matrix passes); margin-space L-BFGS and Newton count
    # feature-matrix passes directly ("x_passes"). Consumers aggregating
    # across solvers must check the unit (bench.py normalizes to passes).
    evals: Array = dataclasses.field(default_factory=lambda: jnp.zeros((), jnp.int32))
    eval_unit: str = dataclasses.field(
        default="objective_evals", metadata=dict(static=True)
    )

    @property
    def x_passes(self) -> Array:
        """``evals`` normalized to feature-matrix passes (the bench unit)."""
        return self.evals * (2 if self.eval_unit == "objective_evals" else 1)

    @property
    def converged(self) -> bool:
        return int(self.reason_code) in (
            REASON_FUNCTION_VALUES_CONVERGED,
            REASON_GRADIENT_CONVERGED,
        )

    @property
    def convergence_reason(self) -> ConvergenceReason:
        return _REASONS[int(self.reason_code)]

    def diagnostics_dict(self) -> dict:
        """Report-ready host scalars. This is a device→host read, so call it
        only at run-report finalize — never inside the dispatch loop."""
        return dict(
            type="fixed_effect",
            iterations=int(self.iterations),
            value=float(self.value),
            grad_norm=float(self.grad_norm),
            reason=self.convergence_reason.value,
            converged=bool(self.converged),
            evals=int(self.evals),
            eval_unit=self.eval_unit,
        )

    def summary(self) -> str:
        """Human-readable per-iteration table (tracker toSummaryString)."""
        n = int(self.iterations)
        if self.loss_history.shape[0] < n + 1:
            # track_history=False run: only aggregates are available.
            return (
                f"iterations={n} value={float(self.value):.6e} "
                f"|grad|={float(self.grad_norm):.6e} "
                f"reason: {self.convergence_reason.value} (history not tracked)"
            )
        lines = ["iter    loss           |grad|"]
        for i in range(n + 1):
            lines.append(
                f"{i:4d}    {float(self.loss_history[i]):.6e}   "
                f"{float(self.grad_norm_history[i]):.6e}"
            )
        lines.append(f"reason: {self.convergence_reason.value}")
        return "\n".join(lines)


def check_convergence(
    value: Array,
    prev_value: Array,
    grad_norm: Array,
    init_grad_norm: Array,
    tol: float,
    iteration: Array,
    max_iter: int,
) -> Array:
    """Reason code for the current state (Optimizer.scala:126-139 semantics):
    gradient converged relative to the initial gradient norm; function values
    converged on relative improvement; max iterations."""
    rel_impr = jnp.abs(value - prev_value) / jnp.maximum(jnp.abs(prev_value), 1e-12)
    code = jnp.where(
        grad_norm <= tol * jnp.maximum(init_grad_norm, 1e-12),
        REASON_GRADIENT_CONVERGED,
        jnp.where(
            rel_impr <= tol,
            REASON_FUNCTION_VALUES_CONVERGED,
            jnp.where(iteration >= max_iter, REASON_MAX_ITERATIONS, REASON_NOT_CONVERGED),
        ),
    )
    return code.astype(jnp.int32)


def project_to_box(
    w: Array, box: Optional[Tuple[Array, Array]]
) -> Array:
    """Coefficient box projection (reference
    OptimizationUtils.projectCoefficientsToSubspace, OptimizationUtils.scala:56)."""
    if box is None:
        return w
    lower, upper = box
    return jnp.clip(w, lower, upper)
