"""Strong-Wolfe line search, fully jittable.

Role parity: the reference delegates line search to Breeze's
``StrongWolfeLineSearch`` inside breeze.optimize.LBFGS (used via
photon-lib optimization/LBFGS.scala:38-79). Here it is a single
``lax.while_loop`` state machine (bracket phase + zoom phase, one objective
evaluation per loop step — evaluations are full passes over the sharded batch,
so evaluation count is the cost model). Interpolation is safeguarded
quadratic; termination and fallbacks follow Nocedal & Wright alg. 3.5/3.6.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

_BRACKET = 0
_ZOOM = 1
_DONE = 2


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class LineSearchResult:
    alpha: Array
    value: Array
    deriv: Array  # directional derivative at alpha
    evals: Array
    success: Array  # strong Wolfe conditions met


def _interp(a_lo, f_lo, g_lo, a_hi, f_hi):
    """Safeguarded quadratic interpolation for the zoom trial point."""
    d = a_hi - a_lo
    denom = f_hi - f_lo - g_lo * d
    a_q = a_lo - 0.5 * g_lo * d * d / jnp.where(jnp.abs(denom) > 1e-20, denom, 1.0)
    # Keep the trial strictly inside [lo, hi] with a 10% margin; fall back to
    # bisection when interpolation misbehaves.
    lo = jnp.minimum(a_lo, a_hi)
    hi = jnp.maximum(a_lo, a_hi)
    margin = 0.1 * (hi - lo)
    bad = (
        jnp.isnan(a_q)
        | (jnp.abs(denom) <= 1e-20)
        | (a_q < lo + margin)
        | (a_q > hi - margin)
    )
    return jnp.where(bad, 0.5 * (a_lo + a_hi), a_q)


def strong_wolfe(
    fg: Callable[[Array], Tuple[Array, Array]],
    f0: Array,
    dg0: Array,
    init_alpha: Array,
    c1: float = 1e-4,
    c2: float = 0.9,
    max_evals: int = 20,
    max_alpha: float = 1e10,
) -> LineSearchResult:
    """Find alpha satisfying f(a) <= f0 + c1*a*dg0 and |f'(a)| <= c2*|dg0|.

    Args:
      fg: alpha -> (f(x + alpha*p), p·∇f(x + alpha*p)). Must be jittable.
      f0, dg0: value and directional derivative at alpha=0 (dg0 < 0 required).
      init_alpha: first trial step.

    On budget exhaustion returns the best sufficient-decrease point seen
    (practical fallback; keeps L-BFGS making progress on ill-scaled problems).
    """
    dtype = jnp.asarray(f0).dtype
    zero = jnp.zeros((), dtype)

    # state: (phase, a_prev, f_prev, g_prev, a_lo, f_lo, g_lo, a_hi, f_hi,
    #         a_cur, evals, a_best, f_best, g_best, success)
    state0 = (
        jnp.int32(_BRACKET),
        zero, f0, dg0,  # prev
        zero, f0, dg0,  # lo
        zero, f0,       # hi (f only; g_hi unused by quad interp)
        jnp.asarray(init_alpha, dtype),
        jnp.int32(0),
        zero, f0, dg0,  # best sufficient-decrease point
        jnp.bool_(False),
    )

    suff = lambda a, f: f <= f0 + c1 * a * dg0
    curv = lambda g: jnp.abs(g) <= -c2 * dg0

    def cond(state):
        phase, evals = state[0], state[10]
        return (phase != _DONE) & (evals < max_evals)

    def body(state):
        (phase, a_prev, f_prev, g_prev, a_lo, f_lo, g_lo, a_hi, f_hi,
         a_cur, evals, a_best, f_best, g_best, success) = state

        f, g = fg(a_cur)
        evals = evals + 1

        ok = suff(a_cur, f)
        better = ok & (f < f_best)
        a_best = jnp.where(better, a_cur, a_best)
        f_best = jnp.where(better, f, f_best)
        g_best = jnp.where(better, g, g_best)

        def bracket_step():
            fail = (~ok) | ((evals > 1) & (f >= f_prev))
            wolfe = ok & curv(g)
            rising = ok & (g >= 0)
            # zoom(lo=prev, hi=cur) on failure; zoom(lo=cur, hi=prev) on rise.
            z_lo_a = jnp.where(fail, a_prev, a_cur)
            z_lo_f = jnp.where(fail, f_prev, f)
            z_lo_g = jnp.where(fail, g_prev, g)
            z_hi_a = jnp.where(fail, a_cur, a_prev)
            z_hi_f = jnp.where(fail, f, f_prev)
            to_zoom = fail | rising
            nphase = jnp.where(wolfe, _DONE, jnp.where(to_zoom, _ZOOM, _BRACKET)).astype(jnp.int32)
            trial = jnp.where(
                to_zoom,
                _interp(z_lo_a, z_lo_f, z_lo_g, z_hi_a, z_hi_f),
                jnp.minimum(2.0 * a_cur, max_alpha),
            )
            return (
                nphase,
                a_cur, f, g,          # prev ← cur
                z_lo_a, z_lo_f, z_lo_g,
                z_hi_a, z_hi_f,
                trial,
                evals,
                jnp.where(wolfe, a_cur, a_best),
                jnp.where(wolfe, f, f_best),
                jnp.where(wolfe, g, g_best),
                success | wolfe,
            )

        def zoom_step():
            fail = (~ok) | (f >= f_lo)
            wolfe = (~fail) & curv(g)
            # On fail: hi ← cur. Else lo ← cur (and hi ← old lo if the slope
            # says the minimum is on the other side).
            flip = (~fail) & (g * (a_hi - a_lo) >= 0)
            n_hi_a = jnp.where(fail, a_cur, jnp.where(flip, a_lo, a_hi))
            n_hi_f = jnp.where(fail, f, jnp.where(flip, f_lo, f_hi))
            n_lo_a = jnp.where(fail, a_lo, a_cur)
            n_lo_f = jnp.where(fail, f_lo, f)
            n_lo_g = jnp.where(fail, g_lo, g)
            interval_dead = jnp.abs(n_hi_a - n_lo_a) <= 1e-12 * jnp.maximum(1.0, n_hi_a)
            nphase = jnp.where(wolfe | interval_dead, _DONE, _ZOOM).astype(jnp.int32)
            trial = _interp(n_lo_a, n_lo_f, n_lo_g, n_hi_a, n_hi_f)
            return (
                nphase,
                a_cur, f, g,
                n_lo_a, n_lo_f, n_lo_g,
                n_hi_a, n_hi_f,
                trial,
                evals,
                jnp.where(wolfe, a_cur, a_best),
                jnp.where(wolfe, f, f_best),
                jnp.where(wolfe, g, g_best),
                success | wolfe,
            )

        return jax.lax.cond(phase == _BRACKET, bracket_step, zoom_step)

    final = jax.lax.while_loop(cond, body, state0)
    (_, _, _, _, a_lo, f_lo, g_lo, _, _, _, evals, a_best, f_best, g_best, success) = final

    # Fallback: best Wolfe point if found, else best sufficient-decrease point,
    # else the zoom lo endpoint (never worse than f0 by construction).
    have_best = f_best < f0
    alpha = jnp.where(success | have_best, a_best, a_lo)
    value = jnp.where(success | have_best, f_best, f_lo)
    deriv = jnp.where(success | have_best, g_best, g_lo)
    return LineSearchResult(alpha=alpha, value=value, deriv=deriv, evals=evals, success=success)
