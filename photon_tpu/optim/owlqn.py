"""OWL-QN: Orthant-Wise Limited-memory Quasi-Newton for L1/elastic-net.

Parity target: reference photon-lib optimization/OWLQN.scala:39-70 (which
wraps breeze.optimize.OWLQN; supports mutable l1RegularizationWeight for
regularization sweeps — here ``GLMObjective.with_l1``).

Algorithm (Andrew & Gao 2007, public): minimize f(w) + λ‖w‖₁ by
  1. pseudo-gradient: subgradient choosing the orthant of steepest descent,
  2. L-BFGS two-loop direction from the smooth-curvature history,
  3. sign-align the direction with the negative pseudo-gradient,
  4. backtracking (Armijo on the regularized objective) with orthant
     projection: trial points are clipped to the orthant of the search point.

Fully jittable: one ``lax.while_loop`` per optimize call, inner backtracking
as a nested while_loop. The intercept is excluded from the L1 term via the
``l1_mask`` argument (reference interceptOpt convention).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from photon_tpu.optim.common import (
    OptimizeResult,
    OptimizerConfig,
    REASON_MAX_ITERATIONS,
    REASON_NOT_CONVERGED,
    check_convergence,
)
from photon_tpu.optim.lbfgs import two_loop_direction

Array = jax.Array
ValueAndGrad = Callable[[Array], Tuple[Array, Array]]


def _pseudo_gradient(w: Array, g: Array, l1: Array) -> Array:
    """Steepest-descent subgradient of f + λ‖·‖₁ (λ per-coordinate)."""
    right = g + l1  # derivative approaching from the right (w→0⁺)
    left = g - l1  # from the left
    pg_zero = jnp.where(left > 0, left, jnp.where(right < 0, right, 0.0))
    return jnp.where(w > 0, g + l1, jnp.where(w < 0, g - l1, pg_zero))


def minimize_owlqn(
    value_and_grad: ValueAndGrad,
    w0: Array,
    l1_weight: float,
    config: OptimizerConfig = OptimizerConfig(),
    l1_mask: Optional[Array] = None,
) -> OptimizeResult:
    """Minimize f(w) + λ·‖mask∘w‖₁ where f is smooth (loss + L2 for
    elastic net, reference RegularizationContext L1/L2 split).

    Args:
      value_and_grad: smooth part only.
      l1_mask: optional 0/1 vector; 0 entries (e.g. intercept) are unpenalized.
    """
    m, max_iter, tol = config.memory, config.max_iter, config.tol
    d = w0.shape[0]
    dtype = w0.dtype
    l1 = jnp.full((d,), l1_weight, dtype)
    if l1_mask is not None:
        l1 = l1 * l1_mask

    def full_value(w):
        f, g = value_and_grad(w)
        return f + jnp.sum(l1 * jnp.abs(w)), f, g

    F0, f0, g0 = full_value(w0)
    pg0 = _pseudo_gradient(w0, g0, l1)
    pg0_norm = jnp.linalg.norm(pg0)

    hist_len = config.history_len
    state0 = dict(
        w=w0, F=F0, g=g0, it=jnp.int32(0),
        reason=jnp.int32(REASON_NOT_CONVERGED),
        s_hist=jnp.zeros((m, d), dtype),
        y_hist=jnp.zeros((m, d), dtype),
        rho_hist=jnp.zeros((m,), dtype),
        num_stored=jnp.int32(0),
        head=jnp.int32(0),
        evals=jnp.int32(1),
        loss_hist=jnp.full((hist_len,), F0, dtype),
        gnorm_hist=jnp.full((hist_len,), pg0_norm, dtype),
    )

    def cond(st):
        return (st["reason"] == REASON_NOT_CONVERGED) & (st["it"] < max_iter)

    def body(st):
        w, F, g = st["w"], st["F"], st["g"]
        pg = _pseudo_gradient(w, g, l1)
        p = two_loop_direction(
            pg, st["s_hist"], st["y_hist"], st["rho_hist"], st["num_stored"], st["head"]
        )
        # Sign alignment: zero out components that disagree with -pg.
        p = jnp.where(p * -pg > 0, p, 0.0)
        fallback = jnp.dot(p, pg) >= 0
        p = jnp.where(fallback, -pg, p)

        # Orthant: sign(w), or sign(-pg) where w == 0.
        xi = jnp.where(w != 0, jnp.sign(w), jnp.sign(-pg))

        dirderiv = jnp.dot(pg, p)
        init_step = jnp.where(
            st["num_stored"] == 0,
            1.0 / jnp.maximum(jnp.linalg.norm(p), 1e-12),
            1.0,
        ).astype(dtype)

        # Backtracking Armijo on the regularized objective with orthant projection.
        def bt_cond(bs):
            alpha, Ft, _wt, _gt, evals = bs
            armijo = Ft <= F + 1e-4 * alpha * dirderiv
            return (~armijo) & (evals < config.max_line_search_evals)

        def bt_body(bs):
            alpha, _Ft, _wt, _gt, evals = bs
            alpha = alpha * 0.5
            wt = _orthant_project(w + alpha * p, xi)
            Ft, _ft, gt = full_value(wt)
            return alpha, Ft, wt, gt, evals + 1

        w1 = _orthant_project(w + init_step * p, xi)
        F1, _f1, g1 = full_value(w1)
        alpha, F_new, w_new, g_new, bt_evals = jax.lax.while_loop(
            bt_cond, bt_body, (init_step, F1, w1, g1, jnp.int32(1))
        )

        s = w_new - w
        y = g_new - g  # curvature pairs from the SMOOTH gradient (per OWL-QN)
        sy = jnp.dot(s, y)
        store = sy > 1e-12
        slot = (st["head"] + 1) % m
        s_hist = jnp.where(store, st["s_hist"].at[slot].set(s), st["s_hist"])
        y_hist = jnp.where(store, st["y_hist"].at[slot].set(y), st["y_hist"])
        rho_hist = jnp.where(
            store, st["rho_hist"].at[slot].set(1.0 / jnp.maximum(sy, 1e-30)), st["rho_hist"]
        )
        head = jnp.where(store, slot, st["head"])
        num_stored = jnp.where(store, jnp.minimum(st["num_stored"] + 1, m), st["num_stored"])

        it = st["it"] + 1
        pg_new = _pseudo_gradient(w_new, g_new, l1)
        pgn = jnp.linalg.norm(pg_new)
        reason = check_convergence(F_new, F, pgn, pg0_norm, tol, it, max_iter)
        return dict(
            w=w_new, F=F_new, g=g_new, it=it, reason=reason,
            s_hist=s_hist, y_hist=y_hist, rho_hist=rho_hist,
            num_stored=num_stored, head=head,
            evals=st["evals"] + bt_evals,
            loss_hist=st["loss_hist"].at[jnp.minimum(it, config.history_len - 1)].set(F_new),
            gnorm_hist=st["gnorm_hist"].at[jnp.minimum(it, config.history_len - 1)].set(pgn),
        )

    st = jax.lax.while_loop(cond, body, state0)
    idx = jnp.arange(config.history_len)
    pg_final = _pseudo_gradient(st["w"], st["g"], l1)
    loss_hist = jnp.where(idx <= st["it"], st["loss_hist"], st["F"])
    gnorm_hist = jnp.where(idx <= st["it"], st["gnorm_hist"], jnp.linalg.norm(pg_final))
    reason = jnp.where(
        st["reason"] == REASON_NOT_CONVERGED, REASON_MAX_ITERATIONS, st["reason"]
    )
    return OptimizeResult(
        w=st["w"], value=st["F"], grad_norm=jnp.linalg.norm(pg_final),
        iterations=st["it"], reason_code=reason,
        loss_history=loss_hist, grad_norm_history=gnorm_hist,
        evals=st["evals"],
    )


def _orthant_project(w: Array, xi: Array) -> Array:
    """Clip w to the orthant defined by xi (zero where signs disagree)."""
    return jnp.where(w * xi > 0, w, 0.0)
