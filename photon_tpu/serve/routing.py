"""Consistent-hash request routing for the scorer fleet.

Photon ML's premise is that no single machine holds the model: random
effects shard by entity across the cluster (PAPER.md §2.9). The serving
analogue is this module — a consistent-hash ring over ENTITY IDS that maps
every ``/v1/score`` request to the scorer replica owning that entity's
shard. Cache hit rate becomes a *routing* property instead of a *budget*
property: each replica's hot set is the disjoint slice of entities the ring
assigns it, so the fleet-wide hot set is the union of N disjoint
per-replica working sets (Snap ML's hierarchical node-local/cluster split,
PAPERS.md, is the shape).

Determinism is the load-bearing property. The ring hash is
``blake2b`` — stable across processes, platforms, and Python hash
randomization — so the HTTP front end, every scorer replica, and an
offline test all derive the SAME owner for a key from the same
``(members, vnodes, seed)`` snapshot. tests/test_fleet.py asserts this
across a subprocess boundary, plus the classic consistent-hashing bound:
adding/removing one member moves ≤ 1/N + ε of keys.

Snapshots are plain JSON dicts (members + vnodes + seed + version) and
travel over the existing framed IPC as the ``ring`` op — a replica whose
membership view changes rebuilds the ring locally and re-derives its
:class:`~photon_tpu.serve.store.StorePartition` predicate from it.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

HASH_BITS = 64
HASH_SPACE = 1 << HASH_BITS


def stable_hash(key: str, seed: int = 0) -> int:
    """Process-stable 64-bit hash of a string key. ``blake2b`` keyed by the
    ring seed — NOT Python's ``hash`` (randomized per process) and NOT
    ``crc32`` (too little dispersion for vnode placement)."""
    h = hashlib.blake2b(
        str(key).encode("utf-8"),
        digest_size=8,
        key=seed.to_bytes(8, "big", signed=False),
    )
    return int.from_bytes(h.digest(), "big")


class HashRing:
    """Consistent-hash ring: ``vnodes`` virtual points per member, owner of
    a key = member of the first point clockwise from the key's hash.

    Mutations (:meth:`add` / :meth:`remove`) bump ``version`` — the fleet
    broadcasts the snapshot and every holder rebuilds, so two processes
    with the same version always agree on every assignment. Not
    thread-safe; holders mutate under their own lock (the router's) or
    replace the instance wholesale (replicas, via ``from_snapshot``).
    """

    def __init__(
        self,
        members: Sequence[str] = (),
        vnodes: int = 64,
        seed: int = 0,
        version: int = 0,
        weights: Optional[Dict[str, int]] = None,
    ):
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self.vnodes = int(vnodes)
        self.seed = int(seed)
        self.version = int(version)
        # Per-member vnode counts for heterogeneous hosts: a member with
        # weight 2 places 2×vnodes points and owns ~2× the hash space.
        # Members absent from the map get the default count, so old
        # snapshots (no ``weights`` key) rebuild bit-identically.
        self._weights: Dict[str, int] = {
            str(m): int(w) for m, w in (weights or {}).items()
        }
        self._members: List[str] = []
        self._points: List[Tuple[int, str]] = []  # sorted (hash, member)
        self._hashes: List[int] = []
        for m in members:
            self._insert(str(m))

    # -- membership --------------------------------------------------------

    def member_vnodes(self, member: str) -> int:
        """Virtual-point count for ``member``: ``vnodes × weight``."""
        w = self._weights.get(str(member), 1)
        if w < 1:
            raise ValueError(f"member weight must be >= 1, got {w}")
        return self.vnodes * w

    def _insert(self, member: str) -> None:
        if member in self._members:
            raise ValueError(f"ring member {member!r} already present")
        self._members.append(member)
        for v in range(self.member_vnodes(member)):
            h = stable_hash(f"{member}#{v}", self.seed)
            bisect.insort(self._points, (h, member))
        self._hashes = [h for h, _ in self._points]

    def add(self, member: str, weight: Optional[int] = None) -> int:
        """Add a member (optionally weighted); returns the new ring
        version."""
        member = str(member)
        if weight is not None:
            self._weights[member] = int(weight)
        self._insert(member)
        self.version += 1
        return self.version

    def remove(self, member: str) -> int:
        """Remove a member; returns the new ring version."""
        member = str(member)
        if member not in self._members:
            raise ValueError(f"ring member {member!r} not present")
        self._members.remove(member)
        self._points = [(h, m) for h, m in self._points if m != member]
        self._hashes = [h for h, _ in self._points]
        self.version += 1
        return self.version

    @property
    def members(self) -> List[str]:
        return list(self._members)

    def __contains__(self, member: str) -> bool:
        return str(member) in self._members

    def __len__(self) -> int:
        return len(self._members)

    # -- assignment --------------------------------------------------------

    def owner(self, key) -> Optional[str]:
        """The member owning ``key`` (None on an empty ring)."""
        if not self._points:
            return None
        h = stable_hash(str(key), self.seed)
        i = bisect.bisect_right(self._hashes, h)
        if i == len(self._points):
            i = 0  # wrap
        return self._points[i][1]

    def preference(self, key, n: Optional[int] = None) -> List[str]:
        """Failover order for ``key``: the owner, then each DISTINCT member
        met walking clockwise. A dead owner's traffic drains onto ring
        successors (who score the foreign entities FE-only) instead of
        erroring."""
        if not self._points:
            return []
        n = len(self._members) if n is None else min(n, len(self._members))
        h = stable_hash(str(key), self.seed)
        i = bisect.bisect_right(self._hashes, h)
        out: List[str] = []
        for step in range(len(self._points)):
            m = self._points[(i + step) % len(self._points)][1]
            if m not in out:
                out.append(m)
                if len(out) >= n:
                    break
        return out

    # -- introspection ------------------------------------------------------

    def shard_ranges(self, max_arcs_per_member: int = 8) -> Dict[str, dict]:
        """Per-member arc summary for ``/healthz``: owned fraction of the
        hash space, arc count, and the first few ``[lo, hi)`` arcs in hex
        (arcs beyond ``max_arcs_per_member`` are elided — vnode counts make
        the full list noise)."""
        out: Dict[str, dict] = {
            m: dict(fraction=0.0, arcs=0, ranges=[]) for m in self._members
        }
        if not self._points:
            return out
        for j, (hi, member) in enumerate(self._points):
            lo = self._points[j - 1][0] if j > 0 else self._points[-1][0]
            span = (hi - lo) % HASH_SPACE
            if span == 0 and len(self._points) == 1:
                span = HASH_SPACE
            rec = out[member]
            rec["fraction"] += span / HASH_SPACE
            rec["arcs"] += 1
            if len(rec["ranges"]) < max_arcs_per_member:
                rec["ranges"].append([f"{lo:016x}", f"{hi:016x}"])
        for rec in out.values():
            rec["fraction"] = round(rec["fraction"], 6)
        return out

    # -- wire format --------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-able ring state. ``from_snapshot`` on ANY process rebuilds
        an identical assignment — members are sorted so the snapshot is
        canonical regardless of join order."""
        snap = dict(
            members=sorted(self._members),
            vnodes=self.vnodes,
            seed=self.seed,
            version=self.version,
        )
        live_weights = {
            m: w
            for m, w in sorted(self._weights.items())
            if m in self._members and w != 1
        }
        if live_weights:  # omit when uniform: old consumers stay compatible
            snap["weights"] = live_weights
        return snap

    @classmethod
    def from_snapshot(cls, snap: dict) -> "HashRing":
        return cls(
            members=snap.get("members") or (),
            vnodes=int(snap.get("vnodes", 64)),
            seed=int(snap.get("seed", 0)),
            version=int(snap.get("version", 0)),
            weights=snap.get("weights") or None,
        )


def route_key(
    entity_ids: Optional[dict], route_re_type: Optional[str]
) -> Optional[str]:
    """The string key a request routes on: its entity id for the routing
    RE type. Falls back to the lexicographically-first entity id when the
    routing type is absent (so multi-type requests still route
    deterministically), and None for entity-less requests (any replica
    scores those identically — they are FE-only by construction)."""
    if not entity_ids:
        return None
    if route_re_type is not None:
        key = entity_ids.get(route_re_type)
        if key is not None:
            return str(key)
    for rt in sorted(entity_ids):
        if entity_ids[rt] is not None:
            return str(entity_ids[rt])
    return None


def moved_keys(
    before: HashRing, after: HashRing, keys: Sequence[str]
) -> List[str]:
    """Keys whose owner differs between two rings — the ring-stability
    tests' measurement (≤ 1/N + ε of keys move on a single join/leave)."""
    return [k for k in keys if before.owner(k) != after.owner(k)]
