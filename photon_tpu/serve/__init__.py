"""Online GAME serving: micro-batched scoring, hot/cold entity residency,
zero-downtime reload. See serve/engine.py for the composition."""

from photon_tpu.serve.admission import (
    BATCH,
    INTERACTIVE,
    AdmissionConfig,
    AdmissionController,
    QuotaExceededError,
    TokenBucket,
    parse_tenant_rates,
)
from photon_tpu.serve.batcher import (
    BackpressureError,
    DeadlineExceededError,
    MicroBatcher,
    ScoreRequest,
)
from photon_tpu.serve.engine import ServeConfig, ServingEngine, load_engine
from photon_tpu.serve.frontend import (
    ScorerClient,
    ScorerServer,
    ServingFrontend,
)
from photon_tpu.serve.store import HotColdEntityStore

__all__ = [
    "AdmissionConfig",
    "AdmissionController",
    "BackpressureError",
    "BATCH",
    "DeadlineExceededError",
    "HotColdEntityStore",
    "INTERACTIVE",
    "MicroBatcher",
    "QuotaExceededError",
    "ScoreRequest",
    "ScorerClient",
    "ScorerServer",
    "ServeConfig",
    "ServingEngine",
    "ServingFrontend",
    "TokenBucket",
    "load_engine",
    "parse_tenant_rates",
]
