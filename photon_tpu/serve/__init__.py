"""Online GAME serving: micro-batched scoring, hot/cold entity residency,
zero-downtime reload, and the consistent-hash scorer fleet. See
serve/engine.py for the single-process composition and serve/fleet.py for
the multi-replica topology."""

from photon_tpu.serve.admission import (
    BATCH,
    INTERACTIVE,
    AdmissionConfig,
    AdmissionController,
    FleetAdmissionLedger,
    QuotaExceededError,
    TokenBucket,
    parse_tenant_rates,
)
from photon_tpu.serve.batcher import (
    BackpressureError,
    DeadlineExceededError,
    MicroBatcher,
    ScoreRequest,
)
from photon_tpu.serve.engine import ServeConfig, ServingEngine, load_engine
from photon_tpu.serve.fleet import (
    FleetBackend,
    FleetHTTPFrontend,
    FleetRouter,
    ReplicaScorerServer,
    ScorerFleet,
    partition_from_snapshot,
)
from photon_tpu.serve.frontend import (
    ScorerClient,
    ScorerServer,
    ServingFrontend,
)
from photon_tpu.serve.routing import HashRing, route_key, stable_hash
from photon_tpu.serve.store import HotColdEntityStore, StorePartition

__all__ = [
    "AdmissionConfig",
    "AdmissionController",
    "BackpressureError",
    "BATCH",
    "DeadlineExceededError",
    "FleetAdmissionLedger",
    "FleetBackend",
    "FleetHTTPFrontend",
    "FleetRouter",
    "HashRing",
    "HotColdEntityStore",
    "INTERACTIVE",
    "MicroBatcher",
    "QuotaExceededError",
    "ReplicaScorerServer",
    "ScoreRequest",
    "ScorerClient",
    "ScorerFleet",
    "ScorerServer",
    "ServeConfig",
    "ServingEngine",
    "ServingFrontend",
    "StorePartition",
    "TokenBucket",
    "load_engine",
    "parse_tenant_rates",
    "partition_from_snapshot",
    "route_key",
    "stable_hash",
]
