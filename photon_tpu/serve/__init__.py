"""Online GAME serving: micro-batched scoring, hot/cold entity residency,
zero-downtime reload. See serve/engine.py for the composition."""

from photon_tpu.serve.batcher import (
    BackpressureError,
    DeadlineExceededError,
    MicroBatcher,
    ScoreRequest,
)
from photon_tpu.serve.engine import ServeConfig, ServingEngine, load_engine
from photon_tpu.serve.store import HotColdEntityStore

__all__ = [
    "BackpressureError",
    "DeadlineExceededError",
    "HotColdEntityStore",
    "MicroBatcher",
    "ScoreRequest",
    "ServeConfig",
    "ServingEngine",
    "load_engine",
]
