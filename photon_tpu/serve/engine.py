"""Online GAME serving engine: micro-batched scoring with zero-downtime
model reload.

Composition of the two sibling modules plus the batch stack's own scorer:
a :class:`~photon_tpu.serve.batcher.MicroBatcher` admits and batches
requests, a :class:`~photon_tpu.serve.store.HotColdEntityStore` resolves
entity ids to device-resident coefficient rows, and the SAME jitted
``GameTransformer`` program the batch scoring driver runs produces the
scores — which is what makes the CI bit-parity check (serve vs batch
driver, atol=0) meaningful rather than aspirational.

The no-retrace contract, end to end:

1. startup ``warm_up`` scores an inert template batch at EVERY row bucket in
   ``bucket_grid(max_batch_size)`` and compiles every hot-store upload
   scatter, so all program shapes exist before traffic;
2. every live batch pads up the same grid (``pad_game_batch``), so it lands
   on a warmed shape;
3. the per-batch scoring model swaps table VALUES only (identical pytree
   structure via ``with_coefficients``), so promotions and reloads reuse the
   compiled program.

``retraces_since_warmup`` exposes the in-trace counter delta — the
observable the serve CI stage and ``bench.py --serve-ab`` assert to be 0.

Reload is build-then-swap: the incoming model gets its OWN store +
transformer + warm-up while the old state keeps serving; the swap happens
under the engine's scoring lock, so in-flight batches drain on the old
state and the next batch scores on the new one. No request ever observes a
half-loaded model.

Graceful degradation (ISSUE 6): a reload whose build/warm-up fails leaves
the OLD state serving (the failure is reported via :class:`ReloadError` and
``stats()['last_reload_error']``), and each managed RE type carries a
circuit breaker — repeated ``resolve`` failures trip it, after which that
type's entity ids resolve to -1 (cold start ⇒ the RE contributes 0, i.e.
FE-only scoring, on already-compiled program shapes) until a cooldown
half-opens it. Requests keep answering throughout; ``stats()`` (and the
HTTP ``/healthz``) report the degraded set.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_tpu.data.game_data import GameBatch
from photon_tpu.data.index_map import EntityIndex, IndexMap
from photon_tpu.data.padding import bucket_grid, pad_game_batch
from photon_tpu.data.random_effect import bucket_dim
from photon_tpu.estimators.game_transformer import GameTransformer
from photon_tpu.models.game import GameModel
from photon_tpu.obs.metrics import registry
from photon_tpu.obs.export import exporter_health
from photon_tpu.obs.report import telemetry_sink_health
from photon_tpu.obs.quality import QualityConfig, QualityPlane, task_name
from photon_tpu.obs.slo import SLOTracker
from photon_tpu.obs.trace import flight_recorder, tracer
from photon_tpu.serve.admission import (
    INTERACTIVE,
    AdmissionConfig,
    AdmissionController,
)
from photon_tpu.serve.batcher import MicroBatcher, ScoreRequest
from photon_tpu.serve.store import HotColdEntityStore, StorePartition
from photon_tpu.utils import faults, resources

logger = logging.getLogger("photon_tpu")


class ReloadError(RuntimeError):
    """A reload failed to build/warm the new model generation. The old
    generation is still serving — the error is a report, not an outage."""


@dataclasses.dataclass
class ServeConfig:
    max_batch_size: int = 64  # rounded UP onto the bucket_dim grid
    max_delay_ms: float = 2.0  # oldest request's max queue dwell
    queue_cap: int = 1024  # admission bound; beyond it submits shed
    hot_bytes: int = 64 << 20  # device budget for cached RE tables
    default_deadline_ms: Optional[float] = None  # per-request unless given
    breaker_threshold: int = 3  # consecutive resolve failures to trip
    breaker_cooldown_s: float = 30.0  # open duration before half-open probe
    admission: Optional[AdmissionConfig] = None  # per-tenant quotas/classes
    max_versions: int = 2  # resident generations (primary + candidates)
    shadow_fraction: float = 0.0  # of primary traffic re-scored on shadow
    # Fraction of label-joined records re-scored on EACH shadow candidate
    # (its online-quality lane). 1.0 gives every candidate a dense
    # (score, label) stream — the experiment plane's GP observations.
    shadow_quality_fraction: float = 1.0
    # A promotion is "settled" (rollback parent unpinned, breaker-trip
    # monitoring window closed) this many seconds after promote(). <= 0
    # keeps the parent pinned until the next promote/rollback.
    promotion_settle_s: float = 300.0
    # Multi-chip serving: split every dense hot table into this many
    # entity shards laid out over the device mesh (same consistent-hash
    # plan the sharded trainer uses — parallel/entity_shard.py). None =
    # single-device tables. Scores merge with the one all-gather XLA
    # inserts for the slot gather against the sharded table.
    device_shards: Optional[int] = None


class _Breaker:
    """Per-RE-type circuit breaker. Single-writer (the engine's batch lock
    serializes _assemble), so plain fields suffice."""

    def __init__(self, threshold: int, cooldown_s: float):
        self.threshold = max(int(threshold), 1)
        self.cooldown_s = float(cooldown_s)
        self.failures = 0
        self.open_until = 0.0
        self.trips = 0

    @property
    def open(self) -> bool:
        return time.monotonic() < self.open_until

    def record_failure(self) -> bool:
        """Count one failure; returns True when this one trips the breaker
        (reaching the threshold, or failing the half-open probe after a
        cooldown — that re-trips immediately)."""
        half_open_probe = self.open_until > 0.0 and not self.open
        self.failures += 1
        if half_open_probe or self.failures >= self.threshold:
            self.open_until = time.monotonic() + self.cooldown_s
            self.failures = 0
            self.trips += 1
            return True
        return False

    def record_success(self) -> None:
        self.failures = 0
        self.open_until = 0.0


def _features_from_json(features: Dict) -> Dict:
    """Inverse of the spool's ``_jsonable_features``: dict payloads pass
    through, 2-list (indices, values) pairs become sparse tuples, dense
    lists become float32 vectors — the shapes ``_dense_row`` accepts."""
    out: Dict[str, object] = {}
    for shard, val in (features or {}).items():
        if isinstance(val, dict):
            out[shard] = val
        elif (isinstance(val, (list, tuple)) and len(val) == 2
              and isinstance(val[0], (list, tuple))):
            out[shard] = (
                np.asarray(val[0], np.int64),
                np.asarray(val[1], np.float32),
            )
        else:
            out[shard] = np.asarray(val, np.float32)
    return out


def _model_task(model: GameModel):
    """The GLM task this model family trains (for the quality plane's
    link/loss choice): first task found on any coordinate's model."""
    for m in getattr(model, "models", {}).values():
        task = getattr(m, "task", None) or getattr(
            getattr(m, "model", None), "task", None
        )
        if task is not None:
            return task
    return None


@dataclasses.dataclass
class _State:
    """Everything that swaps atomically on reload."""

    store: HotColdEntityStore
    transformer: GameTransformer
    model_version: str
    warm_traces: int  # trace_count right after warm-up


class _ShadowLane:
    """Per-candidate shadow accounting. Each resident candidate that
    shadows primary traffic owns one lane: its own traffic fraction, its
    own fractional-sampling accumulator (the N-way split stays exact and
    RNG-free — candidate ``i`` at fraction ``f`` scores every ``1/f``-th
    primary request regardless of what the other lanes sample), and its
    own divergence record so N concurrent candidates never alias into one
    series."""

    __slots__ = ("fraction", "acc", "count", "div_sum", "div_max",
                 "samples", "quality_acc", "started_at", "seq")

    def __init__(self, fraction: float, seq: int):
        self.fraction = float(fraction)
        self.acc = 0.0  # divergence-sampling accumulator
        self.count = 0
        self.div_sum = 0.0
        self.div_max = 0.0
        self.samples: deque = deque(maxlen=256)
        self.quality_acc = 0.0  # label re-score accumulator (quality lane)
        self.started_at = time.time()
        self.seq = seq  # start order; highest = "the" shadow for legacy API

    def stats(self, version: str) -> Dict:
        return dict(
            version=version,
            fraction=self.fraction,
            count=self.count,
            max_divergence=self.div_max,
            mean_divergence=self.div_sum / self.count if self.count else 0.0,
        )


class ServingEngine:
    """In-process serving core; cli/game_serving.py adds the HTTP front end.

    ``model`` must be the HOST-side master (``load_game_model(...,
    to_device=False)``) — the store decides what becomes device-resident.
    """

    def __init__(
        self,
        model: GameModel,
        entity_indexes: Optional[Dict[str, EntityIndex]] = None,
        index_maps: Optional[Dict[str, IndexMap]] = None,
        config: Optional[ServeConfig] = None,
        model_version: str = "0",
        partition: Optional[StorePartition] = None,
    ):
        self.config = config or ServeConfig()
        self.max_batch = bucket_dim(int(self.config.max_batch_size))
        # Fleet shard ownership: every generation's store is built with the
        # current partition; set_partition swaps the predicate live.
        self._partition = partition
        self._entity_indexes = dict(entity_indexes or {})
        self._index_maps = dict(index_maps or {})
        self._shard_dims = model.feature_shard_dims()
        self._intercept_col = {
            shard: (
                self._index_maps[shard].get_index(IndexMap.INTERCEPT)
                if shard in self._index_maps
                else -1
            )
            for shard in self._shard_dims
        }
        self._lock = threading.RLock()
        self._reloads = 0
        self._reload_failures = 0
        self._last_reload_error: Optional[str] = None
        # Per-RE-type circuit breakers: engine-owned (they outlive reloads —
        # a flapping store should stay degraded across a model swap).
        self._breakers: Dict[str, _Breaker] = {}
        # Admission lives HERE (the one device-owning process), never in
        # front-end workers — quota state must be globally consistent no
        # matter how many processes fan requests in.
        self.admission = AdmissionController(self.config.admission)
        # Multi-version residency: every generation is a full _State (its own
        # store + transformer + warm-up), but versions differ only by table
        # VALUES, so marginal versions cost memory — never a live-path
        # compile. ``_primary`` answers unpinned traffic; ``_shadows`` maps
        # candidate version → lane: each lane re-scores its own deterministic
        # fraction of primary traffic (independent fractional accumulators,
        # so an N-way split stays exact and RNG-free) without touching
        # responses. The single-shadow rollout API (start_shadow /
        # stop_shadow / shadow_stats with no argument) operates on the most
        # recently started lane.
        state = self._build_state(model, model_version)
        self._states: Dict[str, _State] = {state.model_version: state}
        self._primary: str = state.model_version
        self._shadows: Dict[str, _ShadowLane] = {}
        self._shadow_seq = 0  # start order; newest lane answers legacy API
        self._shadow_fraction = float(self.config.shadow_fraction)
        self._promotion: Optional[Dict] = None
        # Feedback spool (streaming freshness loop): when attached, every
        # scored primary request is offered to the spool's label join.
        self._feedback = None
        # SLO plane: availability + latency fed per completion, staleness
        # sampled against the last primary-generation change. Lives on the
        # engine (the one device-owning process); fleet replicas each run
        # their own and the scrape merges them.
        self.slo = SLOTracker()
        # Model-quality plane (obs/quality.py): streaming AUC/calibration
        # over the spool's joined (score, label) pairs, keyed by
        # (model_version, tenant, re_type). ``enable_quality_baseline``
        # adds the frozen-baseline lane (labeled traffic re-scored on a
        # pinned generation) so freshness lift is measured, not modeled.
        self.quality = QualityPlane(
            QualityConfig(task=task_name(_model_task(model)))
        )
        self._quality_baseline: Optional[str] = None
        self._quality_fraction = 1.0
        self._quality_acc = 0.0  # fractional-sampling accumulator
        self._last_model_update = time.time()
        self.batcher = MicroBatcher(
            self._score_batch,
            max_batch_size=self.max_batch,
            max_delay_s=self.config.max_delay_ms / 1000.0,
            queue_cap=self.config.queue_cap,
        )

    # -- state construction (startup and reload share it) -------------------

    def _build_state(self, model: GameModel, version: str) -> _State:
        """Store + transformer + FULL warm-up for one model generation.
        Runs entirely off the scoring lock so reloads never stall traffic.

        Warm-up is the engine's biggest allocation burst (every hot table
        plus every solve-cache executable for the batch grid), so a device
        OOM here gets contained: release the partial build, collect dropped
        buffers, retry once. The retry rebuilds from the host master — no
        caller ever sees a half-warmed generation. A second OOM raises a
        clean :class:`~photon_tpu.utils.resources.DeviceMemoryError` (the
        reload path keeps serving the old generation)."""

        def build() -> _State:
            faults.check("serve.warm_up", label=version)
            store = HotColdEntityStore(
                model,
                self._entity_indexes,
                hot_bytes=self.config.hot_bytes,
                # Floor: one batch's unique entities always fit resident.
                min_hot_rows=self.max_batch,
                partition=self._partition,
                device_shards=self.config.device_shards,
            )
            store.warm_uploads(self.max_batch)
            transformer = GameTransformer(store.scoring_model())
            template = self._template_batch(store)
            traces = transformer.warm_up(template, bucket_grid(self.max_batch))
            registry().gauge("serve_warmup_traces").set(traces)
            return _State(store, transformer, version, transformer.trace_count)

        with tracer().span("serve/warm_up"):
            try:
                return resources.oom_retry(
                    build, site="serve.warm_up",
                    counter="serve_warmup_oom_retries_total",
                )
            except Exception as exc:
                if not resources.is_device_oom(exc):
                    raise
                raise resources.DeviceMemoryError(
                    f"serve engine: device OOM warming up model version "
                    f"{version!r} even after retry. Shrink --hot-bytes or "
                    "--max-batch, evict serving versions, or add device "
                    "memory."
                ) from exc

    def _template_batch(self, store: HotColdEntityStore) -> GameBatch:
        """1-row inert batch with the production layout: dense zero features
        per shard, entity -1 (cold start) per RE type. Tracing is
        shape-driven, so values are irrelevant."""
        import jax.numpy as jnp

        return GameBatch(
            label=jnp.zeros(1, jnp.float32),
            offset=jnp.zeros(1, jnp.float32),
            weight=jnp.ones(1, jnp.float32),
            features={
                s: jnp.zeros((1, d), jnp.float32)
                for s, d in self._shard_dims.items()
            },
            entity_ids={
                rt: jnp.full(1, -1, jnp.int32)
                for rt in store.entity_re_types
            },
        )

    # -- request assembly ---------------------------------------------------

    def _dense_row(self, shard: str, value) -> np.ndarray:
        """One request's feature payload → dense (d,) float32. Serving
        always densifies: per-row dot products over a fixed d are row-count
        independent, which is what buys bit-parity with the batch driver."""
        d = self._shard_dims[shard]
        row = np.zeros(d, np.float32)
        icpt = self._intercept_col.get(shard, -1)
        if icpt >= 0:
            row[icpt] = 1.0
        if value is None:
            return row
        if isinstance(value, dict):
            imap = self._index_maps.get(shard)
            for k, v in value.items():
                if isinstance(k, str):
                    if imap is None:
                        raise ValueError(
                            f"string feature keys need an index map for "
                            f"shard {shard!r}"
                        )
                    j = imap.get_index(k)
                else:
                    j = int(k)
                if 0 <= j < d:
                    row[j] = v  # unknown features drop (batch-path parity)
            return row
        if (
            isinstance(value, (tuple, list))
            and len(value) == 2
            and not np.isscalar(value[0])
            and np.ndim(value[0]) == 1
            and np.ndim(value[1]) == 1
            and len(value[0]) == len(value[1])
            and len(value[0]) != d
        ):
            idx = np.asarray(value[0], np.int64)
            vals = np.asarray(value[1], np.float32)
            ok = (idx >= 0) & (idx < d)
            row[idx[ok]] = vals[ok]
            return row
        # Dense vectors are taken verbatim — the caller owns every column,
        # intercept included (that's what the parity harness feeds).
        arr = np.asarray(value, np.float32)
        if arr.shape != (d,):
            raise ValueError(
                f"shard {shard!r} expects a ({d},) vector, got {arr.shape}"
            )
        return arr

    def _assemble(
        self, requests: List[ScoreRequest], store: HotColdEntityStore
    ) -> GameBatch:
        n = len(requests)
        features = {}
        for shard in self._shard_dims:
            features[shard] = np.stack(
                [self._dense_row(shard, r.features.get(shard)) for r in requests]
            )
        entity_ids = {}
        for rt in store.entity_re_types:
            keys = [r.entity_ids.get(rt, -1) for r in requests]
            slots, batch_degraded = self._resolve_guarded(store, rt, keys)
            entity_ids[rt] = slots
            if batch_degraded:
                # Breaker-open / failed resolve: every request in this
                # batch scored FE-only for this type — flight-recorder bait.
                for r in requests:
                    r.degraded = True
            elif self._partition is not None and self._partition.applies_to(rt):
                # Foreign (non-owned) entities degrade FE-only per request.
                for r, key in zip(requests, keys):
                    if key != -1 and not self._partition.owns(key):
                        r.degraded = True
        return GameBatch(
            label=np.zeros(n, np.float32),
            offset=np.asarray([r.offset for r in requests], np.float32),
            weight=np.ones(n, np.float32),
            features=features,
            entity_ids=entity_ids,
        )

    def _breaker(self, re_type: str) -> _Breaker:
        b = self._breakers.get(re_type)
        if b is None:
            b = self._breakers[re_type] = _Breaker(
                self.config.breaker_threshold, self.config.breaker_cooldown_s
            )
        return b

    def _resolve_guarded(
        self, store: HotColdEntityStore, re_type: str, keys: List
    ) -> tuple:
        """``store.resolve`` behind the RE type's circuit breaker. Open
        breaker (or a failing resolve) degrades THIS batch's type to all
        -1 slots — cold-start semantics, so the random effect contributes 0
        and scoring proceeds FE-only on already-compiled shapes. Returns
        ``(slots, degraded)`` so the assembler can mark the requests for
        the flight recorder."""
        breaker = self._breaker(re_type)
        reg = registry()
        if breaker.open:
            reg.counter("serve_requests_degraded_total", re_type=re_type).inc(
                len(keys)
            )
            return np.full(len(keys), -1, np.int32), True
        try:
            slots = store.resolve(re_type, keys)
        except Exception as exc:  # noqa: BLE001 — degrade, never crash
            reg.counter("serve_store_errors_total", re_type=re_type).inc()
            if breaker.record_failure():
                reg.counter("serve_breaker_trips_total", re_type=re_type).inc()
                logger.warning(
                    "serving: circuit breaker for RE type %r OPEN for "
                    "%.1fs after resolve failure: %s",
                    re_type, breaker.cooldown_s, exc,
                )
            else:
                logger.warning(
                    "serving: resolve failed for RE type %r (%d/%d to "
                    "breaker trip): %s",
                    re_type, breaker.failures, breaker.threshold, exc,
                )
            reg.counter("serve_requests_degraded_total", re_type=re_type).inc(
                len(keys)
            )
            return np.full(len(keys), -1, np.int32), True
        breaker.record_success()
        return slots, False

    # -- the batcher's score_fn --------------------------------------------

    @property
    def _state(self) -> _State:
        """The primary generation's state (legacy single-version alias)."""
        return self._states[self._primary]

    def _resolve_version(self, pin: Optional[str]) -> str:
        """A version pin → resident state key: exact match, else basename
        (callers pin ``gen-3``; the engine may key the full model dir).
        Unknown pins raise ValueError (→ HTTP 400 in the front end)."""
        if pin is None:
            return self._primary
        pin = str(pin)
        if pin in self._states:
            return pin
        for key in self._states:
            if os.path.basename(str(key).rstrip("/")) == pin:
                return key
        raise ValueError(
            f"unknown model version {pin!r}; resident: "
            f"{sorted(self.versions)}"
        )

    def _score_on(self, state: _State, requests: List[ScoreRequest]) -> np.ndarray:
        import jax

        n = len(requests)
        with tracer().span("score"):
            faults.check("serve.score")
            batch = self._assemble(requests, state.store)
            batch = pad_game_batch(batch, bucket_dim(n), xp=np)
            # Sharded hot tables live on a mesh: replicate the batch over
            # it so the jitted scorer sees consistent placements (a plain
            # device_put would commit to device 0 and fail the jit's
            # incompatible-devices check against mesh-resident tables).
            dev = jax.device_put(batch, state.store.batch_sharding)
            scores = state.transformer.transform(
                dev, model=state.store.scoring_model()
            )
            return np.asarray(scores)[:n]

    def _score_batch(self, requests: List[ScoreRequest]) -> Sequence[float]:
        with self._lock:  # vs promote/reload swap; store.resolve single-writer
            out = np.zeros(len(requests), np.float32)
            groups: Dict[str, List[int]] = {}
            for i, r in enumerate(requests):
                key = r.model_version or self._primary
                if key not in self._states:
                    # Pinned version evicted between submit and flush (a
                    # promote/evict race): the primary answers rather than
                    # failing the whole batch.
                    registry().counter("serve_pin_fallback_total").inc()
                    logger.warning(
                        "serving: pinned version %r evicted before flush; "
                        "scoring on primary %r", key, self._primary,
                    )
                    key = self._primary
                    r.degraded = True
                # Record the generation that ACTUALLY scores this request —
                # the front ends report req.model_version, and the caller
                # must never see a pin label a score it didn't produce.
                r.model_version = key
                groups.setdefault(key, []).append(i)
            for key, idxs in groups.items():
                sub = [requests[i] for i in idxs]
                scores = self._score_on(self._states[key], sub)
                out[idxs] = scores
                if key == self._primary:
                    if self._shadows:
                        self._maybe_shadow_score(sub, scores)
                    if self._feedback is not None:
                        self._record_feedback(sub, scores)
            return out

    def _record_feedback(
        self, requests: List[ScoreRequest], scores: np.ndarray
    ) -> None:
        """Land scored primary requests in the feedback spool's label join.
        Observability-only: a spool failure counts, never surfaces to the
        caller or the scoring path."""
        spool = self._feedback
        if spool is None:
            return
        try:
            for r, s in zip(requests, scores):
                if r.uid is None:
                    continue  # no join key: the label could never match
                spool.observe_scored(
                    uid=r.uid,
                    features=r.features,
                    entity_ids=r.entity_ids,
                    offset=r.offset,
                    score=float(s),
                    model_version=r.model_version,
                    tenant=getattr(r, "tenant", None),
                    trace=getattr(r, "trace", None),
                )
        except Exception as exc:  # noqa: BLE001 — feedback never hurts callers
            registry().counter("feedback_errors_total").inc()
            logger.warning("serving: feedback spool observe failed: %s", exc)

    def _maybe_shadow_score(
        self, requests: List[ScoreRequest], primary_scores: np.ndarray
    ) -> None:
        """Re-score a deterministic ``shadow_fraction`` sample of primary
        traffic on the shadow generation, recording score divergence.
        Responses are untouched — shadow cost is observability only, and a
        shadow failure degrades to "no sample", never to a caller error.

        Fault site ``serve.shadow_diverge`` perturbs the shadow scores so
        the watcher's divergence bound must refuse the candidate. With N
        concurrent lanes the fault takes the candidate basename as its
        label, so a plan can regress one candidate and leave the rest."""
        reg = registry()
        for key, lane in list(self._shadows.items()):
            if key not in self._states:
                continue  # lane outlived its generation (evict race)
            take: List[int] = []
            for i in range(len(requests)):
                lane.acc += lane.fraction
                if lane.acc >= 1.0:
                    lane.acc -= 1.0
                    take.append(i)
            if not take:
                continue
            short = os.path.basename(key.rstrip("/"))
            state = self._states[key]
            try:
                shadow_scores = np.asarray(
                    self._score_on(state, [requests[i] for i in take]),
                    np.float32,
                )
            except Exception as exc:  # noqa: BLE001 — never hurts callers
                reg.counter(
                    "serve_shadow_errors_total", model_version=short
                ).inc()
                logger.warning(
                    "serving: shadow scoring on %r failed: %s", key, exc
                )
                continue
            if faults.injector().fire(
                "serve.shadow_diverge", label=short
            ) is not None:
                shadow_scores = shadow_scores + 1.0
            # The candidate label keeps N concurrent shadow series apart —
            # an unlabeled serve_shadow_divergence would alias every lane
            # into one histogram.
            hist = reg.histogram("serve_shadow_divergence",
                                 model_version=short)
            for j, i in enumerate(take):
                p, s = float(primary_scores[i]), float(shadow_scores[j])
                div = abs(s - p)
                hist.observe(div)
                lane.count += 1
                lane.div_sum += div
                lane.div_max = max(lane.div_max, div)
                lane.samples.append(
                    dict(uid=requests[i].uid, primary=p, shadow=s,
                         divergence=div)
                )
            reg.counter(
                "serve_shadow_scored_total", model_version=short
            ).inc(len(take))

    # -- public API ---------------------------------------------------------

    def submit(
        self,
        request: ScoreRequest,
        deadline_s: Optional[float] = None,
        tenant: Optional[str] = None,
        priority: str = INTERACTIVE,
        model_version: Optional[str] = None,
    ):
        """Admit (quota + priority class), then enqueue. Shed requests
        raise on THIS thread (``QuotaExceededError``/``BackpressureError``,
        both → HTTP 429); admitted requests return a Future and report
        their end-to-end latency into ``serve_tenant_latency_s``.

        ``model_version`` (or ``request.model_version``) pins the request to
        a resident generation; unknown pins raise ValueError here, on the
        caller's thread."""
        pin = model_version or request.model_version
        if pin is not None:
            with self._lock:
                request.model_version = self._resolve_version(pin)
        if tenant is not None:
            request.tenant = tenant  # per-tenant feedback sampling
        if deadline_s is None and self.config.default_deadline_ms is not None:
            deadline_s = self.config.default_deadline_ms / 1000.0
        self.admission.admit(
            tenant,
            priority,
            queue_depth=self.batcher.queue_depth,
            queue_cap=self.config.queue_cap,
        )
        t0 = time.monotonic()
        fut = self.batcher.submit(request, deadline_s, priority=priority)

        def _observe_done(f):
            dt = time.monotonic() - t0
            # Traced requests stamp their trace id as an OpenMetrics
            # exemplar on the tenant-latency histogram, linking the
            # scrape to the flight-recorder tree for the same request.
            tr = getattr(request, "trace", None)
            tid = tr.get("traceId") if isinstance(tr, dict) else None
            self.admission.observe_latency(tenant, dt, trace_id=tid)
            # SLO feed: availability (admitted requests that errored) and
            # latency for successes; staleness sampled per completion
            # against the last primary-generation change. All host math.
            try:
                ok = f.exception() is None
            except Exception:  # noqa: BLE001 — cancelled futures count bad
                ok = False
            self.slo.record_request(ok, dt if ok else None)
            self.slo.record_staleness(time.time() - self._last_model_update)

        fut.add_done_callback(_observe_done)
        return fut

    def score(
        self,
        features: Dict[str, object],
        entity_ids: Optional[Dict[str, object]] = None,
        offset: float = 0.0,
        deadline_s: Optional[float] = None,
        tenant: Optional[str] = None,
        priority: str = INTERACTIVE,
        model_version: Optional[str] = None,
    ) -> float:
        """Synchronous convenience wrapper: one request, blocking."""
        return self.submit(
            ScoreRequest(features, dict(entity_ids or {}), offset),
            deadline_s,
            tenant=tenant,
            priority=priority,
            model_version=model_version,
        ).result()

    @property
    def model_version(self) -> str:
        return self._primary

    @property
    def versions(self) -> List[str]:
        return list(self._states)

    @property
    def shadow_version(self) -> Optional[str]:
        """The most recently started shadow candidate (legacy single-shadow
        view); None when no lane is active."""
        lane = self._newest_shadow_locked()
        return lane[0] if lane else None

    @property
    def shadow_versions(self) -> List[str]:
        """All active shadow candidates, oldest lane first."""
        with self._lock:
            return sorted(self._shadows, key=lambda k: self._shadows[k].seq)

    def _newest_shadow_locked(self) -> Optional[Tuple[str, "_ShadowLane"]]:
        if not self._shadows:
            return None
        key = max(self._shadows, key=lambda k: self._shadows[k].seq)
        return key, self._shadows[key]

    @property
    def retraces_since_warmup(self) -> int:
        """0 is the contract; anything else means a live batch compiled.
        Summed over every resident generation — a candidate that compiles
        on live traffic is just as much a contract breach as the primary."""
        return sum(
            s.transformer.trace_count - s.warm_traces
            for s in self._states.values()
        )

    def _total_trips(self) -> int:
        return sum(b.trips for b in self._breakers.values())

    def _maybe_settle_promotion_locked(self) -> None:
        """Clear ``_promotion`` once its monitoring window has passed:
        ``promotion_settle_s`` after promote(), the promoted generation is
        considered adopted — the rollback parent unpins (becomes evictable)
        and ``trips_since_promotion`` stops counting against it. Without
        this the parent stays pinned forever and, at the default
        ``max_versions=2``, the pin set alone fills the residency cap."""
        promo = self._promotion
        settle = float(self.config.promotion_settle_s or 0.0)
        if promo is None or settle <= 0:
            return
        if time.time() - promo["at"] >= settle:
            self._promotion = None
            logger.info(
                "serving: promotion of %r settled after %.0fs; parent %r "
                "no longer pinned", promo["version"], settle, promo["parent"],
            )

    def _evict_locked(self, protect: Optional[str] = None) -> None:
        """Drop oldest resident generations beyond ``max_versions``. The
        primary, the shadow, the current promotion's parent (the rollback
        target), and ``protect`` (a generation being loaded right now) are
        never evicted — residency may temporarily exceed the cap rather
        than drop any of those."""
        cap = max(int(self.config.max_versions), 1)
        self._maybe_settle_promotion_locked()
        keep = {self._primary, protect, self._quality_baseline}
        keep.update(self._shadows)  # every live candidate lane stays pinned
        if self._promotion is not None:
            keep.add(self._promotion["parent"])
        for key in list(self._states):
            if len(self._states) <= cap:
                break
            if key in keep:
                continue
            del self._states[key]
            logger.info("serving: evicted resident generation %r", key)
        if len(self._states) > cap:
            logger.warning(
                "serving: %d generations resident over max_versions=%d "
                "(primary/shadow/rollback-parent/loading are never evicted)",
                len(self._states), cap,
            )

    def load_version(
        self, model: GameModel, model_version: Optional[str] = None
    ) -> Dict:
        """Build + warm ``model`` as a RESIDENT generation without touching
        the primary. Traffic can pin to it immediately; ``start_shadow`` /
        ``promote`` move it through the rollout lifecycle.

        A failed build/warm-up raises :class:`ReloadError`; nothing resident
        changes — the error is also visible in
        ``stats()['last_reload_error']`` until a load succeeds."""
        self._reloads += 1
        version = model_version or f"reload-{self._reloads}"
        try:
            faults.check("serve.reload")
            new_state = self._build_state(model, version)  # off the lock
        except Exception as exc:  # noqa: BLE001 — keep serving what we have
            self._reload_failures += 1
            self._last_reload_error = f"{version}: {exc}"
            registry().counter("serve_reload_failures_total").inc()
            logger.warning(
                "serving: load of %r failed (%s); resident generations "
                "unchanged", version, exc,
            )
            raise ReloadError(
                f"reload to {version!r} failed: {exc}"
            ) from exc
        with self._lock:
            self._states[new_state.model_version] = new_state
            self._evict_locked(protect=new_state.model_version)
            resident = new_state.model_version in self._states
        if not resident:
            # _evict_locked protects the new generation, so this is a
            # should-never-happen backstop — but success must only ever be
            # reported for a generation that is actually resident.
            self._reload_failures += 1
            self._last_reload_error = f"{version}: evicted during load"
            registry().counter("serve_reload_failures_total").inc()
            raise ReloadError(
                f"reload to {version!r} failed: evicted during load"
            )
        self._last_reload_error = None
        registry().counter("serve_model_reloads_total").inc()
        return dict(model_version=version, store=new_state.store.stats())

    def load_delta_version(
        self, base_version: str, delta: Dict, model_version: str
    ) -> Dict:
        """Register a micro-generation as a RESIDENT version by applying a
        per-entity delta onto an already-resident base — no disk load of the
        full model, no store rebuild, no warm-up pass.

        ``delta`` is the ``io.model_io.read_delta_rows`` payload:
        ``{"re_rows": {cid: (entity_idx, rows)}, "fixed": {cid: means}}``.
        The clone's scoring pytree has the same structure as the base's, so
        the BASE's warmed transformer serves it — a delta load is O(changed
        rows) in device work and compiles nothing (the scatter shapes hit
        the module-global jit cache). Raises :class:`ReloadError` when the
        base is not resident or the delta is not applicable in place (entity
        growth, projected coordinate) — callers fall back to a full
        ``load_version``."""
        self._reloads += 1
        version = model_version
        try:
            faults.check("serve.reload")
            with self._lock:
                base_key = self._resolve_version(base_version)
                base_state = self._states[base_key]
            with tracer().span("serve/delta_apply"):
                store = base_state.store.clone_with_delta(
                    delta.get("re_rows") or {}, delta.get("fixed") or {}
                )
            # Shared transformer: identical pytree structure means zero new
            # traces; warm_traces snapshots the shared counter so the
            # retrace contract stays a strict zero-iff-no-retrace signal.
            new_state = _State(
                store, base_state.transformer, version,
                base_state.transformer.trace_count,
            )
        except Exception as exc:  # noqa: BLE001 — keep serving what we have
            self._reload_failures += 1
            self._last_reload_error = f"{version}: {exc}"
            registry().counter("serve_reload_failures_total").inc()
            logger.warning(
                "serving: delta load of %r onto %r failed (%s); resident "
                "generations unchanged", version, base_version, exc,
            )
            raise ReloadError(
                f"delta load to {version!r} failed: {exc}"
            ) from exc
        with self._lock:
            self._states[version] = new_state
            self._evict_locked(protect=version)
            resident = version in self._states
        if not resident:
            self._reload_failures += 1
            self._last_reload_error = f"{version}: evicted during load"
            registry().counter("serve_reload_failures_total").inc()
            raise ReloadError(
                f"delta load to {version!r} failed: evicted during load"
            )
        self._last_reload_error = None
        registry().counter("serve_delta_loads_total").inc()
        return dict(
            model_version=version, base=base_key, store=new_state.store.stats()
        )

    # -- feedback spool (streaming freshness loop) --------------------------

    def attach_feedback(self, spool) -> None:
        """Attach a :class:`~photon_tpu.stream.spool.FeedbackSpool`: scored
        primary requests land in its label join; :meth:`feedback_label`
        completes the join. The engine owns the spool's lifecycle from here
        (closed with the engine)."""
        self._feedback = spool
        # Every completed label join also feeds the model-quality plane
        # (called outside the spool lock; failures count, never raise).
        spool.on_join = self._on_feedback_join

    def feedback_label(
        self, uid: str, label: float, ts: Optional[float] = None
    ) -> bool:
        """Report an observed label for a previously scored request. True
        when the joined record landed in the spool."""
        if self._feedback is None:
            raise ValueError("feedback spool not enabled on this engine")
        return self._feedback.observe_label(uid, label, ts)

    # -- model-quality plane (obs/quality.py) -------------------------------

    def enable_quality_baseline(
        self, model_version: str, fraction: float = 1.0
    ) -> None:
        """Pin a resident generation as the quality plane's FROZEN
        BASELINE: a deterministic ``fraction`` of labeled traffic is
        re-scored on it (observability-only — a failure degrades to no
        sample), so per-version AUC lift is the difference of two measured
        online curves over the same requests. The version must be resident
        and stays so: the baseline joins the never-evicted pin set
        (primary, shadow, rollback parent) for as long as it is enabled."""
        with self._lock:
            key = self._resolve_version(model_version)
        self._quality_baseline = key
        self._quality_fraction = float(fraction)
        self._quality_acc = 0.0
        self.quality.set_baseline(key)
        logger.info(
            "serving: quality baseline pinned to %r (fraction %.3f)",
            key, fraction,
        )

    def _on_feedback_join(self, rec: dict) -> None:
        """One joined (score, label) record from the spool → the quality
        plane, plus the frozen-baseline lane's re-score when enabled."""
        ids = rec.get("entityIds") or {}
        re_type = ",".join(sorted(ids)) if ids else ""
        tenant = rec.get("tenant")
        trace_id = (rec.get("trace") or {}).get("traceId")
        label = float(rec.get("label") or 0.0)
        self.quality.observe(
            score=float(rec.get("score") or 0.0),
            label=label,
            model_version=rec.get("modelVersion"),
            tenant=tenant,
            re_type=re_type,
            ts=rec.get("ts"),
            label_ts=rec.get("labelTs"),
            trace_id=trace_id,
            slo=self.slo,
        )
        rec_version = os.path.basename(
            str(rec.get("modelVersion") or "").rstrip("/")
        )
        self._candidate_quality_lanes(rec, label, tenant, re_type,
                                      trace_id, rec_version)
        base = self._quality_baseline
        if base is None:
            return
        if rec_version == os.path.basename(str(base).rstrip("/")):
            return  # the baseline scored it already — no second lane
        self._quality_acc += self._quality_fraction
        if self._quality_acc < 1.0:
            return
        self._quality_acc -= 1.0
        try:
            score = self._baseline_score(rec, base)
        except Exception as exc:  # noqa: BLE001 — lane never hurts callers
            registry().counter("quality_baseline_errors_total").inc()
            logger.warning(
                "serving: baseline quality re-score on %r failed: %s",
                base, exc,
            )
            return
        self.quality.observe(
            score=score,
            label=label,
            model_version=base,
            tenant=tenant,
            re_type=re_type,
            ts=rec.get("ts"),
            label_ts=rec.get("labelTs"),
            trace_id=trace_id,
            slo=self.slo,  # no-op for the baseline key (plane skips it)
        )
        registry().counter("quality_baseline_scored_total").inc()

    def _candidate_quality_lanes(
        self, rec: dict, label: float, tenant, re_type: str, trace_id,
        rec_version: str,
    ) -> None:
        """Re-score one joined label on EVERY active shadow candidate and
        feed the quality plane under that candidate's version key — the
        per-candidate streaming AUC/deviance the experiment plane's GP
        observes. Observability-only (a failure degrades to no sample), no
        SLO feed: a bad CANDIDATE must burn its own quality series and get
        poisoned, never page the primary's gate."""
        if not self._shadows:
            return
        frac = float(self.config.shadow_quality_fraction)
        if frac <= 0.0:
            return
        for key, lane in list(self._shadows.items()):
            short = os.path.basename(str(key).rstrip("/"))
            if short == rec_version:
                continue  # the candidate scored it already (pinned traffic)
            lane.quality_acc += frac
            if lane.quality_acc < 1.0:
                continue
            lane.quality_acc -= 1.0
            try:
                score = self._baseline_score(rec, key)
            except Exception as exc:  # noqa: BLE001 — never hurts callers
                registry().counter(
                    "quality_candidate_errors_total", model_version=short
                ).inc()
                logger.warning(
                    "serving: candidate quality re-score on %r failed: %s",
                    key, exc,
                )
                continue
            self.quality.observe(
                score=score,
                label=label,
                model_version=key,
                tenant=tenant,
                re_type=re_type,
                ts=rec.get("ts"),
                label_ts=rec.get("labelTs"),
                trace_id=trace_id,
                slo=None,  # candidate lanes never feed the global gate
            )
            registry().counter(
                "quality_candidate_scored_total", model_version=short
            ).inc()

    def _baseline_score(self, rec: dict, base: str) -> float:
        """Score one spool record's features on the pinned baseline
        generation, bypassing admission and the SLO request feed (an
        internal measurement must not spend tenant quota or count against
        availability). Shapes pad onto the warmed bucket grid, so the lane
        keeps the zero-retrace contract."""
        req = ScoreRequest(
            _features_from_json(rec.get("features") or {}),
            dict(rec.get("entityIds") or {}),
            float(rec.get("offset") or 0.0),
        )
        with self._lock:
            key = self._resolve_version(base)
            state = self._states[key]
            return float(self._score_on(state, [req])[0])

    def start_shadow(
        self, model_version: str, fraction: Optional[float] = None
    ) -> None:
        """Mirror a deterministic sample of primary traffic onto a resident
        candidate. Each call ADDS a lane (or resets an existing one), so N
        candidates can shadow concurrently — each with its own fraction,
        accumulator, and divergence record; the no-argument legacy API
        (``stop_shadow()`` / ``shadow_stats()`` / ``shadow_version``)
        addresses the most recently started lane. Starting an already
        shadowing version resets its record so a quota check reads the new
        phase only."""
        with self._lock:
            key = self._resolve_version(model_version)
            if key == self._primary:
                raise ValueError("cannot shadow the primary onto itself")
            frac = float(fraction) if fraction is not None \
                else self._shadow_fraction
            self._shadow_fraction = frac
            self._shadow_seq += 1
            self._shadows[key] = _ShadowLane(frac, self._shadow_seq)
        logger.info(
            "serving: shadowing %.3f of primary traffic onto %r "
            "(%d concurrent lane(s))", frac, key, len(self._shadows),
        )

    def stop_shadow(self, model_version: Optional[str] = None) -> None:
        """Stop one candidate's lane, or EVERY lane when no version is
        given (the legacy single-shadow call)."""
        with self._lock:
            if model_version is None:
                self._shadows.clear()
                return
            key = self._resolve_version(model_version)
            self._shadows.pop(key, None)

    def shadow_stats(self, model_version: Optional[str] = None) -> Dict:
        """Divergence record for one candidate lane (``model_version``), or
        the legacy single-shadow view: the most recently started lane's
        record plus a ``candidates`` map carrying EVERY lane keyed by
        version — N concurrent shadows never alias into one series."""
        with self._lock:
            if model_version is not None:
                key = self._resolve_version(model_version)
                lane = self._shadows.get(key)
                if lane is None:
                    return dict(version=None, count=0,
                                max_divergence=0.0, mean_divergence=0.0)
                return lane.stats(key)
            per_lane = {
                k: lane.stats(k) for k, lane in self._shadows.items()
            }
            newest = self._newest_shadow_locked()
            if newest is None:
                return dict(version=None, count=0, max_divergence=0.0,
                            mean_divergence=0.0, candidates=per_lane)
            out = newest[1].stats(newest[0])
            out["candidates"] = per_lane
            return out

    def shadow_samples(
        self, model_version: Optional[str] = None
    ) -> List[Dict]:
        """Recent (uid, primary, shadow) score pairs — the rollout soak's
        bit-exactness evidence. One lane's samples when ``model_version``
        is given, else the most recently started lane's."""
        with self._lock:
            if model_version is not None:
                key = self._resolve_version(model_version)
                lane = self._shadows.get(key)
                return list(lane.samples) if lane else []
            newest = self._newest_shadow_locked()
            return list(newest[1].samples) if newest else []

    def promote(self, model_version: str) -> Dict:
        """Make a resident generation the primary, remembering the previous
        primary as the ROLLBACK PARENT (pinned against eviction). The swap
        happens under the scoring lock: in-flight batches drain on the old
        primary, the next batch scores on the new one — same zero-downtime
        story as reload, zero compiles because the state is already warm."""
        with self._lock:
            key = self._resolve_version(model_version)
            if key == self._primary:
                return dict(model_version=key, parent=None)
            parent = self._primary
            self._promotion = dict(
                version=key,
                parent=parent,
                at=time.time(),
                trips_at=self._total_trips(),
            )
            self._primary = key
            self._shadows.pop(key, None)  # a primary never shadows itself
            self._last_model_update = time.time()  # SLO staleness clock
        registry().counter("serve_promotions_total").inc()
        logger.info("serving: promoted %r (parent %r)", key, parent)
        return dict(model_version=key, parent=parent)

    def trips_since_promotion(self) -> int:
        """Breaker trips since the last ``promote`` — the watcher's rollback
        signal. 0 when nothing was promoted, or once the promotion's
        ``promotion_settle_s`` monitoring window has passed."""
        with self._lock:
            self._maybe_settle_promotion_locked()
            promo = self._promotion
            return self._total_trips() - promo["trips_at"] if promo else 0

    def promotion_in_window(self) -> bool:
        """True while a promotion is inside its ``promotion_settle_s``
        monitoring window — the span during which the SLO gate may still
        unwind it (after settle, a rollback target no longer exists)."""
        with self._lock:
            self._maybe_settle_promotion_locked()
            return self._promotion is not None

    def rollback(self, reason: str = "") -> Optional[str]:
        """Demote the promoted generation back to its parent. Returns the
        demoted version (for the caller to poison), or None when there is
        no promotion to unwind or the parent is gone."""
        with self._lock:
            promo = self._promotion
            if promo is None or promo["parent"] not in self._states:
                return None
            demoted = self._primary
            self._primary = promo["parent"]
            self._promotion = None
            self._shadows.clear()
        registry().counter("serve_rollbacks_total").inc()
        logger.warning(
            "serving: rolled back %r -> %r (%s)",
            demoted, self._primary, reason or "no reason given",
        )
        return demoted

    def reload(self, model: GameModel, model_version: Optional[str] = None) -> Dict:
        """Zero-downtime swap to ``model``: load as a resident generation,
        then promote it. The direct path (no shadow phase) — the rollout
        watcher uses load_version/start_shadow/promote instead.

        A failed build/warm-up raises :class:`ReloadError` and leaves the
        OLD state serving, untouched — the error is also visible in
        ``stats()['last_reload_error']`` until a reload succeeds."""
        out = self.load_version(model, model_version)
        with tracer().span("serve/reload_swap"):
            self.promote(out["model_version"])
        return out

    def set_partition(self, partition: Optional[StorePartition]) -> Dict:
        """Swap the fleet shard-ownership predicate live on EVERY resident
        generation's store (ring rebalance / membership change). Rows the
        new predicate disowns age out of the hot set; newly-owned rows
        promote on their next request (or, for compacted hosts, after the
        next reload rebuilds the host subset)."""
        with self._lock:
            self._partition = partition
            for state in self._states.values():
                state.store.set_partition(partition)
            stats = self._state.store.partition_stats()
        return dict(
            partition=stats,
            versions=sorted(self._states),
        )

    def shard_export(
        self,
        target_snapshot: Dict,
        target_member: Optional[str] = None,
        include_cold: bool = True,
    ) -> Dict:
        """Warm-handoff export from the PRIMARY generation's store (the one
        live traffic resolves against), serialized with scoring on the
        batch lock — see ``HotColdEntityStore.shard_export``."""
        with self._lock:
            return self._state.store.shard_export(
                target_snapshot,
                target_member=target_member,
                include_cold=include_cold,
            )

    def shard_import(self, payload: Dict) -> Dict:
        """Install a peer's handoff payload on EVERY resident generation's
        store (host rows + hot-set pre-promotion) under the batch lock.
        Upload chunks stay within the warmed scatter buckets, so the
        zero-post-warmup-retrace contract holds through a handoff."""
        out: Dict = {}
        with self._lock:
            for version, state in self._states.items():
                out[version] = state.store.shard_import(
                    payload, upload_chunk=self.max_batch
                )
        return out

    def stats(self) -> Dict:
        state = self._state
        degraded = sorted(
            rt for rt, b in self._breakers.items() if b.open
        )
        trips = self.trips_since_promotion()  # may settle the promotion
        promo = self._promotion
        return dict(
            model_version=state.model_version,
            versions=sorted(self._states),
            primary=self._primary,
            shadow=self.shadow_version,
            shadows=self.shadow_versions,
            shadow_stats=self.shadow_stats(),
            promotion=dict(promo) if promo else None,
            trips_since_promotion=trips,
            queue_depth=self.batcher.queue_depth,
            max_batch_size=self.max_batch,
            trace_count=state.transformer.trace_count,
            retraces_since_warmup=self.retraces_since_warmup,
            store=state.store.stats(),
            partition=state.store.partition_stats(),
            degraded=bool(degraded) or self._last_reload_error is not None,
            degraded_re_types=degraded,
            breaker_trips={
                rt: b.trips for rt, b in self._breakers.items() if b.trips
            },
            reload_failures=self._reload_failures,
            last_reload_error=self._last_reload_error,
            tenants=self.admission.snapshot(),
            feedback=(
                self._feedback.stats() if self._feedback is not None else None
            ),
            slo=self._slo_block(),
            quality=self._quality_block(),
            telemetry_sink=telemetry_sink_health(),
            flight_recorder=flight_recorder().stats(),
            otlp_exporter=exporter_health(),
        )

    def _slo_block(self) -> Dict:
        """The ``/healthz`` SLO block; also the flush point that mirrors
        burn/state into gauges so the ``/metrics`` scrape carries them."""
        self.slo.record_staleness(time.time() - self._last_model_update)
        try:
            self.slo.publish_metrics()
        except Exception:  # noqa: BLE001 — stats must never fail on obs
            pass
        snap = self.slo.snapshot()
        snap["model_staleness_now_s"] = time.time() - self._last_model_update
        return snap

    def _quality_block(self) -> Dict:
        """The healthz model-quality block; also the flush point mirroring
        windowed per-version AUC/ECE/lift into ``quality_*`` gauges so the
        ``/metrics`` scrape (and the fleet merge) carries them."""
        try:
            self.quality.publish()
        except Exception:  # noqa: BLE001 — stats must never fail on obs
            pass
        return self.quality.snapshot()

    def close(self, drain: bool = True) -> None:
        self.batcher.close(drain=drain)
        if self._feedback is not None:
            try:
                self._feedback.close()
            except Exception:  # noqa: BLE001 — close must not raise
                logger.exception("serving: feedback spool close failed")


def load_engine(
    model_dir: str,
    artifacts_dir: Optional[str] = None,
    config: Optional[ServeConfig] = None,
    model_version: Optional[str] = None,
    partition: Optional[StorePartition] = None,
) -> ServingEngine:
    """Build an engine from a trained model directory the way the batch
    scoring driver would: index maps + entity indexes from the artifacts
    dir (default: the model dir's parent = the training output dir), model
    loaded HOST-side (the store owns device residency)."""
    from photon_tpu.io.model_io import (
        delta_info,
        load_resolved_game_model,
        model_re_types,
        read_model_metadata,
        resolve_delta_chain,
    )

    artifacts = artifacts_dir or os.path.dirname(model_dir.rstrip("/"))
    # A cold start can land directly on a delta micro-generation (LATEST
    # points at it): the coordinate/shard universe then comes from the
    # whole resolved chain, not the layer's few touched coordinates.
    layers = (
        resolve_delta_chain(model_dir)
        if delta_info(model_dir) is not None
        else [model_dir]
    )
    meta: Dict[str, object] = {"coordinates": {}}
    for layer in layers:
        for cid, info in read_model_metadata(layer).get(
            "coordinates", {}
        ).items():
            meta["coordinates"].setdefault(cid, info)
    index_maps: Dict[str, IndexMap] = {}
    for coord in meta.get("coordinates", {}).values():
        shard = coord.get("featureShard")
        path = os.path.join(artifacts, f"index-map-{shard}.json")
        if shard and shard not in index_maps and os.path.exists(path):
            index_maps[shard] = IndexMap.load(path)
    entity_indexes: Dict[str, EntityIndex] = {}
    for re_type in model_re_types(meta):
        path = os.path.join(artifacts, f"entity-index-{re_type}.json")
        if os.path.exists(path):
            entity_indexes[re_type] = EntityIndex.load(path)
    model = load_resolved_game_model(
        model_dir, index_maps, entity_indexes, to_device=False
    )
    engine = ServingEngine(
        model,
        entity_indexes=entity_indexes,
        index_maps=index_maps,
        config=config,
        model_version=model_version or model_dir.rstrip("/"),
        partition=partition,
    )
    # The publish root this engine was loaded from: generation manifests
    # live here, which is what the /v1/experiment rollup reads.
    engine.artifacts_dir = artifacts
    return engine
