"""Multi-process serving front end: N HTTP workers, one device-owning scorer.

The stdlib ``ThreadingHTTPServer`` front end shares one GIL with the jitted
scorer — fine for correctness, fatal for sustained p99 once request
parsing competes with dispatch (the carried-over risk in the serving PR,
ROADMAP "Serving front end that survives real traffic"). This module splits
the two across PROCESSES:

- **Workers** (N of them) accept connections on a SHARED listening socket
  (created before fork, so the kernel load-balances ``accept`` across
  processes), parse/validate HTTP+JSON, and forward each request over a
  Unix-domain socket to the scorer. Workers never import jax — they are
  pure stdlib and fork-safe by construction.
- **Scorer** (exactly one, the parent) owns the device: admission →
  ``MicroBatcher`` → ``ServingEngine``, the SAME path the in-process server
  uses, byte for byte. Requests from all workers co-batch in the one
  flusher, so multi-process serving keeps the zero-retrace and bit-parity
  contracts of the single-process engine.

Fork discipline (the part that is easy to get wrong): workers are forked
while the parent is still single-threaded and has NOT initialized the JAX
backend — forking after backend init duplicates locked mutexes and device
handles into children. ``ServingFrontend.fork_workers()`` must therefore
run before ``load_engine``; workers retry-connect to the scorer socket
until the (slow, warm-up-bound) parent starts listening.

Wire protocol: 4-byte big-endian length + UTF-8 JSON per frame, one
id-correlated request/response stream per worker connection. Responses
complete out of order (a shed answers before a queued score), which is what
lets one worker pipeline hundreds of in-flight requests over one socket.

Errors cross the boundary as ``{code, kind, error}`` payloads and are
re-raised client-side as the SAME exception types the engine raises
(``QuotaExceededError``/``BackpressureError`` → 429,
``DeadlineExceededError`` → 504, ``ValueError`` → 400), so the HTTP layer
has exactly one classification function for both deployment shapes.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import logging
import os
import queue
import shutil
import socket
import socketserver
import struct
import tempfile
import threading
import time
import traceback
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeoutError
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional
from urllib.parse import parse_qs

from photon_tpu.obs.metrics import (
    PROMETHEUS_CONTENT_TYPE,
    registry,
    render_prometheus,
)
from photon_tpu.obs.trace import (
    TraceContext,
    flight_recorder,
    merge_trace_dumps,
    mint_context,
    new_span_id,
    tracer,
)
from photon_tpu.serve.admission import INTERACTIVE, PRIORITIES, QuotaExceededError
from photon_tpu.serve.batcher import (
    BackpressureError,
    DeadlineExceededError,
    ScoreRequest,
)

logger = logging.getLogger("photon_tpu")

_LEN = struct.Struct(">I")
MAX_FRAME_BYTES = 64 << 20

# Shared secret for the TCP transport's HMAC handshake. Environment, never
# argv: command lines are world-readable via /proc.
FLEET_SECRET_ENV = "PHOTON_TPU_FLEET_SECRET"


# ---------------------------------------------------------------------------
# Request parsing + error classification (shared by both deployment shapes)
# ---------------------------------------------------------------------------


def request_from_json(obj: dict) -> ScoreRequest:
    if not isinstance(obj, dict) or "features" not in obj:
        raise ValueError("request must be a JSON object with 'features'")
    return ScoreRequest(
        features=dict(obj["features"]),
        entity_ids=dict(obj.get("entityIds", {})),
        offset=float(obj.get("offset", 0.0)),
        uid=obj.get("uid"),
        model_version=obj.get("modelVersion"),
    )


def classify_exception(exc: BaseException):
    """(http_code, kind) for one request failure. ``kind`` separates the
    shed REASONS that share a status code — quota sheds and queue
    backpressure both 429, but tenants (and the soak bench) need to tell
    them apart."""
    kind = getattr(exc, "http_kind", None)
    if isinstance(exc, QuotaExceededError):
        return 429, kind or getattr(exc, "reason", "quota")
    if isinstance(exc, BackpressureError):
        return 429, kind or "backpressure"
    if isinstance(exc, (DeadlineExceededError, FutureTimeoutError)):
        return 504, kind or "deadline"
    if isinstance(exc, (ValueError, KeyError, json.JSONDecodeError)):
        return 400, kind or "bad_request"
    return 500, kind or "internal"


def _exception_from_payload(msg: dict) -> BaseException:
    """Rebuild the engine's exception type from a scorer error frame, so
    worker-side HTTP mapping is identical to the in-process path."""
    code = int(msg.get("code", 500))
    kind = msg.get("kind", "internal")
    text = str(msg.get("error", "scorer error"))
    exc: BaseException
    if code == 429:
        if kind in ("quota", "batch_capacity"):
            exc = QuotaExceededError(
                text, msg.get("tenant", "?"), reason=kind
            )
        else:
            exc = BackpressureError(text)
    elif code == 504:
        exc = DeadlineExceededError(text)
    elif code == 400:
        exc = ValueError(text)
    else:
        exc = RuntimeError(text)
    exc.http_kind = kind  # preserve the original classification verbatim
    return exc


def score_jsonl(body: bytes, submit, result_timeout_s: Optional[float] = None):
    """``/v1/score-batch`` core: submit every parseable line FIRST (they
    co-batch in the flusher), then collect in order. Each line resolves
    independently: ``{"score": s}`` on success, else ``{"error", "code",
    "kind"}`` — a malformed line is a per-line 400, never conflated with a
    429 shed (they used to share one except clause)."""
    futures: List[object] = []
    for line in body.splitlines():
        if not line.strip():
            continue
        try:
            futures.append(submit(json.loads(line)))
        except Exception as exc:  # noqa: BLE001 — per-line failure
            futures.append(exc)
    out = []
    for f in futures:
        if isinstance(f, BaseException):
            code, kind = classify_exception(f)
            out.append({"error": str(f), "code": code, "kind": kind})
        else:
            try:
                res = f.result(result_timeout_s)
                out.append({"score": res["score"]})
            except Exception as exc:  # noqa: BLE001 — per-line failure
                code, kind = classify_exception(exc)
                out.append({"error": str(exc), "code": code, "kind": kind})
    return out


def apply_feedback(engine, body: dict) -> dict:
    """``/v1/feedback`` core, shared by both deployment shapes: ``body`` is
    one ``{"uid", "label", "ts"?}`` object or ``{"labels": [...]}`` for a
    batch. Each item completes the feedback spool's label join for a
    previously scored request; items whose uid already aged out of the join
    window are counted as ``dropped``, not errors. Raises ``ValueError``
    (→ 400) when the engine has no spool attached or an item is malformed."""
    if not isinstance(body, dict):
        raise ValueError("feedback body must be a JSON object")
    items = body.get("labels")
    if items is None:
        items = [body]
    if not isinstance(items, list):
        raise ValueError("'labels' must be a list of {uid, label} objects")
    joined = 0
    dropped = 0
    for item in items:
        if (
            not isinstance(item, dict)
            or "uid" not in item
            or "label" not in item
        ):
            raise ValueError("each feedback item needs 'uid' and 'label'")
        ts = item.get("ts")
        ok = engine.feedback_label(
            str(item["uid"]),
            float(item["label"]),
            float(ts) if ts is not None else None,
        )
        if ok:
            joined += 1
        else:
            dropped += 1
    return {"joined": joined, "dropped": dropped}


def _stamp_labels(snap: dict, **labels) -> dict:
    """Fill ``labels`` into a metric snapshot record where absent (existing
    labels win) — how a merged fleet scrape tells the frontend's instruments
    from each replica's without rewriting anything the producer stamped."""
    merged = dict(snap.get("labels") or {})
    for k, v in labels.items():
        merged.setdefault(str(k), str(v))
    return dict(snap, labels=merged)


# ---------------------------------------------------------------------------
# Framed IPC
# ---------------------------------------------------------------------------


def _send_frame(sock: socket.socket, obj: dict, lock: threading.Lock) -> None:
    data = json.dumps(obj).encode()
    with lock:
        sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> Optional[dict]:
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME_BYTES:
        raise ValueError(f"IPC frame of {length} bytes exceeds cap")
    payload = _recv_exact(sock, length)
    if payload is None:
        return None
    return json.loads(payload.decode())


# ---------------------------------------------------------------------------
# Transport endpoints: Unix paths and tcp://host:port
# ---------------------------------------------------------------------------


def parse_endpoint(endpoint: str):
    """``("unix", path)`` for a plain filesystem path, ``("tcp", (host,
    port))`` for a ``tcp://host:port`` URL. Everything above the socket —
    the frame protocol, op table, trace propagation — is family-agnostic."""
    if endpoint.startswith("tcp://"):
        hostport = endpoint[len("tcp://"):]
        host, sep, port = hostport.rpartition(":")
        if not sep:
            raise ValueError(f"tcp endpoint needs host:port, got {endpoint!r}")
        return "tcp", (host or "127.0.0.1", int(port))
    return "unix", endpoint


def _hmac_hex(secret: str, message: str) -> str:
    return hmac.new(
        secret.encode(), message.encode(), hashlib.sha256
    ).hexdigest()


def _auth_server(conn: socket.socket, secret: str) -> bool:
    """Server half of the mutual challenge/response handshake, first frames
    on the connection: we challenge with a fresh per-connection nonce, the
    peer answers HMAC-SHA256(secret, nonce) plus its own nonce, and we prove
    ourselves back over that — so neither side ever sends the secret, and a
    recorded handshake can't be replayed against either end."""
    lock = threading.Lock()
    nonce = os.urandom(16).hex()
    try:
        conn.settimeout(10.0)
        _send_frame(conn, dict(op="auth_challenge", nonce=nonce), lock)
        msg = _recv_frame(conn)
        got = str((msg or {}).get("mac", ""))
        if not hmac.compare_digest(_hmac_hex(secret, nonce), got):
            registry().counter("fleet_auth_failures_total").inc()
            _send_frame(conn, dict(op="auth_fail"), lock)
            return False
        peer_nonce = str((msg or {}).get("nonce", ""))
        _send_frame(
            conn, dict(op="auth_ok", mac=_hmac_hex(secret, peer_nonce)), lock
        )
        conn.settimeout(None)
        return True
    except (OSError, ValueError):
        return False


def _auth_client(sock: socket.socket, secret: str) -> None:
    """Client half: answer the server's challenge, then verify the server's
    proof over OUR nonce before trusting anything it frames back. A MAC
    mismatch raises ``PermissionError`` — callers must not retry it the way
    they retry a not-yet-listening endpoint."""
    lock = threading.Lock()
    sock.settimeout(10.0)
    msg = _recv_frame(sock)
    if not msg or msg.get("op") != "auth_challenge":
        raise ConnectionError("scorer endpoint did not issue auth challenge")
    nonce = os.urandom(16).hex()
    _send_frame(
        sock,
        dict(
            op="auth_response",
            mac=_hmac_hex(secret, str(msg.get("nonce", ""))),
            nonce=nonce,
        ),
        lock,
    )
    reply = _recv_frame(sock)
    if (
        not reply
        or reply.get("op") != "auth_ok"
        or not hmac.compare_digest(
            _hmac_hex(secret, nonce), str(reply.get("mac", ""))
        )
    ):
        raise PermissionError(
            "fleet transport auth failed (shared secret mismatch)"
        )
    sock.settimeout(None)


# ---------------------------------------------------------------------------
# Scorer side (the one device-owning process)
# ---------------------------------------------------------------------------


class ScorerServer:
    """Accepts worker connections on a Unix socket and executes ops against
    the engine. Per connection: one reader thread (parses frames, submits)
    and one writer thread (serializes responses from a queue) — responses
    complete out of order via the engine futures' done-callbacks, so a
    single connection carries arbitrarily many in-flight requests."""

    def __init__(self, engine, socket_path: str, secret: Optional[str] = None):
        self.engine = engine
        self.socket_path = socket_path
        self._family = parse_endpoint(socket_path)[0]
        if secret is None and self._family == "tcp":
            secret = os.environ.get(FLEET_SECRET_ENV)
        if self._family == "tcp" and not secret:
            raise ValueError(
                "TCP scorer endpoints require a shared secret "
                f"(set ${FLEET_SECRET_ENV}) — refusing to listen "
                "unauthenticated off-host"
            )
        self.secret = secret
        self._sock: Optional[socket.socket] = None
        self._threads: List[threading.Thread] = []
        self._conns: List[socket.socket] = []
        self._lock = threading.Lock()
        self._closed = False

    def start(self) -> None:
        fam, addr = parse_endpoint(self.socket_path)
        if fam == "unix":
            if os.path.exists(self.socket_path):
                os.unlink(self.socket_path)
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.bind(self.socket_path)
            self._sock.listen(128)
        else:
            self._sock = socket.create_server(addr, backlog=128)
            host, port = self._sock.getsockname()[:2]
            # Re-resolve so a port-0 bind advertises the real port.
            self.socket_path = f"tcp://{host}:{port}"
        t = threading.Thread(
            target=self._accept_loop, name="scorer-accept", daemon=True
        )
        t.start()
        self._threads.append(t)

    def _accept_loop(self) -> None:
        assert self._sock is not None
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # listener closed
            if self._family == "tcp":
                try:
                    conn.setsockopt(
                        socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                    )
                except OSError:
                    pass
            with self._lock:
                if self._closed:
                    conn.close()
                    return
                self._conns.append(conn)
            t = threading.Thread(
                target=self._serve_conn, args=(conn,),
                name="scorer-conn", daemon=True,
            )
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket) -> None:
        if self.secret is not None and not _auth_server(conn, self.secret):
            try:
                conn.close()
            except OSError:
                pass
            return
        out: "queue.Queue[Optional[dict]]" = queue.Queue()
        wlock = threading.Lock()

        def _writer() -> None:
            while True:
                msg = out.get()
                if msg is None:
                    return
                try:
                    _send_frame(conn, msg, wlock)
                except OSError:
                    return  # worker went away; reader notices EOF too

        wt = threading.Thread(target=_writer, name="scorer-write", daemon=True)
        wt.start()
        try:
            while True:
                try:
                    msg = _recv_frame(conn)
                except (OSError, ValueError):
                    break
                if msg is None:
                    break
                self._dispatch(msg, out)
        finally:
            out.put(None)
            wt.join(timeout=5.0)
            try:
                conn.close()
            except OSError:
                pass

    def _error_payload(self, rid, exc: BaseException) -> dict:
        code, kind = classify_exception(exc)
        payload = dict(
            id=rid, ok=False, code=code, kind=kind, error=str(exc)
        )
        if isinstance(exc, QuotaExceededError):
            payload["tenant"] = exc.tenant
        return payload

    def _dispatch(self, msg: dict, out: "queue.Queue") -> None:
        rid = msg.get("id")
        op = msg.get("op")
        try:
            if op == "score":
                self._op_score(rid, msg, out)
            elif op == "stats":
                out.put(dict(id=rid, ok=True, result=self._op_stats()))
            elif op == "reload":
                # Off-thread: a reload warms a whole model generation;
                # this connection's scores must keep flowing meanwhile.
                threading.Thread(
                    target=self._op_reload, args=(rid, msg, out),
                    name="scorer-reload", daemon=True,
                ).start()
            elif op == "feedback":
                out.put(dict(
                    id=rid, ok=True, result=self._op_feedback(msg),
                ))
            elif op == "metrics":
                out.put(dict(id=rid, ok=True, result=self._op_metrics(msg)))
            elif op == "experiment":
                out.put(dict(
                    id=rid, ok=True, result=self._op_experiment(msg),
                ))
            elif op == "traces":
                out.put(dict(id=rid, ok=True, result=self._op_traces(msg)))
            elif op == "ping":
                out.put(dict(id=rid, ok=True, result="pong"))
            else:
                raise ValueError(f"unknown scorer op {op!r}")
        except Exception as exc:  # noqa: BLE001 — per-request failure
            out.put(self._error_payload(rid, exc))

    def _op_score(self, rid, msg: dict, out: "queue.Queue") -> None:
        req = request_from_json(msg.get("request") or {})
        ctx = TraceContext.from_dict(msg.get("trace"))
        sid: Optional[str] = None
        if ctx is not None and ctx.sampled:
            # Pre-mint this hop's span id so downstream consumers (fleet
            # replicas, the feedback spool) can parent on it before the
            # span itself completes on the done-callback below.
            sid = new_span_id()
            req.trace = ctx.child(sid).to_dict()
        t0 = time.monotonic()
        fut = self.engine.submit(
            req,
            tenant=msg.get("tenant"),
            priority=msg.get("priority") or INTERACTIVE,
            model_version=msg.get("modelVersion"),
        )

        def _done(f: Future) -> None:
            exc = f.exception()
            if sid is not None:
                try:
                    dt = time.monotonic() - t0
                    tracer().record(
                        "scorer/score", dt, parent="",
                        context=ctx, span_id=sid,
                    )
                    flight_recorder().finish(
                        ctx.trace_id, dt,
                        error=None if exc is None else str(exc),
                        degraded=bool(getattr(req, "degraded", False)),
                        forced=ctx.forced,
                    )
                except Exception:
                    pass  # telemetry must never fail the response
            if exc is not None:
                out.put(self._error_payload(rid, exc))
            else:
                out.put(dict(
                    id=rid, ok=True,
                    result=dict(
                        score=f.result(),
                        # The engine records the generation that actually
                        # scored the request on it at flush time.
                        modelVersion=(
                            req.model_version or self.engine.model_version
                        ),
                    ),
                ))

        fut.add_done_callback(_done)

    def _op_stats(self) -> dict:
        return self.engine.stats()

    def _op_feedback(self, msg: dict) -> dict:
        return apply_feedback(self.engine, msg.get("body") or {})

    def _op_experiment(self, msg: dict) -> dict:
        return experiment_rollup(self.engine)

    def _op_metrics(self, msg: dict) -> List[dict]:
        """Registry snapshot for the worker-side ``/metrics`` merge.
        Subclasses that front a whole fleet override this to return the
        fleet-wide labeled merge."""
        return registry().snapshot()

    def _op_traces(self, msg: dict) -> List[dict]:
        """This process's kept flight-recorder trees; subclasses fronting a
        fleet override to merge the replicas' rings in."""
        return flight_recorder().traces(limit=msg.get("limit"))

    def _op_reload(self, rid, msg: dict, out: "queue.Queue") -> None:
        try:
            from photon_tpu.io.model_io import load_game_model

            model_dir = msg.get("modelDir")
            if not model_dir:
                raise ValueError("reload needs {'modelDir': path}")
            model = load_game_model(
                model_dir, self.engine._index_maps,
                self.engine._entity_indexes, to_device=False,
            )
            info = self.engine.reload(
                model, msg.get("modelVersion") or model_dir
            )
            out.put(dict(id=rid, ok=True, result=info))
        except Exception as exc:  # noqa: BLE001 — per-request failure
            out.put(self._error_payload(rid, exc))

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            conns = list(self._conns)
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5.0)
        if self._family == "unix" and os.path.exists(self.socket_path):
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass


# ---------------------------------------------------------------------------
# Worker side
# ---------------------------------------------------------------------------


class ScorerClient:
    """One worker's connection to the scorer: id-correlated async frames.
    ``submit_score`` returns a Future resolving to the scorer's result dict
    (or raising the reconstructed engine exception); a lost connection
    fails every in-flight future with ``ConnectionError``."""

    def __init__(
        self,
        socket_path: str,
        connect_timeout_s: float = 120.0,
        secret: Optional[str] = None,
    ):
        fam, addr = parse_endpoint(socket_path)
        if secret is None and fam == "tcp":
            secret = os.environ.get(FLEET_SECRET_ENV)
        self.endpoint = socket_path
        deadline = time.monotonic() + connect_timeout_s
        last_err: Optional[BaseException] = None
        delay = 0.05  # capped exponential backoff while the scorer warms
        while True:
            sock: Optional[socket.socket] = None
            try:
                if fam == "unix":
                    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                    sock.connect(addr)
                else:
                    sock = socket.create_connection(addr, timeout=10.0)
                    sock.settimeout(None)
                    sock.setsockopt(
                        socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                    )
                if secret is not None:
                    _auth_client(sock, secret)
                break
            except PermissionError:
                # Wrong shared secret: retrying can't fix it.
                if sock is not None:
                    sock.close()
                raise
            except OSError as exc:
                last_err = exc
                if sock is not None:
                    sock.close()
                if time.monotonic() >= deadline:
                    raise ConnectionError(
                        f"scorer endpoint {socket_path} not reachable after "
                        f"{connect_timeout_s:.0f}s: {last_err}"
                    ) from last_err
                time.sleep(min(delay, max(0.0, deadline - time.monotonic())))
                delay = min(delay * 2.0, 1.0)
        self._sock = sock
        self._wlock = threading.Lock()
        self._plock = threading.Lock()
        self._pending: Dict[int, Future] = {}
        self._next_id = 0
        self._closed = False
        self._reader = threading.Thread(
            target=self._read_loop, name="scorer-client-read", daemon=True
        )
        self._reader.start()

    def _read_loop(self) -> None:
        try:
            while True:
                msg = _recv_frame(self._sock)
                if msg is None:
                    break
                with self._plock:
                    fut = self._pending.pop(msg.get("id"), None)
                if fut is None:
                    continue
                if msg.get("ok"):
                    fut.set_result(msg.get("result"))
                else:
                    fut.set_exception(_exception_from_payload(msg))
        except (OSError, ValueError):
            pass
        finally:
            with self._plock:
                pending, self._pending = self._pending, {}
            for fut in pending.values():
                fut.set_exception(
                    ConnectionError("scorer connection lost")
                )

    def request(self, op: str, **payload) -> Future:
        fut: Future = Future()
        with self._plock:
            if self._closed:
                raise ConnectionError("scorer client closed")
            rid = self._next_id
            self._next_id += 1
            self._pending[rid] = fut
        try:
            _send_frame(
                self._sock, dict(id=rid, op=op, **payload), self._wlock
            )
        except OSError as exc:
            with self._plock:
                self._pending.pop(rid, None)
            raise ConnectionError(f"scorer connection lost: {exc}") from exc
        return fut

    def submit_score(
        self,
        raw_request: dict,
        tenant: Optional[str] = None,
        priority: str = INTERACTIVE,
        model_version: Optional[str] = None,
        trace: Optional[dict] = None,
    ) -> Future:
        return self.request(
            "score", request=raw_request, tenant=tenant, priority=priority,
            modelVersion=model_version, trace=trace,
        )

    def call(self, op: str, timeout_s: float = 30.0, **payload):
        return self.request(op, **payload).result(timeout_s)

    def close(self) -> None:
        with self._plock:
            self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._reader.join(timeout=5.0)


# ---------------------------------------------------------------------------
# HTTP layer (shared by in-process and multi-process deployments)
# ---------------------------------------------------------------------------


def experiment_rollup(engine) -> dict:
    """``/v1/experiment`` payload: the manifest-derived experiment rollup
    for the publish root this engine serves from (the manifests ARE the
    experiment store — a dead manager leaves a readable history), plus the
    engine's LIVE candidate state (resident shadow lanes and their
    divergence counters), which manifests can't know."""
    from photon_tpu.experiment import experiment_summary

    root = getattr(engine, "artifacts_dir", None)
    if not root:
        version = str(getattr(engine, "model_version", "") or "")
        parent = os.path.dirname(version.rstrip("/"))
        root = parent if os.path.isdir(parent) else None
    doc: dict = {"publishRoot": root, "experiments": []}
    if root:
        try:
            doc.update(experiment_summary(root))
        except Exception as exc:  # noqa: BLE001 — rollup is best-effort
            doc["error"] = str(exc)
    try:
        doc["live"] = {
            "primary": engine.model_version,
            "shadows": engine.shadow_versions,
            "shadowStats": engine.shadow_stats(),
        }
    except Exception:  # noqa: BLE001 — a closing engine must not 500 this
        pass
    return doc


class LocalBackend:
    """Direct engine access — the single-process deployment shape."""

    def __init__(self, engine, result_timeout_s: float = 120.0):
        self.engine = engine
        self.result_timeout_s = result_timeout_s

    def submit(
        self, raw_request: dict, tenant: Optional[str], priority: str,
        model_version: Optional[str] = None,
        trace: Optional[dict] = None,
    ) -> Future:
        req = request_from_json(raw_request)
        ctx = TraceContext.from_dict(trace)
        sid: Optional[str] = None
        if ctx is not None and ctx.sampled:
            sid = new_span_id()
            req.trace = ctx.child(sid).to_dict()
        t0 = time.monotonic()
        src = self.engine.submit(
            req, tenant=tenant, priority=priority,
            model_version=model_version,
        )
        dst: Future = Future()

        def _done(f: Future) -> None:
            exc = f.exception()
            # The HTTP handler owns the flight-recorder finish (it also
            # times the response write); it reads the degraded flag off
            # the future because the request object never crosses back.
            dst._photon_degraded = bool(getattr(req, "degraded", False))
            if sid is not None:
                try:
                    tracer().record(
                        "engine/score", time.monotonic() - t0,
                        parent="", context=ctx, span_id=sid,
                    )
                except Exception:
                    pass
            if exc is not None:
                dst.set_exception(exc)
            else:
                dst.set_result(dict(
                    score=f.result(),
                    # The engine records the generation that actually
                    # scored the request on it at flush time.
                    modelVersion=(
                        req.model_version or self.engine.model_version
                    ),
                ))

        src.add_done_callback(_done)
        return dst

    def stats(self) -> dict:
        return self.engine.stats()

    def metrics_text(self) -> str:
        return render_prometheus(
            registry().snapshot(), extra_labels={"replica": "frontend"}
        )

    def traces(self, limit: Optional[int] = None) -> List[dict]:
        return merge_trace_dumps(flight_recorder().traces(limit=limit))

    def reload(self, body: dict) -> dict:
        from photon_tpu.io.model_io import load_game_model

        model_dir = body.get("modelDir")
        if not model_dir:
            raise ValueError("reload needs {'modelDir': path}")
        # Index maps / entity indexes are generation-stable artifacts
        # (the training pipeline reuses them across model refreshes);
        # only the coefficient tables swap.
        model = load_game_model(
            model_dir, self.engine._index_maps, self.engine._entity_indexes,
            to_device=False,
        )
        return self.engine.reload(model, body.get("modelVersion") or model_dir)

    def feedback(self, body: dict) -> dict:
        return apply_feedback(self.engine, body)

    def experiment(self) -> dict:
        return experiment_rollup(self.engine)


class RemoteBackend:
    """Scorer access over the IPC channel — the worker deployment shape."""

    def __init__(self, client: ScorerClient, worker_index: int = 0,
                 result_timeout_s: float = 120.0):
        self.client = client
        self.worker_index = worker_index
        self.result_timeout_s = result_timeout_s

    def submit(
        self, raw_request: dict, tenant: Optional[str], priority: str,
        model_version: Optional[str] = None,
        trace: Optional[dict] = None,
    ) -> Future:
        return self.client.submit_score(
            raw_request, tenant, priority, model_version, trace=trace
        )

    def stats(self) -> dict:
        stats = self.client.call("stats", timeout_s=30.0)
        stats["worker"] = self.worker_index
        stats["workerPid"] = os.getpid()
        return stats

    def metrics_text(self) -> str:
        """Fleet-merged Prometheus text: the scorer's instruments (labeled
        ``replica="scorer"`` unless a producer already stamped a replica —
        fleet relays return per-replica labels) plus this worker's own."""
        remote: List[dict] = []
        try:
            remote = self.client.call("metrics", timeout_s=30.0) or []
        except Exception:
            registry().counter("frontend_scorer_scrape_errors_total").inc()
        snaps = [
            _stamp_labels(s, replica=f"worker{self.worker_index}")
            for s in registry().snapshot()
        ]
        snaps.extend(_stamp_labels(s, replica="scorer") for s in remote)
        return render_prometheus(snaps)

    def traces(self, limit: Optional[int] = None) -> List[dict]:
        """Kept traces merged by trace id across this worker and the
        scorer (and, behind a fleet relay, every replica) — one request's
        spans reassemble into one entry regardless of which process kept
        which hop."""
        local = flight_recorder().traces(limit=limit)
        try:
            remote = self.client.call("traces", timeout_s=30.0, limit=limit)
        except Exception:
            remote = []
        return merge_trace_dumps(local + (remote or []))

    def reload(self, body: dict) -> dict:
        # A reload builds + warms a whole generation; give it real time.
        return self.client.call(
            "reload", timeout_s=600.0,
            modelDir=body.get("modelDir"),
            modelVersion=body.get("modelVersion"),
        )

    def feedback(self, body: dict) -> dict:
        return self.client.call("feedback", timeout_s=30.0, body=body)

    def experiment(self) -> dict:
        return self.client.call("experiment", timeout_s=30.0)


def make_http_handler(backend):
    """The ONE endpoint implementation, parameterized by backend — local
    engine or remote scorer. Tenant comes from the ``X-Tenant`` header (or
    a per-request ``tenant`` field), priority from ``X-Priority`` /
    ``priority`` (``interactive`` default, ``batch`` for bulk callers),
    and a version pin from ``X-Model-Version`` / ``modelVersion`` —
    pinned requests score on that resident generation (400 on an unknown
    pin); unpinned requests follow the primary."""

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # Idle keep-alive connections release their thread after this, so
        # worker drain (server_close joins handler threads) can finish.
        timeout = 5.0

        def log_message(self, fmt, *args):  # route through logging
            logger.debug("http: " + fmt, *args)

        def _reply(self, code: int, payload: bytes, ctype="application/json"):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def _reply_json(self, code: int, obj) -> None:
            self._reply(code, (json.dumps(obj) + "\n").encode())

        def _body(self) -> bytes:
            length = int(self.headers.get("Content-Length", 0))
            return self.rfile.read(length)

        def _tenant_priority(self, obj: Optional[dict] = None):
            tenant = self.headers.get("X-Tenant")
            priority = self.headers.get("X-Priority")
            if isinstance(obj, dict):
                tenant = obj.get("tenant", tenant)
                priority = obj.get("priority", priority)
            priority = priority or INTERACTIVE
            if priority not in PRIORITIES:
                raise ValueError(
                    f"priority must be one of {PRIORITIES}, got {priority!r}"
                )
            return tenant, priority

        def _model_version(self, obj: Optional[dict] = None):
            version = self.headers.get("X-Model-Version")
            if isinstance(obj, dict):
                version = obj.get("modelVersion", version)
            return version

        def _query_int(self, key: str) -> Optional[int]:
            if "?" not in self.path:
                return None
            vals = parse_qs(self.path.split("?", 1)[1]).get(key)
            try:
                return int(vals[0]) if vals else None
            except (TypeError, ValueError):
                return None

        def do_GET(self):
            try:
                route = self.path.split("?", 1)[0]
                if route == "/healthz":
                    self._reply_json(200, backend.stats())
                elif route == "/metrics":
                    self._reply(
                        200, backend.metrics_text().encode(),
                        ctype=PROMETHEUS_CONTENT_TYPE,
                    )
                elif route == "/v1/traces":
                    self._reply_json(200, {
                        "traces": backend.traces(
                            limit=self._query_int("limit")
                        ),
                    })
                elif route == "/v1/experiment":
                    self._reply_json(200, backend.experiment())
                else:
                    self._reply_json(404, {"error": f"no route {self.path}"})
            except Exception as exc:  # noqa: BLE001 — classified below
                code, kind = classify_exception(exc)
                if code == 500:
                    logger.exception("request failed")
                self._reply_json(code, {"error": str(exc), "kind": kind})

        def do_POST(self):
            try:
                if self.path == "/v1/score":
                    self._score_one()
                elif self.path == "/v1/score-batch":
                    self._score_jsonl()
                elif self.path == "/v1/reload":
                    body = self._body()
                    info = backend.reload(json.loads(body) if body else {})
                    self._reply_json(200, info)
                elif self.path == "/v1/feedback":
                    body = self._body()
                    info = backend.feedback(json.loads(body) if body else {})
                    self._reply_json(200, info)
                else:
                    self._reply_json(404, {"error": f"no route {self.path}"})
            except Exception as exc:  # noqa: BLE001 — classified below
                code, kind = classify_exception(exc)
                if code == 500:
                    logger.exception("request failed")
                payload = {"error": str(exc), "kind": kind}
                tenant = getattr(exc, "tenant", None)
                if tenant is not None:
                    payload["tenant"] = tenant
                self._reply_json(code, payload)

        def _trace_context(self) -> TraceContext:
            """Adopt the client's ``traceparent`` (arrives forced — an
            explicit header is a request to SEE the trace) or mint a fresh
            tail-sampled root context."""
            ctx = TraceContext.from_traceparent(self.headers.get("traceparent"))
            return ctx if ctx is not None else mint_context()

        def _score_one(self):
            obj = json.loads(self._body())
            tenant, priority = self._tenant_priority(obj)
            ctx = self._trace_context()
            sid = new_span_id()
            t0 = time.monotonic()
            error: Optional[str] = None
            fut: Optional[Future] = None
            try:
                fut = backend.submit(
                    obj, tenant, priority, self._model_version(obj),
                    trace=ctx.child(sid).to_dict(),
                )
                res = fut.result(backend.result_timeout_s)
                self._reply_json(200, res)
            except Exception as exc:
                error = str(exc)
                raise
            finally:
                dt = time.monotonic() - t0
                try:
                    tracer().record(
                        "http/v1/score", dt, parent="",
                        context=ctx, span_id=sid,
                    )
                    flight_recorder().finish(
                        ctx.trace_id, dt, error=error,
                        degraded=bool(getattr(fut, "_photon_degraded", False)),
                        forced=ctx.forced,
                    )
                except Exception:
                    pass  # telemetry must never fail the response

        def _score_jsonl(self):
            tenant, priority = self._tenant_priority()
            version = self._model_version()
            ctx = self._trace_context()
            sid = new_span_id()
            down = ctx.child(sid).to_dict()
            t0 = time.monotonic()
            try:
                out = score_jsonl(
                    self._body(),
                    lambda obj: backend.submit(
                        obj, tenant, priority,
                        obj.get("modelVersion", version), trace=down,
                    ),
                    result_timeout_s=backend.result_timeout_s,
                )
                payload = "".join(json.dumps(o) + "\n" for o in out).encode()
                self._reply(200, payload, ctype="application/jsonl")
            finally:
                dt = time.monotonic() - t0
                try:
                    tracer().record(
                        "http/v1/score-batch", dt, parent="",
                        context=ctx, span_id=sid,
                    )
                    # Per-line failures answer in the body, so the batch
                    # itself finishes clean; a forced/slow batch still keeps.
                    flight_recorder().finish(
                        ctx.trace_id, dt, forced=ctx.forced
                    )
                except Exception:
                    pass

    return Handler


class _InheritedSocketHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer over an already-bound, already-listening socket
    (the pre-fork shared listener). ``daemon_threads=False`` +
    ``block_on_close`` makes ``server_close`` JOIN in-flight handler
    threads — that's the worker-side drain."""

    daemon_threads = False

    def __init__(self, sock: socket.socket, handler):
        socketserver.BaseServer.__init__(self, sock.getsockname()[:2], handler)
        self.socket = sock
        host, port = sock.getsockname()[:2]
        self.server_name = host
        self.server_port = port


def worker_main(
    listen_sock: socket.socket,
    scorer_path: str,
    worker_index: int,
    connect_timeout_s: float = 120.0,
) -> None:
    """Body of one forked HTTP worker. Blocks until SIGTERM/SIGINT, then
    drains in-flight requests and returns. Never imports jax."""
    client = ScorerClient(scorer_path, connect_timeout_s=connect_timeout_s)
    backend = RemoteBackend(client, worker_index=worker_index)
    server = _InheritedSocketHTTPServer(listen_sock, make_http_handler(backend))

    import signal as _signal

    def _stop(signum, frame):
        threading.Thread(target=server.shutdown, daemon=True).start()

    _signal.signal(_signal.SIGTERM, _stop)
    _signal.signal(_signal.SIGINT, _stop)
    logger.info("serve worker %d up (pid %d)", worker_index, os.getpid())
    try:
        server.serve_forever(poll_interval=0.1)
    finally:
        server.server_close()  # joins in-flight handler threads
        client.close()


# ---------------------------------------------------------------------------
# Parent-side orchestration
# ---------------------------------------------------------------------------


class ServingFrontend:
    """Pre-fork lifecycle for the multi-process deployment.

    Call order matters and is asserted: ``__init__`` (bind the shared
    listener) → ``fork_workers()`` (parent still single-threaded, NO jax
    yet) → build the engine → ``start_scorer(engine)`` → serve →
    ``shutdown()`` (SIGTERM workers first so admission stops, then drain
    the engine)."""

    def __init__(self, host: str, port: int, num_workers: int,
                 backlog: int = 128,
                 scorer_endpoint: Optional[str] = None):
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        self.num_workers = int(num_workers)
        self._listen_sock = socket.create_server(
            (host, port), backlog=backlog
        )
        self.host, self.port = self._listen_sock.getsockname()[:2]
        self._scorer_dir = tempfile.mkdtemp(prefix="photon-serve-")
        if scorer_endpoint is None:
            self.scorer_path = os.path.join(self._scorer_dir, "scorer.sock")
        else:
            fam = parse_endpoint(scorer_endpoint)[0]
            if fam == "tcp":
                # Workers fork (and start connecting) BEFORE the scorer
                # binds, so a tcp endpoint must name its port up front —
                # there is no post-bind channel to hand a kernel-assigned
                # port back to the children. The shared secret rides
                # $PHOTON_TPU_FLEET_SECRET (never argv: /proc/*/cmdline
                # is world-readable).
                if parse_endpoint(scorer_endpoint)[1][1] == 0:
                    raise ValueError(
                        "tcp scorer endpoints need an explicit port "
                        "(workers fork before the scorer binds)"
                    )
            self.scorer_path = scorer_endpoint
        self.pids: List[int] = []
        self._live: Dict[int, bool] = {}
        self.worker_exits: Dict[int, int] = {}
        self.scorer: Optional[ScorerServer] = None
        self._forked = False

    def fork_workers(self) -> None:
        """Fork the HTTP workers. MUST run before the parent touches jax
        (fork duplicates only the calling thread — a forked copy of an
        initialized backend inherits locked mutexes and dead threads)."""
        assert not self._forked, "workers already forked"
        self._forked = True
        for widx in range(self.num_workers):
            pid = os.fork()
            if pid == 0:
                code = 0
                try:
                    worker_main(self._listen_sock, self.scorer_path, widx)
                except BaseException:  # noqa: BLE001 — report, then die
                    traceback.print_exc()
                    code = 1
                finally:
                    os._exit(code)
            self.pids.append(pid)
            self._live[pid] = True
        self._listen_sock.close()  # only workers accept

    def start_scorer(self, engine) -> None:
        self.scorer = ScorerServer(engine, self.scorer_path)
        self.scorer.start()

    def poll_workers(self) -> List[int]:
        """Reap any workers that died; returns the pids reaped this call.
        A dead worker is logged and counted — the surviving workers keep
        accepting (the shared listener load-balances around the gap)."""
        from photon_tpu.obs.metrics import registry

        reaped = []
        for pid in self.pids:
            if not self._live.get(pid):
                continue
            try:
                done, status = os.waitpid(pid, os.WNOHANG)
            except ChildProcessError:
                done = pid
                status = 0
            if done == pid:
                self._live[pid] = False
                code = os.waitstatus_to_exitcode(status)
                self.worker_exits[pid] = code
                reaped.append(pid)
                registry().counter("serve_worker_exits_total").inc()
                logger.warning(
                    "serve worker pid %d exited with code %s "
                    "(%d/%d workers remain)",
                    pid, code, self.live_workers(), self.num_workers,
                )
        return reaped

    def live_workers(self) -> int:
        return sum(1 for alive in self._live.values() if alive)

    def shutdown(self, timeout_s: float = 15.0) -> Dict[int, int]:
        """Drain in order: workers first (no new admissions), then the IPC
        server, leaving the caller to drain the engine last."""
        from photon_tpu.utils.shutdown import terminate_children

        live = [pid for pid in self.pids if self._live.get(pid)]
        exits = terminate_children(live, timeout_s=timeout_s)
        for pid, code in exits.items():
            self._live[pid] = False
            self.worker_exits[pid] = code
        if self.scorer is not None:
            self.scorer.close()
        shutil.rmtree(self._scorer_dir, ignore_errors=True)
        return exits
