"""Per-tenant admission control for the serving front end.

Multi-tenant fairness is a policy problem, not a kernel problem (Snap ML's
lesson, PAPERS.md): a single abusive caller can destroy everyone's p99 long
before the scorer saturates. This module decides — BEFORE a request touches
the micro-batcher — whether a tenant may spend queue capacity, using two
orthogonal mechanisms layered on the existing
:class:`~photon_tpu.serve.batcher.BackpressureError` machinery:

1. **Token-bucket QPS quotas.** Each tenant owns a bucket refilled at
   ``qps`` tokens/s up to ``burst``; an empty bucket sheds the request with
   :class:`QuotaExceededError` (a ``BackpressureError`` subclass, so every
   existing 429 path keeps working unchanged while shed REASONS stay
   distinguishable in metrics).
2. **Priority classes.** ``interactive`` traffic may use the whole queue;
   ``batch`` traffic is admitted only while queue depth is below
   ``batch_queue_fraction`` of the cap, and the batcher may additionally
   preempt queued batch-class requests when an interactive submit finds the
   queue full — bulk backfill never starves latency-sensitive callers.

All state lives in the single scorer process (the front-end workers hold no
quota state), so quotas are globally consistent no matter how many HTTP
workers fan requests in. The clock is injectable for deterministic tests.

Telemetry: ``serve_tenant_requests_total{tenant,priority}``,
``serve_tenant_shed_total{tenant,reason}`` and
``serve_tenant_latency_s{tenant}`` flow through the obs/ registry and land
in the run report / ``/healthz`` snapshot.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, Optional

from photon_tpu.obs.metrics import registry
from photon_tpu.serve.batcher import BackpressureError

# Priority classes: plain strings on the wire (HTTP header / JSON field /
# IPC frame) and in the batcher, so no enum crosses process boundaries.
INTERACTIVE = "interactive"
BATCH = "batch"
PRIORITIES = (INTERACTIVE, BATCH)

DEFAULT_TENANT = "default"


class QuotaExceededError(BackpressureError):
    """The tenant exhausted its admission budget. Subclasses
    ``BackpressureError`` so the HTTP layer's existing 429 mapping applies;
    ``reason`` distinguishes quota sheds from capacity sheds in metrics."""

    def __init__(self, message: str, tenant: str, reason: str = "quota"):
        super().__init__(message)
        self.tenant = tenant
        self.reason = reason


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/s refill up to ``burst``
    capacity. Monotonic, injectable clock; thread-safe (one lock per
    tenant bucket — admission is cheap, contention is per-tenant)."""

    def __init__(
        self,
        rate: float,
        burst: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if rate <= 0:
            raise ValueError(f"token bucket rate must be > 0, got {rate}")
        self.rate = float(rate)
        self.burst = float(burst) if burst is not None else max(self.rate, 1.0)
        self._clock = clock
        self._tokens = self.burst
        self._last = clock()
        self._lock = threading.Lock()

    def try_acquire(self, n: float = 1.0) -> bool:
        with self._lock:
            now = self._clock()
            self._tokens = min(
                self.burst, self._tokens + (now - self._last) * self.rate
            )
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    @property
    def tokens(self) -> float:
        with self._lock:
            now = self._clock()
            return min(self.burst, self._tokens + (now - self._last) * self.rate)


def parse_tenant_rates(spec: Optional[str]) -> Dict[str, float]:
    """CLI helper: ``"tenantA=5,tenantB=250"`` → ``{"tenantA": 5.0, ...}``."""
    out: Dict[str, float] = {}
    if not spec:
        return out
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"tenant rate spec entry {part!r} must look like name=qps"
            )
        name, rate = part.split("=", 1)
        out[name.strip()] = float(rate)
    return out


@dataclasses.dataclass
class AdmissionConfig:
    """Quota policy. ``default_qps=None`` means unknown tenants are
    unlimited (quota-exempt) — quotas then apply only to tenants named in
    ``tenant_qps``. Burst defaults to ``max(qps, 1)`` per tenant."""

    default_qps: Optional[float] = None
    default_burst: Optional[float] = None
    tenant_qps: Dict[str, float] = dataclasses.field(default_factory=dict)
    tenant_burst: Dict[str, float] = dataclasses.field(default_factory=dict)
    batch_queue_fraction: float = 0.5  # batch admitted below this depth

    def enabled(self) -> bool:
        return self.default_qps is not None or bool(self.tenant_qps)


class AdmissionController:
    """Admission decisions + per-tenant accounting for one scorer process.

    ``admit`` raises :class:`QuotaExceededError` (→ HTTP 429) or returns
    None; it never blocks — shedding is an exception on the caller's
    thread, same discipline as the batcher's backpressure."""

    def __init__(
        self,
        config: Optional[AdmissionConfig] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.config = config or AdmissionConfig()
        self._clock = clock
        self._buckets: Dict[str, Optional[TokenBucket]] = {}
        self._lock = threading.Lock()
        self._admitted: Dict[str, int] = {}
        self._shed: Dict[str, int] = {}

    def _bucket(self, tenant: str) -> Optional[TokenBucket]:
        with self._lock:
            if tenant not in self._buckets:
                cfg = self.config
                rate = cfg.tenant_qps.get(tenant, cfg.default_qps)
                if rate is None:
                    self._buckets[tenant] = None  # quota-exempt
                else:
                    self._buckets[tenant] = TokenBucket(
                        rate,
                        cfg.tenant_burst.get(tenant, cfg.default_burst),
                        clock=self._clock,
                    )
            return self._buckets[tenant]

    def _record_shed(self, tenant: str, reason: str) -> None:
        registry().counter(
            "serve_tenant_shed_total", tenant=tenant, reason=reason
        ).inc()
        with self._lock:
            self._shed[tenant] = self._shed.get(tenant, 0) + 1

    def admit(
        self,
        tenant: Optional[str],
        priority: str = INTERACTIVE,
        queue_depth: int = 0,
        queue_cap: int = 0,
    ) -> None:
        """Charge one request against ``tenant``'s budget. Batch-class
        traffic is additionally refused while the queue is already
        ``batch_queue_fraction`` full — that headroom is reserved for
        interactive callers."""
        tenant = tenant or DEFAULT_TENANT
        registry().counter(
            "serve_tenant_requests_total", tenant=tenant, priority=priority
        ).inc()
        if (
            priority == BATCH
            and queue_cap > 0
            and queue_depth >= self.config.batch_queue_fraction * queue_cap
        ):
            self._record_shed(tenant, "batch_capacity")
            raise QuotaExceededError(
                f"batch-class request from tenant {tenant!r} shed: queue "
                f"depth {queue_depth} is past the batch admission share "
                f"({self.config.batch_queue_fraction:.0%} of {queue_cap})",
                tenant,
                reason="batch_capacity",
            )
        bucket = self._bucket(tenant)
        if bucket is not None and not bucket.try_acquire():
            self._record_shed(tenant, "quota")
            raise QuotaExceededError(
                f"tenant {tenant!r} exceeded its {bucket.rate:g} qps quota "
                f"(burst {bucket.burst:g}); request shed",
                tenant,
            )
        with self._lock:
            self._admitted[tenant] = self._admitted.get(tenant, 0) + 1

    def observe_latency(
        self,
        tenant: Optional[str],
        latency_s: float,
        trace_id: Optional[str] = None,
    ) -> None:
        # trace_id (traced requests only) becomes an OpenMetrics exemplar
        # on the series — the scrape-to-flight-recorder link.
        registry().histogram(
            "serve_tenant_latency_s", tenant=tenant or DEFAULT_TENANT
        ).observe(latency_s, trace_id=trace_id)

    def snapshot(self) -> Dict[str, Dict]:
        """Per-tenant admission state for ``/healthz`` and the soak bench."""
        with self._lock:
            tenants = set(self._admitted) | set(self._shed) | set(self._buckets)
            out = {}
            for t in sorted(tenants):
                bucket = self._buckets.get(t)
                out[t] = dict(
                    admitted=self._admitted.get(t, 0),
                    shed=self._shed.get(t, 0),
                    qps_limit=bucket.rate if bucket is not None else None,
                    burst=bucket.burst if bucket is not None else None,
                )
            return out


def tenant_quality(quality_snapshots) -> Dict[str, Dict]:
    """Reduce QualityPlane snapshots (one per scorer replica) to the
    per-tenant quality keys the admission ledger surfaces: count-weighted
    ``quality_auc`` / ``auc_lift`` across every (model_version, re_type)
    cell the tenant appears in. The frozen-baseline lane is excluded — it
    is the yardstick the lift is measured against, not a tenant's live
    quality."""
    agg: Dict[str, Dict] = {}
    for snap in quality_snapshots:
        if not isinstance(snap, dict):
            continue
        baseline = snap.get("baseline")
        for entry in snap.get("versions") or []:
            if baseline and entry.get("model_version") == baseline:
                continue
            tenant = entry.get("tenant") or DEFAULT_TENANT
            n = int(entry.get("count") or 0)
            if n <= 0:
                continue
            a = agg.setdefault(
                tenant,
                dict(n=0, auc_w=0.0, auc_n=0, lift_w=0.0, lift_n=0),
            )
            a["n"] += n
            auc = entry.get("auc")
            if auc is not None:
                a["auc_w"] += float(auc) * n
                a["auc_n"] += n
            lift = entry.get("auc_lift")
            if lift is not None:
                a["lift_w"] += float(lift) * n
                a["lift_n"] += n
    out: Dict[str, Dict] = {}
    for tenant, a in agg.items():
        rec: Dict = dict(observations=a["n"])
        if a["auc_n"]:
            rec["quality_auc"] = round(a["auc_w"] / a["auc_n"], 6)
        if a["lift_n"]:
            rec["auc_lift"] = round(a["lift_w"] / a["lift_n"], 6)
        out[tenant] = rec
    return out


class FleetAdmissionLedger(AdmissionController):
    """Fleet-global admission: ONE token-bucket ledger for the whole scorer
    fleet, living in the routing front end (single-coordinator model — the
    frontend already sees every request, so the coordinator is free; no
    gossip protocol to converge or partition).

    Replica engines run with admission DISABLED (default unlimited config),
    so a tenant's budget is charged exactly once fleet-wide — an abusive
    tenant is shed identically whether the fleet has 1 replica or 50, which
    is the ISSUE's "fleet-wide shed counts match single-process admission
    semantics" bar.

    On top of the inherited quota/priority machinery this ledger tracks
    per-replica in-flight counts (begin/end around each routed request) —
    the router's least-loaded tiebreak for entity-less requests and the
    drain discipline's "replica is idle" signal.
    """

    def __init__(
        self,
        config: Optional[AdmissionConfig] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        super().__init__(config=config, clock=clock)
        self._inflight: Dict[str, int] = {}
        self._quality: Dict[str, Dict] = {}

    def begin(self, replica_id: str) -> None:
        with self._lock:
            self._inflight[replica_id] = self._inflight.get(replica_id, 0) + 1

    def end(self, replica_id: str) -> None:
        with self._lock:
            n = self._inflight.get(replica_id, 0) - 1
            if n <= 0:
                self._inflight.pop(replica_id, None)
            else:
                self._inflight[replica_id] = n

    def inflight(self, replica_id: Optional[str] = None) -> int:
        with self._lock:
            if replica_id is not None:
                return self._inflight.get(replica_id, 0)
            return sum(self._inflight.values())

    def update_quality(self, per_tenant: Optional[Dict[str, Dict]]) -> None:
        """Install the latest per-tenant quality rollup (see
        :func:`tenant_quality`); merged into :meth:`snapshot` so the fleet
        ``/healthz`` tenants block reports admission AND model quality for
        each caller side by side."""
        with self._lock:
            self._quality = {
                str(t): dict(v) for t, v in (per_tenant or {}).items()
            }

    def snapshot(self) -> Dict[str, Dict]:
        out = super().snapshot()
        with self._lock:
            quality = {t: dict(v) for t, v in self._quality.items()}
        for tenant, rec in quality.items():
            out.setdefault(
                tenant,
                dict(admitted=0, shed=0, qps_limit=None, burst=None),
            ).update(rec)
        return out

    def fleet_snapshot(self) -> Dict:
        """Tenant quota state + per-replica in-flight depth for the fleet
        ``/healthz`` block."""
        with self._lock:
            inflight = dict(self._inflight)
        return dict(tenants=self.snapshot(), inflight=inflight)
