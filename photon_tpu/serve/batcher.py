"""Bounded micro-batching queue for online GAME scoring.

The serving engine's admission layer, shaped by the hierarchical-batching
lesson of Snap ML (PAPERS.md) and this repo's single-compile dispatch
discipline: requests queue on their caller threads, one flusher thread
drains them into micro-batches that flush on MAX-BATCH-SIZE or DEADLINE
(whichever first), and every batch's row count pads UP the shared
``bucket_dim`` shape grid (data/padding.py) so the jitted scorer dispatches
on a handful of warmed program shapes — zero retraces after warm-up.

Load shedding is explicit, not implicit: when queue depth would exceed
``queue_cap``, ``submit`` raises :class:`BackpressureError` on the CALLER's
thread immediately (counted in ``serve_requests_shed_total``) instead of
letting latency collapse for everyone already queued. Per-request deadlines
are honored at flush time: a request whose deadline passed while queued
fails with :class:`DeadlineExceededError` without spending scorer time.

Threading contract: ``submit`` is thread-safe (any number of front-end
threads); scoring runs ONLY on the flusher thread via the ``score_fn``
callback, which therefore needs no internal locking against other batches.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Sequence

from photon_tpu.obs.metrics import registry
from photon_tpu.obs.trace import tracer
from photon_tpu.utils import resources


class BackpressureError(RuntimeError):
    """Queue depth exceeded the cap — the caller should back off/retry.
    Raised at submit time so shed cost is one exception, not a queued
    request that times out later."""


class DeadlineExceededError(RuntimeError):
    """The request's deadline passed before its batch reached the scorer."""


@dataclasses.dataclass
class ScoreRequest:
    """One scoring request. ``features`` maps feature-shard name → a dense
    (d,) float vector, a {column: value} dict, or an (indices, values)
    pair — the batcher densifies rows host-side (serving shards are the
    model's own dims). ``entity_ids`` maps RE type → interned int or raw
    string id (resolved through the store's EntityIndex)."""

    features: Dict[str, object]
    entity_ids: Dict[str, object] = dataclasses.field(default_factory=dict)
    offset: float = 0.0
    uid: Optional[object] = None
    # Version pin: None scores on the engine's primary generation; a set
    # value is resolved (exact key or basename) against the resident
    # versions at submit time — unknown pins raise there, on the caller's
    # thread, never inside a batch. After scoring the engine overwrites
    # this with the generation that ACTUALLY produced the score (the
    # primary for unpinned requests, or on a pin-evicted fallback), so
    # response labels are always truthful.
    model_version: Optional[str] = None
    # Set by ServingEngine.submit from its ``tenant`` argument: rides along
    # so the feedback spool can apply per-tenant sampling fractions.
    tenant: Optional[str] = None
    # Cross-process trace context (TraceContext.to_dict() shape), stamped
    # by whichever frontend admitted the request: the engine hands it to
    # downstream hops (fleet replicas) and to the feedback spool so a
    # micro-generation can name the requests that fed it.
    trace: Optional[dict] = None
    # Set by the engine when this request's score was produced under a
    # degraded path (breaker-open FE-only resolve, pin-eviction fallback):
    # the flight recorder keeps such requests' span trees.
    degraded: bool = False


@dataclasses.dataclass
class _Pending:
    request: ScoreRequest
    future: Future
    enqueue_t: float
    deadline_t: Optional[float]
    priority: str = "interactive"


class MicroBatcher:
    """Flush-on-size-or-deadline micro-batcher with bounded admission.

    ``score_fn(requests) -> sequence of float scores`` runs on the flusher
    thread; its exceptions fail that batch's futures only — the batcher
    keeps serving subsequent batches.
    """

    def __init__(
        self,
        score_fn: Callable[[List[ScoreRequest]], Sequence[float]],
        max_batch_size: int = 64,
        max_delay_s: float = 0.002,
        queue_cap: int = 1024,
        name: str = "serve",
    ):
        if max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        self._score_fn = score_fn
        self.max_batch_size = int(max_batch_size)
        self.max_delay_s = float(max_delay_s)
        self.queue_cap = int(queue_cap)
        self.name = name
        self._pending: deque = deque()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False
        self._in_flight = 0
        self._thread = threading.Thread(
            target=self._flush_loop, name=f"photon-{name}-flush", daemon=True
        )
        self._thread.start()

    # -- producer side -----------------------------------------------------

    def submit(
        self,
        request: ScoreRequest,
        deadline_s: Optional[float] = None,
        priority: str = "interactive",
    ) -> Future:
        """Enqueue one request; returns a Future resolving to its float
        score. ``deadline_s`` is a relative budget (seconds from now)
        covering queue wait + scoring. ``priority`` is the admission class:
        when the queue is at cap, an interactive submit PREEMPTS the
        newest queued batch-class request (which fails with
        ``BackpressureError``) instead of being shed itself — bulk
        backfill yields capacity to latency-sensitive traffic."""
        reg = registry()
        now = time.monotonic()
        fut: Future = Future()
        victim: Optional[_Pending] = None
        # Host memory pressure tightens the admission cap (half at soft,
        # quarter at hard): each queued request pins host buffers, and
        # shedding by backpressure beats dying by OOM-killer.
        cap = resources.tightened_cap(self.queue_cap)
        with self._cond:
            if self._closed:
                raise RuntimeError(f"batcher {self.name!r} is closed")
            if len(self._pending) >= cap:
                if priority != "batch":
                    for i in range(len(self._pending) - 1, -1, -1):
                        if self._pending[i].priority == "batch":
                            victim = self._pending[i]
                            del self._pending[i]
                            reg.counter(
                                "serve_requests_preempted_total"
                            ).inc()
                            break
                if victim is None:
                    reg.counter("serve_requests_shed_total").inc()
                    raise BackpressureError(
                        f"serve queue depth {len(self._pending)} at cap "
                        f"{cap}; request shed"
                    )
            self._pending.append(
                _Pending(
                    request,
                    fut,
                    now,
                    None if deadline_s is None else now + float(deadline_s),
                    priority,
                )
            )
            reg.counter("serve_requests_total").inc()
            self._cond.notify_all()
        if victim is not None:
            # Outside the lock: done-callbacks run inline on set_exception.
            victim.future.set_exception(
                BackpressureError(
                    "batch-class request preempted by interactive traffic "
                    "at full queue; retry with backoff"
                )
            )
        return fut

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._pending)

    # -- flusher -----------------------------------------------------------

    def _flush_loop(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._closed:
                    self._cond.wait(0.1)
                if self._closed and not self._pending:
                    return
                # Fill-or-deadline: wait for a full batch, but never hold
                # the oldest request past max_delay.
                while (
                    len(self._pending) < self.max_batch_size
                    and not self._closed
                ):
                    remaining = self.max_delay_s - (
                        time.monotonic() - self._pending[0].enqueue_t
                    )
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                batch = [
                    self._pending.popleft()
                    for _ in range(
                        min(len(self._pending), self.max_batch_size)
                    )
                ]
                self._in_flight = len(batch)
            try:
                self._run_batch(batch)
            finally:
                with self._cond:
                    self._in_flight = 0
                    self._cond.notify_all()

    def _run_batch(self, batch: List[_Pending]) -> None:
        reg = registry()
        now = time.monotonic()
        live: List[_Pending] = []
        for p in batch:
            if p.deadline_t is not None and now > p.deadline_t:
                reg.counter("serve_deadline_missed_total").inc()
                p.future.set_exception(
                    DeadlineExceededError(
                        f"deadline passed {now - p.deadline_t:.4f}s before "
                        "scoring"
                    )
                )
            else:
                live.append(p)
        if not live:
            return
        with tracer().span(f"{self.name}/batch"):
            for p in live:
                reg.histogram("serve_queue_wait_s").observe(now - p.enqueue_t)
            try:
                scores = self._score_fn([p.request for p in live])
            except BaseException as exc:  # noqa: BLE001 — fail THIS batch only
                for p in live:
                    if not p.future.done():
                        p.future.set_exception(exc)
                return
            with tracer().span("respond"):
                done_t = time.monotonic()
                for p, s in zip(live, scores):
                    reg.histogram("serve_request_latency_s").observe(
                        done_t - p.enqueue_t
                    )
                    p.future.set_result(float(s))
        reg.histogram("serve_batch_rows").observe(len(live))
        reg.counter("serve_batches_total").inc()
        reg.gauge("serve_batch_fill").set(len(live) / self.max_batch_size)

    # -- lifecycle ---------------------------------------------------------

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Block until the queue is empty and no batch is in flight."""
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while self._pending or self._in_flight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
        return True

    def close(self, drain: bool = True) -> None:
        """Stop accepting requests; by default score out what's queued."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            if not drain:
                while self._pending:
                    p = self._pending.popleft()
                    p.future.set_exception(
                        RuntimeError(f"batcher {self.name!r} closed")
                    )
            self._cond.notify_all()
        self._thread.join(timeout=30.0)
