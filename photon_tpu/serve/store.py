"""Hot/cold entity coefficient store for online GAME scoring.

Photon ML's GAME shape — one global model plus millions of per-entity
models — makes serving a lookup-then-score problem. The lookup side is this
module: per-entity coefficient rows live COLD on the host (the numpy master
copy ``load_game_model(to_device=False)`` returns) and HOT in a
device-resident table under an explicit byte budget, with LRU demotion.
Request entity ids resolve to hot-table SLOTS; misses gather their rows from
the host master and upload them in one shape-bucketed scatter per batch, so
the device never holds more than the working set and the jitted scorer's
program shapes never change.

Slot discipline: coordinates sharing a random-effect type share ONE slot
assignment (their tables are indexed by the same ``entity_ids`` array in the
batch), so the LRU is per RE type with one device table per coordinate.
A type whose full table fits the budget is PINNED — full device residency,
entity ids pass through as slots, the miss path never runs. Unknown/cold
entities resolve to slot -1 and score 0, exactly the batch path's
cold-start semantics.

Projected (subspace) random-effect models get the same treatment at BLOCK
granularity: each per-block subspace table keeps a hot row pool, and the
device-resident ``entity_block``/``entity_row`` maps are rewritten by
scatter as entities promote and demote (a demoted entity's map entry goes
to -1 — it can never be read for a requested entity, because ``resolve``
promotes every entity of the batch before the scorer runs). Entity ids pass
through as indices for projected types either way, pinned or not.

The LRU policy itself (recency order, in-use protection, demotion
accounting) lives in data/residency.py — shared verbatim with the
out-of-core TRAINING store (algorithm/re_store.py), so serving and training
cannot drift on residency semantics.

Zero-downtime reload builds a NEW store (and scorer) for the incoming model
while the old one keeps serving, then swaps atomically — see
serve/engine.py. The store itself is single-writer: the engine serializes
``resolve``/upload under its batch lock.
"""

from __future__ import annotations

import base64
import dataclasses
import logging
from typing import Dict, List, Optional, Sequence

import numpy as np

from photon_tpu.data.random_effect import bucket_dim
from photon_tpu.data.residency import SlotLru
from photon_tpu.models.coefficients import Coefficients
from photon_tpu.models.game import (
    FixedEffectModel,
    GameModel,
    ProjectedRandomEffectModel,
    RandomEffectModel,
)
from photon_tpu.models.glm import GeneralizedLinearModel
from photon_tpu.obs.metrics import registry
from photon_tpu.serve.routing import HashRing
from photon_tpu.utils import faults, resources

logger = logging.getLogger("photon_tpu")

_scatter_rows = None

_SHARD_AXIS = "data"  # mesh axis name for device-sharded hot tables


@dataclasses.dataclass
class StorePartition:
    """Entity-shard ownership for ONE fleet replica: this store serves only
    the entities the consistent-hash ring assigns ``replica_id`` (the same
    ring the front-end router uses, so a correctly routed request always
    lands on the owner). A non-owned (foreign) entity resolves to -1 —
    cold-start semantics, the random effect contributes 0 and the request
    scores FE-only on already-compiled shapes. That is the fleet's
    cross-shard fallback: mis-routed and orphaned entities degrade, never
    error.

    ``compact_host=True`` additionally shards the OOC host master by the
    same hash (``algorithm/re_store.py``-style keying, at entity-row
    granularity): only owned rows are kept host-side, so N replicas hold
    ~1/N of the coefficient bytes each. The trade: an entity that becomes
    owned AFTER build (ring rebalance toward this replica) has no host row
    and stays FE-only until the engine rebuilds the store (reload) — the
    documented re-home procedure.

    ``re_types=None`` shards every budget-managed type; the fleet normally
    passes just the routing RE type so secondary types stay fully
    replicated (exact scores on every replica). Pinned types (full device
    residency — they fit the budget) are never sharded: replicating a
    small table is cheaper than degrading its lookups."""

    replica_id: str
    ring: HashRing
    re_types: Optional[tuple] = None
    compact_host: bool = True

    def applies_to(self, re_type: str) -> bool:
        return self.re_types is None or re_type in self.re_types

    def owns(self, key) -> bool:
        return self.ring.owner(str(key)) == self.replica_id


def _owned_mask(
    partition: StorePartition, entity_index, num_entities: int
) -> np.ndarray:
    """(E,) bool: which dense entity indices this replica owns. Hashes the
    SAME string the router hashes — the raw entity id via the entity index
    when one exists, else the decimal index (callers that send pre-interned
    int keys route on that same decimal form)."""
    owned = np.zeros(num_entities, bool)
    for i in range(num_entities):
        key = entity_index.entity_id(i) if entity_index is not None else i
        owned[i] = partition.owns(key)
    return owned


def _oom_contained(re_type: str, fn):
    """Run a device scatter/upload with OOM containment: on
    RESOURCE_EXHAUSTED, release dropped table buffers (the scatters are
    functional — the superseded tables are garbage the allocator may still
    hold) and retry once, counting
    ``serve_store_oom_evictions_total{re_type}``. ``fn`` must be
    idempotent. A second OOM becomes a clean
    :class:`~photon_tpu.utils.resources.DeviceMemoryError`."""
    import gc

    try:
        return fn()
    except Exception as exc:
        if not resources.is_device_oom(exc):
            raise
        registry().counter(
            "serve_store_oom_evictions_total", re_type=re_type
        ).inc()
        logger.warning(
            "serve store: device OOM uploading %s rows; collecting dropped "
            "buffers and retrying once: %s", re_type, exc,
        )
        gc.collect()
        try:
            return fn()
        except Exception as exc2:
            if not resources.is_device_oom(exc2):
                raise
            raise resources.DeviceMemoryError(
                f"serve store: device OOM uploading {re_type} rows even "
                "after releasing dropped buffers. Shrink --hot-bytes / the "
                "hot-row capacity or the max batch size, or add device "
                "memory."
            ) from exc2


def _scatter(table, idx, rows):
    """Jitted hot-table row upload. ``idx`` is padded to a bucketed length
    with an out-of-range value (``mode="drop"`` discards it — NB negative
    indices WRAP in XLA scatters, so high-out-of-range is the safe filler).
    One executable per shape triple; ``warm_uploads`` compiles them before
    traffic. Shared by 2-D coefficient-table and 1-D entity-map scatters."""
    global _scatter_rows
    if _scatter_rows is None:
        import jax

        # NOT donated: the previous table buffer may still be referenced by
        # a scoring-model pytree a caller holds (e.g. the transformer's
        # init-time model) — donating it would invalidate those references.
        _scatter_rows = jax.jit(lambda t, i, r: t.at[i].set(r, mode="drop"))
    return _scatter_rows(table, idx, rows)


@dataclasses.dataclass
class _ReGroup:
    """All random-effect coordinates sharing one RE type: one slot LRU,
    one device table per coordinate."""

    re_type: str
    coord_ids: List[str]
    host_coefs: Dict[str, np.ndarray]  # cid -> (E, d) float32 master copy
    num_entities: int
    capacity: int  # H: hot rows (== num_entities when pinned)
    pinned: bool
    tables: Dict[str, object] = dataclasses.field(default_factory=dict)
    lru: Optional[SlotLru] = None
    # Fleet partition state: ``owned[i]`` is this replica's ownership of
    # dense entity i (None = unsharded type); ``compact_of[i]`` maps a full
    # entity index to its compacted host row (-1 = row absent host-side).
    owned: Optional[np.ndarray] = None
    compact_of: Optional[np.ndarray] = None
    # Device-shard state (multi-chip serving): the hot table is laid out as
    # S contiguous per-shard segments of ``shard_cap`` rows, sharded over
    # the device mesh's data axis so each segment is resident on the device
    # the training side trained it on (parallel/entity_shard.py — the same
    # plan, same ring, same hashed keys). Pinned groups address the table
    # through ``perm`` (entity → shard-grouped slot); unpinned groups run
    # one SlotLru per segment (``shard_lrus``) over disjoint slot ranges.
    shard_plan: Optional[object] = None
    shard_cap: Optional[int] = None
    perm: Optional[np.ndarray] = None  # pinned: (E,) entity -> slot
    shard_lrus: Optional[List[SlotLru]] = None

    @property
    def row_bytes(self) -> int:
        return sum(4 * c.shape[1] for c in self.host_coefs.values())

    def _lru_for(self, entity: int) -> SlotLru:
        if self.shard_lrus is not None:
            return self.shard_lrus[int(self.shard_plan.shard_of[entity])]
        return self.lru

    def slot_get(self, entity: int) -> Optional[int]:
        return self._lru_for(entity).get(entity)

    def slot_peek(self, entity: int) -> Optional[int]:
        return self._lru_for(entity).peek(entity)

    def slot_claim(self, entity: int, protected) -> int:
        return self._lru_for(entity).claim(entity, protected)

    def resident_count(self) -> int:
        if self.pinned:
            return self.num_entities
        if self.shard_lrus is not None:
            return sum(len(l) for l in self.shard_lrus)
        return len(self.lru)


@dataclasses.dataclass
class _ProjCoord:
    """One projected coordinate's hot state: per-block hot tables + the
    device entity→(block, row) maps the scorer gathers through."""

    cid: str
    sub: ProjectedRandomEffectModel  # host master (block_coefs as numpy)
    host_blocks: List[np.ndarray]  # [(E_b, d_b) float32]
    entity_block: np.ndarray  # (E,) host master map
    entity_row: np.ndarray  # (E,)
    capacities: List[int]  # hot rows per block
    lrus: List[Optional[SlotLru]]  # entity id -> hot row, per block
    tables: List[object]  # device [(H_b, d_b)]
    dev_entity_block: object  # device (E,) int32; -1 = cold (scores 0)
    dev_entity_row: object  # device (E,) int32
    demoted: List[int] = dataclasses.field(default_factory=list)

    @property
    def hot_bytes(self) -> int:
        return sum(
            4 * h * b.shape[1] for h, b in zip(self.capacities, self.host_blocks)
        )


@dataclasses.dataclass
class _ProjGroup:
    """Projected coordinates sharing one RE type. Unlike dense groups they
    need no shared slot space: ``resolve`` returns entity INDICES (the
    per-coordinate device maps translate entity → hot row), so each
    coordinate promotes into its own block tables independently."""

    re_type: str
    num_entities: int
    coords: List[_ProjCoord]
    pinned: bool  # every coordinate fully resident → no promotion path
    owned: Optional[np.ndarray] = None  # fleet partition mask (no compaction)


class HotColdEntityStore:
    """Entity-model residency manager + scoring-model factory.

    ``hot_bytes`` bounds the device bytes of CACHED random-effect tables
    (split across RE types proportionally to their full size). The floor is
    ``min_hot_rows`` per type — the engine passes its max batch size, which
    guarantees every unique entity of one batch fits resident simultaneously
    (the resolve path never has to evict a slot the current batch needs).
    """

    def __init__(
        self,
        model: GameModel,
        entity_indexes: Optional[Dict] = None,
        hot_bytes: int = 64 << 20,
        min_hot_rows: int = 64,
        partition: Optional[StorePartition] = None,
        device_shards: Optional[int] = None,
    ):
        import jax

        self._entity_indexes = dict(entity_indexes or {})
        self._partition = partition
        # Multi-chip mode: split every dense hot table into ``device_shards``
        # entity shards (consistent-hash plan shared with training) and lay
        # them out over the device mesh's data axis. The mesh spans the
        # largest device count that divides the shard count, so per-shard
        # segments chunk evenly; a single-device backend degrades to S
        # segments on one chip (same slot discipline, no mesh surprises).
        self._device_shards: Optional[int] = None
        self._mesh = None
        self._table_sharding = None
        self._replicated_sharding = None
        if device_shards:
            from jax.sharding import Mesh, NamedSharding, PartitionSpec

            S = int(device_shards)
            devs = jax.devices()
            n_use = max(
                k for k in range(1, min(S, len(devs)) + 1) if S % k == 0
            )
            self._device_shards = S
            self._mesh = Mesh(
                np.asarray(devs[:n_use]), (_SHARD_AXIS,)
            )
            self._table_sharding = NamedSharding(
                self._mesh, PartitionSpec(_SHARD_AXIS)
            )
            self._replicated_sharding = NamedSharding(
                self._mesh, PartitionSpec()
            )
        self._groups: Dict[str, _ReGroup] = {}
        self._proj_groups: Dict[str, _ProjGroup] = {}
        self._re_subs: Dict[str, RandomEffectModel] = {}
        base: Dict[str, object] = {}

        by_type: Dict[str, List] = {}
        proj_by_type: Dict[str, List] = {}
        for cid, sub in model.models.items():
            if isinstance(sub, RandomEffectModel):
                by_type.setdefault(sub.re_type, []).append((cid, sub))
            elif isinstance(sub, ProjectedRandomEffectModel):
                proj_by_type.setdefault(sub.re_type, []).append((cid, sub))
            else:
                base[cid] = jax.device_put(sub)

        # One budget pool across dense AND projected types, split
        # proportionally to each type's full table size.
        budget_total = sum(
            sum(4 * np.asarray(s.coefficients).shape[1] for _, s in subs)
            * max(np.asarray(subs[0][1].coefficients).shape[0], 1)
            for subs in by_type.values()
        ) + sum(
            sum(self._proj_full_bytes(s) for _, s in subs)
            for subs in proj_by_type.values()
        )
        reg = registry()
        for re_type, subs in by_type.items():
            host = {
                cid: np.ascontiguousarray(
                    np.asarray(s.coefficients, dtype=np.float32)
                )
                for cid, s in subs
            }
            E = {c.shape[0] for c in host.values()}
            if len(E) != 1:
                raise ValueError(
                    f"RE type {re_type!r}: coordinates disagree on entity "
                    f"count {sorted(E)}"
                )
            E = E.pop()
            row_bytes = sum(4 * c.shape[1] for c in host.values())
            full_bytes = row_bytes * max(E, 1)
            share = (
                int(hot_bytes * full_bytes / budget_total)
                if budget_total
                else hot_bytes
            )
            cap = max(int(min_hot_rows), share // max(row_bytes, 1))
            pinned = cap >= E
            cap = min(cap, E) if pinned else cap
            owned = None
            compact_of = None
            # Partition applies only to budget-managed (unpinned) types: a
            # pinned table is fully resident everywhere, so sharding it
            # would degrade lookups to save nothing.
            if partition is not None and partition.applies_to(re_type) \
                    and not pinned:
                owned = _owned_mask(
                    partition, self._entity_indexes.get(re_type), E
                )
                owned_count = int(owned.sum())
                # The shard, not the full table, is this replica's working
                # set: capacity beyond the owned count would never fill.
                cap = max(int(min_hot_rows), min(cap, max(owned_count, 1)))
                if partition.compact_host:
                    sel = np.flatnonzero(owned)
                    compact_of = np.full(E, -1, np.int32)
                    compact_of[sel] = np.arange(sel.size, dtype=np.int32)
                    host = {
                        cid: np.ascontiguousarray(host[cid][sel])
                        for cid in host
                    }
                reg.gauge(
                    "serve_store_owned_entities", re_type=re_type
                ).set(owned_count)
            shard_plan = None
            shard_cap = None
            perm = None
            shard_lrus = None
            if self._device_shards:
                from photon_tpu.parallel.entity_shard import build_shard_plan

                shard_plan = build_shard_plan(
                    E,
                    self._device_shards,
                    entity_index=self._entity_indexes.get(re_type),
                )
                S = shard_plan.n_shards
                if pinned:
                    # Shard-grouped full residency: segment s holds shard
                    # s's entities at their local indices, padded to the
                    # largest shard so segments chunk evenly over the mesh.
                    shard_cap = max(int(shard_plan.counts.max()), 1)
                    cap = S * shard_cap
                    perm = (
                        shard_plan.shard_of.astype(np.int64) * shard_cap
                        + shard_plan.local_of
                    ).astype(np.int32)
                else:
                    # Budget split evenly across segments, floored at
                    # min_hot_rows EACH: one batch's entities may all hash
                    # to a single shard, and its segment alone must hold
                    # them resident simultaneously.
                    shard_cap = max(int(min_hot_rows), cap // S)
                    cap = S * shard_cap
                    shard_lrus = [
                        SlotLru(
                            shard_cap,
                            on_demote=self._demote_counter(re_type),
                            base=s * shard_cap,
                        )
                        for s in range(S)
                    ]
            group = _ReGroup(
                re_type=re_type,
                coord_ids=[cid for cid, _ in subs],
                host_coefs=host,
                num_entities=E,
                capacity=max(cap, 1),
                pinned=pinned,
                owned=owned,
                compact_of=compact_of,
                shard_plan=shard_plan,
                shard_cap=shard_cap,
                perm=perm,
                shard_lrus=shard_lrus,
            )
            if pinned:
                if perm is not None:
                    tabs = {}
                    for cid in group.coord_ids:
                        t = np.zeros(
                            (group.capacity, host[cid].shape[1]), np.float32
                        )
                        t[perm] = host[cid]
                        tabs[cid] = jax.device_put(t, self._table_sharding)
                    group.tables = tabs
                else:
                    group.tables = {
                        cid: jax.device_put(host[cid])
                        for cid in group.coord_ids
                    }
            else:
                group.tables = {
                    cid: jax.device_put(
                        np.zeros(
                            (group.capacity, host[cid].shape[1]), np.float32
                        ),
                        self._table_sharding,
                    )
                    for cid in group.coord_ids
                }
                if shard_lrus is None:
                    group.lru = SlotLru(
                        group.capacity, on_demote=self._demote_counter(re_type)
                    )
            self._groups[re_type] = group
            for cid, s in subs:
                self._re_subs[cid] = s
            reg.gauge("serve_store_hot_rows", re_type=re_type).set(
                group.capacity
            )
            reg.gauge("serve_store_hot_bytes", re_type=re_type).set(
                group.capacity * row_bytes
            )
            reg.gauge("serve_store_pinned", re_type=re_type).set(int(pinned))
        for re_type, subs in proj_by_type.items():
            group = self._build_proj_group(
                re_type, subs, hot_bytes, budget_total, min_hot_rows
            )
            # Projected types shard by predicate only (foreign → -1); their
            # block-structured host masters stay whole — block compaction
            # would need a remap per block and buys little (the maps are
            # int32, the blocks are small by construction).
            if partition is not None and partition.applies_to(re_type) \
                    and not group.pinned:
                group.owned = _owned_mask(
                    partition,
                    self._entity_indexes.get(re_type),
                    group.num_entities,
                )
            self._proj_groups[re_type] = group
            hot = sum(c.hot_bytes for c in group.coords)
            reg.gauge("serve_store_hot_rows", re_type=re_type).set(
                sum(sum(c.capacities) for c in group.coords)
            )
            reg.gauge("serve_store_hot_bytes", re_type=re_type).set(hot)
            reg.gauge("serve_store_pinned", re_type=re_type).set(
                int(group.pinned)
            )
        self._base = base

    @staticmethod
    def _proj_full_bytes(sub: ProjectedRandomEffectModel) -> int:
        return sum(
            4 * np.asarray(b).shape[0] * np.asarray(b).shape[1]
            for b in sub.block_coefs
        )

    def _demote_counter(self, re_type: str):
        def on_demote(_victim, _slot):
            registry().counter(
                "serve_store_demotions_total", re_type=re_type
            ).inc()

        return on_demote

    def _build_proj_group(
        self, re_type, subs, hot_bytes, budget_total, min_hot_rows
    ) -> _ProjGroup:
        """Per-block hot/cold state for projected coordinates. Budget share
        splits across a coordinate's blocks proportionally to block size,
        floored at ``min_hot_rows`` rows per block — any one batch's
        entities may all land in one block, so every block must be able to
        hold a full batch's worth of hot rows simultaneously."""
        import jax

        coords: List[_ProjCoord] = []
        num_entities = 0
        for cid, sub in subs:
            host_blocks = [
                np.ascontiguousarray(np.asarray(b, dtype=np.float32))
                for b in sub.block_coefs
            ]
            entity_block = np.asarray(sub.entity_block, np.int32)
            entity_row = np.asarray(sub.entity_row, np.int32)
            E = int(entity_block.shape[0])
            num_entities = max(num_entities, E)
            full_bytes = sum(4 * b.shape[0] * b.shape[1] for b in host_blocks)
            share = (
                int(hot_bytes * full_bytes / budget_total)
                if budget_total
                else hot_bytes
            )
            capacities: List[int] = []
            for b in host_blocks:
                b_bytes = 4 * b.shape[0] * max(b.shape[1], 1)
                b_share = (
                    int(share * b_bytes / full_bytes) if full_bytes else share
                )
                cap = max(
                    int(min_hot_rows), b_share // max(4 * b.shape[1], 1)
                )
                capacities.append(max(min(cap, b.shape[0]), 1))
            pinned = all(
                c >= b.shape[0] for c, b in zip(capacities, host_blocks)
            )
            demoted: List[int] = []
            if pinned:
                capacities = [b.shape[0] for b in host_blocks]
                tables = [jax.device_put(b) for b in host_blocks]
                lrus: List[Optional[SlotLru]] = [None] * len(host_blocks)
                dev_entity_block = jax.device_put(entity_block)
                dev_entity_row = jax.device_put(entity_row)
            else:
                tables = [
                    jax.device_put(np.zeros((c, b.shape[1]), np.float32))
                    for c, b in zip(capacities, host_blocks)
                ]
                demote = self._proj_demoter(re_type, demoted)
                lrus = [SlotLru(c, on_demote=demote) for c in capacities]
                # Everything starts COLD: map entries are -1 until promoted.
                dev_entity_block = jax.device_put(
                    np.full((E,), -1, np.int32)
                )
                dev_entity_row = jax.device_put(np.zeros((E,), np.int32))
            coords.append(
                _ProjCoord(
                    cid=cid,
                    sub=sub,
                    host_blocks=host_blocks,
                    entity_block=entity_block,
                    entity_row=entity_row,
                    capacities=capacities,
                    lrus=lrus,
                    tables=tables,
                    dev_entity_block=dev_entity_block,
                    dev_entity_row=dev_entity_row,
                    demoted=demoted,
                )
            )
        return _ProjGroup(
            re_type=re_type,
            num_entities=num_entities,
            coords=coords,
            pinned=all(self._coord_pinned(c) for c in coords),
        )

    def _proj_demoter(self, re_type: str, demoted: List[int]):
        counter = self._demote_counter(re_type)

        def on_demote(victim, slot):
            demoted.append(int(victim))
            counter(victim, slot)

        return on_demote

    @staticmethod
    def _coord_pinned(coord: _ProjCoord) -> bool:
        return all(lru is None for lru in coord.lrus)

    # -- residency ---------------------------------------------------------

    @property
    def device_shards(self) -> Optional[int]:
        """Hot-table shard count in multi-chip mode (None = single-table)."""
        return self._device_shards

    @property
    def mesh(self):
        """The device mesh sharded hot tables live on (None = unsharded).
        The engine replicates request batches over it so the jitted scorer
        sees consistent placements; the score merge is the one all-gather
        XLA inserts for the slot gather against the sharded table."""
        return self._mesh

    @property
    def batch_sharding(self):
        """Replicated NamedSharding for request batches (None = unsharded)."""
        return self._replicated_sharding

    def shard_snapshot(self, re_type: str) -> Optional[dict]:
        """The entity→shard assignment identity for ``re_type`` — comparable
        against ``EntityShardPlan.snapshot()`` from the training side (tests
        assert train and serve derive the same assignment from the ring)."""
        group = self._groups.get(re_type)
        if group is None or group.shard_plan is None:
            return None
        return group.shard_plan.snapshot()

    @property
    def re_types(self) -> List[str]:
        """RE types under hot/cold management (table-swapped at scoring)."""
        return list(self._groups)

    @property
    def entity_re_types(self) -> List[str]:
        """Every RE type a batch must carry entity ids for — dense managed
        groups plus projected (entity-index-addressed) types."""
        return list(self._groups) + [
            t for t in self._proj_groups if t not in self._groups
        ]

    def group(self, re_type: str) -> Optional[_ReGroup]:
        return self._groups.get(re_type)

    def proj_group(self, re_type: str) -> Optional[_ProjGroup]:
        return self._proj_groups.get(re_type)

    def _intern(self, re_type: str, key, num_entities: int) -> int:
        """Request entity key → dense [0, E) index; -1 when unknown."""
        if isinstance(key, str):
            eidx = self._entity_indexes.get(re_type)
            i = eidx.lookup(key) if eidx is not None else -1
        else:
            i = int(key)
        return i if 0 <= i < num_entities else -1

    def resolve(self, re_type: str, keys: Sequence) -> np.ndarray:
        """Entity keys (interned ints or raw string ids) → hot-table slots
        (dense groups) or entity indices (projected groups), promoting
        misses from the host master. -1 rows (cold start) pass through and
        score 0. Single-writer: the engine's batch lock serializes calls."""
        faults.check("serve.store_resolve", label=re_type)
        group = self._groups.get(re_type)
        if group is None:
            proj = self._proj_groups.get(re_type)
            if proj is None:
                return np.full(len(keys), -1, np.int32)
            ids = np.fromiter(
                (self._intern(re_type, k, proj.num_entities) for k in keys),
                dtype=np.int32,
                count=len(keys),
            )
            if proj.owned is not None:
                ids = self._mask_foreign(re_type, proj.owned, None, ids)
            if not proj.pinned:
                self._promote_projected(proj, ids)
            return ids
        ids = np.fromiter(
            (self._intern(re_type, k, group.num_entities) for k in keys),
            dtype=np.int64,
            count=len(keys),
        )
        if group.owned is not None or group.compact_of is not None:
            ids = self._mask_foreign(
                re_type, group.owned, group.compact_of, ids
            )
        if group.pinned:
            ids = ids.astype(np.int32)
            if group.perm is None:
                return ids
            # Device-sharded pinned table: slots are shard-grouped, so the
            # passthrough routes through the entity→slot permutation.
            out = np.full(len(ids), -1, np.int32)
            pos = ids >= 0
            out[pos] = group.perm[ids[pos]]
            return out

        reg = registry()
        slots = np.empty(len(ids), np.int32)
        in_use = set()
        misses: List[int] = []  # entity ids needing upload, slot assigned
        hits = 0
        for j, e in enumerate(ids):
            e = int(e)
            if e < 0:
                slots[j] = -1
                continue
            slot = group.slot_get(e)
            if slot is not None:
                if e not in in_use and e not in misses:
                    hits += 1
            else:
                slot = self._claim_slot(group, e, in_use)
                misses.append(e)
            in_use.add(e)
            slots[j] = slot
        if hits:
            reg.counter("serve_store_hits_total", re_type=re_type).inc(hits)
        if misses:
            reg.counter("serve_store_misses_total", re_type=re_type).inc(
                len(misses)
            )
            # Idempotent: a pure scatter of host rows into already-claimed
            # slots, so the OOM containment may safely run it twice.
            _oom_contained(re_type, lambda: self._upload(group, misses))
        return slots

    def _mask_foreign(
        self,
        re_type: str,
        owned: Optional[np.ndarray],
        compact_of: Optional[np.ndarray],
        ids: np.ndarray,
    ) -> np.ndarray:
        """Foreign (non-owned, or owned-but-host-row-absent after a ring
        rebalance onto a compacted master) entities → -1. They score
        FE-only — the fleet's degrade-instead-of-error fallback — and are
        counted per type so the soak can prove correctly routed traffic
        never takes this path."""
        pos = np.flatnonzero(ids >= 0)
        if pos.size == 0:
            return ids
        idx = ids[pos].astype(np.int64)
        servable = (
            owned[idx] if owned is not None
            else np.ones(idx.size, bool)
        )
        if compact_of is not None:
            servable = servable & (compact_of[idx] >= 0)
        foreign = int(pos.size - servable.sum())
        if foreign:
            registry().counter(
                "serve_store_foreign_total", re_type=re_type
            ).inc(foreign)
            ids = ids.copy()
            ids[pos[~servable]] = -1
        return ids

    def set_partition(self, partition: Optional[StorePartition]) -> None:
        """Swap the ownership predicate live (ring rebalance / drain).
        Cheap — only the owned masks recompute; compacted host rows are NOT
        re-fetched, so an entity newly owned by this replica but absent
        from its compacted master stays FE-only until the engine rebuilds
        the store (the reload-based re-home procedure). Hot rows that just
        became foreign age out of the LRU naturally — they can no longer be
        requested through resolve. Callers serialize with resolve (the
        engine's batch lock)."""
        self._partition = partition
        for re_type, group in self._groups.items():
            if group.pinned:
                continue
            if partition is not None and partition.applies_to(re_type):
                group.owned = _owned_mask(
                    partition,
                    self._entity_indexes.get(re_type),
                    group.num_entities,
                )
            else:
                # Unsharded again; compact_of (if any) keeps masking the
                # rows this replica never had.
                group.owned = None
        for re_type, proj in self._proj_groups.items():
            if (partition is not None and partition.applies_to(re_type)
                    and not proj.pinned):
                proj.owned = _owned_mask(
                    partition,
                    self._entity_indexes.get(re_type),
                    proj.num_entities,
                )
            else:
                proj.owned = None

    def partition_stats(self) -> Optional[dict]:
        """Shard-ownership summary for ``/healthz``'s fleet snapshot."""
        part = self._partition
        if part is None:
            return None
        types = {}
        for re_type, group in self._groups.items():
            if group.owned is None and group.compact_of is None:
                continue
            types[re_type] = dict(
                owned=(
                    int(group.owned.sum()) if group.owned is not None
                    else None
                ),
                entities=group.num_entities,
                compacted=group.compact_of is not None,
                host_rows=(
                    int(next(iter(group.host_coefs.values())).shape[0])
                    if group.host_coefs else 0
                ),
            )
        for re_type, proj in self._proj_groups.items():
            if proj.owned is not None:
                types[re_type] = dict(
                    owned=int(proj.owned.sum()),
                    entities=proj.num_entities,
                    compacted=False,
                    projected=True,
                )
        return dict(
            replica_id=part.replica_id,
            ring_version=part.ring.version,
            ring_members=len(part.ring),
            compact_host=part.compact_host,
            re_types=types,
        )

    # -- warm shard handoff ------------------------------------------------

    def shard_export(
        self,
        target_snapshot: dict,
        target_member: Optional[str] = None,
        include_cold: bool = True,
    ) -> dict:
        """Everything a new owner needs BEFORE the ring flips: for each
        sharded dense group, the entities this replica serves today whose
        owner changes under ``target_snapshot`` (optionally only those
        moving to ``target_member``), their host coefficient rows (raw
        float32 bytes, base64 — exact, so handed-off rows score
        bit-identically), and a hot flag for rows currently resident in
        this replica's device cache. ``include_cold=False`` trims the
        payload to the hot set — the join case, where the newcomer built
        its own host shard from disk and only needs cache warmth.
        Callers serialize with resolve (the engine's batch lock)."""
        part = self._partition
        out = dict(
            fromReplica=part.replica_id if part is not None else None,
            targetVersion=int(target_snapshot.get("version", 0)),
            groups=[],
        )
        if part is None:
            return out
        target = HashRing.from_snapshot(target_snapshot)
        for re_type, group in self._groups.items():
            if group.pinned or not part.applies_to(re_type):
                continue
            eidx = self._entity_indexes.get(re_type)
            keys: List[object] = []
            hot: List[bool] = []
            dense: List[int] = []
            for i in range(group.num_entities):
                if group.owned is not None and not group.owned[i]:
                    continue
                if group.compact_of is not None and group.compact_of[i] < 0:
                    continue  # no host row here — nothing to hand off
                key = eidx.entity_id(i) if eidx is not None else i
                new_owner = target.owner(key)
                if new_owner == part.replica_id:
                    continue
                if target_member is not None and new_owner != target_member:
                    continue
                is_hot = group.slot_peek(i) is not None
                if not include_cold and not is_hot:
                    continue
                keys.append(key)
                hot.append(bool(is_hot))
                dense.append(i)
            if not keys:
                continue
            idx = np.asarray(dense, np.int64)
            src = (
                group.compact_of[idx].astype(np.int64)
                if group.compact_of is not None
                else idx
            )
            coords = {}
            for cid in group.coord_ids:
                rows = np.ascontiguousarray(
                    group.host_coefs[cid][src], dtype=np.float32
                )
                coords[cid] = dict(
                    dim=int(rows.shape[1]),
                    rows=base64.b64encode(rows.tobytes()).decode("ascii"),
                )
            out["groups"].append(
                dict(reType=re_type, keys=keys, hot=hot, coords=coords)
            )
        return out

    def shard_import(self, payload: dict, upload_chunk: int = 64) -> dict:
        """Install a peer's :meth:`shard_export` payload: append host rows
        this (compacted) master lacks — killing the FE-only window that
        otherwise follows a drain, since ``set_partition`` never re-fetches
        rows — and pre-promote the peer's hot set into the device cache so
        the first post-flip requests hit instead of miss. ``upload_chunk``
        must not exceed the warmed max batch size (the scatter buckets are
        already compiled; a bigger chunk would retrace). Callers serialize
        with resolve (the engine's batch lock)."""
        stats = dict(rowsAdded=0, rowsKnown=0, unknownKeys=0, promoted=0)
        reg = registry()
        for rec in payload.get("groups") or []:
            re_type = rec.get("reType")
            group = self._groups.get(re_type)
            if group is None or group.pinned:
                continue
            keys = rec.get("keys") or []
            hot_flags = list(rec.get("hot") or [False] * len(keys))
            ids = np.fromiter(
                (self._intern(re_type, k, group.num_entities) for k in keys),
                dtype=np.int64,
                count=len(keys),
            )
            known = ids >= 0
            stats["unknownKeys"] += int((~known).sum())
            decoded: Optional[Dict[str, np.ndarray]] = {}
            for cid in group.coord_ids:
                c = (rec.get("coords") or {}).get(cid)
                if c is None:
                    decoded = None
                    break
                arr = np.frombuffer(
                    base64.b64decode(c["rows"]), np.float32
                ).reshape(-1, int(c["dim"]))
                if arr.shape[0] != len(keys):
                    decoded = None
                    break
                decoded[cid] = arr
            if decoded is None:
                continue
            kn = np.flatnonzero(known)
            if group.compact_of is not None and kn.size:
                missing = kn[group.compact_of[ids[kn]] < 0]
                if missing.size:
                    base_rows = int(
                        next(iter(group.host_coefs.values())).shape[0]
                        if group.host_coefs
                        else 0
                    )
                    for cid in group.coord_ids:
                        group.host_coefs[cid] = np.ascontiguousarray(
                            np.vstack(
                                [group.host_coefs[cid], decoded[cid][missing]]
                            )
                        )
                    group.compact_of[ids[missing]] = base_rows + np.arange(
                        missing.size, dtype=np.int32
                    )
                    stats["rowsAdded"] += int(missing.size)
                    reg.counter(
                        "serve_store_handoff_rows_total", re_type=re_type
                    ).inc(int(missing.size))
                stats["rowsKnown"] += int(kn.size - missing.size)
            else:
                stats["rowsKnown"] += int(kn.size)
            promote = [
                int(e)
                for e, h in zip(ids, hot_flags)
                if h and e >= 0 and group.slot_peek(int(e)) is None
            ]
            if group.compact_of is not None:
                promote = [e for e in promote if group.compact_of[e] >= 0]
            promote = promote[: group.capacity]
            chunk_n = max(1, int(upload_chunk))
            promoted_here = 0
            for start in range(0, len(promote), chunk_n):
                chunk = promote[start:start + chunk_n]
                for e in chunk:
                    group.slot_claim(e, ())
                _oom_contained(
                    re_type, lambda c=list(chunk): self._upload(group, c)
                )
                promoted_here += len(chunk)
            if promoted_here:
                stats["promoted"] += promoted_here
                reg.counter(
                    "serve_store_handoff_promoted_total", re_type=re_type
                ).inc(promoted_here)
        return stats

    def _claim_slot(self, group: _ReGroup, entity: int, in_use: set) -> int:
        # Demotes the least-recently-used entity that is NOT part of the
        # current batch. capacity ≥ max batch size guarantees a victim.
        try:
            return group.slot_claim(entity, in_use)
        except RuntimeError:
            what = (
                f"shard segment capacity {group.shard_cap}"
                if group.shard_lrus is not None
                else f"capacity {group.capacity}"
            )
            raise RuntimeError(
                f"hot store for {group.re_type!r} exhausted: batch has more "
                f"unique entities than {what}"
            ) from None

    def _upload(self, group: _ReGroup, entities: List[int]) -> None:
        """One bucketed scatter per coordinate: miss count pads up the
        shape grid, filler indices land out of range and drop."""
        faults.check("serve.store_upload", label=group.re_type)
        m = len(entities)
        m_b = bucket_dim(m)
        idx = np.full(m_b, group.capacity, np.int32)
        idx[:m] = [group.slot_peek(e) for e in entities]
        ent = np.asarray(entities, np.int64)
        if group.compact_of is not None:
            # Only servable entities reach here (resolve masked the rest),
            # so every compacted row index is valid.
            ent = group.compact_of[ent].astype(np.int64)
        for cid in group.coord_ids:
            host = group.host_coefs[cid]
            rows = np.zeros((m_b, host.shape[1]), np.float32)
            rows[:m] = host[ent]
            group.tables[cid] = _scatter(group.tables[cid], idx, rows)

    def _promote_projected(self, proj: _ProjGroup, ids: np.ndarray) -> None:
        """Promote this batch's entities into each projected coordinate's
        per-block hot tables and rewrite the device entity maps. A demoted
        victim's map entry is scattered to -1 in the same pass — stale rows
        are never read because every REQUESTED entity is promoted here,
        before the scorer runs."""
        reg = registry()
        batch_ids = [int(e) for e in ids if e >= 0]
        for coord in proj.coords:
            if self._coord_pinned(coord):
                continue
            # Injected ``oom`` rules here take the same contained
            # gc-and-retry path a real allocator failure would.
            _oom_contained(
                proj.re_type,
                lambda: faults.check("serve.store_upload",
                                     label=proj.re_type),
            )
            # Entities of this batch grouped by their host block, for the
            # per-block in-use protection sets.
            in_use_by_block: Dict[int, set] = {}
            for e in batch_ids:
                b = int(coord.entity_block[e])
                if b >= 0:
                    in_use_by_block.setdefault(b, set()).add(e)
            misses: List[int] = []  # promoted entity ids, slot assigned
            rows_of: Dict[int, int] = {}
            hits = 0
            seen = set()
            for e in batch_ids:
                if e in seen:
                    continue
                seen.add(e)
                b = int(coord.entity_block[e])
                if b < 0:
                    continue  # entity has no model in this coordinate
                lru = coord.lrus[b]
                slot = lru.get(e)
                if slot is not None:
                    hits += 1
                    continue
                slot = self._claim_proj_slot(
                    proj, coord, b, e, in_use_by_block[b]
                )
                rows_of[e] = slot
                misses.append(e)
            if hits:
                reg.counter(
                    "serve_store_hits_total", re_type=proj.re_type
                ).inc(hits)
            if not misses and not coord.demoted:
                continue
            if misses:
                reg.counter(
                    "serve_store_misses_total", re_type=proj.re_type
                ).inc(len(misses))
                _oom_contained(
                    proj.re_type,
                    lambda: self._upload_projected_rows(
                        coord, misses, rows_of
                    ),
                )
            _oom_contained(
                proj.re_type,
                lambda: self._rewrite_proj_maps(proj, coord, misses, rows_of),
            )

    def _claim_proj_slot(
        self, proj: _ProjGroup, coord: _ProjCoord, block: int, entity: int,
        in_use: set,
    ) -> int:
        try:
            return coord.lrus[block].claim(entity, in_use)
        except RuntimeError:
            raise RuntimeError(
                f"hot store for {proj.re_type!r} exhausted: batch has more "
                f"unique entities in block {block} than capacity "
                f"{coord.capacities[block]}"
            ) from None

    def _upload_projected_rows(
        self, coord: _ProjCoord, misses: List[int], rows_of: Dict[int, int]
    ) -> None:
        """Bucketed row scatter per block that has promotions."""
        by_block: Dict[int, List[int]] = {}
        for e in misses:
            by_block.setdefault(int(coord.entity_block[e]), []).append(e)
        for b, ents in by_block.items():
            m = len(ents)
            m_b = bucket_dim(m)
            idx = np.full(m_b, coord.capacities[b], np.int32)
            idx[:m] = [rows_of[e] for e in ents]
            host = coord.host_blocks[b]
            rows = np.zeros((m_b, host.shape[1]), np.float32)
            rows[:m] = host[coord.entity_row[np.asarray(ents, np.int64)]]
            coord.tables[b] = _scatter(coord.tables[b], idx, rows)

    def _rewrite_proj_maps(
        self, proj: _ProjGroup, coord: _ProjCoord, misses: List[int],
        rows_of: Dict[int, int],
    ) -> None:
        """One bucketed scatter pair updating the device entity maps for
        this resolve: promoted entities point at their new hot rows,
        demotion victims go cold (-1)."""
        # Drain IN PLACE: the SlotLru on_demote closures captured this list
        # object at build time — rebinding would orphan it and every later
        # victim would silently keep its stale (hot) map entry. The clear
        # happens only after both scatters land, so an OOM-contained retry
        # of this whole function still sees every victim (no demotions can
        # occur in between — nothing here claims slots).
        victims = list(coord.demoted)
        m = len(misses) + len(victims)
        m_b = bucket_dim(m)
        E = coord.entity_block.shape[0]
        idx = np.full(m_b, E, np.int32)  # out-of-range filler → dropped
        blk = np.full(m_b, -1, np.int32)
        row = np.zeros(m_b, np.int32)
        idx[: len(victims)] = victims
        for j, e in enumerate(misses):
            idx[len(victims) + j] = e
            blk[len(victims) + j] = int(coord.entity_block[e])
            row[len(victims) + j] = rows_of[e]
        coord.dev_entity_block = _scatter(coord.dev_entity_block, idx, blk)
        coord.dev_entity_row = _scatter(coord.dev_entity_row, idx, row)
        coord.demoted.clear()

    def warm_uploads(self, max_batch: int) -> None:
        """Compile the upload scatters for every miss-count bucket ≤
        ``max_batch`` (no-op rows: every filler index drops), so promotion
        never compiles under a request. Projected map scatters warm to
        2×max_batch — one resolve may rewrite a miss AND a victim entry per
        promoted entity."""
        import jax

        for group in self._groups.values():
            if group.pinned:
                continue
            m = 1
            while True:
                m_b = bucket_dim(m)
                idx = np.full(m_b, group.capacity, np.int32)
                for cid in group.coord_ids:
                    d = group.host_coefs[cid].shape[1]
                    group.tables[cid] = _scatter(
                        group.tables[cid], idx, np.zeros((m_b, d), np.float32)
                    )
                if m_b >= bucket_dim(max_batch):
                    break
                m = m_b + 1
            for cid in group.coord_ids:
                jax.block_until_ready(group.tables[cid])
        for proj in self._proj_groups.values():
            for coord in proj.coords:
                if self._coord_pinned(coord):
                    continue
                E = coord.entity_block.shape[0]
                m = 1
                while True:
                    m_b = bucket_dim(m)
                    for b, table in enumerate(coord.tables):
                        idx = np.full(m_b, coord.capacities[b], np.int32)
                        coord.tables[b] = _scatter(
                            table, idx,
                            np.zeros((m_b, table.shape[1]), np.float32),
                        )
                    if m_b >= bucket_dim(2 * max_batch):
                        break
                    m = m_b + 1
                m = 1
                while True:
                    m_b = bucket_dim(m)
                    idx = np.full(m_b, E, np.int32)
                    zeros = np.zeros(m_b, np.int32)
                    coord.dev_entity_block = _scatter(
                        coord.dev_entity_block, idx, zeros
                    )
                    coord.dev_entity_row = _scatter(
                        coord.dev_entity_row, idx, zeros
                    )
                    if m_b >= bucket_dim(2 * max_batch):
                        break
                    m = m_b + 1
                jax.block_until_ready(coord.dev_entity_block)
                for table in coord.tables:
                    jax.block_until_ready(table)

    # -- delta overlay -----------------------------------------------------

    def clone_with_delta(
        self,
        re_rows: Dict[str, tuple],
        fixed: Optional[Dict[str, np.ndarray]] = None,
    ) -> "HotColdEntityStore":
        """A NEW store serving base ⊕ delta without reloading the base
        model: per-entity coefficient rows (``re_rows``: cid → (idx, rows),
        the shape ``io/model_io.py:read_delta_rows`` returns) overlay copies
        of the touched host masters, and fixed-effect means (``fixed``:
        cid → (d,) array) replace the base means value-only — the scoring
        pytree structure is unchanged, so a transformer warmed on the base
        scores the clone without a retrace.

        Sharing discipline: entity indexes, RE submodel metadata, projected
        groups, and every UNTOUCHED dense group are shared with the base
        store, hot cache included — safe because untouched host masters are
        byte-identical and the engine serializes every resolve/upload under
        one batch lock. Touched groups get copied hosts; pinned tables are
        rewritten by one functional bucketed scatter per coordinate (the
        base version's tables are never mutated — multi-version residency
        holds), unpinned groups restart cold with fresh tables + LRU and
        refill on demand from the patched master.

        Raises ValueError when the delta cannot be applied in place —
        unknown coordinate, projected coordinate, feature-dim mismatch, or
        an entity index outside the base entity space (the delta grew the
        entity set). Callers treat that as "fall back to a full
        resolved-model load".
        """
        import jax

        re_rows = re_rows or {}
        fixed = fixed or {}
        proj_cids = {
            c.cid for proj in self._proj_groups.values() for c in proj.coords
        }
        group_of: Dict[str, _ReGroup] = {
            cid: g for g in self._groups.values() for cid in g.coord_ids
        }
        for cid, (idx, rows) in re_rows.items():
            if cid in proj_cids:
                raise ValueError(
                    f"delta touches projected coordinate {cid!r}; in-place "
                    "apply supports dense random effects only"
                )
            group = group_of.get(cid)
            if group is None:
                raise ValueError(
                    f"delta coordinate {cid!r} is not a random-effect "
                    "coordinate of the base model"
                )
            idx = np.asarray(idx)
            rows = np.asarray(rows, np.float32)
            host = group.host_coefs[cid]
            if rows.ndim != 2 or rows.shape[1] != host.shape[1]:
                raise ValueError(
                    f"delta rows for {cid!r} have width "
                    f"{rows.shape[1] if rows.ndim == 2 else rows.shape}, "
                    f"base table has {host.shape[1]}"
                )
            if int(idx.shape[0]) != int(rows.shape[0]):
                raise ValueError(
                    f"delta for {cid!r}: {idx.shape[0]} indices vs "
                    f"{rows.shape[0]} rows"
                )
            if idx.size and (
                int(idx.min()) < 0 or int(idx.max()) >= group.num_entities
            ):
                raise ValueError(
                    f"delta for {cid!r} addresses entities outside the base "
                    f"entity space [0, {group.num_entities}) — the delta "
                    "grew the entity set"
                )
        for cid, means in fixed.items():
            sub = self._base.get(cid)
            if not isinstance(sub, FixedEffectModel):
                raise ValueError(
                    f"delta fixed effect {cid!r} is not a fixed-effect "
                    "coordinate of the base model"
                )
            means = np.asarray(means, np.float32)
            old = np.asarray(sub.model.coefficients.means)
            if means.shape != old.shape:
                raise ValueError(
                    f"delta fixed effect {cid!r} has shape {means.shape}, "
                    f"base has {old.shape}"
                )

        new = object.__new__(HotColdEntityStore)
        new._entity_indexes = self._entity_indexes
        new._re_subs = self._re_subs
        new._proj_groups = self._proj_groups
        new._partition = self._partition
        new._device_shards = self._device_shards
        new._mesh = self._mesh
        new._table_sharding = self._table_sharding
        new._replicated_sharding = self._replicated_sharding
        base = dict(self._base)
        for cid, means in fixed.items():
            sub = base[cid]
            coefs = sub.model.coefficients
            base[cid] = FixedEffectModel(
                model=GeneralizedLinearModel(
                    Coefficients(
                        jax.device_put(np.asarray(means, np.float32)),
                        coefs.variances,
                    ),
                    sub.model.task,
                ),
                feature_shard=sub.feature_shard,
            )
        new._base = base
        groups: Dict[str, _ReGroup] = {}
        for re_type, group in self._groups.items():
            touched = {
                cid: re_rows[cid] for cid in group.coord_ids if cid in re_rows
            }
            if not touched:
                groups[re_type] = group
                continue
            host2: Dict[str, np.ndarray] = {}
            for cid in group.coord_ids:
                if cid in touched:
                    idx, rows = touched[cid]
                    idx = np.asarray(idx, np.int64)
                    rows = np.asarray(rows, np.float32)
                    if group.compact_of is not None:
                        # Sharded host master: the delta addresses full
                        # entity space; rows this replica doesn't hold are
                        # another replica's to apply.
                        cidx = group.compact_of[idx].astype(np.int64)
                        keep = cidx >= 0
                        idx, rows = cidx[keep], rows[keep]
                    h = group.host_coefs[cid].copy()
                    h[idx] = rows
                    host2[cid] = h
                else:
                    host2[cid] = group.host_coefs[cid]
            g2 = _ReGroup(
                re_type=re_type,
                coord_ids=list(group.coord_ids),
                host_coefs=host2,
                num_entities=group.num_entities,
                capacity=group.capacity,
                pinned=group.pinned,
                owned=group.owned,
                compact_of=group.compact_of,
                shard_plan=group.shard_plan,
                shard_cap=group.shard_cap,
                perm=group.perm,
            )
            if group.pinned:
                tables: Dict[str, object] = {}
                for cid in group.coord_ids:
                    if cid not in touched:
                        tables[cid] = group.tables[cid]
                        continue
                    idx, rows = touched[cid]
                    idx = np.asarray(idx, np.int64)
                    rows = np.asarray(rows, np.float32)
                    m = int(idx.shape[0])
                    m_b = bucket_dim(m)
                    # capacity == num_entities when pinned: the filler
                    # index is out of range and drops, like _upload's.
                    # Device-sharded tables are addressed through the
                    # entity→slot permutation (shard-grouped layout).
                    pad_idx = np.full(m_b, group.capacity, np.int32)
                    pad_idx[:m] = (
                        group.perm[idx] if group.perm is not None else idx
                    )
                    pad_rows = np.zeros((m_b, rows.shape[1]), np.float32)
                    pad_rows[:m] = rows
                    tables[cid] = _oom_contained(
                        re_type,
                        lambda t=group.tables[cid], i=pad_idx, r=pad_rows: (
                            _scatter(t, i, r)
                        ),
                    )
                g2.tables = tables
            else:
                g2.tables = {
                    cid: jax.device_put(
                        np.zeros(
                            (g2.capacity, host2[cid].shape[1]), np.float32
                        ),
                        self._table_sharding,
                    )
                    for cid in group.coord_ids
                }
                if group.shard_lrus is not None:
                    g2.shard_lrus = [
                        SlotLru(
                            group.shard_cap,
                            on_demote=self._demote_counter(re_type),
                            base=s * group.shard_cap,
                        )
                        for s in range(group.shard_plan.n_shards)
                    ]
                else:
                    g2.lru = SlotLru(
                        g2.capacity, on_demote=self._demote_counter(re_type)
                    )
            groups[re_type] = g2
        new._groups = groups
        registry().counter("serve_store_delta_clones_total").inc()
        return new

    # -- scoring model -----------------------------------------------------

    def scoring_model(self) -> GameModel:
        """The model the jitted scorer runs: device submodels, with every
        cached random-effect table swapped in (slot-indexed). Pytree
        structure is identical call to call and reload to reload — the
        tables change VALUE only, so the scorer never retraces."""
        models = dict(self._base)
        for re_type, group in self._groups.items():
            for cid in group.coord_ids:
                models[cid] = self._re_subs[cid].with_coefficients(
                    group.tables[cid]
                )
        for proj in self._proj_groups.values():
            for coord in proj.coords:
                sub = coord.sub
                # Auxiliary arrays (variances) are dropped like the dense
                # ``with_coefficients`` path: one pytree structure across
                # reloads, never a retrace on swap.
                models[coord.cid] = ProjectedRandomEffectModel(
                    block_coefs=list(coord.tables),
                    col_maps=list(sub.col_maps),
                    inv_maps=list(sub.inv_maps),
                    entity_block=coord.dev_entity_block,
                    entity_row=coord.dev_entity_row,
                    d_full=sub.d_full,
                    re_type=sub.re_type,
                    feature_shard=sub.feature_shard,
                    task=sub.task,
                )
        return GameModel(models)

    def stats(self) -> Dict[str, dict]:
        out = {}
        for re_type, group in self._groups.items():
            out[re_type] = dict(
                entities=group.num_entities,
                hot_capacity=group.capacity,
                hot_resident=group.resident_count(),
                pinned=group.pinned,
                hot_bytes=group.capacity * group.row_bytes,
            )
            if group.owned is not None:
                out[re_type]["owned_entities"] = int(group.owned.sum())
                out[re_type]["compacted_host"] = group.compact_of is not None
            if group.shard_plan is not None:
                out[re_type]["device_shards"] = group.shard_plan.n_shards
                out[re_type]["shard_rows"] = group.shard_cap
        for re_type, proj in self._proj_groups.items():
            out[re_type] = dict(
                entities=proj.num_entities,
                hot_capacity=sum(sum(c.capacities) for c in proj.coords),
                hot_resident=sum(
                    sum(c.capacities)
                    if self._coord_pinned(c)
                    else sum(len(l) for l in c.lrus if l is not None)
                    for c in proj.coords
                ),
                pinned=proj.pinned,
                hot_bytes=sum(c.hot_bytes for c in proj.coords),
                projected=True,
            )
        return out
