"""Hot/cold entity coefficient store for online GAME scoring.

Photon ML's GAME shape — one global model plus millions of per-entity
models — makes serving a lookup-then-score problem. The lookup side is this
module: per-entity coefficient rows live COLD on the host (the numpy master
copy ``load_game_model(to_device=False)`` returns) and HOT in a
device-resident table under an explicit byte budget, with LRU demotion.
Request entity ids resolve to hot-table SLOTS; misses gather their rows from
the host master and upload them in one shape-bucketed scatter per batch, so
the device never holds more than the working set and the jitted scorer's
program shapes never change.

Slot discipline: coordinates sharing a random-effect type share ONE slot
assignment (their tables are indexed by the same ``entity_ids`` array in the
batch), so the LRU is per RE type with one device table per coordinate.
A type whose full table fits the budget is PINNED — full device residency,
entity ids pass through as slots, the miss path never runs. Unknown/cold
entities resolve to slot -1 and score 0, exactly the batch path's
cold-start semantics.

Zero-downtime reload builds a NEW store (and scorer) for the incoming model
while the old one keeps serving, then swaps atomically — see
serve/engine.py. The store itself is single-writer: the engine serializes
``resolve``/upload under its batch lock.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

import numpy as np

from photon_tpu.data.random_effect import bucket_dim
from photon_tpu.models.game import (
    FixedEffectModel,
    GameModel,
    ProjectedRandomEffectModel,
    RandomEffectModel,
)
from photon_tpu.obs.metrics import registry
from photon_tpu.utils import faults

_scatter_rows = None


def _scatter(table, idx, rows):
    """Jitted hot-table row upload. ``idx`` is padded to a bucketed length
    with the out-of-range value H (``mode="drop"`` discards it — NB negative
    indices WRAP in XLA scatters, so high-out-of-range is the safe filler).
    One executable per (H, d, m_bucket) shape; ``warm_uploads`` compiles
    them before traffic."""
    global _scatter_rows
    if _scatter_rows is None:
        import jax

        # NOT donated: the previous table buffer may still be referenced by
        # a scoring-model pytree a caller holds (e.g. the transformer's
        # init-time model) — donating it would invalidate those references.
        _scatter_rows = jax.jit(lambda t, i, r: t.at[i].set(r, mode="drop"))
    return _scatter_rows(table, idx, rows)


@dataclasses.dataclass
class _ReGroup:
    """All random-effect coordinates sharing one RE type: one slot LRU,
    one device table per coordinate."""

    re_type: str
    coord_ids: List[str]
    host_coefs: Dict[str, np.ndarray]  # cid -> (E, d) float32 master copy
    num_entities: int
    capacity: int  # H: hot rows (== num_entities when pinned)
    pinned: bool
    tables: Dict[str, object] = dataclasses.field(default_factory=dict)
    slot_of: "OrderedDict[int, int]" = dataclasses.field(
        default_factory=OrderedDict
    )
    free_slots: List[int] = dataclasses.field(default_factory=list)

    @property
    def row_bytes(self) -> int:
        return sum(4 * c.shape[1] for c in self.host_coefs.values())


class HotColdEntityStore:
    """Entity-model residency manager + scoring-model factory.

    ``hot_bytes`` bounds the device bytes of CACHED random-effect tables
    (split across RE types proportionally to their full size). The floor is
    ``min_hot_rows`` per type — the engine passes its max batch size, which
    guarantees every unique entity of one batch fits resident simultaneously
    (the resolve path never has to evict a slot the current batch needs).
    """

    def __init__(
        self,
        model: GameModel,
        entity_indexes: Optional[Dict] = None,
        hot_bytes: int = 64 << 20,
        min_hot_rows: int = 64,
    ):
        import jax

        self._entity_indexes = dict(entity_indexes or {})
        self._groups: Dict[str, _ReGroup] = {}
        self._re_subs: Dict[str, RandomEffectModel] = {}
        # RE types whose tables serve fully device-resident OUTSIDE the LRU
        # (projected models): entity ids pass straight through as indices.
        self._passthrough: Dict[str, int] = {}
        base: Dict[str, object] = {}

        by_type: Dict[str, List] = {}
        for cid, sub in model.models.items():
            if isinstance(sub, RandomEffectModel):
                by_type.setdefault(sub.re_type, []).append((cid, sub))
            else:
                # Fixed effects and projected RE models serve device-resident
                # as-is (projected tables are already the compact subspace
                # form — their hot/cold split is an open item).
                if isinstance(sub, ProjectedRandomEffectModel):
                    self._passthrough[sub.re_type] = max(
                        self._passthrough.get(sub.re_type, 0),
                        int(sub.num_entities),
                    )
                base[cid] = jax.device_put(sub)

        budget_total = sum(
            sum(4 * np.asarray(s.coefficients).shape[1] for _, s in subs)
            * max(np.asarray(subs[0][1].coefficients).shape[0], 1)
            for subs in by_type.values()
        )
        for re_type, subs in by_type.items():
            host = {
                cid: np.ascontiguousarray(
                    np.asarray(s.coefficients, dtype=np.float32)
                )
                for cid, s in subs
            }
            E = {c.shape[0] for c in host.values()}
            if len(E) != 1:
                raise ValueError(
                    f"RE type {re_type!r}: coordinates disagree on entity "
                    f"count {sorted(E)}"
                )
            E = E.pop()
            row_bytes = sum(4 * c.shape[1] for c in host.values())
            full_bytes = row_bytes * max(E, 1)
            share = (
                int(hot_bytes * full_bytes / budget_total)
                if budget_total
                else hot_bytes
            )
            cap = max(int(min_hot_rows), share // max(row_bytes, 1))
            pinned = cap >= E
            cap = min(cap, E) if pinned else cap
            group = _ReGroup(
                re_type=re_type,
                coord_ids=[cid for cid, _ in subs],
                host_coefs=host,
                num_entities=E,
                capacity=max(cap, 1),
                pinned=pinned,
            )
            if pinned:
                group.tables = {
                    cid: jax.device_put(host[cid]) for cid in group.coord_ids
                }
            else:
                group.tables = {
                    cid: jax.device_put(
                        np.zeros(
                            (group.capacity, host[cid].shape[1]), np.float32
                        )
                    )
                    for cid in group.coord_ids
                }
                group.free_slots = list(range(group.capacity - 1, -1, -1))
            self._groups[re_type] = group
            for cid, s in subs:
                self._re_subs[cid] = s
            reg = registry()
            reg.gauge("serve_store_hot_rows", re_type=re_type).set(
                group.capacity
            )
            reg.gauge("serve_store_hot_bytes", re_type=re_type).set(
                group.capacity * row_bytes
            )
            reg.gauge("serve_store_pinned", re_type=re_type).set(int(pinned))
        self._base = base

    # -- residency ---------------------------------------------------------

    @property
    def re_types(self) -> List[str]:
        """RE types under hot/cold management (table-swapped at scoring)."""
        return list(self._groups)

    @property
    def entity_re_types(self) -> List[str]:
        """Every RE type a batch must carry entity ids for — managed groups
        plus passthrough (projected) types."""
        return list(self._groups) + [
            t for t in self._passthrough if t not in self._groups
        ]

    def group(self, re_type: str) -> Optional[_ReGroup]:
        return self._groups.get(re_type)

    def _intern(self, re_type: str, key, num_entities: int) -> int:
        """Request entity key → dense [0, E) index; -1 when unknown."""
        if isinstance(key, str):
            eidx = self._entity_indexes.get(re_type)
            i = eidx.lookup(key) if eidx is not None else -1
        else:
            i = int(key)
        return i if 0 <= i < num_entities else -1

    def resolve(self, re_type: str, keys: Sequence) -> np.ndarray:
        """Entity keys (interned ints or raw string ids) → hot-table slots,
        promoting misses from the host master. -1 rows (cold start) pass
        through and score 0. Single-writer: the engine's batch lock
        serializes calls."""
        faults.check("serve.store_resolve", label=re_type)
        group = self._groups.get(re_type)
        if group is None:
            E = self._passthrough.get(re_type)
            if E is None:
                return np.full(len(keys), -1, np.int32)
            return np.fromiter(
                (self._intern(re_type, k, E) for k in keys),
                dtype=np.int32,
                count=len(keys),
            )
        ids = np.fromiter(
            (self._intern(re_type, k, group.num_entities) for k in keys),
            dtype=np.int64,
            count=len(keys),
        )
        if group.pinned:
            return ids.astype(np.int32)

        reg = registry()
        slots = np.empty(len(ids), np.int32)
        in_use = set()
        misses: List[int] = []  # entity ids needing upload, slot assigned
        hits = 0
        for j, e in enumerate(ids):
            e = int(e)
            if e < 0:
                slots[j] = -1
                continue
            slot = group.slot_of.get(e)
            if slot is not None:
                group.slot_of.move_to_end(e)
                if e not in in_use and e not in misses:
                    hits += 1
            else:
                slot = self._claim_slot(group, in_use)
                group.slot_of[e] = slot
                misses.append(e)
            in_use.add(e)
            slots[j] = slot
        if hits:
            reg.counter("serve_store_hits_total", re_type=re_type).inc(hits)
        if misses:
            reg.counter("serve_store_misses_total", re_type=re_type).inc(
                len(misses)
            )
            self._upload(group, misses)
        return slots

    def _claim_slot(self, group: _ReGroup, in_use: set) -> int:
        if group.free_slots:
            return group.free_slots.pop()
        # Demote the least-recently-used entity that is NOT part of the
        # current batch. capacity ≥ max batch size guarantees a victim.
        for victim in group.slot_of:
            if victim not in in_use:
                slot = group.slot_of.pop(victim)
                registry().counter(
                    "serve_store_demotions_total", re_type=group.re_type
                ).inc()
                return slot
        raise RuntimeError(
            f"hot store for {group.re_type!r} exhausted: batch has more "
            f"unique entities than capacity {group.capacity}"
        )

    def _upload(self, group: _ReGroup, entities: List[int]) -> None:
        """One bucketed scatter per coordinate: miss count pads up the
        shape grid, filler indices land out of range and drop."""
        faults.check("serve.store_upload", label=group.re_type)
        m = len(entities)
        m_b = bucket_dim(m)
        idx = np.full(m_b, group.capacity, np.int32)
        idx[:m] = [group.slot_of[e] for e in entities]
        ent = np.asarray(entities, np.int64)
        for cid in group.coord_ids:
            host = group.host_coefs[cid]
            rows = np.zeros((m_b, host.shape[1]), np.float32)
            rows[:m] = host[ent]
            group.tables[cid] = _scatter(group.tables[cid], idx, rows)

    def warm_uploads(self, max_batch: int) -> None:
        """Compile the upload scatters for every miss-count bucket ≤
        ``max_batch`` (no-op rows: every filler index drops), so promotion
        never compiles under a request."""
        import jax

        for group in self._groups.values():
            if group.pinned:
                continue
            m = 1
            while True:
                m_b = bucket_dim(m)
                idx = np.full(m_b, group.capacity, np.int32)
                for cid in group.coord_ids:
                    d = group.host_coefs[cid].shape[1]
                    group.tables[cid] = _scatter(
                        group.tables[cid], idx, np.zeros((m_b, d), np.float32)
                    )
                if m_b >= bucket_dim(max_batch):
                    break
                m = m_b + 1
            for cid in group.coord_ids:
                jax.block_until_ready(group.tables[cid])

    # -- scoring model -----------------------------------------------------

    def scoring_model(self) -> GameModel:
        """The model the jitted scorer runs: device submodels, with every
        cached random-effect table swapped in (slot-indexed). Pytree
        structure is identical call to call and reload to reload — the
        tables change VALUE only, so the scorer never retraces."""
        models = dict(self._base)
        for re_type, group in self._groups.items():
            for cid in group.coord_ids:
                models[cid] = self._re_subs[cid].with_coefficients(
                    group.tables[cid]
                )
        return GameModel(models)

    def stats(self) -> Dict[str, dict]:
        out = {}
        for re_type, group in self._groups.items():
            out[re_type] = dict(
                entities=group.num_entities,
                hot_capacity=group.capacity,
                hot_resident=(
                    group.num_entities if group.pinned else len(group.slot_of)
                ),
                pinned=group.pinned,
                hot_bytes=group.capacity * group.row_bytes,
            )
        return out
