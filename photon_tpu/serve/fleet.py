"""Scorer fleet: N entity-sharded scorer processes behind one router.

Photon ML's premise (PAPER.md §2.9) is that no single machine holds the
model; this module is the serving side of that claim. Topology:

- **One routing front end** (this process): owns the :class:`HashRing`,
  one framed-socket :class:`~photon_tpu.serve.frontend.ScorerClient` per
  replica, and the :class:`~photon_tpu.serve.admission.FleetAdmissionLedger`
  — the single coordinator for fleet-global tenant quotas (the frontend
  already sees every request, so the coordinator is free; no gossip).
- **N scorer replicas** (subprocesses, ``python -m photon_tpu.serve.fleet``):
  each a full :class:`~photon_tpu.serve.engine.ServingEngine` whose
  :class:`~photon_tpu.serve.store.StorePartition` claims only the entities
  the ring assigns it. A replica's hot set is its DISJOINT ring shard —
  cache hit rate is a routing property, not a budget property.

Degradation, never errors: a request landing on a replica that does not
own its entity (mis-route, membership churn, failover after a SIGKILL)
resolves that entity cold → the random effect contributes 0 → FE-only
score. The ``serve.replica_kill`` fault site (fired from the replica
heartbeat, targeted per replica via ``PHOTON_TPU_FAULT_PLAN`` in the
replica's environment) proves the full cycle: kill → router marks the
member dead → its shard fails over along the ring's preference order to
live successors (FE-only for the foreign entities) → revive → re-home to
exact scores. Elastic membership reuses the rollout watcher's settle
discipline: a leaving replica drains its in-flight work before the ring
drops it and the fleet re-partitions.

``bench.py --fleet-soak`` drives the whole story; ``./ci.sh fleet`` is
the 3-replica smoke. The runbook lives in README.md ("Fleet serving
runbook").
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import socket as socket_mod
import subprocess
import sys
import threading
import time
from collections import OrderedDict
from concurrent.futures import Future
from http.server import ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence

from photon_tpu.obs.metrics import registry, render_prometheus
from photon_tpu.obs.slo import (
    DRILL_PAGE_RULES,
    DRILL_WARN_RULES,
    Objective,
    SLOTracker,
)
from photon_tpu.obs.trace import (
    TraceContext,
    flight_recorder,
    merge_trace_dumps,
    new_span_id,
    tracer,
)
from photon_tpu.serve.admission import (
    INTERACTIVE,
    AdmissionConfig,
    FleetAdmissionLedger,
    tenant_quality,
)
from photon_tpu.serve.batcher import BackpressureError
from photon_tpu.serve.frontend import (
    FLEET_SECRET_ENV,
    ScorerClient,
    ScorerServer,
    _stamp_labels,
    make_http_handler,
    parse_endpoint,
)
from photon_tpu.serve.routing import HashRing, route_key
from photon_tpu.serve.store import StorePartition
from photon_tpu.utils import faults

logger = logging.getLogger("photon_tpu")

# Router-side member states. DRAINING members finish in-flight work but
# receive no new requests; DEAD members are skipped until revived.
LIVE = "live"
DRAINING = "draining"
DEAD = "dead"


def partition_from_snapshot(
    replica_id: str,
    snapshot: dict,
    route_re_type: Optional[str] = None,
    compact_host: bool = True,
) -> StorePartition:
    """A replica's shard-ownership predicate from a ring snapshot. When a
    routing RE type is named, ONLY that type shards — secondary types stay
    fully replicated on every member, which is what makes a routed
    request's score bit-identical to the batch driver's (the routed type
    is hot-or-cold exactly as a single process would have it; every other
    type is simply there)."""
    return StorePartition(
        replica_id=str(replica_id),
        ring=HashRing.from_snapshot(snapshot),
        re_types=(route_re_type,) if route_re_type else None,
        compact_host=compact_host,
    )


# ---------------------------------------------------------------------------
# Replica side
# ---------------------------------------------------------------------------


class ReplicaScorerServer(ScorerServer):
    """The per-replica IPC server: everything ``ScorerServer`` speaks
    (score/stats/reload/feedback/ping) plus the fleet control plane —
    ``ring`` installs a new membership snapshot live (the elastic-join
    rebalance path) and ``replica_info`` answers the router's probes."""

    def __init__(
        self,
        engine,
        socket_path: str,
        replica_id: str,
        route_re_type: Optional[str] = None,
        compact_host: bool = True,
    ):
        super().__init__(engine, socket_path)
        self.replica_id = str(replica_id)
        self.route_re_type = route_re_type
        self.compact_host = compact_host
        self.ring_version: Optional[int] = None
        # Split-brain guard: which router id last (successfully) claimed a
        # ring epoch on this replica. A DIFFERENT router pushing the same or
        # an older epoch is two coordinators fighting over one fleet — the
        # push is rejected and flagged so the routers' SLO planes can page.
        self.ring_claimant: Optional[str] = None

    def _dispatch(self, msg: dict, out) -> None:
        rid = msg.get("id")
        op = msg.get("op")
        if op == "ring":
            try:
                snap = msg.get("snapshot") or {}
                router_id = msg.get("routerId")
                version = int(snap.get("version", 0))
                if (
                    router_id is not None
                    and self.ring_claimant is not None
                    and router_id != self.ring_claimant
                    and self.ring_version is not None
                    and version <= self.ring_version
                ):
                    registry().counter("fleet_split_brain_total").inc()
                    logger.error(
                        "fleet replica %s: SPLIT BRAIN — router %s pushed "
                        "ring v%d but router %s already claims v%d; "
                        "rejecting",
                        self.replica_id, router_id, version,
                        self.ring_claimant, self.ring_version,
                    )
                    out.put(dict(id=rid, ok=True, result=dict(
                        splitBrain=True, rejected=True,
                        claimant=self.ring_claimant,
                        ringVersion=self.ring_version,
                    )))
                    return
                partition = partition_from_snapshot(
                    self.replica_id,
                    snap,
                    msg.get("routeReType", self.route_re_type),
                    compact_host=self.compact_host,
                )
                info = self.engine.set_partition(partition)
                self.ring_version = partition.ring.version
                if router_id is not None:
                    self.ring_claimant = str(router_id)
                logger.info(
                    "fleet replica %s: installed ring v%s (%d members)",
                    self.replica_id, partition.ring.version,
                    len(partition.ring),
                )
                info = dict(info, splitBrain=False)
                out.put(dict(id=rid, ok=True, result=info))
            except Exception as exc:  # noqa: BLE001 — per-request failure
                out.put(self._error_payload(rid, exc))
            return
        if op == "shard_export":
            try:
                out.put(dict(id=rid, ok=True, result=self.engine.shard_export(
                    msg.get("snapshot") or {},
                    target_member=msg.get("targetMember"),
                    include_cold=bool(msg.get("includeCold", True)),
                )))
            except Exception as exc:  # noqa: BLE001 — per-request failure
                out.put(self._error_payload(rid, exc))
            return
        if op == "shard_import":
            try:
                out.put(dict(id=rid, ok=True, result=self.engine.shard_import(
                    msg.get("payload") or {},
                )))
            except Exception as exc:  # noqa: BLE001 — per-request failure
                out.put(self._error_payload(rid, exc))
            return
        if op == "replica_info":
            try:
                out.put(dict(id=rid, ok=True, result=dict(
                    replica=self.replica_id,
                    pid=os.getpid(),
                    ringVersion=self.ring_version,
                    ringClaimant=self.ring_claimant,
                    partition=self.engine.stats().get("partition"),
                )))
            except Exception as exc:  # noqa: BLE001 — per-request failure
                out.put(self._error_payload(rid, exc))
            return
        # "metrics" (the per-replica counter/gauge scrape, every instrument
        # carrying the ``replica`` default label) and "traces" (the
        # flight-recorder ring) come from the ScorerServer base.
        super()._dispatch(msg, out)


def _replica_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "photon-tpu-fleet-replica",
        description="One scorer-fleet replica: a ServingEngine owning the "
        "ring shard of its --replica-id, served over a framed Unix socket.",
    )
    p.add_argument("--socket", required=True,
                   help="framed-IPC endpoint: a Unix socket path, or "
                   "tcp://host:port for the cross-host transport (the "
                   f"shared secret rides ${FLEET_SECRET_ENV}, never argv)")
    p.add_argument("--replica-id", required=True)
    p.add_argument("--model-dir", required=True)
    p.add_argument("--artifacts-dir", default=None)
    p.add_argument("--ring", required=True,
                   help="ring snapshot JSON (members/vnodes/seed/version)")
    p.add_argument("--route-re-type", default=None,
                   help="RE type the fleet shards; others stay replicated")
    p.add_argument("--no-compact-host", action="store_true",
                   help="keep the full host master per replica (re-homing "
                   "without reload, at full host memory per member)")
    p.add_argument("--hot-bytes", type=int, default=64 << 20)
    p.add_argument("--max-batch-size", type=int, default=64)
    p.add_argument("--max-delay-ms", type=float, default=2.0)
    p.add_argument("--queue-cap", type=int, default=1024)
    p.add_argument("--spool-dir", default=None,
                   help="BASE feedback spool dir; this replica spools into "
                   "<base>/<replica-id> (the updater polls the glob)")
    p.add_argument("--feedback-join-ttl", type=float, default=300.0)
    p.add_argument("--heartbeat-s", type=float, default=0.25,
                   help="fault-site heartbeat period (serve.replica_kill)")
    p.add_argument("--verbose", action="store_true")
    return p


def replica_main(argv: Optional[Sequence[str]] = None) -> int:
    args = _replica_argparser().parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format=f"%(asctime)s {args.replica_id} %(levelname)s %(message)s",
    )
    # Before ANY instrument exists: every serve metric this process emits
    # carries replica=<id>, so a merged fleet report stays attributable.
    registry().set_default_labels(replica=args.replica_id)

    snap = json.loads(args.ring)
    partition = partition_from_snapshot(
        args.replica_id, snap, args.route_re_type,
        compact_host=not args.no_compact_host,
    )

    from photon_tpu.serve.engine import ServeConfig, load_engine

    config = ServeConfig(
        max_batch_size=args.max_batch_size,
        max_delay_ms=args.max_delay_ms,
        queue_cap=args.queue_cap,
        hot_bytes=args.hot_bytes,
    )
    engine = load_engine(
        args.model_dir, args.artifacts_dir, config, partition=partition
    )

    if args.spool_dir:
        from photon_tpu.stream.spool import FeedbackSpool, SpoolConfig

        spool_dir = os.path.join(args.spool_dir, args.replica_id)
        spool = FeedbackSpool(
            spool_dir, SpoolConfig(join_ttl_s=args.feedback_join_ttl)
        )
        spool.start_auto_flush()
        engine.attach_feedback(spool)
        logger.info("fleet replica %s: spool at %s",
                    args.replica_id, spool_dir)

    server = ReplicaScorerServer(
        engine, args.socket, args.replica_id, args.route_re_type,
        compact_host=not args.no_compact_host,
    )
    server.ring_version = partition.ring.version
    server.start()

    stop = threading.Event()

    def _term(signum, frame):  # noqa: ARG001 — signal signature
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)

    # Machine-readable ready banner (the controller logs it; liveness is
    # established by the router's retry-connect, not by parsing this).
    print(json.dumps(dict(
        event="ready", replica=args.replica_id, pid=os.getpid(),
        # server.socket_path, not args.socket: a tcp://host:0 bind
        # advertises the resolved port.
        socket=server.socket_path, ringVersion=partition.ring.version,
        partition=engine.stats().get("partition"),
    )), flush=True)

    # Heartbeat: the serve.replica_kill fault site lives HERE, on the main
    # thread, so a plan rule (targeted per replica via the label) SIGKILLs
    # the whole process mid-traffic — the crash the failover drill needs.
    while not stop.is_set():
        faults.check("serve.replica_kill", label=args.replica_id)
        stop.wait(args.heartbeat_s)

    # SIGTERM drain: stop accepting, let in-flight batches finish.
    logger.info("fleet replica %s: draining", args.replica_id)
    server.close()
    engine.close(drain=True)
    return 0


# ---------------------------------------------------------------------------
# Router side (the front-end process)
# ---------------------------------------------------------------------------


class FleetRouter:
    """Consistent-hash request routing over the replica set.

    Every request routes by its entity key's ring owner; a dead owner's
    traffic walks the ring's preference order to the first live member
    (which scores the foreign entities FE-only — degraded, never an
    error). Entity-less requests go to the least-loaded live member.
    A lost connection mid-flight retries the request on the next live
    candidate, so a SIGKILL'd replica costs zero caller errors.
    """

    UID_OWNER_CAP = 1 << 18  # uid → replica memory bound (feedback routing)

    def __init__(
        self,
        ring: HashRing,
        ledger: FleetAdmissionLedger,
        route_re_type: Optional[str] = None,
        queue_cap: int = 1024,
        result_timeout_s: float = 120.0,
        router_id: Optional[str] = None,
        secret: Optional[str] = None,
    ):
        self.ring = ring
        self.ledger = ledger
        self.route_re_type = route_re_type
        self.queue_cap = int(queue_cap)
        self.result_timeout_s = result_timeout_s
        # Stable per-router identity for the split-brain guard: every ring
        # push carries it, and a replica that already follows a DIFFERENT
        # router for this epoch rejects the push and says so.
        self.router_id = router_id or (
            f"router-{os.getpid()}-{os.urandom(3).hex()}"
        )
        self.secret = secret
        # Drill-scale burn windows: a sustained split-brain pages within
        # seconds (the same state machine the serve SLOs run).
        self.slo = SLOTracker(
            objectives=[Objective("fleet_split_brain", 0.999)],
            page_rules=DRILL_PAGE_RULES,
            warn_rules=DRILL_WARN_RULES,
            min_events=1,
        )
        self._lock = threading.RLock()
        self._clients: Dict[str, ScorerClient] = {}
        self._state: Dict[str, str] = {}
        self._uid_owner: "OrderedDict[str, str]" = OrderedDict()

    # -- membership ---------------------------------------------------------

    def attach(
        self, replica_id: str, socket_path: str,
        connect_timeout_s: float = 180.0,
    ) -> ScorerClient:
        """Connect (retrying while the replica warms) and mark live."""
        client = ScorerClient(socket_path, connect_timeout_s,
                              secret=self.secret)
        with self._lock:
            old = self._clients.get(replica_id)
            self._clients[replica_id] = client
            self._state[replica_id] = LIVE
        if old is not None:
            old.close()
        return client

    def mark(self, replica_id: str, state: str) -> None:
        with self._lock:
            self._state[replica_id] = state

    def detach(self, replica_id: str) -> None:
        with self._lock:
            client = self._clients.pop(replica_id, None)
            self._state.pop(replica_id, None)
        if client is not None:
            client.close()

    def client(self, replica_id: str) -> Optional[ScorerClient]:
        with self._lock:
            return self._clients.get(replica_id)

    def states(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._state)

    def live_members(self) -> List[str]:
        with self._lock:
            return [
                m for m in self.ring.members
                if self._state.get(m) == LIVE and m in self._clients
            ]

    def _on_conn_lost(self, replica_id: str) -> None:
        with self._lock:
            if self._state.get(replica_id) == LIVE:
                self._state[replica_id] = DEAD
                logger.warning(
                    "fleet: replica %s connection lost; marked dead "
                    "(shard fails over FE-only)", replica_id,
                )

    # -- scoring ------------------------------------------------------------

    def _candidates(self, key: Optional[str]) -> List[str]:
        if key is not None:
            pref = self.ring.preference(key)
            with self._lock:
                return [
                    m for m in pref
                    if self._state.get(m) == LIVE and m in self._clients
                ]
        live = self.live_members()
        # Entity-less requests are FE-only everywhere: least-loaded wins.
        return sorted(live, key=lambda m: self.ledger.inflight(m))

    def submit(
        self,
        raw_request: dict,
        tenant: Optional[str],
        priority: str = INTERACTIVE,
        model_version: Optional[str] = None,
        trace: Optional[dict] = None,
    ) -> Future:
        # Fleet-global admission: ONE ledger charge per request, before any
        # replica sees it — identical shed semantics at any fleet size.
        self.ledger.admit(
            tenant, priority,
            queue_depth=self.ledger.inflight(),
            queue_cap=self.queue_cap,
        )
        entity_ids = (
            raw_request.get("entityIds")
            if isinstance(raw_request, dict) else None
        )
        key = route_key(entity_ids, self.route_re_type)
        cands = self._candidates(key)
        if not cands:
            raise BackpressureError("no live scorer replicas")
        dst: Future = Future()
        self._try(
            raw_request, tenant, priority, model_version, trace, cands, dst
        )
        return dst

    def _try(
        self, raw_request, tenant, priority, model_version, trace,
        cands: List[str], dst: Future,
    ) -> None:
        replica_id, rest = cands[0], cands[1:]
        client = self.client(replica_id)
        if client is None:
            self._advance(
                raw_request, tenant, priority, model_version, trace,
                replica_id, rest, dst,
                ConnectionError(f"replica {replica_id} not attached"),
            )
            return
        registry().counter("fleet_requests_total", replica=replica_id).inc()
        self.ledger.begin(replica_id)
        t0 = time.monotonic()
        try:
            src = client.submit_score(
                raw_request, tenant, priority, model_version, trace=trace
            )
        except ConnectionError as exc:
            self.ledger.end(replica_id)
            registry().counter(
                "fleet_rpc_errors_total", replica=replica_id
            ).inc()
            self._on_conn_lost(replica_id)
            self._advance(
                raw_request, tenant, priority, model_version, trace,
                replica_id, rest, dst, exc,
            )
            return

        def _done(f: Future) -> None:
            self.ledger.end(replica_id)
            registry().histogram(
                "fleet_rpc_latency_s", replica=replica_id, op="score"
            ).observe(time.monotonic() - t0)
            exc = f.exception()
            if isinstance(exc, ConnectionError):
                registry().counter(
                    "fleet_rpc_errors_total", replica=replica_id
                ).inc()
                # The replica died with this request in flight. Scoring is
                # read-only → safe to replay on the next live candidate.
                self._on_conn_lost(replica_id)
                self._advance(
                    raw_request, tenant, priority, model_version, trace,
                    replica_id, rest, dst, exc,
                )
            elif exc is not None:
                dst.set_exception(exc)
            else:
                res = dict(f.result() or {})
                res["replica"] = replica_id
                uid = (
                    raw_request.get("uid")
                    if isinstance(raw_request, dict) else None
                )
                if uid is not None:
                    self._record_uid(str(uid), replica_id)
                dst.set_result(res)

        src.add_done_callback(_done)

    def _advance(
        self, raw_request, tenant, priority, model_version, trace,
        failed_id: str, rest: List[str], dst: Future,
        exc: BaseException,
    ) -> None:
        registry().counter("fleet_failover_total", replica=failed_id).inc()
        with self._lock:
            nxt = [
                m for m in rest
                if self._state.get(m) == LIVE and m in self._clients
            ]
        if nxt:
            self._try(
                raw_request, tenant, priority, model_version, trace, nxt, dst
            )
        else:
            dst.set_exception(exc)

    def _record_uid(self, uid: str, replica_id: str) -> None:
        with self._lock:
            self._uid_owner[uid] = replica_id
            self._uid_owner.move_to_end(uid)
            while len(self._uid_owner) > self.UID_OWNER_CAP:
                self._uid_owner.popitem(last=False)

    def uid_owner(self, uid: str) -> Optional[str]:
        with self._lock:
            return self._uid_owner.get(uid)

    # -- control plane ------------------------------------------------------

    def rpc_call(
        self, replica_id: str, op: str, timeout_s: float = 30.0, **payload
    ):
        """One timed control-plane RPC to a member: every call lands in the
        per-peer ``fleet_rpc_latency_s{replica,op}`` histogram, every
        failure in ``fleet_rpc_errors_total{replica}`` — the two signals a
        cross-host deployment alerts on. Raises on failure (callers decide
        whether a member failing the op is fatal)."""
        client = self.client(replica_id)
        if client is None:
            raise ConnectionError(f"replica {replica_id} not attached")
        t0 = time.monotonic()
        try:
            res = client.call(op, timeout_s=timeout_s, **payload)
        except Exception:
            registry().counter(
                "fleet_rpc_errors_total", replica=replica_id
            ).inc()
            raise
        finally:
            registry().histogram(
                "fleet_rpc_latency_s", replica=replica_id, op=op
            ).observe(time.monotonic() - t0)
        return res

    def broadcast_ring(self, timeout_s: float = 120.0) -> Dict[str, dict]:
        """Push the current ring snapshot to every live replica (each
        rebuilds its partition predicate in place). Returns per-replica
        results; a member failing the push is marked dead. Each reply
        feeds the ``fleet_split_brain`` SLO objective: a replica that
        rejects this router's claim because ANOTHER router owns the epoch
        is a bad event, and a sustained burn of those pages."""
        snap = self.ring.snapshot()
        out: Dict[str, dict] = {}
        for replica_id in self.live_members():
            if self.client(replica_id) is None:
                continue
            try:
                res = self.rpc_call(
                    replica_id, "ring", timeout_s=timeout_s,
                    snapshot=snap, routeReType=self.route_re_type,
                    routerId=self.router_id,
                )
            except Exception as exc:  # noqa: BLE001 — per-member failure
                logger.warning(
                    "fleet: ring push to %s failed: %s", replica_id, exc
                )
                self._on_conn_lost(replica_id)
                out[replica_id] = dict(error=str(exc))
                continue
            split = bool((res or {}).get("splitBrain"))
            self.slo.record_event("fleet_split_brain", good=not split)
            if split:
                logger.error(
                    "fleet: replica %s rejected ring v%d — epoch claimed "
                    "by router %s (split brain)",
                    replica_id, snap.get("version"),
                    (res or {}).get("claimant"),
                )
            out[replica_id] = res
        return out

    def replica_stats(self, timeout_s: float = 30.0) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for replica_id in self.live_members():
            if self.client(replica_id) is None:
                continue
            try:
                out[replica_id] = self.rpc_call(
                    replica_id, "stats", timeout_s=timeout_s
                )
            except Exception as exc:  # noqa: BLE001 — per-member failure
                out[replica_id] = dict(error=str(exc))
        try:
            self.ledger.update_quality(tenant_quality(
                res.get("quality")
                for res in out.values() if isinstance(res, dict)
            ))
        except Exception:  # noqa: BLE001 — stats must never fail on obs
            pass
        return out

    def replica_metrics(self, timeout_s: float = 30.0) -> Dict[str, dict]:
        """Per-replica metrics scrape: ``{replica: {"ok": True, "metrics":
        [snapshot records]}}`` for members that answered, ``{"ok": False,
        "error": str}`` for members that died mid-scrape. A partial fleet
        scrape stays LABELED as partial — the merged ``/metrics`` render
        marks the missing member instead of silently presenting a smaller
        fleet as the whole one."""
        out: Dict[str, dict] = {}
        for replica_id in self.live_members():
            if self.client(replica_id) is None:
                out[replica_id] = dict(ok=False, error="not attached")
                continue
            try:
                out[replica_id] = dict(
                    ok=True,
                    metrics=self.rpc_call(
                        replica_id, "metrics", timeout_s=timeout_s
                    ) or [],
                )
            except Exception as exc:  # noqa: BLE001 — per-member failure
                out[replica_id] = dict(ok=False, error=str(exc))
        return out

    def replica_traces(
        self, limit: Optional[int] = None, timeout_s: float = 30.0,
    ) -> List[dict]:
        """Every live member's kept flight-recorder trees (concatenated;
        callers merge by trace id). A member failing the scrape contributes
        nothing — trace dumps are diagnostics, not bookkeeping."""
        entries: List[dict] = []
        for replica_id in self.live_members():
            if self.client(replica_id) is None:
                continue
            try:
                entries.extend(
                    self.rpc_call(
                        replica_id, "traces", timeout_s=timeout_s, limit=limit
                    ) or []
                )
            except Exception:  # noqa: BLE001 — per-member failure
                pass
        return entries

    def fleet_snapshot(self) -> dict:
        """The ``/healthz`` ``fleet`` block: ring version, per-replica
        shard ranges, member states, the global admission ledger, and this
        router's identity + split-brain SLO state."""
        try:
            self.slo.publish_metrics()
        except Exception:  # noqa: BLE001 — stats must never fail on obs
            pass
        return dict(
            ringVersion=self.ring.version,
            routerId=self.router_id,
            members=self.ring.members,
            states=self.states(),
            routeReType=self.route_re_type,
            shardRanges=self.ring.shard_ranges(),
            admission=self.ledger.fleet_snapshot(),
            slo=self.slo.snapshot(),
        )


class FleetBackend:
    """The ``make_http_handler`` backend for the fleet front end: submits
    route through the ring, ``/healthz`` carries the fleet snapshot,
    reloads broadcast, and feedback follows each uid back to the replica
    that scored it (so the label joins in the RIGHT per-replica spool)."""

    def __init__(self, router: FleetRouter, result_timeout_s: float = 120.0):
        self.router = router
        self.result_timeout_s = result_timeout_s

    def submit(
        self, raw_request: dict, tenant: Optional[str], priority: str,
        model_version: Optional[str] = None,
        trace: Optional[dict] = None,
    ) -> Future:
        return self.router.submit(
            raw_request, tenant, priority, model_version, trace=trace
        )

    def stats(self) -> dict:
        from photon_tpu.obs.export import exporter_health

        return dict(
            fleet=self.router.fleet_snapshot(),
            replicas=self.router.replica_stats(),
            # Frontend-process exporter health: a dead collector must be
            # visible in /healthz without ever gating readiness.
            otlp_exporter=exporter_health(),
        )

    def metrics_snapshots(self) -> List[dict]:
        """Fleet-merged snapshot records: this process's instruments
        (``replica="frontend"``) plus every replica's (their own labels).
        A replica that failed the scrape shows up as
        ``fleet_scrape_failed{replica=...} 1`` — visible, not missing."""
        snaps = [
            _stamp_labels(s, replica="frontend")
            for s in registry().snapshot()
        ]
        for replica_id, res in self.router.replica_metrics().items():
            if res.get("ok"):
                snaps.extend(
                    _stamp_labels(s, replica=replica_id)
                    for s in res.get("metrics") or []
                )
            else:
                snaps.append(dict(
                    record="metric", metric="fleet_scrape_failed",
                    type="gauge", labels={"replica": str(replica_id)},
                    value=1, stats=None,
                ))
        return snaps

    def metrics_text(self) -> str:
        return render_prometheus(self.metrics_snapshots())

    def traces(self, limit: Optional[int] = None) -> List[dict]:
        """One merged entry per trace id across the frontend process and
        every replica — a routed request's http/relay/replica hops
        reassemble here."""
        entries = list(flight_recorder().traces(limit=limit))
        entries.extend(self.router.replica_traces(limit=limit))
        return merge_trace_dumps(entries)

    def reload(self, body: dict) -> dict:
        out: Dict[str, dict] = {}
        for replica_id in self.router.live_members():
            client = self.router.client(replica_id)
            if client is None:
                continue
            out[replica_id] = client.call(
                "reload", timeout_s=600.0,
                modelDir=body.get("modelDir"),
                modelVersion=body.get("modelVersion"),
            )
        return out

    def feedback(self, body: dict) -> dict:
        if not isinstance(body, dict):
            raise ValueError("feedback body must be a JSON object")
        items = body.get("labels")
        if items is None:
            items = [body]
        if not isinstance(items, list):
            raise ValueError("'labels' must be a list of {uid, label} objects")
        # Group by the replica that scored each uid; unknown uids (aged out
        # of the router's map, or scored before a restart) broadcast.
        grouped: Dict[Optional[str], List[dict]] = {}
        for item in items:
            uid = item.get("uid") if isinstance(item, dict) else None
            owner = self.router.uid_owner(str(uid)) if uid is not None else None
            grouped.setdefault(owner, []).append(item)
        joined = 0
        dropped = 0
        for owner, chunk in grouped.items():
            targets = (
                [owner] if owner in self.router.live_members()
                else self.router.live_members()
            )
            chunk_joined = 0
            for replica_id in targets:
                client = self.router.client(replica_id)
                if client is None:
                    continue
                try:
                    res = client.call(
                        "feedback", timeout_s=30.0, body={"labels": chunk}
                    )
                except Exception as exc:  # noqa: BLE001 — per-member failure
                    logger.warning(
                        "fleet: feedback to %s failed: %s", replica_id, exc
                    )
                    continue
                chunk_joined += int(res.get("joined", 0))
                if chunk_joined >= len(chunk):
                    break  # broadcast resolved every uid already
            joined += chunk_joined
            dropped += max(0, len(chunk) - chunk_joined)
        return {"joined": joined, "dropped": dropped}


class FleetRelayScorerServer(ScorerServer):
    """The scorer-socket server for a FLEET front end: lets
    :class:`~photon_tpu.serve.frontend.ServingFrontend`'s forked HTTP
    workers (which speak the ordinary scorer IPC) sit in front of a whole
    replica fleet instead of one local engine. Each ``score`` routes
    through the :class:`FleetBackend`'s ring; ``metrics``/``traces``
    answer with the fleet-wide merge, so a worker's ``/metrics`` and
    ``/v1/traces`` see every replica.

    Trace-wise this is the middle hop: the worker's http span is the
    parent, this relay records ``relay/route`` under it, and the replica
    that scores records its ``scorer/score`` under the relay span — three
    processes, one tree."""

    def __init__(self, backend: FleetBackend, socket_path: str):
        super().__init__(engine=None, socket_path=socket_path)
        self.backend = backend

    def _op_score(self, rid, msg: dict, out) -> None:
        raw = msg.get("request") or {}
        ctx = TraceContext.from_dict(msg.get("trace"))
        sid: Optional[str] = None
        down: Optional[dict] = None
        if ctx is not None and ctx.sampled:
            sid = new_span_id()
            down = ctx.child(sid).to_dict()
        t0 = time.monotonic()
        fut = self.backend.submit(
            raw,
            msg.get("tenant"),
            msg.get("priority") or INTERACTIVE,
            msg.get("modelVersion"),
            trace=down,
        )

        def _done(f: Future) -> None:
            exc = f.exception()
            if sid is not None:
                try:
                    dt = time.monotonic() - t0
                    tracer().record(
                        "relay/route", dt, parent="",
                        context=ctx, span_id=sid,
                    )
                    flight_recorder().finish(
                        ctx.trace_id, dt,
                        error=None if exc is None else str(exc),
                        forced=ctx.forced,
                    )
                except Exception:
                    pass  # telemetry must never fail the response
            if exc is not None:
                out.put(self._error_payload(rid, exc))
            else:
                out.put(dict(id=rid, ok=True, result=f.result()))

        fut.add_done_callback(_done)

    def _op_stats(self) -> dict:
        return self.backend.stats()

    def _op_feedback(self, msg: dict) -> dict:
        return self.backend.feedback(msg.get("body") or {})

    def _op_reload(self, rid, msg: dict, out) -> None:
        try:
            out.put(dict(
                id=rid, ok=True,
                result=self.backend.reload(dict(
                    modelDir=msg.get("modelDir"),
                    modelVersion=msg.get("modelVersion"),
                )),
            ))
        except Exception as exc:  # noqa: BLE001 — per-request failure
            out.put(self._error_payload(rid, exc))

    def _op_metrics(self, msg: dict) -> List[dict]:
        return self.backend.metrics_snapshots()

    def _op_traces(self, msg: dict) -> List[dict]:
        return self.backend.traces(limit=msg.get("limit"))


class FleetHTTPFrontend:
    """ThreadingHTTPServer speaking the standard serving API over a
    :class:`FleetBackend`, on a background thread. ``port`` is resolved
    after ``start`` (pass 0 to let the OS pick)."""

    def __init__(self, backend: FleetBackend, host: str = "127.0.0.1",
                 port: int = 0):
        self.backend = backend
        self._httpd = ThreadingHTTPServer(
            (host, port), make_http_handler(backend)
        )
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "FleetHTTPFrontend":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs=dict(poll_interval=0.1),
            name="fleet-http", daemon=True,
        )
        self._thread.start()
        return self

    def close(self) -> None:
        # shutdown() blocks forever unless serve_forever is running; a
        # frontend that was never start()ed still needs its socket closed.
        if self._thread is not None:
            self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10.0)


# ---------------------------------------------------------------------------
# Fleet controller (spawn / join / drain / kill / revive)
# ---------------------------------------------------------------------------


def _free_port() -> int:
    """Reserve a loopback TCP port (bind-0, read, release). The replica
    re-binds it with SO_REUSEADDR moments later; the window is the same one
    every ephemeral-port test harness accepts."""
    s = socket_mod.socket(socket_mod.AF_INET, socket_mod.SOCK_STREAM)
    try:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]
    finally:
        s.close()


class ScorerFleet:
    """Owns the replica subprocesses and the elastic-membership protocol.

    Lifecycle verbs: ``start`` (spawn + connect the initial set), ``join``
    (spawn with the post-join ring, wait ready, THEN flip routing — the
    warming replica never sees traffic early), ``leave`` (drain in-flight
    via the settle discipline, drop from the ring, broadcast, SIGTERM),
    ``kill`` (SIGKILL, ring UNCHANGED — the shard fails over FE-only along
    the preference order), ``revive`` (respawn the same id, reconnect,
    traffic re-homes to exact scores), ``shutdown``.
    """

    def __init__(
        self,
        model_dir: str,
        workdir: str,
        artifacts_dir: Optional[str] = None,
        route_re_type: Optional[str] = None,
        vnodes: int = 64,
        seed: int = 0,
        hot_bytes: int = 64 << 20,
        max_batch_size: int = 64,
        max_delay_ms: float = 2.0,
        queue_cap: int = 1024,
        admission: Optional[AdmissionConfig] = None,
        spool_base: Optional[str] = None,
        compact_host: bool = True,
        result_timeout_s: float = 120.0,
        connect_timeout_s: float = 300.0,
        heartbeat_s: float = 0.25,
        replica_env: Optional[Dict[str, Dict[str, str]]] = None,
        transport: str = "unix",
        secret: Optional[str] = None,
        weights: Optional[Dict[str, int]] = None,
    ):
        if transport not in ("unix", "tcp"):
            raise ValueError(f"transport must be unix|tcp, got {transport!r}")
        self.model_dir = model_dir
        self.artifacts_dir = artifacts_dir
        self.workdir = workdir
        self.route_re_type = route_re_type
        self.hot_bytes = int(hot_bytes)
        self.max_batch_size = int(max_batch_size)
        self.max_delay_ms = float(max_delay_ms)
        self.queue_cap = int(queue_cap)
        self.spool_base = spool_base
        self.compact_host = compact_host
        self.connect_timeout_s = connect_timeout_s
        self.heartbeat_s = float(heartbeat_s)
        self.transport = transport
        # TCP needs the shared handshake secret on both ends; generate one
        # for loopback fleets when the environment doesn't provide it.
        if transport == "tcp" and not secret:
            secret = os.environ.get(FLEET_SECRET_ENV) or os.urandom(16).hex()
        self.secret = secret
        self._endpoints: Dict[str, str] = {}
        # Per-replica extra environment — how a drill targets ONE replica
        # with a PHOTON_TPU_FAULT_PLAN kill rule.
        self.replica_env = dict(replica_env or {})
        os.makedirs(workdir, exist_ok=True)
        self.ring = HashRing(vnodes=vnodes, seed=seed, weights=weights)
        self.ledger = FleetAdmissionLedger(admission)
        self.router = FleetRouter(
            self.ring, self.ledger, route_re_type,
            queue_cap=queue_cap, result_timeout_s=result_timeout_s,
            secret=self.secret if transport == "tcp" else None,
        )
        self._procs: Dict[str, subprocess.Popen] = {}
        self._logs: Dict[str, object] = {}

    # -- plumbing -----------------------------------------------------------

    def socket_path(self, replica_id: str) -> str:
        """The replica's framed-IPC endpoint: a workdir Unix socket path,
        or (``transport="tcp"``) a loopback ``tcp://`` endpoint with a port
        reserved at first use — the SAME frame protocol either way."""
        if self.transport == "tcp":
            ep = self._endpoints.get(replica_id)
            if ep is None:
                ep = f"tcp://127.0.0.1:{_free_port()}"
                self._endpoints[replica_id] = ep
            return ep
        return os.path.join(self.workdir, f"scorer-{replica_id}.sock")

    def log_path(self, replica_id: str) -> str:
        return os.path.join(self.workdir, f"scorer-{replica_id}.log")

    def _spawn(self, replica_id: str, ring_snapshot: dict) -> subprocess.Popen:
        cmd = [
            sys.executable, "-m", "photon_tpu.serve.fleet",
            "--socket", self.socket_path(replica_id),
            "--replica-id", replica_id,
            "--model-dir", self.model_dir,
            "--ring", json.dumps(ring_snapshot),
            "--hot-bytes", str(self.hot_bytes),
            "--max-batch-size", str(self.max_batch_size),
            "--max-delay-ms", str(self.max_delay_ms),
            "--queue-cap", str(self.queue_cap),
            "--heartbeat-s", str(self.heartbeat_s),
        ]
        if self.artifacts_dir:
            cmd += ["--artifacts-dir", self.artifacts_dir]
        if self.route_re_type:
            cmd += ["--route-re-type", self.route_re_type]
        if self.spool_base:
            cmd += ["--spool-dir", self.spool_base]
        if not self.compact_host:
            cmd += ["--no-compact-host"]
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        if self.transport == "tcp" and self.secret:
            env[FLEET_SECRET_ENV] = self.secret
        # The replica must import photon_tpu no matter the caller's cwd:
        # put the package's parent dir on its path explicitly.
        import photon_tpu

        pkg_root = os.path.dirname(os.path.dirname(photon_tpu.__file__))
        parts = [pkg_root] + [
            p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p
        ]
        env["PYTHONPATH"] = os.pathsep.join(dict.fromkeys(parts))
        env.update(self.replica_env.get(replica_id, {}))
        log = open(self.log_path(replica_id), "ab")
        old_log = self._logs.pop(replica_id, None)
        if old_log is not None:
            try:
                old_log.close()
            except OSError:
                pass
        self._logs[replica_id] = log
        proc = subprocess.Popen(
            cmd, stdout=log, stderr=subprocess.STDOUT, env=env
        )
        self._procs[replica_id] = proc
        logger.info(
            "fleet: spawned replica %s (pid %d)", replica_id, proc.pid
        )
        return proc

    # -- lifecycle ----------------------------------------------------------

    def start(self, replica_ids: Sequence[str]) -> "ScorerFleet":
        for replica_id in replica_ids:
            self.ring.add(replica_id)
        snap = self.ring.snapshot()
        for replica_id in replica_ids:
            self._spawn(replica_id, snap)
        for replica_id in replica_ids:
            self.router.attach(
                replica_id, self.socket_path(replica_id),
                self.connect_timeout_s,
            )
        return self

    def join(
        self,
        replica_id: str,
        warm: bool = True,
        weight: Optional[int] = None,
    ) -> None:
        """Elastic join: the newcomer warms with the POST-join ring (its
        partition is right from birth), traffic flips only once it is
        connectable, then the incumbents re-partition.

        ``warm=True`` additionally streams each incumbent's HOT rows for
        the keys the new ring reassigns to the newcomer — BEFORE the ring
        flips — so the newcomer's first requests hit a warm cache instead
        of paying a cold-start miss storm (the join-side degradation
        window). ``warm=False`` is the measured-for-contrast cold path."""
        future_ring = HashRing.from_snapshot(self.ring.snapshot())
        future_ring.add(replica_id, weight=weight)
        future_snap = future_ring.snapshot()
        self._spawn(replica_id, future_snap)
        self.router.attach(
            replica_id, self.socket_path(replica_id), self.connect_timeout_s
        )
        if warm:
            self._warm_handoff_to(replica_id, future_snap, include_cold=False)
        self.ring.add(replica_id, weight=weight)  # newcomer already holds it
        self.router.broadcast_ring()
        logger.info("fleet: %s joined (ring v%d)", replica_id,
                    self.ring.version)

    def _warm_handoff_to(
        self, newcomer: str, future_snap: dict, include_cold: bool
    ) -> None:
        """Stream every incumbent's handoff payload for ``newcomer`` (its
        owned entities moving there under ``future_snap``). Best-effort: a
        member failing its export degrades THAT slice to the cold path —
        membership changes must never hinge on a warm-up RPC."""
        t0 = time.monotonic()
        moved = dict(rows=0, promoted=0)
        for member in self.router.live_members():
            if member == newcomer:
                continue
            try:
                payload = self.router.rpc_call(
                    member, "shard_export", timeout_s=120.0,
                    snapshot=future_snap, targetMember=newcomer,
                    includeCold=include_cold,
                )
                if not (payload or {}).get("groups"):
                    continue
                res = self.router.rpc_call(
                    newcomer, "shard_import", timeout_s=120.0,
                    payload=payload,
                )
                for stats in (res or {}).values():
                    moved["rows"] += int(stats.get("rowsAdded", 0))
                    moved["promoted"] += int(stats.get("promoted", 0))
            except Exception as exc:  # noqa: BLE001 — best-effort warm-up
                logger.warning(
                    "fleet: warm handoff %s->%s failed (cold for that "
                    "slice): %s", member, newcomer, exc,
                )
        logger.info(
            "fleet: warm handoff to %s: %d rows, %d pre-promoted (%.2fs)",
            newcomer, moved["rows"], moved["promoted"],
            time.monotonic() - t0,
        )

    def leave(
        self, replica_id: str, settle_s: float = 30.0, warm: bool = True,
    ) -> None:
        """Graceful leave, same settle discipline as the rollout watcher:
        stop routing new work to the member, wait for its in-flight count
        to drain (bounded by ``settle_s``), re-partition the survivors,
        then SIGTERM (the replica's own drain finishes anything left).

        ``warm=True`` first streams the leaver's shard to its new owners,
        grouped per survivor under the post-leave ring — host rows AND the
        hot set. Without it, compacted survivors have no host rows for the
        inherited keys and serve them FE-only until a reload (the drain
        degradation window this kills)."""
        future_ring = HashRing.from_snapshot(self.ring.snapshot())
        if replica_id in future_ring:
            future_ring.remove(replica_id)
        future_snap = future_ring.snapshot()
        if warm and replica_id in self.ring:
            t0 = time.monotonic()
            moved = dict(rows=0, promoted=0)
            for survivor in self.router.live_members():
                if survivor == replica_id:
                    continue
                try:
                    payload = self.router.rpc_call(
                        replica_id, "shard_export", timeout_s=120.0,
                        snapshot=future_snap, targetMember=survivor,
                        includeCold=True,
                    )
                    if not (payload or {}).get("groups"):
                        continue
                    res = self.router.rpc_call(
                        survivor, "shard_import", timeout_s=120.0,
                        payload=payload,
                    )
                    for stats in (res or {}).values():
                        moved["rows"] += int(stats.get("rowsAdded", 0))
                        moved["promoted"] += int(stats.get("promoted", 0))
                except Exception as exc:  # noqa: BLE001 — best-effort
                    logger.warning(
                        "fleet: warm handoff %s->%s failed (FE-only for "
                        "that slice until reload): %s",
                        replica_id, survivor, exc,
                    )
            logger.info(
                "fleet: drain handoff from %s: %d rows, %d pre-promoted "
                "(%.2fs)", replica_id, moved["rows"], moved["promoted"],
                time.monotonic() - t0,
            )
        self.router.mark(replica_id, DRAINING)
        deadline = time.monotonic() + settle_s
        while (
            self.ledger.inflight(replica_id) > 0
            and time.monotonic() < deadline
        ):
            time.sleep(0.02)
        if replica_id in self.ring:
            self.ring.remove(replica_id)
        self.router.broadcast_ring()
        self.router.detach(replica_id)
        proc = self._procs.pop(replica_id, None)
        if proc is not None:
            proc.terminate()
            try:
                proc.wait(timeout=30.0)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        logger.info("fleet: %s left (ring v%d)", replica_id,
                    self.ring.version)

    def kill(self, replica_id: str) -> None:
        """SIGKILL a replica, ring unchanged — the crash drill. Its shard
        fails over FE-only to ring successors until ``revive``."""
        proc = self._procs.pop(replica_id, None)
        if proc is not None:
            proc.kill()
            proc.wait()
        self.router.mark(replica_id, DEAD)
        logger.info("fleet: %s SIGKILLed (shard failing over FE-only)",
                    replica_id)

    def revive(self, replica_id: str) -> None:
        """Bring a dead member back under the same id: respawn with the
        CURRENT ring, reconnect, mark live — its keys re-home from
        FE-only fallback to exact scores with zero ring movement."""
        self._spawn(replica_id, self.ring.snapshot())
        self.router.attach(
            replica_id, self.socket_path(replica_id), self.connect_timeout_s
        )
        logger.info("fleet: %s revived", replica_id)

    def reap(self) -> Dict[str, int]:
        """Collect exit codes of replicas that died on their own (the
        fault-plan kill path); marks them dead for the router."""
        out: Dict[str, int] = {}
        for replica_id, proc in list(self._procs.items()):
            code = proc.poll()
            if code is not None:
                out[replica_id] = code
                self._procs.pop(replica_id, None)
                self.router.mark(replica_id, DEAD)
        return out

    def fleet_snapshot(self) -> dict:
        snap = self.router.fleet_snapshot()
        snap["pids"] = {
            rid: proc.pid for rid, proc in self._procs.items()
        }
        return snap

    def shutdown(self, timeout_s: float = 30.0) -> None:
        for replica_id in list(self.router.states()):
            self.router.detach(replica_id)
        for replica_id, proc in list(self._procs.items()):
            proc.terminate()
        deadline = time.monotonic() + timeout_s
        for replica_id, proc in list(self._procs.items()):
            try:
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        self._procs.clear()
        for log in self._logs.values():
            try:
                log.close()
            except OSError:
                pass
        self._logs.clear()


if __name__ == "__main__":
    sys.exit(replica_main())
