"""Down-sampling for fixed-effect training.

Parity target: reference ``DownSampler`` trait (photon-lib
sampling/DownSampler.scala:28-67), ``BinaryClassificationDownSampler``
(negatives only, reweighted; BinaryClassificationDownSampler.scala:32) and
``DefaultDownSampler`` (DefaultDownSampler.scala:28), selected per task by
``DownSamplerHelper`` (photon-api sampling/DownSamplerHelper.scala).

TPU-first: sampling is a deterministic-by-seed weight mask — dropped samples
get weight 0, kept samples are reweighted by 1/rate, and shapes never change
(no filter/shuffle). Weighted objectives make this exact.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from photon_tpu.data.batch import LabeledBatch
from photon_tpu.types import TaskType

Array = jax.Array


@dataclasses.dataclass
class DownSampler:
    """Uniform down-sampling of all samples (DefaultDownSampler role)."""

    rate: float
    seed: int = 0

    def _keep(self, n: int, salt: int) -> Array:
        key = jax.random.fold_in(jax.random.PRNGKey(self.seed), salt)
        return jax.random.uniform(key, (n,)) < self.rate

    def apply(self, batch: LabeledBatch) -> LabeledBatch:
        keep = self._keep(batch.n, 0)
        new_w = jnp.where(keep, batch.weight / self.rate, 0.0)
        return LabeledBatch(batch.label, batch.features, batch.offset, new_w, batch.uid)


@dataclasses.dataclass
class DefaultDownSampler(DownSampler):
    pass


@dataclasses.dataclass
class BinaryClassificationDownSampler(DownSampler):
    """Down-samples only the negative class, reweighting kept negatives by
    1/rate so the implied class prior is unchanged."""

    def apply(self, batch: LabeledBatch) -> LabeledBatch:
        keep = self._keep(batch.n, 1)
        is_neg = batch.label <= 0
        new_w = jnp.where(
            is_neg, jnp.where(keep, batch.weight / self.rate, 0.0), batch.weight
        )
        return LabeledBatch(batch.label, batch.features, batch.offset, new_w, batch.uid)


def down_sampler_for_task(task: TaskType, rate: float, seed: int = 0) -> DownSampler:
    """Task → sampler dispatch (DownSamplerHelper role)."""
    if task in (TaskType.LOGISTIC_REGRESSION, TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM):
        return BinaryClassificationDownSampler(rate, seed)
    return DefaultDownSampler(rate, seed)
