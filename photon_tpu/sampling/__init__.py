from photon_tpu.sampling.down_sampler import (  # noqa: F401
    BinaryClassificationDownSampler,
    DefaultDownSampler,
    DownSampler,
    down_sampler_for_task,
)
