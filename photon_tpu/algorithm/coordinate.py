"""Coordinate protocol: one block of the GAME coordinate-descent problem.

Parity target: reference ``Coordinate`` / ``ModelCoordinate`` (photon-lib
algorithm/Coordinate.scala:28-84, ModelCoordinate.scala:28-63) — trainModel
(± initial model, ± residual scores) and score(model).

TPU-first: residuals are a flat (n,) score array aligned with the GameBatch
sample axis (``addScoresToOffsets`` is addition, not a join).
"""

from __future__ import annotations

import abc
from typing import Any, Optional, Tuple

import jax

from photon_tpu.data.game_data import GameBatch

Array = jax.Array


class Coordinate(abc.ABC):
    """One coordinate: owns its view of the data + optimization problem."""

    coordinate_id: str

    @abc.abstractmethod
    def train(
        self,
        batch: GameBatch,
        residual_scores: Optional[Array] = None,
        initial_model: Optional[Any] = None,
    ) -> Tuple[Any, Any]:
        """Train against residuals of all other coordinates; returns
        (model, tracker-like diagnostics). The four trainModel overloads of
        the reference collapse into the two optional arguments."""

    @abc.abstractmethod
    def score(self, model: Any, batch: GameBatch) -> Array:
        """Per-sample raw scores of this coordinate's model (no offsets)."""

    @abc.abstractmethod
    def zero_model(self) -> Any:
        """Initial all-zeros model (initializeZeroModel role, reference
        GeneralizedLinearOptimizationProblem.scala:35-91)."""


class ModelCoordinate(Coordinate):
    """Score-only coordinate for locked (partial-retrain) blocks
    (reference FixedEffectModelCoordinate / RandomEffectModelCoordinate)."""

    def __init__(self, coordinate_id: str, inner: Coordinate, model: Any):
        self.coordinate_id = coordinate_id
        self._inner = inner
        self._model = model

    def train(self, batch, residual_scores=None, initial_model=None):
        return self._model, None

    def score(self, model, batch):
        return self._inner.score(self._model if model is None else model, batch)

    def zero_model(self):
        return self._model
