"""Random-effect coordinate: millions of tiny per-entity GLMs as vmapped
batched solves.

Parity target: reference ``RandomEffectCoordinate`` (photon-api
algorithm/RandomEffectCoordinate.scala:37-339) — the reference's hot loop is
`activeData.join(optimizationProblems).mapValues{ per-entity L-BFGS }`,
serial per Spark partition (SURVEY.md §3.2 "HOT LOOP"), plus
``RandomEffectOptimizationProblem`` (an RDD of per-entity problems) and
``RandomEffectOptimizationTracker`` (aggregated convergence stats).

TPU-first: each fixed-shape EntityBlock (E, n_max, d) trains ALL its entities
simultaneously with ``jax.vmap`` over the jittable L-BFGS — one SPMD program
per block instead of millions of serial solves. Entity rows shard over the
mesh's entity axis; there is no cross-entity communication (matching the
reference's embarrassing parallelism, but saturating the MXU with batched
(n_max, d) matvecs). The per-entity tracker reduces to aggregate counts
exactly like the reference's tracker.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from photon_tpu.algorithm.coordinate import Coordinate
from photon_tpu.data.batch import LabeledBatch
from photon_tpu.data.game_data import GameBatch
from photon_tpu.data.random_effect import EntityBlock, RandomEffectDataset, pearson_feature_mask
from photon_tpu.models.game import RandomEffectModel
from photon_tpu.ops.objective import GLMObjective
from photon_tpu.optim.common import (
    OptimizerConfig,
    REASON_FUNCTION_VALUES_CONVERGED,
    REASON_GRADIENT_CONVERGED,
    REASON_MAX_ITERATIONS,
)
from photon_tpu.optim.lbfgs import minimize_lbfgs  # noqa: F401 (TRON/HVP paths)
from photon_tpu.optim.margin_lbfgs import minimize_lbfgs_margin
from photon_tpu.optim.tron import minimize_tron
from photon_tpu.optim.owlqn import minimize_owlqn
from photon_tpu.optim.factory import OptimizerSpec
from photon_tpu.types import OptimizerType, TaskType

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class RandomEffectTrackerStats:
    """Aggregate convergence stats across entity solves
    (RandomEffectOptimizationTracker.scala role). A pytree so trackers ride
    along in coordinate-descent checkpoints."""

    num_entities: int
    num_converged: int
    num_max_iter: int
    mean_iterations: float
    max_iterations: int

    def summary(self) -> str:
        return (
            f"entities={self.num_entities} converged={self.num_converged} "
            f"hit_max_iter={self.num_max_iter} iters(mean={self.mean_iterations:.1f}, "
            f"max={self.max_iterations})"
        )


def _solve_block(
    block: EntityBlock,
    offsets: Array,  # (E, n_max) per-sample residual offsets
    w0: Array,  # (E, d) warm-start coefficients
    objective: GLMObjective,
    spec: OptimizerSpec,
    config: OptimizerConfig,
    feature_mask: Optional[Array] = None,  # (E, d) 0/1 Pearson mask
):
    """vmap one optimizer over all entities of a block. Returns (E, d) coefs +
    per-entity (iterations, reason) for the tracker."""

    def solve_one(feat, lab, wt, off, w_init, fmask, tmask):
        lb = LabeledBatch(lab, feat, off, wt)
        if feature_mask is not None:
            # Optimize f_m(w) = f(w ∘ m): chain rule masks the gradient and
            # sandwiches the Hessian (M H M) so every solver sees a
            # consistent restricted objective.
            def vg(w):
                v, g = objective.value_and_grad(w * fmask, lb)
                return v, g * fmask

            hvp = lambda w, v: fmask * objective.hvp(w * fmask, fmask * v, lb)
        else:
            vg = lambda w: objective.value_and_grad(w, lb)
            hvp = lambda w, v: objective.hvp(w, v, lb)

        if objective.l1_weight > 0.0:
            l1_mask = None
            if objective.intercept_index is not None:
                l1_mask = jnp.ones_like(w_init).at[objective.intercept_index].set(0.0)
            res = minimize_owlqn(vg, w_init, objective.l1_weight, config, l1_mask)
        elif spec.optimizer == OptimizerType.TRON:
            res = minimize_tron(vg, hvp, w_init, config, spec.max_cg_iter)
        elif feature_mask is not None and (
            objective.normalization is not None
            and objective.normalization.shifts is not None
        ):
            # Shift normalization computes es over the FULL w, so masking X
            # columns does not silence masked coordinates (they'd train as
            # pseudo-intercepts). Keep the gradient-masked formulation.
            res = minimize_lbfgs(vg, w_init, config)
        else:
            # Margin-space L-BFGS on the feature-masked batch: X∘m keeps the
            # GLM margin structure, and masked coordinates (appearing only in
            # the separable L2 term) reach the same post-mask optimum as the
            # gradient-masked formulation.
            lb_m = (
                LabeledBatch(lab, feat * fmask[None, :], off, wt)
                if feature_mask is not None
                else lb
            )
            res = minimize_lbfgs_margin(objective, lb_m, w_init, config)
        w_out = res.w * fmask if feature_mask is not None else res.w
        # Entities under the lower-bound filter keep their initial model
        # (reference filterActiveData semantics: not trained this pass).
        w_out = jnp.where(tmask, w_out, w_init)
        return w_out, res.iterations, res.reason_code

    fmask = (
        feature_mask
        if feature_mask is not None
        else jnp.ones((block.num_entities, block.dim), block.features.dtype)
    )
    return jax.vmap(solve_one)(
        block.features, block.label, block.weight, offsets, w0, fmask, block.train_mask
    )


@dataclasses.dataclass
class RandomEffectCoordinate(Coordinate):
    """Per-entity GLM block over one RE type + feature shard."""

    coordinate_id: str
    dataset: RandomEffectDataset
    task: TaskType
    objective: GLMObjective
    optimizer_spec: OptimizerSpec = dataclasses.field(default_factory=OptimizerSpec)
    compute_variance: bool = False

    def __post_init__(self):
        # Per-entity solves keep only aggregate tracker stats (HBM budget).
        self._config = dataclasses.replace(
            self.optimizer_spec.config(), track_history=False
        )
        self._feature_masks: Dict[int, Array] = {}
        ratio = self.dataset.config.features_to_samples_ratio
        if ratio is not None:
            for i, block in enumerate(self.dataset.blocks):
                counts = jnp.sum(block.weight > 0, axis=1)
                # Per-entity cap: k_e = ratio × that entity's sample count
                # (reference RandomEffectDataConfiguration features/samples
                # ratio semantics).
                k_e = jnp.clip(
                    jnp.ceil(counts.astype(jnp.float32) * ratio).astype(jnp.int32),
                    1,
                    self.dataset.dim,
                )
                self._feature_masks[i] = pearson_feature_mask(
                    block, k_e, always_keep=self.objective.intercept_index
                )

    def train(
        self,
        batch: GameBatch,
        residual_scores: Optional[Array] = None,
        initial_model: Optional[RandomEffectModel] = None,
    ) -> Tuple[RandomEffectModel, RandomEffectTrackerStats]:
        E, d = self.dataset.num_entities, self.dataset.dim
        dtype = batch.offset.dtype
        coefs = (
            initial_model.coefficients
            if initial_model is not None
            else jnp.zeros((E, d), dtype)
        )
        # Residuals for THIS coordinate's solves: batch offsets + other
        # coordinates' scores (addScoresToOffsets, gathered per block).
        total_offset = batch.offset
        if residual_scores is not None:
            total_offset = total_offset + residual_scores

        iter_list, reason_list = [], []
        for i, block in enumerate(self.dataset.blocks):
            offs = block.gather_offsets(total_offset)
            w0 = coefs[block.entity_idx]
            w_new, iters, reasons = _solve_block(
                block, offs, w0, self.objective, self.optimizer_spec, self._config,
                self._feature_masks.get(i),
            )
            coefs = coefs.at[block.entity_idx].set(w_new)
            iter_list.append(iters)
            reason_list.append(reasons)

        variances = None
        if self.compute_variance:
            variances = self._block_variances(coefs, total_offset, dtype)

        model = RandomEffectModel(
            coefs, self.dataset.config.re_type, self.dataset.config.feature_shard,
            self.task, variances,
        )
        stats = self._tracker_stats(iter_list, reason_list)
        return model, stats

    def _block_variances(self, coefs: Array, total_offset: Array, dtype) -> Array:
        """Per-entity coefficient variances via inverse diagonal Hessian
        (reference RandomEffectOptimizationProblem variance computation)."""
        E, d = self.dataset.num_entities, self.dataset.dim
        variances = jnp.ones((E, d), dtype)

        def var_one(feat, lab, wt, off, w):
            lb = LabeledBatch(lab, feat, off, wt)
            diag = self.objective.hessian_diagonal(w, lb)
            return 1.0 / jnp.maximum(diag, 1e-12)

        for block in self.dataset.blocks:
            offs = block.gather_offsets(total_offset)
            v = jax.vmap(var_one)(
                block.features, block.label, block.weight, offs, coefs[block.entity_idx]
            )
            variances = variances.at[block.entity_idx].set(v)
        return variances

    @staticmethod
    def _tracker_stats(iter_list, reason_list) -> RandomEffectTrackerStats:
        if not iter_list:
            return RandomEffectTrackerStats(0, 0, 0, 0.0, 0)
        iters = jnp.concatenate([jnp.ravel(x) for x in iter_list])
        reasons = jnp.concatenate([jnp.ravel(x) for x in reason_list])
        converged = jnp.sum(
            (reasons == REASON_FUNCTION_VALUES_CONVERGED)
            | (reasons == REASON_GRADIENT_CONVERGED)
        )
        return RandomEffectTrackerStats(
            num_entities=int(iters.shape[0]),
            num_converged=int(converged),
            num_max_iter=int(jnp.sum(reasons == REASON_MAX_ITERATIONS)),
            mean_iterations=float(jnp.mean(iters.astype(jnp.float32))),
            max_iterations=int(jnp.max(iters)),
        )

    def score(self, model: RandomEffectModel, batch: GameBatch) -> Array:
        return model.score(batch)

    def zero_model(self) -> RandomEffectModel:
        return RandomEffectModel(
            jnp.zeros((self.dataset.num_entities, self.dataset.dim), jnp.float32),
            self.dataset.config.re_type,
            self.dataset.config.feature_shard,
            self.task,
        )
