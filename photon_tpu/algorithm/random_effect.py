"""Random-effect coordinate: millions of tiny per-entity GLMs as vmapped
batched solves.

Parity target: reference ``RandomEffectCoordinate`` (photon-api
algorithm/RandomEffectCoordinate.scala:37-339) — the reference's hot loop is
`activeData.join(optimizationProblems).mapValues{ per-entity L-BFGS }`,
serial per Spark partition (SURVEY.md §3.2 "HOT LOOP"), plus
``RandomEffectOptimizationProblem`` (an RDD of per-entity problems) and
``RandomEffectOptimizationTracker`` (aggregated convergence stats).

TPU-first: each fixed-shape EntityBlock (E, n_max, d) trains ALL its entities
simultaneously with ``jax.vmap`` over the jittable L-BFGS — one SPMD program
per block instead of millions of serial solves. Entity rows shard over the
mesh's entity axis; there is no cross-entity communication (matching the
reference's embarrassing parallelism, but saturating the MXU with batched
(n_max, d) matvecs). The per-entity tracker reduces to aggregate counts
exactly like the reference's tracker.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.algorithm.coordinate import Coordinate
from photon_tpu.algorithm.solve_cache import SolveCache, default_cache
from photon_tpu.data.batch import LabeledBatch
from photon_tpu.data.game_data import GameBatch
from photon_tpu.data.random_effect import (
    EntityBlock,
    RandomEffectDataset,
    compact_entity_blocks,
    pack_into_sizes,
    pearson_feature_mask,
)
from photon_tpu.models.game import (
    DatumScoringModel,
    ProjectedRandomEffectModel,
    RandomEffectModel,
)
from photon_tpu.obs.trace import span
from photon_tpu.ops.objective import GLMObjective
from photon_tpu.ops.variance import coefficient_variances, normalize_variance_type
from photon_tpu.optim.common import (
    OptimizerConfig,
    REASON_DIVERGED,
    REASON_FUNCTION_VALUES_CONVERGED,
    REASON_GRADIENT_CONVERGED,
    REASON_MAX_ITERATIONS,
)
from photon_tpu.optim.lbfgs import minimize_lbfgs  # noqa: F401 (TRON/HVP paths)
from photon_tpu.optim.margin_lbfgs import minimize_lbfgs_margin
from photon_tpu.optim.newton import minimize_newton
from photon_tpu.optim.tron import minimize_tron
from photon_tpu.optim.owlqn import minimize_owlqn
from photon_tpu.optim.factory import OptimizerSpec
from photon_tpu.types import OptimizerType, TaskType, VarianceComputationType
from photon_tpu.utils import faults

Array = jax.Array

# Widest per-entity dimension for which the default solver forms exact
# (d, d) Hessians: above this, batched Newton's E·d² HBM footprint and d³
# Cholesky cost lose to margin-LBFGS's d-linear iterations.
NEWTON_AUTO_MAX_DIM = 128


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class RandomEffectTrackerStats:
    """Aggregate convergence stats across entity solves
    (RandomEffectOptimizationTracker.scala role). A pytree so trackers ride
    along in coordinate-descent checkpoints.

    The per-row iteration/reason arrays stay ON DEVICE: building the tracker
    after a coordinate pass costs no host sync, so the coordinate-descent
    sequence never blocks mid-pass on diagnostics. Python scalars
    materialize lazily — through the aggregate properties or ``summary()``,
    which is where the device→host transfer happens. ``valid`` masks
    shape-bucket padding rows out of every aggregate.
    """

    iterations: Array  # (T,) per-row iteration counts, blocks concatenated
    reasons: Array  # (T,) per-row termination reason codes
    valid: Array  # (T,) bool — False for shape-bucket padding rows

    @staticmethod
    def empty() -> "RandomEffectTrackerStats":
        z = jnp.zeros((0,), jnp.int32)
        return RandomEffectTrackerStats(z, z, jnp.zeros((0,), bool))

    @property
    def num_entities(self) -> int:
        return int(jnp.sum(self.valid))

    @property
    def num_converged(self) -> int:
        conv = (self.reasons == REASON_FUNCTION_VALUES_CONVERGED) | (
            self.reasons == REASON_GRADIENT_CONVERGED
        )
        return int(jnp.sum(conv & self.valid))

    @property
    def num_max_iter(self) -> int:
        return int(jnp.sum((self.reasons == REASON_MAX_ITERATIONS) & self.valid))

    @property
    def num_quarantined(self) -> int:
        """Entities whose solve diverged and kept their previous coefficients
        (the in-trace guard in solve_cache.block_solver)."""
        return int(jnp.sum((self.reasons == REASON_DIVERGED) & self.valid))

    @property
    def mean_iterations(self) -> float:
        n = jnp.maximum(jnp.sum(self.valid), 1)
        return float(
            jnp.sum(jnp.where(self.valid, self.iterations, 0).astype(jnp.float32))
            / n
        )

    @property
    def max_iterations(self) -> int:
        if self.iterations.shape[0] == 0:
            return 0
        return int(jnp.max(jnp.where(self.valid, self.iterations, 0)))

    def summary(self) -> str:
        return (
            f"entities={self.num_entities} converged={self.num_converged} "
            f"hit_max_iter={self.num_max_iter} quarantined={self.num_quarantined} "
            f"iters(mean={self.mean_iterations:.1f}, max={self.max_iterations})"
        )

    def diagnostics_dict(self) -> dict:
        """Report-ready aggregates. Materializes the device-resident rows —
        call only at run-report finalize, never inside the dispatch loop."""
        return dict(
            type="random_effect",
            entities=self.num_entities,
            converged=self.num_converged,
            hit_max_iter=self.num_max_iter,
            quarantined=self.num_quarantined,
            mean_iterations=self.mean_iterations,
            max_iterations=self.max_iterations,
        )


def newton_eligible(
    objective: GLMObjective, spec: OptimizerSpec, block_dim: int, has_mask: bool
) -> bool:
    """Static routing predicate for _solve_block: batched Newton serves
    smooth, unmasked, shift-free problems — by default up to
    NEWTON_AUTO_MAX_DIM, always under an explicit NEWTON spec."""
    has_shifts = (
        objective.normalization is not None
        and not objective.normalization.is_identity
        and objective.normalization.shifts is not None
    )
    return (
        objective.l1_weight == 0.0
        and not has_mask
        and not has_shifts
        and (
            spec.optimizer == OptimizerType.NEWTON
            or (
                spec.optimizer == OptimizerType.LBFGS
                and block_dim <= NEWTON_AUTO_MAX_DIM
            )
        )
    )


def _solve_block(
    block: EntityBlock,
    offsets: Array,  # (E, n_max) per-sample residual offsets
    w0: Array,  # (E, d) warm-start coefficients
    objective: GLMObjective,
    spec: OptimizerSpec,
    config: OptimizerConfig,
    feature_mask: Optional[Array] = None,  # (E, d) 0/1 Pearson mask
    re_kernel: str = "xla",
):
    """vmap one optimizer over all entities of a block. Returns (E, d) coefs +
    per-entity (iterations, reason) for the tracker.

    Solver routing (one production path — the same program bench.py measures):
    L1 → OWL-QN; explicit TRON honored; otherwise smooth unmasked problems at
    random-effect widths (d ≤ NEWTON_AUTO_MAX_DIM) run batched damped Newton
    (optim/newton.py — 3-5 iterations of MXU Hessian assembly + Cholesky,
    vs the reference's per-entity Breeze L-BFGS inside mapValues,
    RandomEffectCoordinate.scala:228-283), with margin-space L-BFGS as the
    wide-d / feature-masked / shift-normalized fallback.

    ``re_kernel`` (already resolved, never "auto") selects the Newton-system
    assembly lowering for the Newton route only — "pallas"/"pallas_bf16x"
    fuse the Hessian + gradient reductions into one Pallas read of each
    entity's slab, batched by this function's vmap into one grid instance
    per block row (ops/pallas_newton). Non-Newton routes (OWL-QN, TRON,
    margin-L-BFGS fallbacks) ignore it.
    """
    use_newton = newton_eligible(
        objective, spec, block.dim, has_mask=feature_mask is not None
    )

    norm = objective.normalization
    folded = norm is not None and not norm.is_identity

    def solve_one(feat, lab, wt, off, w_init, fmask, tmask):
        lb = LabeledBatch(lab, feat, off, wt)
        # Models live in MODEL space; the folded objective optimizes in
        # transformed space (reference SingleNodeOptimizationProblem.scala:95
        # converts out, Optimizer.scala:167 converts the warm start in).
        w_start = norm.model_to_transformed_space(w_init) if folded else w_init
        if feature_mask is not None:
            # Optimize f_m(w) = f(w ∘ m): chain rule masks the gradient and
            # sandwiches the Hessian (M H M) so every solver sees a
            # consistent restricted objective.
            def vg(w):
                v, g = objective.value_and_grad(w * fmask, lb)
                return v, g * fmask

            def hvp_factory(w):
                hv = objective.linearized_hvp(w * fmask, lb)
                return lambda v: fmask * hv(fmask * v)
        else:
            vg = lambda w: objective.value_and_grad(w, lb)

            def hvp_factory(w):
                return objective.linearized_hvp(w, lb)

        if objective.l1_weight > 0.0:
            l1_mask = None
            if objective.intercept_index is not None:
                l1_mask = jnp.ones_like(w_init).at[objective.intercept_index].set(0.0)
            res = minimize_owlqn(vg, w_start, objective.l1_weight, config, l1_mask)
        elif use_newton:
            res = minimize_newton(objective, lb, w_start, config, kernel=re_kernel)
        elif spec.optimizer == OptimizerType.TRON:
            res = minimize_tron(
                vg, None, w_start, config, spec.max_cg_iter,
                hvp_factory=hvp_factory,
            )
        elif feature_mask is not None and (
            objective.normalization is not None
            and objective.normalization.shifts is not None
        ):
            # Shift normalization computes es over the FULL w, so masking X
            # columns does not silence masked coordinates (they'd train as
            # pseudo-intercepts). Keep the gradient-masked formulation.
            res = minimize_lbfgs(vg, w_start, config)
        else:
            # Margin-space L-BFGS on the feature-masked batch: X∘m keeps the
            # GLM margin structure, and masked coordinates (appearing only in
            # the separable L2 term) reach the same post-mask optimum as the
            # gradient-masked formulation.
            lb_m = (
                LabeledBatch(lab, feat * fmask[None, :], off, wt)
                if feature_mask is not None
                else lb
            )
            res = minimize_lbfgs_margin(objective, lb_m, w_start, config)
        w_out = res.w * fmask if feature_mask is not None else res.w
        if folded:
            w_out = norm.transformed_to_model_space(w_out)
        # Entities under the lower-bound filter keep their initial model
        # (reference filterActiveData semantics: not trained this pass).
        w_out = jnp.where(tmask, w_out, w_init)
        return w_out, res.iterations, res.reason_code

    fmask = (
        feature_mask
        if feature_mask is not None
        else jnp.ones((block.num_entities, block.dim), block.features.dtype)
    )
    return jax.vmap(solve_one)(
        block.features, block.label, block.weight, offsets, w0, fmask, block.train_mask
    )


@dataclasses.dataclass
class RandomEffectCoordinate(Coordinate):
    """Per-entity GLM block over one RE type + feature shard."""

    coordinate_id: str
    dataset: RandomEffectDataset
    task: TaskType
    objective: GLMObjective
    optimizer_spec: OptimizerSpec = dataclasses.field(default_factory=OptimizerSpec)
    # SIMPLE (diag-inverse) or FULL (Cholesky inverse diagonal, vmapped over
    # entities); bool accepted for compatibility (True → SIMPLE).
    compute_variance: object = VarianceComputationType.NONE
    # Compiled-solver cache; None → the process-wide shared default
    # (algorithm/solve_cache.default_cache), so every coordinate / λ-sweep
    # config with the same static setup reuses one executable per shape
    # bucket instead of retracing each CD pass.
    solve_cache: Optional[SolveCache] = None
    # Convergence-gated active-set passes: pass k computes a per-entity
    # "still active" mask IN the solve graph (relative coefficient delta vs
    # ``convergence_tol``); at the next pass boundary the host fetches those
    # tiny (E,) masks — materialized a full pass ago, so the fetch drains no
    # queue — and only still-active entities are re-solved, compacted onto
    # entity allocations the first full pass already compiled (zero new
    # retraces by construction). Converged entities keep their coefficients
    # and scores. The mask fetch is the ONE opt-in host sync of this path;
    # everything else preserves the sync-free dispatch invariant.
    active_set: bool = False
    convergence_tol: float = 1e-4
    # Out-of-core residency: with a byte budget, block data lives in a host
    # master (optionally memory-mapped under ``device_spill_dir``) and only
    # a budgeted working set is device-resident, managed by
    # algorithm/re_store.ReDeviceStore. None → fully resident (default).
    device_budget_bytes: Optional[int] = None
    device_spill_dir: Optional[str] = None
    # Host-owned spill layout: with a member id, spill files live under
    # ``<device_spill_dir>/host-<k>/`` (re_store.partition_spill_dir) so a
    # ring rebalance moves files instead of re-streaming rows.
    device_spill_member: Optional[str] = None
    # Newton-system assembly lowering for the per-entity solves
    # (ops/pallas_newton.RE_KERNELS): "auto" picks the fused batched Pallas
    # kernel on a real TPU backend and XLA elsewhere; "pallas" /
    # "pallas_bf16x" force the fused kernel (interpret mode off-TPU — the
    # CPU parity/bench path); "xla" forces the two-read einsum lowering.
    # Part of the solver-cache key, so variants never share executables.
    re_kernel: str = "auto"
    # Device placement for the entity-sharded multi-device path
    # (parallel/entity_shard.py): commit this coordinate's blocks,
    # coefficients, and solves to ONE device. The solve cache needs no
    # per-device keying — the same jitted executable serves every device of
    # a backend (one trace, bit-identical results), so sharded coordinates
    # share cache entries whenever their block geometry matches. None keeps
    # the default (backend-chosen) placement.
    device: Optional[object] = None

    def __post_init__(self):
        self.compute_variance = normalize_variance_type(self.compute_variance)
        from photon_tpu.ops.pallas_newton import resolve_re_kernel

        self._re_kernel = resolve_re_kernel(self.re_kernel)
        if self.solve_cache is None:
            self.solve_cache = default_cache()
        # Per-entity solves keep only aggregate tracker stats (HBM budget).
        self._config = dataclasses.replace(
            self.optimizer_spec.config(), track_history=False
        )
        self._store = None
        self.last_residency_stats: Optional[dict] = None
        if self.device is not None:
            if self.dataset.projected:
                raise ValueError(
                    "per-device placement supports dense RE datasets only "
                    "(projected blocks route through the default device)"
                )
            if self.compute_variance != VarianceComputationType.NONE:
                raise ValueError(
                    "per-device placement does not support coefficient "
                    "variance computation (the variance pass assembles on "
                    "the default device)"
                )
        if self.device_budget_bytes:
            if self.dataset.projected:
                import logging

                logging.getLogger("photon_tpu").warning(
                    "coordinate %s: out-of-core residency supports dense RE "
                    "datasets only (projected blocks keep content-defined "
                    "col_map widths); training fully resident",
                    self.coordinate_id,
                )
            elif self.dataset.config.features_to_samples_ratio is not None:
                raise ValueError(
                    "out-of-core residency is incompatible with "
                    "features_to_samples_ratio (Pearson masks pin every "
                    "block on device at construction)"
                )
            elif self.compute_variance != VarianceComputationType.NONE:
                raise ValueError(
                    "out-of-core residency does not support coefficient "
                    "variance computation (the variance pass re-reads every "
                    "block outside the residency budget)"
                )
            else:
                from photon_tpu.algorithm.re_store import ReDeviceStore

                self._store = ReDeviceStore(
                    self.dataset.blocks,
                    self.device_budget_bytes,
                    self.coordinate_id,
                    self.device_spill_dir,
                    device=self.device,
                    spill_member=self.device_spill_member,
                )
                # Drop the device references: from here on the dataset's
                # blocks ARE the host master, and device placement happens
                # only through the store's budgeted upload stage.
                self.dataset.blocks = self._store.blocks
        if self.device is not None and self._store is None:
            # Commit every block to the owning device BEFORE derived state
            # (Pearson masks inherit placement from the block arrays).
            self.dataset.blocks = [
                jax.device_put(b, self.device) for b in self.dataset.blocks
            ]
        self._feature_masks: Dict[int, Array] = {}
        ratio = self.dataset.config.features_to_samples_ratio
        if ratio is not None:
            for i, block in enumerate(self.dataset.blocks):
                counts = jnp.sum(block.weight > 0, axis=1)
                # Per-entity cap: k_e = ratio × that entity's sample count
                # (reference RandomEffectDataConfiguration features/samples
                # ratio semantics).
                k_e = jnp.clip(
                    jnp.ceil(counts.astype(jnp.float32) * ratio).astype(jnp.int32),
                    1,
                    block.dim,
                )
                self._feature_masks[i] = pearson_feature_mask(
                    block, k_e, always_keep=self._block_intercept(block)
                )
        # Memoized per-block objectives: the solver-cache key pins the
        # normalization arrays by identity, so they must be built ONCE and
        # reused across CD passes (rebuilding each pass would defeat the
        # compile cache). Dense blocks memoize by block dim — same-dim dense
        # blocks share ONE objective object, which also lets the active-set
        # path pool their entities into one compacted dispatch under one
        # cache key. Projected blocks (content-defined col_maps) stay
        # per-block.
        self._block_objectives: List[GLMObjective] = []
        obj_memo: Dict[Tuple, GLMObjective] = {}
        for i, b in enumerate(self.dataset.blocks):
            memo_key = (b.dim, None) if b.col_map is None else (b.dim, i)
            obj = obj_memo.get(memo_key)
            if obj is None:
                obj = self._block_objective(b)
                obj_memo[memo_key] = obj
            self._block_objectives.append(obj)
        # Host-side valid-row masks/counts (entity_idx >= 0), computed once
        # at construction so active-set accounting never reads device arrays
        # inside the dispatch loop.
        self._block_valid_rows = [
            np.asarray(b.entity_idx) >= 0 for b in self.dataset.blocks
        ]
        self._block_valid_counts = [
            int(np.sum(v)) for v in self._block_valid_rows
        ]
        self._total_valid_entities = int(sum(self._block_valid_counts))
        self._reset_active_set()

    def _block_intercept(self, block: EntityBlock) -> Optional[int]:
        """Intercept column in BLOCK-local space (global index mapped through
        the block's col_map under subspace projection)."""
        g = self.objective.intercept_index
        if g is None or block.col_map is None:
            return g
        pos = np.flatnonzero(np.asarray(block.col_map) == g)
        return int(pos[0]) if pos.size else None

    def _block_objective(self, block: EntityBlock) -> GLMObjective:
        """Objective with the intercept index (and any normalization
        vectors) remapped to block space — the regularization exemption and
        the folded normalization algebra must follow the projected columns."""
        local = self._block_intercept(block)
        norm = self.objective.normalization
        if block.col_map is not None and norm is not None and not norm.is_identity:
            norm = dataclasses.replace(
                norm,
                factors=None if norm.factors is None else norm.factors[block.col_map],
                shifts=None if norm.shifts is None else norm.shifts[block.col_map],
                intercept_index=local,
            )
            return dataclasses.replace(
                self.objective, intercept_index=local, normalization=norm
            )
        if (
            block.col_map is None
            and block.dim > self.dataset.dim
            and norm is not None
            and not norm.is_identity
        ):
            # Dense block padded to a d bucket: extend the normalization
            # vectors with identity entries (factor 1, shift 0) so the folded
            # algebra matches the padded width. Padded columns are all-zero
            # features, so their coefficients stay at the warm start.
            pad = block.dim - self.dataset.dim
            norm = dataclasses.replace(
                norm,
                factors=None
                if norm.factors is None
                else jnp.concatenate(
                    [norm.factors, jnp.ones((pad,), norm.factors.dtype)]
                ),
                shifts=None
                if norm.shifts is None
                else jnp.concatenate(
                    [norm.shifts, jnp.zeros((pad,), norm.shifts.dtype)]
                ),
            )
            return dataclasses.replace(
                self.objective, intercept_index=local, normalization=norm
            )
        if local == self.objective.intercept_index:
            return self.objective
        return dataclasses.replace(self.objective, intercept_index=local)

    # --- active-set pass gating -------------------------------------------

    def _reset_active_set(self) -> None:
        self._cd_pass = 0
        # [(device active mask, device quarantined mask, src_block, src_row)]
        # from the LAST dispatch — src maps route each mask row back to
        # (original block, row).
        self._pending_masks: Optional[list] = None
        self.last_active_set_stats: Optional[dict] = None
        self._fetched_quarantined = 0

    def begin_cd_pass(self, cd_iteration: int) -> None:
        """Pass-boundary hook, called by CoordinateDescent before this
        coordinate's update: a descent restarting at iteration 0 begins with
        a full (ungated) pass, discarding any mask state left over from a
        previous run of the same coordinate object. With an out-of-core
        store, this is also the residency epoch boundary (per-pass eviction
        accounting resets; resident blocks stay warm across passes)."""
        if cd_iteration == 0:
            self._reset_active_set()
        if self._store is not None:
            self._store.begin_pass(cd_iteration)

    def export_active_state(self) -> Optional[dict]:
        """Checkpointable snapshot of the active-set gate: the CD pass
        counter plus the RESOLVED per-block keep masks (host bool arrays).
        Called by CoordinateDescent at a pass-boundary checkpoint — the
        checkpoint write itself materializes every device array, so reading
        the masks here costs nothing extra. None when there is no gate state
        (active_set off, or no pass dispatched yet)."""
        if not self.active_set or self._pending_masks is None:
            return None
        keep = self._fetch_active_masks(count_quarantined=False)
        return dict(
            cd_pass=int(self._cd_pass),
            keep=[np.asarray(k) for k in keep],
        )

    def restore_active_state(self, state: Optional[dict]) -> None:
        """Inverse of :meth:`export_active_state`: reinstall the keep masks
        as identity-mapped pending entries so the first resumed pass is
        gated exactly like the pass the checkpoint interrupted would have
        been — a resume neither re-solves converged entities nor loses
        quarantine/retirement decisions."""
        self._reset_active_set()
        if not self.active_set or state is None:
            return
        self._cd_pass = int(state["cd_pass"])
        pending = []
        for i, k in enumerate(state["keep"]):
            k = np.asarray(k, bool)
            valid = self._block_valid_rows[i]
            sb = np.where(valid, i, -1).astype(np.int32)
            sr = np.where(
                valid, np.arange(k.shape[0], dtype=np.int32), -1
            ).astype(np.int32)
            pending.append((k, np.zeros(k.shape, bool), sb, sr))
        self._pending_masks = pending

    def _fetch_active_masks(self, count_quarantined: bool = True) -> List[np.ndarray]:
        """HOST fetch of the per-entity active masks the PREVIOUS pass
        computed in-graph — the one opt-in sync of the active-set path. The
        (E,) bool arrays were materialized a full CD pass ago, so the fetch
        does not stall the dispatch pipeline. Entities of blocks that were
        not dispatched last pass have no mask entry and stay retired (the
        active set shrinks monotonically within a descent).

        Divergence-quarantine counts piggyback on this same fetch (the masks
        travel together from the same dispatch), so the guards add no host
        syncs of their own."""
        active = [np.zeros((b.num_entities,), bool) for b in self.dataset.blocks]
        quarantined = 0
        with span("re_mask_fetch"):
            for mask_dev, quar_dev, sb, sr in self._pending_masks:
                valid = sr >= 0
                m = np.asarray(mask_dev) & valid
                for b in np.unique(sb[m]):
                    active[b][sr[m & (sb == b)]] = True
                if count_quarantined:
                    quarantined += int(np.sum(np.asarray(quar_dev) & valid))
        if count_quarantined:
            self._fetched_quarantined = quarantined
            if quarantined:
                from photon_tpu.obs.metrics import registry

                registry().counter(
                    "re_entities_quarantined", coordinate=self.coordinate_id
                ).inc(quarantined)
        return active

    def _compact_feature_mask(self, idxs, sb_local, sr, block_c):
        """Gather per-entity Pearson mask rows through the same src pairs a
        compacted block was built from (padding rows get all-ones — inert:
        train_mask=False pins their output to the warm start)."""
        if not self._feature_masks:
            return None
        parts = []
        real = sb_local >= 0
        for b in np.unique(sb_local[real]):
            rows = sr[real & (sb_local == b)]
            parts.append(self._feature_masks[idxs[b]][rows])
        pad = int(np.sum(~real))
        if pad:
            parts.append(jnp.ones((pad, block_c.dim), parts[0].dtype))
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts)

    def _identity_entry(self, i: int):
        """Dispatch-plan entry for original block i (identity src maps;
        shape-bucket padding rows carry (-1, -1) so per-pass accounting and
        the next mask fetch both see only real entities)."""
        b = self.dataset.blocks[i]
        valid = self._block_valid_rows[i]
        return (
            b,
            self._block_objectives[i],
            self._feature_masks.get(i),
            np.where(valid, i, -1).astype(np.int32),
            np.where(valid, np.arange(b.num_entities), -1).astype(np.int32),
        )

    def _dense_dispatch_entries(
        self, keep: List[np.ndarray], to_device: bool = True
    ) -> list:
        """Dispatch plan for a gated dense pass: group same-geometry blocks,
        pool their still-active rows, and repack them onto entity
        allocations the first full pass already compiled (zero new retraces
        by construction — see data/random_effect.pack_into_sizes). Falls
        back to whole-block skipping when repacking would not shrink the
        dispatched allocation."""
        groups: Dict[Tuple[int, int], List[int]] = {}
        for i, b in enumerate(self.dataset.blocks):
            groups.setdefault((b.n_max, b.dim), []).append(i)
        entries = []
        for idxs in groups.values():
            keeps = [keep[i] for i in idxs]
            live = [i for i, k in zip(idxs, keeps) if k.any()]
            if not live:
                continue  # whole group converged: nothing to dispatch
            members = [self.dataset.blocks[i] for i in idxs]
            allowed = [b.num_entities for b in members]
            total = int(sum(int(k.sum()) for k in keeps))
            plan = pack_into_sizes(total, allowed)
            if sum(plan) >= sum(self.dataset.blocks[i].num_entities for i in live):
                # Repacking buys nothing over skipping the fully-converged
                # blocks — dispatch the live originals and skip the gathers.
                entries.extend(self._identity_entry(i) for i in live)
                continue
            obj = self._block_objectives[idxs[0]]
            idx_arr = np.asarray(idxs, np.int32)
            for block_c, sb_local, sr in compact_entity_blocks(
                members, keeps, allowed, to_device=to_device
            ):
                sb = np.where(
                    sb_local >= 0, idx_arr[np.maximum(sb_local, 0)], -1
                ).astype(np.int32)
                mask_c = self._compact_feature_mask(idxs, sb_local, sr, block_c)
                entries.append((block_c, obj, mask_c, sb, sr))
        return entries

    def _publish_active_set_stats(
        self, gated: bool, dispatched_valid: int, dispatched_alloc: int,
        num_dispatches: int,
    ) -> None:
        """Host-int accounting of the pass (no device reads): how many
        entities were re-solved vs skipped, and how much smaller the
        dispatched entity allocation was than a full pass."""
        if not self.active_set:
            self.last_active_set_stats = None
            return
        from photon_tpu.obs.metrics import registry

        total = self._total_valid_entities
        skipped = total - dispatched_valid
        full_alloc = int(sum(b.num_entities for b in self.dataset.blocks))
        ratio = (dispatched_alloc / full_alloc) if full_alloc else 0.0
        reg = registry()
        labels = dict(coordinate=self.coordinate_id)
        reg.gauge("re_entities_active", **labels).set(dispatched_valid)
        reg.counter("re_entities_skipped_total", **labels).inc(skipped)
        reg.histogram("re_compaction_ratio", **labels).observe(ratio)
        self.last_active_set_stats = dict(
            cd_pass=self._cd_pass,
            gated=gated,
            entities_total=total,
            entities_active=dispatched_valid,
            entities_skipped=skipped,
            entities_quarantined=self._fetched_quarantined,
            dispatched_blocks=num_dispatches,
            dispatched_entity_alloc=dispatched_alloc,
            full_entity_alloc=full_alloc,
            compaction_ratio=ratio,
        )

    def train(
        self,
        batch: GameBatch,
        residual_scores: Optional[Array] = None,
        initial_model=None,  # RandomEffectModel | ProjectedRandomEffectModel
    ) -> Tuple[DatumScoringModel, RandomEffectTrackerStats]:
        # Residuals for THIS coordinate's solves: batch offsets + other
        # coordinates' scores (addScoresToOffsets, gathered per block).
        total_offset = batch.offset
        if residual_scores is not None:
            total_offset = total_offset + residual_scores
        if self.device is not None:
            # One (n,) h2d per pass: the flat residual vector follows the
            # coordinate to its owning device so every block gather stays
            # device-local (mixed-device eager ops would otherwise fail).
            total_offset = jax.device_put(total_offset, self.device)
        if self.dataset.projected:
            return self._train_projected(total_offset, initial_model)
        if self._store is not None:
            return self._train_dense_ooc(batch, total_offset, initial_model)
        return self._train_dense(batch, total_offset, initial_model)

    def _train_dense(
        self, batch: GameBatch, total_offset: Array, initial_model
    ) -> Tuple[RandomEffectModel, RandomEffectTrackerStats]:
        E, d = self.dataset.num_entities, self.dataset.dim
        dtype = batch.offset.dtype
        if isinstance(initial_model, ProjectedRandomEffectModel):
            initial_model = initial_model.to_dense()
        coefs = (
            initial_model.coefficients
            if initial_model is not None
            else jnp.zeros((E, d), dtype)
        )
        if self.device is not None:
            # Host-numpy warm starts (out-of-core / sharded-merge models)
            # and fresh zeros both commit to the owning device; a table
            # already resident there passes through untouched.
            coefs = jax.device_put(coefs, self.device)
        # Active-set gate: from pass 2 on (mask state + a warm model), only
        # still-active entities are re-solved, repacked onto already-compiled
        # shapes; converged entities keep their ``coefs`` rows untouched.
        gated = (
            self.active_set
            and self._pending_masks is not None
            and initial_model is not None
        )
        if gated:
            keep = self._fetch_active_masks()
            with span("re_compact"):
                entries = self._dense_dispatch_entries(keep)
        else:
            entries = [self._identity_entry(i) for i in range(len(self.dataset.blocks))]
        tol = self.convergence_tol if self.active_set else None
        if self.device is not None and gated:
            # Compacted blocks are assembled on the default device; move
            # them (and their mask rows) to the owning device. Identity
            # entries are already resident — their puts are no-ops.
            entries = [
                (
                    jax.device_put(block, self.device),
                    obj,
                    None if mask is None else jax.device_put(mask, self.device),
                    sb,
                    sr,
                )
                for block, obj, mask, sb, sr in entries
            ]

        # Sync-free dispatch: issue EVERY block solve before touching any
        # result — no read-modify-write of ``coefs`` between dispatches, so
        # consecutive blocks pipeline on device instead of serializing
        # through the host.
        results = []
        pending = []
        with span("re_dispatch_blocks"):
            for block, obj, mask, sb, sr in entries:
                offs = faults.poison(
                    "solve.re_block", block.gather_offsets(total_offset)
                )
                w0 = self._dense_warm_start(coefs, block, d)
                solver = self.solve_cache.block_solver(
                    obj, self.optimizer_spec, self._config,
                    has_mask=mask is not None, convergence_tol=tol,
                    re_kernel=self._re_kernel,
                )
                if gated and self.solve_cache.max_entries is None:
                    # Compacted shapes were all compiled during the full
                    # first pass; a retrace here is a bug. (With a bounded
                    # cache the entry may have been LRU-evicted — a rebuild
                    # is then legitimate, so the assertion is skipped.)
                    with self.solve_cache.expect_cached(
                        f"active-set dispatch {tuple(block.features.shape)}"
                    ):
                        out = solver(block, offs, w0, mask)
                else:
                    out = solver(block, offs, w0, mask)
                if tol is not None:
                    w, iters, reasons, act, quar = out
                    pending.append((act, quar, sb, sr))
                else:
                    w, iters, reasons = out
                results.append((block, w, iters, reasons))
        if tol is not None:
            self._pending_masks = pending
        self._publish_active_set_stats(
            gated,
            dispatched_valid=int(sum(int(np.sum(sb >= 0)) for *_x, sb, _sr in entries)),
            dispatched_alloc=int(sum(e[0].num_entities for e in entries)),
            num_dispatches=len(entries),
        )
        self._cd_pass += 1

        # Per-block scatters (still async-dispatched, no host sync): each
        # scatter's signature depends only on that block's (E_alloc,) shape,
        # which the full first pass already compiled — so a gated pass that
        # dispatches a different NUMBER of blocks reuses the same executables.
        # (A single whole-pass concatenate+scatter would bake the block count
        # into the eager-op signature and recompile at the first compaction.)
        # Shape-bucket padding rows target out-of-range row E and are dropped.
        for b, w, _i, _r in results:
            idx = jnp.where(b.entity_idx >= 0, b.entity_idx, E)
            coefs = coefs.at[idx].set(
                w[:, :d].astype(coefs.dtype), mode="drop"
            )

        variances = None
        if self.compute_variance != VarianceComputationType.NONE:
            variances = self._block_variances(coefs, total_offset, dtype)

        model = RandomEffectModel(
            coefs, self.dataset.config.re_type, self.dataset.config.feature_shard,
            self.task, variances,
        )
        stats = self._tracker_stats(
            [(b.entity_idx, it, rs) for b, _w, it, rs in results]
        )
        return model, stats

    def _dense_warm_start(self, coefs: Array, block: EntityBlock, d: int) -> Array:
        """Fresh (E_b, block.dim) warm-start buffer for a dense block.

        Always a gather (never a view of a live model array), so the solver
        cache may DONATE it; padded entity rows gather row 0 (inert:
        ``train_mask=False`` keeps their output at the warm start, and the
        final scatter drops them); padded feature columns warm-start at 0.
        """
        w0 = coefs[jnp.maximum(block.entity_idx, 0)]
        if block.dim > d:
            w0 = jnp.pad(w0, ((0, 0), (0, block.dim - d)))
        return w0

    def _train_dense_ooc(
        self, batch: GameBatch, total_offset: Array, initial_model
    ) -> Tuple[RandomEffectModel, RandomEffectTrackerStats]:
        """Out-of-core dense pass: host master coefficients and block data,
        device working set under the store's byte budget, traffic on the
        ingest pipeline machinery (h2d upload stage ahead of the dispatch
        loop, d2h download worker behind it, both bounded).

        Parity with :meth:`_train_dense` is BIT-EXACT by construction:

        * Every warm start gathers from ``coefs_prev`` — a host copy of the
          previous pass's coefficients, frozen at pass start. The resident
          path reads the same values: its scatters all land after every
          dispatch, so no solve ever observes another solve's update within
          a pass.
        * An uploaded block is a bit-identical copy of the resident path's
          block (same arrays, same bucket geometry) and therefore runs the
          SAME cached executable — zero retraces across evictions.
        * Results round-trip d2h losslessly (f32 copies, no arithmetic) and
          scatter into disjoint rows of ``coefs_out`` — order-independent,
          so download order cannot perturb values.

        The returned model carries HOST numpy coefficients (the master
        table); scoring gathers rows through them on demand, producing the
        same device values as a resident model.
        """
        from photon_tpu.algorithm.re_store import block_data_bytes
        from photon_tpu.io.pipeline import (
            DEFAULT_QUEUE_DEPTH,
            StageWorker,
            _finalize_pipeline_telemetry,
            _run_staged,
        )
        from photon_tpu.utils.timed import PipelineStats, record_pipeline

        store = self._store
        E, d = self.dataset.num_entities, self.dataset.dim
        if isinstance(initial_model, ProjectedRandomEffectModel):
            initial_model = initial_model.to_dense()
        coefs_prev = (
            np.asarray(initial_model.coefficients, np.float32)
            if initial_model is not None
            else np.zeros((E, d), np.float32)
        )
        coefs_out = coefs_prev.copy()
        gated = (
            self.active_set
            and self._pending_masks is not None
            and initial_model is not None
        )
        store.begin_pass(self._cd_pass)
        if gated:
            keep = self._fetch_active_masks()
            # The residency policy IS the active set: blocks whose entities
            # all converged are evicted right here, at the pass-boundary
            # sync the mask fetch already paid for.
            store.retire(
                [
                    i
                    for i, k in enumerate(keep)
                    if self._block_valid_counts[i] and not k.any()
                ]
            )
            with span("re_compact"):
                entries = self._dense_dispatch_entries(keep, to_device=False)
        else:
            entries = [
                self._identity_entry(i)
                for i in range(len(self.dataset.blocks))
            ]
        tol = self.convergence_tol if self.active_set else None

        # Residency keys: original blocks cache across passes under their
        # dataset index; compacted blocks are transient (their geometry
        # depends on this pass's active set — an entry could never hit) and
        # are released as soon as their results download.
        block_ids = {id(b): i for i, b in enumerate(self.dataset.blocks)}
        plan = []
        for j, entry in enumerate(entries):
            key = block_ids.get(id(entry[0]), ("compact", self._cd_pass, j))
            plan.append((key, entry))

        def upload(item):
            key, (block, obj, mask, sb, sr) = item
            eidx = np.asarray(block.entity_idx)
            w0 = coefs_prev[np.maximum(eidx, 0)]
            if block.dim > d:
                w0 = np.pad(w0, ((0, 0), (0, block.dim - d)))
            cacheable = isinstance(key, int)
            dev_block, w0_dev = store.acquire(key, block, w0, cacheable)
            return (
                block_data_bytes(block), key, cacheable, dev_block, obj,
                mask, sb, sr, eidx, w0_dev,
            )

        results_host: list = []
        pending_host: list = []

        def download(item):
            key, cacheable, sb, sr, eidx, out = item
            if tol is not None:
                w, iters, reasons, act, quar = out
            else:
                w, iters, reasons = out
            w_host = np.asarray(w)  # blocks until the device solve completes
            valid = eidx >= 0
            coefs_out[eidx[valid]] = w_host[valid, :d]
            results_host.append((eidx, np.asarray(iters), np.asarray(reasons)))
            if tol is not None:
                pending_host.append((np.asarray(act), np.asarray(quar), sb, sr))
            store.mark_solve_done()
            store.release(key, cacheable)

        label = f"re_store/{self.coordinate_id}"
        stats = PipelineStats(overlapped=True)
        record_pipeline(label, stats)
        solve_stage = stats.stage("solve")
        worker = StageWorker(
            "d2h", download, stats.stage("d2h"), depth=DEFAULT_QUEUE_DEPTH,
            nbytes_of=lambda item, _res: 4 * int(np.prod(item[5][0].shape)),
        )
        gen = _run_staged(
            lambda: iter(plan),
            lambda item: 0,
            [("h2d", upload, lambda out: out[0])],
            stats,
            depth=DEFAULT_QUEUE_DEPTH,
            overlap=True,
            source_name="plan",
        )
        t0_wall = time.perf_counter()
        try:
            with span("re_dispatch_blocks"):
                for (_nb, key, cacheable, dev_block, obj, mask, sb, sr,
                     eidx, w0_dev) in gen:
                    t0 = time.perf_counter()
                    offs = faults.poison(
                        "solve.re_block", dev_block.gather_offsets(total_offset)
                    )
                    solver = self.solve_cache.block_solver(
                        obj, self.optimizer_spec, self._config,
                        has_mask=mask is not None, convergence_tol=tol,
                        re_kernel=self._re_kernel,
                    )
                    store.mark_solve_start()
                    if gated and self.solve_cache.max_entries is None:
                        with self.solve_cache.expect_cached(
                            f"out-of-core dispatch "
                            f"{tuple(dev_block.features.shape)}"
                        ):
                            out = solver(dev_block, offs, w0_dev, mask)
                    else:
                        out = solver(dev_block, offs, w0_dev, mask)
                    solve_stage.add_busy(time.perf_counter() - t0, 0)
                    worker.submit((key, cacheable, sb, sr, eidx, out))
            worker.close()
        except BaseException:
            store.abort_pass()
            worker.abort()
            raise
        finally:
            close = getattr(gen, "close", None)
            if close is not None:
                close()
            stats.wall_s = time.perf_counter() - t0_wall
            _finalize_pipeline_telemetry(label, stats)
            store.end_pass()

        if tol is not None:
            self._pending_masks = pending_host
        self._publish_active_set_stats(
            gated,
            dispatched_valid=int(
                sum(int(np.sum(sb >= 0)) for *_x, sb, _sr in entries)
            ),
            dispatched_alloc=int(sum(e[0].num_entities for e in entries)),
            num_dispatches=len(entries),
        )
        self._cd_pass += 1
        self.last_residency_stats = dict(
            store.stats(), pipeline=stats.summary()
        )

        model = RandomEffectModel(
            coefs_out, self.dataset.config.re_type,
            self.dataset.config.feature_shard, self.task, None,
        )
        return model, self._tracker_stats(results_host)

    def _train_projected(
        self, total_offset: Array, initial_model
    ) -> Tuple[ProjectedRandomEffectModel, RandomEffectTrackerStats]:
        """Per-block solves in the compact subspace: nothing of width
        ``d_full`` is ever materialized (model projection lives in the
        block's col_map).

        Active-set gating is WHOLE-BLOCK here: a projected block's
        content-defined col_map width cannot merge with another block's
        without a new shape (= a retrace), so a block is skipped only once
        every one of its entities has converged — its previous coefficients
        carry over untouched."""
        entity_block, entity_row, inv_maps = self.dataset.projection_tables()
        gated = (
            self.active_set
            and self._pending_masks is not None
            and isinstance(initial_model, ProjectedRandomEffectModel)
        )
        keep = self._fetch_active_masks() if gated else None
        tol = self.convergence_tol if self.active_set else None
        parts = []
        pending = []
        dispatched_valid = dispatched_alloc = num_dispatches = 0
        block_coefs, block_vars, col_maps, block_offs = [], [], [], []
        # Sync-free dispatch: every block solve is issued before any
        # dependent work (variances) touches the outputs.
        with span("re_dispatch_blocks"):
            for i, block in enumerate(self.dataset.blocks):
                offs = faults.poison(
                    "solve.re_block", block.gather_offsets(total_offset)
                )
                col_maps.append(block.col_map)
                block_offs.append(offs)
                if gated and not keep[i].any():
                    prev = initial_model.block_coefs[i]
                    if prev.shape == (block.num_entities, block.dim):
                        # Fully-converged block: carry the warm coefficients
                        # (aliasing is safe — model arrays are never donated;
                        # _initial_block_coefs copies before a donated solve).
                        block_coefs.append(prev)
                        continue
                w0 = self._initial_block_coefs(block, i, initial_model)
                obj = self._block_objectives[i]
                mask = self._feature_masks.get(i)
                solver = self.solve_cache.block_solver(
                    obj, self.optimizer_spec, self._config,
                    has_mask=mask is not None, convergence_tol=tol,
                    re_kernel=self._re_kernel,
                )
                if gated and self.solve_cache.max_entries is None:
                    with self.solve_cache.expect_cached(
                        f"active-set dispatch {tuple(block.features.shape)}"
                    ):
                        out = solver(block, offs, w0, mask)
                else:
                    out = solver(block, offs, w0, mask)
                if tol is not None:
                    w_new, iters, reasons, act, quar = out
                    pending.append(
                        (
                            act,
                            quar,
                            np.full((block.num_entities,), i, np.int32),
                            np.arange(block.num_entities, dtype=np.int32),
                        )
                    )
                else:
                    w_new, iters, reasons = out
                block_coefs.append(w_new)
                parts.append((block.entity_idx, iters, reasons))
                dispatched_valid += self._block_valid_counts[i]
                dispatched_alloc += block.num_entities
                num_dispatches += 1
        if tol is not None:
            self._pending_masks = pending
        self._publish_active_set_stats(
            gated, dispatched_valid, dispatched_alloc, num_dispatches
        )
        self._cd_pass += 1
        if self.compute_variance != VarianceComputationType.NONE:
            for i, block in enumerate(self.dataset.blocks):
                obj = self._block_objectives[i]

                def var_one(feat, lab, wt, off, w, _obj=obj):
                    lb = LabeledBatch(lab, feat, off, wt)
                    bn = _obj.normalization
                    bfolded = bn is not None and not bn.is_identity
                    wv = bn.model_to_transformed_space(w) if bfolded else w
                    v = coefficient_variances(_obj, wv, lb, self.compute_variance)
                    if bfolded and v is not None and bn.factors is not None:
                        v = v * bn.factors**2
                    return v

                block_vars.append(
                    jax.vmap(var_one)(
                        block.features, block.label, block.weight,
                        block_offs[i], block_coefs[i],
                    )
                )
        model = ProjectedRandomEffectModel(
            block_coefs=block_coefs,
            col_maps=col_maps,
            inv_maps=inv_maps,
            entity_block=entity_block,
            entity_row=entity_row,
            d_full=self.dataset.dim,
            re_type=self.dataset.config.re_type,
            feature_shard=self.dataset.config.feature_shard,
            task=self.task,
            block_variances=(
                block_vars
                if self.compute_variance != VarianceComputationType.NONE
                else None
            ),
        )
        return model, self._tracker_stats(parts)

    def _initial_block_coefs(self, block, block_index: int, initial_model) -> Array:
        """Warm-start coefficients in block space from either model form.

        Always returns a buffer the caller exclusively owns (the solver
        cache DONATES it): a same-shape projected warm start is copied
        instead of aliased, so the caller's ``initial_model`` stays valid
        after the donated solve.
        """
        E_b, d_b = block.num_entities, block.dim
        if initial_model is None:
            return jnp.zeros((E_b, d_b), jnp.float32)
        if isinstance(initial_model, ProjectedRandomEffectModel):
            prev = initial_model.block_coefs[block_index]
            if prev.shape == (E_b, d_b):  # same dataset → same blocks
                return jnp.copy(prev)
            initial_model = initial_model.to_dense()
        # Dense (E, d_full) model: gather rows, project into block space
        # (a fresh gather — donation-safe; padded rows gather row 0, inert).
        return block.project_forward(
            initial_model.coefficients[jnp.maximum(block.entity_idx, 0)]
        )

    def _block_variances(self, coefs: Array, total_offset: Array, dtype) -> Array:
        """Per-entity coefficient variances, SIMPLE or FULL, vmapped per block
        (reference RandomEffectOptimizationProblem variance computation)."""
        E, d = self.dataset.num_entities, self.dataset.dim
        variances = jnp.ones((E, d), dtype)

        parts = []
        for i, block in enumerate(self.dataset.blocks):
            obj = self._block_objectives[i]
            norm = obj.normalization
            folded = norm is not None and not norm.is_identity

            def var_one(feat, lab, wt, off, w, _obj=obj, _norm=norm, _folded=folded):
                lb = LabeledBatch(lab, feat, off, wt)
                wv = _norm.model_to_transformed_space(w) if _folded else w
                v = coefficient_variances(_obj, wv, lb, self.compute_variance)
                if _folded and v is not None and _norm.factors is not None:
                    v = v * _norm.factors**2
                return v

            offs = block.gather_offsets(total_offset)
            v = jax.vmap(var_one)(
                block.features, block.label, block.weight, offs,
                self._dense_warm_start(coefs, block, d),
            )
            parts.append((block, v))
        if parts:
            idx = jnp.concatenate(
                [jnp.where(b.entity_idx >= 0, b.entity_idx, E) for b, _v in parts]
            )
            v_all = jnp.concatenate([v[:, :d] for _b, v in parts])
            variances = variances.at[idx].set(v_all.astype(dtype), mode="drop")
        return variances

    @staticmethod
    def _tracker_stats(parts) -> RandomEffectTrackerStats:
        """Assemble the on-device tracker from per-block
        ``(entity_idx, iterations, reasons)`` triples — concatenations only,
        NO device→host transfer (aggregates materialize in ``summary()``)."""
        if not parts:
            return RandomEffectTrackerStats.empty()
        iters = jnp.concatenate([jnp.ravel(it) for _e, it, _r in parts])
        reasons = jnp.concatenate([jnp.ravel(r) for _e, _i, r in parts])
        valid = jnp.concatenate([jnp.ravel(e) >= 0 for e, _i, _r in parts])
        return RandomEffectTrackerStats(
            iterations=iters.astype(jnp.int32),
            reasons=reasons.astype(jnp.int32),
            valid=valid,
        )

    def score(self, model, batch: GameBatch) -> Array:
        return model.score(batch)

    def zero_model(self):
        if self.dataset.projected:
            entity_block, entity_row, inv_maps = self.dataset.projection_tables()
            return ProjectedRandomEffectModel(
                block_coefs=[
                    jnp.zeros((b.num_entities, b.dim), jnp.float32)
                    for b in self.dataset.blocks
                ],
                col_maps=[b.col_map for b in self.dataset.blocks],
                inv_maps=inv_maps,
                entity_block=entity_block,
                entity_row=entity_row,
                d_full=self.dataset.dim,
                re_type=self.dataset.config.re_type,
                feature_shard=self.dataset.config.feature_shard,
                task=self.task,
            )
        return RandomEffectModel(
            jnp.zeros((self.dataset.num_entities, self.dataset.dim), jnp.float32),
            self.dataset.config.re_type,
            self.dataset.config.feature_shard,
            self.task,
        )
