"""Block coordinate descent over named coordinates — the GAME outer loop.

Parity target: reference ``CoordinateDescent`` (photon-lib
algorithm/CoordinateDescent.scala:43-670): update-sequence validation with
locked coordinates (:71-121), the running summedScores residual with
incremental update `summed − oldScores + previousScores` (:441-446),
best-model tracking by validation metric (:576-626), and the
descend/descendWithValidation split (:373-472 / :493-640).

TPU-first: per-coordinate scores are flat (n,) arrays aligned to the
GameBatch sample axis; the residual for coordinate c is simply
``total_scores - scores[c]`` — the reference's persist/unpersist + outer-join
choreography (CoordinateDescent.scala:257-341) has no analogue because
everything is resident device arrays.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from photon_tpu.algorithm.coordinate import Coordinate
from photon_tpu.data.game_data import GameBatch
from photon_tpu.models.game import GameModel
from photon_tpu.obs.metrics import registry
from photon_tpu.obs.trace import span

Array = jax.Array
logger = logging.getLogger(__name__)


@contextmanager
def _export_trace():
    """When an OTLP exporter is installed (``--otlp-endpoint``), run the
    body under a minted trace context so its spans become traced and flow
    through the tracer sink to the collector — the training-side
    enrollment of the serve-side export path. Without an exporter this is
    a no-op: spans stay untraced and pay nothing new. The trace is
    finished against the flight recorder so the open-trace table never
    accumulates training passes."""
    from photon_tpu.obs.export import active_exporter

    if active_exporter() is None:
        yield
        return
    from photon_tpu.obs.trace import flight_recorder, mint_context, tracer

    ctx = mint_context()
    t0 = time.monotonic()
    try:
        with tracer().attach_context(ctx):
            yield
    finally:
        flight_recorder().finish(ctx.trace_id, time.monotonic() - t0)


@dataclasses.dataclass
class CoordinateDescentResult:
    model: GameModel
    best_model: GameModel
    best_metric: Optional[float]
    metric_history: List[Dict[str, float]]
    tracker: Dict[str, list]
    # Host-measured wall seconds per (coordinate, CD pass) solve — the
    # driver-level timing the reference's OptimizationStatesTracker records
    # per optimizer iteration (OptimizationStatesTracker.scala:61-113). Here
    # a whole solve is ONE compiled program, so the solve is the smallest
    # host-observable unit; per-iteration loss/|grad| live in the jit-side
    # history rings instead.
    wall_times: Dict[str, List[float]] = dataclasses.field(default_factory=dict)

    def summary(self) -> str:
        """Per-coordinate optimization summary table (toSummaryString role):
        the jit-recorded per-iteration loss/|grad| histories joined with the
        host-side wall time of each solve."""
        lines: List[str] = []
        for cid, diags in self.tracker.items():
            walls = self.wall_times.get(cid, [])
            for p, diag in enumerate(diags):
                wall = f"{walls[p]:.3f}s" if p < len(walls) else "n/a"
                lines.append(f"-- coordinate {cid!r}, CD pass {p} (wall {wall})")
                body = diag.summary() if hasattr(diag, "summary") else repr(diag)
                lines.extend("   " + ln for ln in body.splitlines())
        return "\n".join(lines)


class CoordinateDescent:
    """Runs the update sequence for ``num_iterations`` passes.

    Args:
      coordinates: coordinate_id -> Coordinate (training problems).
      update_sequence: order of coordinate updates per pass.
      locked_coordinates: ids scored from a fixed pretrained model but never
        retrained (partial retraining, reference CoordinateDescent.scala:55).
    """

    def __init__(
        self,
        coordinates: Dict[str, Coordinate],
        update_sequence: Sequence[str],
        num_iterations: int = 1,
        locked_coordinates: Sequence[str] = (),
    ):
        locked = set(locked_coordinates)
        # Validation (reference :71-121): every id in the sequence must have a
        # coordinate; locked ids must NOT be (re)trained but must exist.
        missing = [c for c in update_sequence if c not in coordinates]
        if missing:
            raise ValueError(f"update sequence references unknown coordinates: {missing}")
        dup = [c for c in update_sequence if update_sequence.count(c) > 1]
        if dup:
            raise ValueError(f"duplicate coordinates in update sequence: {sorted(set(dup))}")
        if not update_sequence:
            raise ValueError("empty update sequence")
        self.coordinates = coordinates
        self.update_sequence = list(update_sequence)
        self.num_iterations = num_iterations
        self.locked = locked

    def run(
        self,
        batch: GameBatch,
        initial_model: Optional[GameModel] = None,
        validation_batch: Optional[GameBatch] = None,
        validation_fn: Optional[Callable[[GameModel, GameBatch], Dict[str, float]]] = None,
        better: Callable[[float, float], bool] = lambda new, old: new < old,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: int = 1,
        checkpoint_tag: Optional[str] = None,
        checkpoint_keep_last: Optional[int] = None,
        emitter=None,  # utils.events.EventEmitter; optimization-log events
        profile: bool = True,
    ) -> CoordinateDescentResult:
        """Descend; with validation data, tracks the best model seen across
        iterations by the primary metric (descendWithValidation role).

        ``better(new, old)`` encodes metric direction (reference
        EvaluatorType.op); default assumes lower-is-better.

        ``profile=True`` (default) blocks on each coordinate's scores so
        ``wall_times`` covers device execution. ``profile=False`` removes
        every ``block_until_ready`` between coordinate updates — back-to-back
        coordinates stay enqueued on device with no host sync, and the
        recorded wall times measure dispatch only.

        With ``checkpoint_dir``, full descent state (models, score arrays,
        iteration counter, metric history) is persisted every
        ``checkpoint_every`` iterations and training RESUMES from the latest
        checkpoint found there — mid-training recovery the reference lacks
        (its warm start is model-only, SURVEY.md §5).
        ``checkpoint_keep_last`` caps how many step files survive (the
        writer prunes the oldest after each publish; on a full disk it also
        prunes before retrying). A save that still fails with ENOSPC after
        the writer's prune-and-retry degrades to a logged warning plus
        ``checkpoint_write_failures_total`` and TRAINING CONTINUES — a full
        checkpoint disk must not kill a run that can still produce its
        final model (degradation priority: the finished artifact outranks
        intermediate durability).
        """
        n = batch.n
        dtype = batch.offset.dtype

        # Initialize models + per-coordinate score vectors.
        models: Dict[str, object] = {}
        scores: Dict[str, Array] = {}
        for cid in self.update_sequence:
            coord = self.coordinates[cid]
            if initial_model is not None and initial_model.get(cid) is not None:
                models[cid] = initial_model.get(cid)
            else:
                if cid in self.locked:
                    raise ValueError(f"locked coordinate {cid} needs a pretrained model")
                models[cid] = None
            scores[cid] = (
                self.coordinates[cid].score(models[cid], batch)
                if models[cid] is not None
                else jnp.zeros((n,), dtype)
            )

        total_scores = jnp.zeros((n,), dtype)
        for s in scores.values():
            total_scores = total_scores + s

        tracker: Dict[str, list] = {cid: [] for cid in self.update_sequence}
        wall_times: Dict[str, List[float]] = {cid: [] for cid in self.update_sequence}
        metric_history: List[Dict[str, float]] = []
        best_metric: Optional[float] = None
        # Seed the best-model slot from the warm start only when a validation
        # pass will actually run and can replace it; without validation the
        # seed would survive to the end and the caller would get the initial
        # model back with every trained pass discarded.
        has_validation = validation_fn is not None and validation_batch is not None
        best_model = GameModel(dict(models)) if (
            has_validation and all(m is not None for m in models.values())
        ) else None

        start_it = 0
        if checkpoint_dir is not None:
            if checkpoint_every < 1:
                raise ValueError(f"checkpoint_every must be >= 1, got {checkpoint_every}")
            from photon_tpu.utils.checkpoint import (
                LegacyCheckpointError,
                load_checkpoint,
            )

            tag = checkpoint_tag or ",".join(self.update_sequence)
            state = step = None
            try:
                # step=None → resume-robust load: a torn newest step (machine
                # crash mid-save) is skipped with a warning and the run
                # resumes one pass earlier; it raises only when EVERY step is
                # unreadable (corruption is never silently discarded).
                state, step = load_checkpoint(checkpoint_dir)
            except FileNotFoundError:
                pass  # fresh directory: nothing to resume
            except LegacyCheckpointError as exc:
                # Only v1 (pickle) checkpoints remain: an upgrade must not
                # turn a resumable job into a crash loop — restart the sweep
                # from step 0 (ADVICE r3).
                logger.warning(
                    "ignoring unreadable legacy checkpoint at %s (%s); "
                    "restarting training from step 0",
                    checkpoint_dir, exc,
                )
            if state is not None:
                if state.get("tag") != tag:
                    raise ValueError(
                        f"checkpoint at {checkpoint_dir} was written for a "
                        f"different configuration (saved tag {state.get('tag')!r}"
                        f" != current {tag!r}); clear the directory or point "
                        "checkpoint_dir elsewhere"
                    )
                with span("cd/resume_restore"):
                    models = state["models"]
                    scores = state["scores"]
                    total_scores = state["total_scores"]
                    metric_history = state["metric_history"]
                    best_metric = state["best_metric"]
                    best_model = state["best_model"]
                    tracker = state["tracker"]
                    wall_times = state.get(
                        "wall_times", {cid: [] for cid in self.update_sequence}
                    )
                    # Reinstall per-coordinate active-set gate state (pass
                    # counter + keep masks) so the first resumed pass is gated
                    # exactly like an uninterrupted run's would be. Older
                    # checkpoints without the field restore to a full pass.
                    active_state = state.get("active_state") or {}
                    for cid, coord in self.coordinates.items():
                        restore = getattr(coord, "restore_active_state", None)
                        if restore is not None:
                            restore(active_state.get(cid))
                start_it = step + 1
                registry().counter("cd_resumes_total").inc()
                logger.info(
                    "resuming coordinate descent from checkpoint step %d", step
                )

        single = len(self.update_sequence) == 1 and self.num_iterations == 1

        for it in range(start_it, self.num_iterations):
            for cid in self.update_sequence:
                if cid in self.locked:
                    continue
                coord = self.coordinates[cid]
                # Pass-boundary hook (duck-typed): active-set coordinates
                # reset their mask state when a descent (re)starts at
                # iteration 0, so reusing a coordinate object across runs
                # always begins with a full pass.
                begin_pass = getattr(coord, "begin_cd_pass", None)
                if begin_pass is not None:
                    begin_pass(it)
                t0 = time.monotonic()
                # Residual: all OTHER coordinates' scores
                # (summedScores − thisCoordinateScores, reference :441-446).
                residual = None if single else total_scores - scores[cid]
                with _export_trace(), span(f"cd/iter{it}/{cid}"):
                    with span("solve"):
                        model, diag = coord.train(batch, residual, models[cid])
                    with span("score"):
                        new_scores = coord.score(model, batch)
                        if profile:
                            # The clock must cover device execution, not
                            # dispatch.
                            jax.block_until_ready(new_scores)
                wall = time.monotonic() - t0
                total_scores = total_scores - scores[cid] + new_scores
                scores[cid] = new_scores
                models[cid] = model
                tracker[cid].append(diag)
                wall_times[cid].append(wall)
                registry().counter(
                    "cd_coordinate_updates_total", coordinate=cid
                ).inc()
                logger.info(
                    "CD iter %d coordinate %s trained in %.2fs", it, cid, wall
                )
                if emitter is not None:
                    from photon_tpu.utils.events import optimization_log_event

                    # diag.summary() reads device-resident history arrays —
                    # a host sync. Under profile=False the dispatch loop must
                    # stay sync-free, so the event carries the summary only
                    # when profiling; the run report reads the same
                    # diagnostics once at finalize either way.
                    emitter.emit(
                        optimization_log_event(
                            coordinate=cid,
                            cd_iteration=it,
                            wall_s=wall,
                            summary=(
                                diag.summary()
                                if profile and hasattr(diag, "summary")
                                else None
                            ),
                            # Active-set accounting: host ints the coordinate
                            # derived from masks it had ALREADY fetched at
                            # the pass boundary — reading them here adds no
                            # sync. None for ungated coordinates.
                            active_set=getattr(
                                coord, "last_active_set_stats", None
                            ),
                            # Out-of-core residency accounting (host ints the
                            # coordinate's store tracked during the pass) —
                            # None for fully-resident coordinates.
                            residency=getattr(
                                coord, "last_residency_stats", None
                            ),
                        )
                    )

            if validation_fn is not None and validation_batch is not None:
                game_model = GameModel(dict(models))
                metrics = validation_fn(game_model, validation_batch)
                metric_history.append(metrics)
                primary = next(iter(metrics.values()))
                if best_metric is None or better(primary, best_metric):
                    best_metric = primary
                    best_model = game_model
                logger.info("CD iter %d validation: %s", it, metrics)

            registry().counter("cd_iterations_total").inc()

            def _save_checkpoint(it=it):
                from photon_tpu.utils import resources
                from photon_tpu.utils.checkpoint import save_checkpoint

                with span("cd/checkpoint_save"):
                    # Active-set gate state rides along (duck-typed): the
                    # resolved keep masks are host bools; the save gathers
                    # every device array anyway, so this adds no extra syncs.
                    active_state = {
                        cid: coord.export_active_state()
                        for cid, coord in self.coordinates.items()
                        if getattr(coord, "export_active_state", None)
                        is not None
                    }
                    try:
                        save_checkpoint(
                            checkpoint_dir,
                            dict(
                                models=models,
                                scores=scores,
                                total_scores=total_scores,
                                metric_history=metric_history,
                                best_metric=best_metric,
                                best_model=best_model,
                                tracker=tracker,
                                wall_times=wall_times,
                                active_state=active_state,
                                tag=checkpoint_tag or ",".join(self.update_sequence),
                            ),
                            it,
                            keep_last=checkpoint_keep_last,
                        )
                    except OSError as exc:
                        # The writer already pruned + retried; a persistent
                        # full disk degrades to lost intermediate durability,
                        # not a lost run.
                        if not resources.is_enospc(exc):
                            raise
                        registry().counter(
                            "checkpoint_write_failures_total"
                        ).inc()
                        logger.warning(
                            "checkpoint save at pass %d failed even after "
                            "pruning (disk full under %s); continuing "
                            "WITHOUT a checkpoint this pass: %s",
                            it, checkpoint_dir, exc,
                        )

            saved = False
            if checkpoint_dir is not None and (it + 1) % checkpoint_every == 0:
                _save_checkpoint()
                saved = True

            # Cooperative SIGTERM/SIGINT: the pass boundary is the safe stop
            # — every coordinate's state is consistent and (when a
            # checkpoint dir exists) durable, so --resume continues from
            # exactly here.
            from photon_tpu.utils.shutdown import (
                GracefulShutdown,
                shutdown_requested,
            )

            signum = shutdown_requested()
            if signum is not None:
                if checkpoint_dir is not None and not saved:
                    _save_checkpoint()
                logger.warning(
                    "coordinate descent stopping after pass %d on signal %d",
                    it, signum,
                )
                raise GracefulShutdown(signum)

            # Same cooperative boundary handles host memory pressure: at the
            # watchdog's hard level, checkpoint what we have and raise a
            # clean actionable error instead of waiting for the OOM-killer's
            # unexplained SIGKILL.
            from photon_tpu.utils import resources

            try:
                resources.check_memory(f"coordinate_descent pass {it}")
            except resources.HostMemoryPressureError:
                if checkpoint_dir is not None and not saved:
                    _save_checkpoint()
                raise

        final = GameModel(dict(models))
        if best_model is None:
            best_model = final
        result = CoordinateDescentResult(
            model=final,
            best_model=best_model,
            best_metric=best_metric,
            metric_history=metric_history,
            tracker=tracker,
            wall_times=wall_times,
        )
        summary = result.summary()
        if summary:
            logger.info("optimization summary:\n%s", summary)
        return result
