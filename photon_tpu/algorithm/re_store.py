"""Out-of-core random-effect training store: host master, device working set.

The trainer previously required every entity block — coefficients AND
training data — device-resident for the whole run, capping model size at
HBM. This module is the training-side twin of the serving hot/cold store
(serve/store.py): the full dataset lives in host memory (optionally
memory-mapped from disk via ``spill_dir``), and a byte-budgeted
working set of device blocks is managed by the shared residency core
(data/residency.py ``ByteBudgetLru`` — Snap ML's hierarchical out-of-core
scheme from PAPERS.md, with active-set gating reinterpreted as the
residency policy: converged entities are precisely the ones safe to evict).

Traffic rides the ingest pipeline machinery (io/pipeline.py): the h2d
upload stage runs on a ``_run_staged`` worker thread ahead of the dispatch
loop, and the d2h download stage drains solver results on a
``StageWorker`` behind it — both with bounded queues, so device residency
is capped by budget + queue depth, and uploads overlap device compute
(JAX async dispatch keeps the device busy while the upload thread blocks
in ``device_put``).

Invariants this store must preserve (the hard part of the design):

* **Zero retraces across evictions** — a re-uploaded block has bit-identical
  shapes/dtypes to its first upload (same bucket-grid geometry), so the
  solve cache hits its compiled executable. Residency changes WHERE a block
  lives, never its aval.
* **Deterministic eviction sequence** — a single upload thread walks the
  dispatch plan in order and releases happen in FIFO dispatch order, so the
  ``ByteBudgetLru`` sees the same call sequence every run (same seed +
  budget ⇒ identical ``eviction_log``).
* **Budget honesty** — a block's cost counts its data arrays plus the
  warm-start and result coefficient buffers that coexist with it in flight;
  the ``re_device_resident_bytes`` gauge tracks admitted cost and its peak
  must stay ≤ the effective budget (budget is floored at the single largest
  block, with a warning, because refusing the largest block would deadlock).
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
from typing import Dict, Hashable, List, Optional, Sequence

import numpy as np

from photon_tpu.data.random_effect import EntityBlock
from photon_tpu.data.residency import ByteBudgetLru
from photon_tpu.obs.metrics import registry
from photon_tpu.utils import faults, resources

logger = logging.getLogger("photon_tpu")

_SPILL_GUARD = resources.DiskBudgetGuard("re_store.spill")

_BLOCK_FIELDS = (
    "entity_idx",
    "features",
    "label",
    "weight",
    "sample_index",
    "train_mask",
)


def spill_partition_tag(member) -> str:
    """Stable short tag naming a ring partition's spill directory. Ints and
    ``name:k`` members use the integer (``updater:3`` → ``3`` — the tag a
    shard worker, the manifest, and the metrics already agree on); any other
    member id gets a short blake2b hex so arbitrary replica ids still map
    to a filesystem-safe, process-stable name."""
    if isinstance(member, int):
        return str(member)
    m = str(member)
    tail = m.rsplit(":", 1)[-1]
    if tail.isdigit():
        return tail
    return hashlib.blake2b(m.encode("utf-8"), digest_size=4).hexdigest()


def partition_spill_dir(spill_root: str, member) -> str:
    """Per-ring-partition spill directory: ``<spill_root>/host-<k>/``.

    The host-owned layout makes rebalance a RENAME problem instead of a
    row-streaming problem: every out-of-core host master spilled for ring
    partition ``k`` lives under one directory, so when a ring change hands
    the partition to a different owner on the same filesystem, adopting its
    spilled state is ``os.replace`` on a handful of files — no row
    re-stream, no decode, no re-encode. Placement here is a LOCALITY hint
    only; ownership is always re-derived from the ring (serve/store.py's
    owned mask, the updater's ``owned_records``), so a mis-located file can
    cost a cold read but never a wrong answer."""
    path = os.path.join(spill_root, f"host-{spill_partition_tag(member)}")
    os.makedirs(path, exist_ok=True)
    return path


def rebalance_spill_layout(spill_root: str, before, after) -> Dict[str, Dict]:
    """Move departed ring members' spill partitions to their successors by
    file rename — the host-owned layout's payoff.

    ``before``/``after`` are :class:`~photon_tpu.serve.routing.HashRing`
    instances (or anything with ``members`` and ``owner``). For each member
    present before but not after, its ``host-<k>/`` files are adopted by
    the member owning the departed id's hash on the AFTER ring — a
    deterministic successor every process derives identically. Files move
    with ``os.replace`` (an inode rename on one filesystem, never a data
    copy); a name collision in the successor's directory keeps both by
    prefixing the adopted file with ``from-<k>__``. Returns per-departed
    stats ``{member: {"successor": str, "moved": int}}``.

    Caveat (by design): the successor of a departed member's NAME hash is
    not necessarily the ring owner of every entity in its files — after a
    move, some adopted rows are foreign to their new directory. That is
    safe because spill placement is a locality hint (see
    :func:`partition_spill_dir`); the next compaction pass re-homes rows
    exactly. The move buys warm disk locality for the common case at
    rename cost, instead of exact re-homing at re-stream cost."""
    out: Dict[str, Dict] = {}
    survivors = set(after.members)
    for member in before.members:
        if member in survivors:
            continue
        src = os.path.join(
            spill_root, f"host-{spill_partition_tag(member)}"
        )
        if not os.path.isdir(src):
            continue
        successor = after.owner(str(member))
        if successor is None:
            continue
        dst = partition_spill_dir(spill_root, successor)
        moved = 0
        for name in sorted(os.listdir(src)):
            src_path = os.path.join(src, name)
            if not os.path.isfile(src_path):
                continue
            dst_path = os.path.join(dst, name)
            if os.path.exists(dst_path):
                dst_path = os.path.join(
                    dst,
                    f"from-{spill_partition_tag(member)}__{name}",
                )
            os.replace(src_path, dst_path)
            moved += 1
        try:
            os.rmdir(src)
        except OSError:
            pass  # non-file leftovers keep the dir; harmless
        registry().counter("re_spill_rebalance_moves_total").inc(moved)
        logger.info(
            "re_store spill rebalance: %s -> %s (%d files renamed)",
            member, successor, moved,
        )
        out[str(member)] = dict(successor=str(successor), moved=moved)
    return out


def host_entity_block(
    block: EntityBlock, spill_dir: Optional[str] = None, index: int = 0
) -> EntityBlock:
    """Rebuild ``block`` with host-numpy leaves (dense blocks only).

    With ``spill_dir``, each array round-trips through an ``.npy`` file and
    comes back memory-mapped read-only — the host master then costs file
    cache, not RSS, and the upload stage's gathers fault in only the pages
    it ships."""
    if block.col_map is not None:
        raise ValueError("out-of-core residency supports dense blocks only")
    fields = {}
    for name in _BLOCK_FIELDS:
        arr = np.asarray(getattr(block, name))
        if spill_dir is not None:
            path = os.path.join(spill_dir, f"block{index:05d}_{name}.npy")
            try:
                _SPILL_GUARD.check()  # ``enospc`` rules for --re-spill-dir
                np.save(path, arr)
                arr = np.load(path, mmap_mode="r")
            except OSError as exc:
                # Disk full under the spill dir: keep this array in host RAM
                # instead (values identical, RSS higher) and remove the
                # partial .npy so it cannot strand space or be mmapped torn.
                _SPILL_GUARD.record(exc)
                _SPILL_GUARD.cleanup(path)
                registry().counter("re_spill_fallbacks_total").inc()
                logger.warning(
                    "re_store spill of block %d field %s to %s failed; "
                    "keeping it in host memory: %s", index, name, spill_dir,
                    exc,
                )
        fields[name] = arr
    return EntityBlock(col_map=None, **fields)


def block_data_bytes(block: EntityBlock) -> int:
    """Host bytes of a block's data arrays."""
    return int(
        sum(np.asarray(getattr(block, f)).nbytes for f in _BLOCK_FIELDS)
    )


def block_device_cost(block: EntityBlock) -> int:
    """Budgeted device cost of holding ``block`` in flight: its data arrays
    plus the warm-start w0 and the solver's result coefficients, both
    (E, dim) f32 — they coexist with the block between upload and
    download."""
    coef_bytes = 2 * block.num_entities * block.dim * 4
    return block_data_bytes(block) + coef_bytes


class ReDeviceStore:
    """Residency manager for one coordinate's entity blocks.

    Keys are block indices into the coordinate's dataset (cacheable across
    passes — a resident block is a free upload next pass) or transient
    tuples for gated-pass compacted blocks (always discarded at release;
    their geometry depends on the pass's active set, so caching them would
    never hit).

    Thread contract: ``acquire`` runs on the h2d stage thread, ``release``
    on the d2h worker thread, ``retire``/``begin_pass``/``end_pass`` on the
    training thread between passes. All state is serialized under one
    condition variable, which doubles as the budget backpressure signal —
    ``acquire`` sleeps until enough protected (in-flight) bytes release.
    """

    def __init__(
        self,
        blocks: Sequence[EntityBlock],
        budget_bytes: int,
        coordinate_id: str,
        spill_dir: Optional[str] = None,
        device=None,
        spill_member=None,
    ):
        # ``spill_member`` opts into the host-owned per-ring-partition
        # layout: spill files land under ``<spill_dir>/host-<k>/`` so a
        # ring rebalance is a file move (rebalance_spill_layout), not a
        # row re-stream.
        if spill_dir is not None and spill_member is not None:
            spill_dir = partition_spill_dir(spill_dir, spill_member)
        elif spill_dir is not None:
            os.makedirs(spill_dir, exist_ok=True)
        self.spill_dir = spill_dir
        self.coordinate_id = coordinate_id
        # Entity-sharded placement (parallel/entity_shard.py): every upload
        # pins to this device so the working set stays local to the shard's
        # owner. None = backend default (the single-device path, unchanged).
        self.device = device
        self.blocks: List[EntityBlock] = [
            host_entity_block(b, spill_dir, i) for i, b in enumerate(blocks)
        ]
        self.block_cost = [block_device_cost(b) for b in self.blocks]
        self.total_cost = int(sum(self.block_cost))
        self.budget = int(budget_bytes)
        max_cost = max(self.block_cost, default=0)
        self._max_cost = max_cost
        self.effective_budget = max(self.budget, max_cost)
        if self.effective_budget > self.budget:
            logger.warning(
                "re_store[%s]: budget %d B below largest block %d B; "
                "flooring effective budget there",
                coordinate_id,
                self.budget,
                max_cost,
            )
        self.lru = ByteBudgetLru(self.effective_budget, on_evict=self._on_evict)
        self._resident: Dict[Hashable, EntityBlock] = {}
        self._protected: set = set()
        self._cond = threading.Condition()
        self._abort = False
        self._inflight_solves = 0
        # Cumulative traffic counters (mirrored into obs metrics).
        self.uploads = 0
        self.upload_hits = 0
        self.overlapped_uploads = 0
        self.upload_bytes = 0
        self.pass_evictions: List[int] = []
        self._pass_eviction_mark = 0
        self._labels = dict(coordinate=coordinate_id)
        self._publish()

    # ------------------------------------------------------------------
    # Pass lifecycle (training thread).
    # ------------------------------------------------------------------

    def begin_pass(self, cd_iteration: int) -> None:
        with self._cond:
            self._abort = False
            self._pass_eviction_mark = self.lru.evictions
        self._publish()

    def end_pass(self) -> None:
        with self._cond:
            self.pass_evictions.append(
                self.lru.evictions - self._pass_eviction_mark
            )
        self._publish()

    def abort_pass(self) -> None:
        """Unstick a blocked upload thread on the error path."""
        with self._cond:
            self._abort = True
            self._cond.notify_all()

    def retire(self, keys: Sequence[Hashable]) -> int:
        """Active-set residency hook: eagerly evict blocks whose entities
        all converged (called at the pass-boundary mask fetch — the
        already-paid sync point). Returns how many were resident."""
        dropped = 0
        with self._cond:
            for key in keys:
                if key in self._protected:
                    continue
                if self.lru.evict(key):
                    self._resident.pop(key, None)
                    dropped += 1
            if dropped:
                self._cond.notify_all()
        if dropped:
            registry().counter(
                "re_store_retired_total", **self._labels
            ).inc(dropped)
            self._publish()
        return dropped

    # ------------------------------------------------------------------
    # Upload / download (pipeline stage threads).
    # ------------------------------------------------------------------

    def acquire(self, key, host_block: EntityBlock, w0_host, cacheable: bool):
        """h2d stage: make ``key`` resident under the budget (blocking on
        in-flight releases when needed) and return ``(device_block, w0)``.
        ``w0`` is always a fresh device buffer — the solver donates it."""
        import jax

        cost = (
            self.block_cost[key]
            if isinstance(key, int)
            else block_device_cost(host_block)
        )
        with self._cond:
            while True:
                if self._abort:
                    raise RuntimeError(
                        f"re_store[{self.coordinate_id}]: pass aborted"
                    )
                if key in self.lru:
                    self.lru.touch(key)
                    break
                if self.lru.would_fit(cost, self._protected):
                    for victim in self.lru.admit(key, cost, self._protected):
                        self._resident.pop(victim, None)
                    break
                self._cond.wait(0.05)
            self._protected.add(key)
            overlapped = self._inflight_solves > 0
        reg = registry()
        dev_block = self._resident.get(key)
        if dev_block is not None:
            self.upload_hits += 1
            reg.counter("re_store_upload_hits_total", **self._labels).inc()
        else:
            dev_block = self._upload_contained(
                lambda: jax.device_put(host_block, self.device),
                f"block {key}",
            )
            nbytes = block_data_bytes(host_block)
            self.uploads += 1
            self.upload_bytes += nbytes
            if overlapped:
                self.overlapped_uploads += 1
            reg.counter("re_store_uploads_total", **self._labels).inc()
            reg.counter("re_store_upload_bytes_total", **self._labels).inc(
                nbytes
            )
            if cacheable:
                with self._cond:
                    self._resident[key] = dev_block
        w0 = self._upload_contained(
            lambda: jax.device_put(np.ascontiguousarray(w0_host), self.device),
            f"w0 for block {key}",
        )
        self._publish()
        return dev_block, w0

    def _upload_contained(self, upload, what: str):
        """Run a device upload with OOM containment: on RESOURCE_EXHAUSTED,
        evict every unprotected resident block, halve the effective budget
        toward the floor (the largest single block — admitting less than
        that would deadlock), release dropped buffers, and retry. The
        XLA allocator can legitimately fail before our budget does — it
        serves fragmented HBM, compiled executables, and other coordinates'
        working sets too — and the out-of-core path is value-identical at
        any budget, so shrinking is bit-safe. A hard
        :class:`~photon_tpu.utils.resources.DeviceMemoryError` fires only
        when the floor itself cannot fit."""
        import gc

        floor_retry = True
        while True:
            try:
                faults.check("re_store.upload")  # ``oom`` injection site
                return upload()
            except Exception as exc:
                if not resources.is_device_oom(exc):
                    raise
                shrunk = self._evict_harder_and_shrink()
                if not shrunk:
                    if not floor_retry:
                        raise resources.DeviceMemoryError(
                            f"re_store[{self.coordinate_id}]: device OOM "
                            f"uploading {what} at the floor budget "
                            f"({self._max_cost} B — the largest single "
                            "block). Containment already evicted the whole "
                            "working set; shrink the block geometry "
                            "(--re-max-block-entities) or add device memory."
                        ) from exc
                    floor_retry = False
                logger.warning(
                    "re_store[%s]: device OOM uploading %s; evicted working "
                    "set, effective budget now %d B, retrying: %s",
                    self.coordinate_id, what, self.effective_budget, exc,
                )
                gc.collect()

    def _evict_harder_and_shrink(self) -> bool:
        """OOM response: drop every unprotected resident block and halve
        the effective budget (floored at the largest single block). Returns
        False when the budget was already at the floor — the caller gets
        exactly one more eviction-only retry before failing hard."""
        with self._cond:
            for victim in list(self.lru.resident):
                if victim in self._protected:
                    continue
                if self.lru.evict(victim):
                    self._resident.pop(victim, None)
            shrunk = self.effective_budget > self._max_cost
            if shrunk:
                self.effective_budget = max(
                    self._max_cost, self.effective_budget // 2
                )
                self.lru.budget = self.effective_budget
                registry().counter(
                    "re_device_budget_shrinks_total", **self._labels
                ).inc()
            self._cond.notify_all()
        self._publish()
        return shrunk

    def release(self, key, cacheable: bool) -> None:
        """d2h worker: the solve's results are materialized on host; the
        block's in-flight protection (and, for transient compacted blocks,
        its residency) can go."""
        with self._cond:
            self._protected.discard(key)
            if not cacheable:
                self.lru.discard(key)
                self._resident.pop(key, None)
            self._cond.notify_all()
        self._publish()

    def mark_solve_start(self) -> None:
        with self._cond:
            self._inflight_solves += 1

    def mark_solve_done(self) -> None:
        with self._cond:
            self._inflight_solves -= 1

    # ------------------------------------------------------------------
    # Introspection.
    # ------------------------------------------------------------------

    def stats(self) -> Dict:
        return dict(
            coordinate=self.coordinate_id,
            budget_bytes=self.budget,
            effective_budget_bytes=self.effective_budget,
            footprint_bytes=self.total_cost,
            resident_bytes=self.lru.resident_bytes,
            peak_bytes=self.lru.peak_bytes,
            resident_blocks=len(self.lru),
            evictions=self.lru.evictions,
            eviction_log=list(self.lru.eviction_log),
            uploads=self.uploads,
            upload_hits=self.upload_hits,
            overlapped_uploads=self.overlapped_uploads,
            upload_bytes=self.upload_bytes,
            pass_evictions=list(self.pass_evictions),
        )

    def _on_evict(self, key) -> None:
        registry().counter("re_store_evictions_total", **self._labels).inc()

    def _publish(self) -> None:
        reg = registry()
        reg.gauge("re_device_resident_bytes", **self._labels).set(
            self.lru.resident_bytes
        )
        reg.gauge("re_device_resident_bytes_peak", **self._labels).set(
            self.lru.peak_bytes
        )
        reg.gauge("re_device_resident_blocks", **self._labels).set(
            len(self.lru)
        )
        reg.gauge("re_device_budget_bytes", **self._labels).set(
            self.effective_budget
        )
