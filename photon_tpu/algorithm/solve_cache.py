"""Compile-once executable cache for the GLMix solver hot paths.

The random-effect coordinate dispatches one vmapped ``_solve_block`` per
EntityBlock per coordinate-descent pass — the paper's hot loop of millions
of per-entity GLM solves (reference RandomEffectCoordinate.scala:228-283)
collapsed into a handful of SPMD programs. Before this cache, every one of
those dispatches re-traced the solver eagerly: K CD passes × B blocks ×
S λ-sweep configs paid K·B·S traces for what is at most a few distinct
(shape, objective, optimizer) combinations.

This module keys ONE jitted executable per

    (block shape bucket, dtype, static objective config, optimizer spec,
     has feature mask)

so repeated CD passes and repeated same-shape blocks reuse a single
executable. Paired with shape bucketing (data/random_effect.py rounds
``(E, n_max, d)`` up to a geometric grid), heterogeneous entity populations
collapse onto a handful of cache entries. The warm-start coefficient buffer
is donated (``donate_argnums``): the (E, d) warm start is dead after the
solve, so XLA reuses its HBM for the output instead of allocating a second
coefficient block.

Key construction notes:

- ``GLMObjective`` / ``OptimizerSpec`` / ``OptimizerConfig`` are keyed by
  their static scalar fields. Normalization vectors and box-constraint
  arrays are keyed by ``id()`` (they are built once per coordinate and
  reused across passes); the cache pins a strong reference to every keyed
  object so an id is never recycled while its entry is alive.
- Trace counting is done INSIDE the traced function (the standard
  trace-counter trick): the Python side effect runs only when JAX actually
  traces, so ``stats.traces`` counts real retraces — including any the
  jit-level cache would hide — and the retrace-regression test in
  tests/test_solve_cache.py asserts on it directly.
- Keys are deliberately DEVICE-POLYMORPHIC: no device or sharding
  component. One traced executable serves every device of a backend, so
  the entity-sharded coordinate (algorithm/sharded_random_effect.py) can
  run S shards across N devices through one shared cache — warming it at
  one device count leaves every other count with zero compiles
  (tests/test_entity_sharded.py asserts this), and the multichip ladder's
  zero-retrace bar needs no per-device keying.

The same cache serves the fixed-effect objective (``fe_solver``): the full
optimizer run over the sharded batch becomes one cached jitted program per
(objective, spec) instead of an eager re-trace of the ``lax.while_loop``
nest on every ``train()`` call.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

# Bounded-cache opt-in: entry cap for every SolveCache constructed without an
# explicit ``max_entries`` (default unbounded — today a λ-sweep is one entry
# per λ, which is fine; the env knob exists for per-λ-objective sweeps that
# blow up the entry count).
MAX_ENTRIES_ENV = "PHOTON_TPU_SOLVE_CACHE_MAX_ENTRIES"


@dataclasses.dataclass
class SolveCacheStats:
    """Counters for cache effectiveness, reported by bench.py.

    traces:  executions of the tracing path (one per distinct executable;
             a retrace of an existing key also counts — that is the point).
    calls:   solver dispatches routed through the cache.
    hits:    dispatches that reused an already-traced executable.
    trace_keys: shape/kind descriptor recorded at each trace, for the
             bench's retrace breakdown.
    """

    traces: int = 0
    calls: int = 0
    hits: int = 0
    evictions: int = 0
    trace_keys: List[Tuple] = dataclasses.field(default_factory=list)

    def as_dict(self) -> Dict[str, Any]:
        return dict(
            traces=self.traces,
            calls=self.calls,
            hits=self.hits,
            evictions=self.evictions,
            trace_keys=[list(k) for k in self.trace_keys],
        )


def _scalar(x):
    """Coerce a numeric config field to a hashable Python scalar; arrays and
    other unhashables fall back to identity (pinned by the cache entry)."""
    if x is None or isinstance(x, (bool, int, float, str)):
        return x
    try:
        hash(x)
        return x
    except TypeError:
        return ("id", id(x))


class SolveCache:
    """Executable cache for block (random-effect) and fixed-effect solves.

    One instance may be shared across coordinates — the module-level
    :func:`default_cache` is shared by every coordinate that is not given an
    explicit cache, so a λ-sweep over the same dataset hits one executable
    set. ``donate=False`` disables warm-start donation (callers that need to
    reuse the w0 buffer after the solve).
    """

    def __init__(self, donate: bool = True, max_entries: Optional[int] = None):
        self.donate = donate
        if max_entries is None:
            env = os.environ.get(MAX_ENTRIES_ENV, "").strip()
            max_entries = int(env) if env else None
        if max_entries is not None and max_entries < 1:
            raise ValueError(f"max_entries must be >= 1, got {max_entries}")
        # LRU bound on ENTRIES (per-key executables). Evicting an entry only
        # drops the cache's reference + pins — a solver callable a caller
        # already holds keeps working (jax.jit owns its own executables); a
        # later dispatch of the same key rebuilds (and re-traces) it.
        self.max_entries = max_entries
        self.stats = SolveCacheStats()
        self._fns: "OrderedDict[Tuple, Callable]" = OrderedDict()
        self._pins: Dict[Tuple, Tuple] = {}  # keep id()-keyed objects alive
        self._lock = threading.Lock()

    # ---- static keys -----------------------------------------------------

    @staticmethod
    def _norm_key(norm) -> Optional[Tuple]:
        if norm is None:
            return None
        return (
            bool(norm.is_identity),
            None if norm.factors is None else ("id", id(norm.factors)),
            None if norm.shifts is None else ("id", id(norm.shifts)),
            _scalar(getattr(norm, "intercept_index", None)),
        )

    @classmethod
    def _objective_key(cls, objective) -> Tuple:
        return (
            objective.loss,
            _scalar(objective.l2_weight),
            _scalar(objective.l1_weight),
            _scalar(objective.intercept_index),
            bool(objective.use_pallas),
            cls._norm_key(objective.normalization),
        )

    @staticmethod
    def _spec_key(spec) -> Tuple:
        return (
            spec.optimizer,
            _scalar(spec.max_iter),
            _scalar(spec.tol),
            _scalar(spec.memory),
            _scalar(spec.max_cg_iter),
            None
            if spec.box is None
            else (("id", id(spec.box[0])), ("id", id(spec.box[1]))),
            bool(spec.track_history),
        )

    @staticmethod
    def _config_key(config) -> Tuple:
        return (
            _scalar(config.max_iter),
            _scalar(config.tol),
            _scalar(config.memory),
            _scalar(config.max_line_search_evals),
            bool(config.track_history),
        )

    # ---- builders --------------------------------------------------------

    def _get_or_build(self, key: Tuple, build: Callable[[], Callable], pins: Tuple):
        with self._lock:
            fn = self._fns.get(key)
            if fn is None:
                fn = build()
                self._fns[key] = fn
                self._pins[key] = pins
                if self.max_entries is not None:
                    while len(self._fns) > self.max_entries:
                        old_key, _old_fn = self._fns.popitem(last=False)
                        self._pins.pop(old_key, None)
                        self.stats.evictions += 1
                        from photon_tpu.obs.metrics import registry

                        registry().counter("solve_cache_evictions_total").inc()
            else:
                self._fns.move_to_end(key)  # LRU touch
        return fn

    def _counted(self, fn: Callable) -> Callable:
        """Wrap a jitted fn with hit/call accounting (trace accounting lives
        inside the traced body, so it also catches shape-driven retraces)."""

        def call(*args):
            before = self.stats.traces
            out = fn(*args)
            self.stats.calls += 1
            if self.stats.traces == before:
                self.stats.hits += 1
            return out

        return call

    def block_solver(
        self, objective, spec, config, has_mask: bool,
        convergence_tol: Optional[float] = None,
        re_kernel: str = "xla",
    ) -> Callable[..., Tuple[Array, Array, Array]]:
        """Jitted ``_solve_block`` executable for one static configuration.

        Returns ``solve(block, offsets, w0[, feature_mask])``. The warm
        start ``w0`` is DONATED (when ``self.donate``): callers must pass a
        buffer that is dead after the call — a fresh gather, or an explicit
        copy of any model-owned array.

        With ``convergence_tol`` set (the active-set gate of
        algorithm/random_effect.py), the traced program ALSO returns a
        per-entity bool ``active`` mask computed in-graph: an entity stays
        active while its coefficient delta exceeds ``tol`` relative to the
        warm start, and shape-bucket padding rows (entity_idx == -1) are
        never active. The tol is part of the cache key, so gated and
        ungated dispatches never share (or invalidate) an executable;
        ``trace_keys`` keeps the same shape-only format either way so trace
        breakdowns of gated and ungated runs stay comparable.

        Every dispatch carries an in-trace divergence quarantine: entity rows
        whose solve produced non-finite coefficients keep their warm start
        and are flagged ``REASON_DIVERGED``; with ``convergence_tol`` the
        program additionally returns a per-entity ``quarantined`` bool mask
        (fifth output) that the coordinate reads at the existing
        pass-boundary mask fetch — no extra host syncs.
        """
        has_mask = bool(has_mask)
        tol = None if convergence_tol is None else float(convergence_tol)
        # ``re_kernel`` (resolved — never "auto") is part of the key: the
        # Newton-system lowering changes the traced program, so XLA and
        # fused-Pallas dispatches must never share an executable.
        re_kernel = str(re_kernel)
        key = (
            "block",
            self._objective_key(objective),
            self._spec_key(spec),
            self._config_key(config),
            has_mask,
            tol,
            re_kernel,
        )

        def build():
            from photon_tpu.algorithm.random_effect import _solve_block
            from photon_tpu.optim.common import REASON_DIVERGED

            stats = self.stats

            def solve(block, offsets, w0, feature_mask=None):
                stats.traces += 1
                stats.trace_keys.append(
                    ("block",) + tuple(block.features.shape) + (has_mask,)
                )
                w, iterations, reasons = _solve_block(
                    block, offsets, w0, objective, spec, config, feature_mask,
                    re_kernel=re_kernel,
                )
                # Per-entity divergence quarantine, fully in-trace: a row
                # whose solve went non-finite keeps its warm start and is
                # flagged REASON_DIVERGED. The reasons array is only read on
                # the host at the pass-boundary mask fetch / report finalize,
                # so the guard adds no syncs.
                row_finite = jnp.all(jnp.isfinite(w), axis=-1)
                w = jnp.where(row_finite[:, None], w, w0)
                reasons = jnp.where(row_finite, reasons, REASON_DIVERGED)
                if tol is None:
                    return w, iterations, reasons
                # Relative coefficient movement in MODEL space; the floor of
                # 1.0 on the reference norm makes near-zero models behave
                # like an absolute tolerance. Quarantined rows have w == w0,
                # hence delta == 0: they retire from the active set.
                delta = jnp.linalg.norm((w - w0).astype(jnp.float32), axis=-1)
                ref = jnp.maximum(
                    jnp.linalg.norm(w0.astype(jnp.float32), axis=-1), 1.0
                )
                valid = block.entity_idx >= 0
                active = (delta > tol * ref) & valid
                # Quarantine keys on the DIVERGED reason, not row_finite:
                # the in-loop guards (Newton's non-finite-objective stop,
                # L-BFGS's iterate rollback) already return a finite w while
                # flagging the row — those entities must still be counted.
                quarantined = (reasons == REASON_DIVERGED) & valid
                return w, iterations, reasons, active, quarantined

            if has_mask:

                def traced(block, offsets, w0, feature_mask):
                    return solve(block, offsets, w0, feature_mask)

            else:

                def traced(block, offsets, w0):
                    return solve(block, offsets, w0)

            donate = (2,) if self.donate else ()
            return jax.jit(traced, donate_argnums=donate)

        fn = self._get_or_build(key, build, (objective, spec, config))
        counted = self._counted(fn)
        if has_mask:
            return counted

        def call(block, offsets, w0, feature_mask=None):
            assert feature_mask is None
            return counted(block, offsets, w0)

        return call

    def fe_solver(self, objective, spec) -> Callable:
        """Jitted fixed-effect solve ``(w0, labeled_batch) -> OptimizeResult``
        for one (objective, spec). The batch is a traced argument, so the
        one cache entry serves every batch of the same structure; w0 is NOT
        donated here (fixed-effect warm starts alias live model buffers)."""
        key = ("fe", self._objective_key(objective), self._spec_key(spec))

        def build():
            from photon_tpu.optim.common import REASON_DIVERGED
            from photon_tpu.optim.factory import make_optimizer

            solve = make_optimizer(objective, spec)
            stats = self.stats

            def traced(w0, lb):
                stats.traces += 1
                stats.trace_keys.append(("fe", int(w0.shape[0])))
                res = solve(w0, lb)
                # Divergence backstop covering every optimizer type: a
                # non-finite final point falls back to the warm start and is
                # flagged DIVERGED (L-BFGS additionally rolls back to the
                # last finite iterate inside its own loop).
                ok = jnp.all(jnp.isfinite(res.w))
                return dataclasses.replace(
                    res,
                    w=jnp.where(ok, res.w, w0),
                    reason_code=jnp.where(
                        ok, res.reason_code, jnp.int32(REASON_DIVERGED)
                    ),
                )

            return jax.jit(traced)

        fn = self._get_or_build(key, build, (objective, spec))
        return self._counted(fn)

    # ---- introspection ---------------------------------------------------

    @contextlib.contextmanager
    def expect_cached(self, what: str = "dispatch"):
        """Assert no NEW executable is traced inside the context.

        The active-set path wraps every compacted dispatch in this: compacted
        blocks are packed exclusively onto entity allocations that the first
        full pass already compiled, so a retrace here is a bug (a shape that
        escaped the allowed-size plan), not a performance wobble. Tracing
        happens synchronously at dispatch time, so the counter check is
        exact even though execution is async.
        """
        traces0, nkeys = self.stats.traces, len(self.stats.trace_keys)
        yield
        if self.stats.traces != traces0:
            raise AssertionError(
                f"{what}: expected a cache hit but traced "
                f"{self.stats.traces - traces0} new executable(s): "
                f"{self.stats.trace_keys[nkeys:]}"
            )

    def trace_mark(self) -> int:
        """Snapshot of the cumulative trace count, for retrace-delta
        assertions across a window (the out-of-core bench and ci stages
        assert ``traces_since(mark) == 0`` after warm-up: residency changes
        where a block lives, never its aval, so evictions must not
        recompile)."""
        return int(self.stats.traces)

    def traces_since(self, mark: int) -> int:
        """New executables traced since :meth:`trace_mark`."""
        return int(self.stats.traces) - int(mark)

    @property
    def num_entries(self) -> int:
        return len(self._fns)

    def clear(self) -> None:
        with self._lock:
            self._fns.clear()
            self._pins.clear()
            self.stats = SolveCacheStats()

    def reset_stats(self) -> None:
        """Zero the counters while KEEPING compiled executables.

        Mutates in place: already-built traced closures captured this stats
        object, so replacing it would route their retrace increments to a
        dead object. Used by ``obs.begin_run`` so a run report counts this
        run's dispatches, not the process's lifetime."""
        with self._lock:
            s = self.stats
            s.traces = 0
            s.calls = 0
            s.hits = 0
            s.evictions = 0
            s.trace_keys.clear()


_default_cache = SolveCache()


def default_cache() -> SolveCache:
    """The process-wide cache shared by coordinates without an explicit one."""
    return _default_cache


def reset_default_cache(
    donate: bool = True, max_entries: Optional[int] = None
) -> SolveCache:
    """Replace the shared cache (tests / benchmark A-B sections)."""
    global _default_cache
    _default_cache = SolveCache(donate=donate, max_entries=max_entries)
    return _default_cache


def cache_stats() -> Dict[str, Any]:
    """Shared-cache counters, in the shape bench.py reports."""
    return _default_cache.stats.as_dict()
