"""Entity-sharded random-effect coordinate: one GAME coordinate, S device
shards.

The multi-device training tentpole for the coordinate-descent path: the RE
coefficient store is sharded by ENTITY across devices using the serving
fleet's consistent-hash ring (parallel/entity_shard.py — the PR-13 disjoint
ownership trick applied to devices instead of replicas). Each shard is a
full :class:`~photon_tpu.algorithm.random_effect.RandomEffectCoordinate`
over ONLY its entities' samples, with its blocks, warm starts, and solves
committed to the owning device; solve caching, drop-mode scatter
discipline, convergence-gated active-set passes, and out-of-core residency
all run unchanged inside each shard. The score/residual merge is the one
cross-device exchange per pass: per-shard coefficient tables gather to a
host master (disjoint rows — exact, order-independent) that scores the flat
batch exactly like a single-device model.

Bit-parity by construction: the shard layout is FIXED (default 8 shards)
independent of device count — shard ``s`` runs on device ``(s*n)//S`` — so
every device count dispatches the identical programs on identical block
geometry and differs only in placement. ``n=1`` IS the single-device run;
``np.array_equal`` holds against any other ``n`` (asserted by
``bench.py --multichip`` and tests/test_entity_sharded.py).

Zero retraces: shards share one :class:`SolveCache`; a shard's block
shapes are stable across passes and across device counts, and the cache
needs no per-device keying (one jitted executable serves every device of a
backend), so after the first full pass no shard ever retraces — including
gated and out-of-core passes, whose compaction plans draw only on
already-compiled allocations.
"""

from __future__ import annotations

import time
from typing import Any, List, Optional, Sequence, Tuple

import jax
import numpy as np

from photon_tpu.algorithm.coordinate import Coordinate
from photon_tpu.algorithm.random_effect import (
    RandomEffectCoordinate,
    RandomEffectTrackerStats,
)
from photon_tpu.algorithm.solve_cache import SolveCache, default_cache
from photon_tpu.data.game_data import GameBatch
from photon_tpu.data.random_effect import (
    RandomEffectDataConfig,
    build_random_effect_dataset,
)
from photon_tpu.models.game import RandomEffectModel
from photon_tpu.obs.trace import span
from photon_tpu.ops.objective import GLMObjective
from photon_tpu.optim.factory import OptimizerSpec
from photon_tpu.parallel.entity_shard import (
    DEFAULT_N_SHARDS,
    EntityShardPlan,
    build_shard_plan,
    merge_shard_coefficients,
)
from photon_tpu.types import TaskType

Array = jax.Array


class ShardedRandomEffectCoordinate(Coordinate):
    """S per-device sub-coordinates behind the single-coordinate protocol.

    Build with :meth:`build` (it owns the per-shard dataset construction).
    ``train`` returns a merged host-master :class:`RandomEffectModel` whose
    rows are each entity's coefficients from its owning shard; warm starts
    stay per-shard on-device across passes (the merged model is for
    scoring/residuals — passing it back as ``initial_model`` re-slices it
    only when it is not this coordinate's own previous output).

    ``last_shard_walls`` holds the previous pass's per-shard
    (dispatch + sync) wall seconds: shards are timed one at a time, so on a
    mesh of real devices each entry is that device's busy time for its own
    work — the per-chip throughput measurement ``bench.py --multichip``
    aggregates.
    """

    def __init__(
        self,
        coordinate_id: str,
        plan: EntityShardPlan,
        shards: Sequence[RandomEffectCoordinate],
        devices: Sequence,
        re_type: str,
        feature_shard: str,
        task: TaskType,
        dim: int,
    ):
        self.coordinate_id = coordinate_id
        self.plan = plan
        self.shards = list(shards)
        self.devices = list(devices)
        self.re_type = re_type
        self.feature_shard = feature_shard
        self.task = task
        self.dim = int(dim)
        self.num_entities = plan.num_entities
        # Per-shard previous-pass models (device-resident warm starts).
        self._shard_models: List[Optional[RandomEffectModel]] = [
            None for _ in self.shards
        ]
        self._last_merged: Optional[RandomEffectModel] = None
        self.last_shard_walls: Optional[List[float]] = None
        self.last_shard_samples: List[int] = [
            sum(
                int(np.sum(np.asarray(b.weight) > 0))
                for b in c.dataset.blocks
            )
            for c in self.shards
        ]

    # -- construction ------------------------------------------------------

    @classmethod
    def build(
        cls,
        coordinate_id: str,
        entity_ids: np.ndarray,
        features: np.ndarray,
        label: np.ndarray,
        weight: np.ndarray,
        num_entities: int,
        config: RandomEffectDataConfig,
        task: TaskType,
        objective: GLMObjective,
        optimizer_spec: Optional[OptimizerSpec] = None,
        plan: Optional[EntityShardPlan] = None,
        n_shards: int = DEFAULT_N_SHARDS,
        seed: int = 0,
        entity_index=None,
        devices: Optional[Sequence] = None,
        solve_cache: Optional[SolveCache] = None,
        active_set: bool = False,
        convergence_tol: float = 1e-4,
        device_budget_bytes: Optional[int] = None,  # PER SHARD
        device_spill_dir: Optional[str] = None,
        re_kernel: str = "auto",
    ) -> "ShardedRandomEffectCoordinate":
        """Shard the flat sample arrays by entity owner and build one
        per-device sub-coordinate per shard.

        Each shard's dataset is built from the SAME flat arrays with
        non-owned samples' entity ids masked to -1 (the builder drops
        them), so ``sample_index`` keeps addressing the GLOBAL batch rows —
        residual gathers need no per-shard batch slicing. Entity indices
        are LOCAL to the shard (ascending-global order), which is what
        makes the per-device coefficient table (E_s, d) instead of (E, d):
        the store is genuinely sharded, not replicated.

        ``device_budget_bytes`` (out-of-core residency) applies PER SHARD —
        the fixed per-device budget of the capacity-scaling story.
        """
        if plan is None:
            plan = build_shard_plan(
                num_entities, n_shards=n_shards, seed=seed,
                entity_index=entity_index,
            )
        if devices is None:
            devices = jax.devices()
        cache = solve_cache if solve_cache is not None else default_cache()
        spec = optimizer_spec or OptimizerSpec()
        per_shard_eids = plan.shard_sample_entities(np.asarray(entity_ids))
        shards: List[RandomEffectCoordinate] = []
        shard_devices = []
        for s in range(plan.n_shards):
            dev = devices[plan.device_of(s, len(devices))]
            shard_devices.append(dev)
            dataset = build_random_effect_dataset(
                per_shard_eids[s],
                features,
                label,
                weight,
                int(plan.counts[s]),
                config,
            )
            shards.append(
                RandomEffectCoordinate(
                    coordinate_id=f"{coordinate_id}/shard{s}",
                    dataset=dataset,
                    task=task,
                    objective=objective,
                    optimizer_spec=spec,
                    solve_cache=cache,
                    active_set=active_set,
                    convergence_tol=convergence_tol,
                    device_budget_bytes=device_budget_bytes,
                    # Host-owned spill layout: shard s's master lives under
                    # ``<spill>/host-<s>/`` so a shard-count rebalance is a
                    # file move (re_store.rebalance_spill_layout).
                    device_spill_dir=device_spill_dir,
                    device_spill_member=(
                        s if device_spill_dir is not None else None
                    ),
                    re_kernel=re_kernel,
                    device=dev,
                )
            )
        return cls(
            coordinate_id=coordinate_id,
            plan=plan,
            shards=shards,
            devices=shard_devices,
            re_type=config.re_type,
            feature_shard=config.feature_shard,
            task=task,
            dim=int(features.shape[1]),
        )

    # -- coordinate protocol -----------------------------------------------

    def begin_cd_pass(self, cd_iteration: int) -> None:
        for c in self.shards:
            c.begin_cd_pass(cd_iteration)

    def train(
        self,
        batch: GameBatch,
        residual_scores: Optional[Array] = None,
        initial_model: Optional[Any] = None,
    ) -> Tuple[RandomEffectModel, RandomEffectTrackerStats]:
        shard_inits = self._shard_initials(initial_model)
        walls: List[float] = []
        shard_models: List[Optional[RandomEffectModel]] = []
        shard_stats = []
        with span("re_sharded_train"):
            for s, coord in enumerate(self.shards):
                # One shard at a time, synced at the end: the wall below is
                # this device's busy time for its own work (per-chip
                # accounting), and shards stay deterministic regardless of
                # host thread scheduling.
                t0 = time.perf_counter()
                model_s, stats_s = coord.train(
                    batch, residual_scores, shard_inits[s]
                )
                jax.block_until_ready(model_s.coefficients)
                walls.append(time.perf_counter() - t0)
                shard_models.append(model_s)
                shard_stats.append(stats_s)
        self._shard_models = shard_models
        self.last_shard_walls = walls

        # Score/residual merge: the one cross-device exchange of the pass.
        # Shards own disjoint entity rows, so the gather into the host
        # master is exact (x + 0 = x; no reduction order to vary).
        with span("re_sharded_merge"):
            merged = RandomEffectModel(
                merge_shard_coefficients(
                    self.plan,
                    [np.asarray(m.coefficients) for m in shard_models],
                    self.dim,
                ),
                self.re_type,
                self.feature_shard,
                self.task,
            )
        self._last_merged = merged
        return merged, self._merge_stats(shard_stats)

    def _shard_initials(
        self, initial_model: Optional[Any]
    ) -> List[Optional[RandomEffectModel]]:
        """Warm starts per shard. Our own previous output reuses the
        device-resident per-shard models (no re-slicing, no h2d); a foreign
        dense model is sliced through the plan onto each shard's local
        entity space."""
        if initial_model is None:
            return [None for _ in self.shards]
        if initial_model is self._last_merged and self._last_merged is not None:
            return list(self._shard_models)
        coefs = np.asarray(initial_model.coefficients, np.float32)
        inits: List[Optional[RandomEffectModel]] = []
        for s in range(self.plan.n_shards):
            ents = self.plan.entities_of(s)
            inits.append(
                RandomEffectModel(
                    jax.device_put(
                        np.ascontiguousarray(coefs[ents, : self.dim]),
                        self.devices[s],
                    ),
                    self.re_type,
                    self.feature_shard,
                    self.task,
                )
            )
        return inits

    @staticmethod
    def _merge_stats(shard_stats: Sequence) -> RandomEffectTrackerStats:
        parts = [st for st in shard_stats if st is not None]
        if not parts:
            return RandomEffectTrackerStats.empty()
        import jax.numpy as jnp

        # Per-shard tracker arrays live on different devices; concatenate
        # host-side (tiny int arrays — this is diagnostics, not hot path).
        return RandomEffectTrackerStats(
            iterations=jnp.asarray(
                np.concatenate([np.asarray(st.iterations) for st in parts])
            ),
            reasons=jnp.asarray(
                np.concatenate([np.asarray(st.reasons) for st in parts])
            ),
            valid=jnp.asarray(
                np.concatenate([np.asarray(st.valid) for st in parts])
            ),
        )

    def score(self, model, batch: GameBatch) -> Array:
        return model.score(batch)

    def zero_model(self) -> RandomEffectModel:
        return RandomEffectModel(
            np.zeros((self.num_entities, self.dim), np.float32),
            self.re_type,
            self.feature_shard,
            self.task,
        )

    # -- diagnostics -------------------------------------------------------

    def device_busy_seconds(self, n_devices: Optional[int] = None) -> List[float]:
        """Previous pass's busy seconds per DEVICE (shard walls folded
        through the shard→device map)."""
        if self.last_shard_walls is None:
            return []
        n = n_devices if n_devices is not None else len(set(map(id, self.devices)))
        busy = [0.0] * n
        for s, w in enumerate(self.last_shard_walls):
            busy[self.plan.device_of(s, n)] += w
        return busy

    def residency_stats(self) -> List[Optional[dict]]:
        return [c.last_residency_stats for c in self.shards]
